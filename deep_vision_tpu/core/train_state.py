"""The single training-state pytree shared by every model in the zoo.

Replaces the reference's four ad-hoc checkpoint payloads (torch dict at
ResNet/pytorch/train.py:417-428, Keras hdf5 at ResNet/tensorflow/train.py:65-78,
save_weights at YOLO/tensorflow/train.py:243-257, tf.train.Checkpoint at
CycleGAN/tensorflow/train.py:133-148) with one functional state:

    {step, params, batch_stats, opt_state, rng}

Everything is a pytree, so pjit shards it, optax updates it, and orbax
checkpoints it without model-specific code.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray  # scalar int32
    params: Any
    batch_stats: Any  # BN running stats ({} for stat-less models)
    opt_state: Any
    rng: jax.Array  # per-step dropout/augment key

    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
        )

    @property
    def variables(self):
        v = {"params": self.params}
        if self.batch_stats:
            v["batch_stats"] = self.batch_stats
        return v


def create_train_state(
    model,
    tx: optax.GradientTransformation,
    sample_input,
    rng: Optional[jax.Array] = None,
    init_kwargs: Optional[dict] = None,
) -> TrainState:
    """Initialize params on host, build optimizer state, return TrainState.

    `sample_input` may be an array or a tuple of arrays fed to `model.init`.
    """
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    init_rng, state_rng = jax.random.split(rng)
    args = sample_input if isinstance(sample_input, tuple) else (sample_input,)
    variables = model.init(
        {"params": init_rng, "dropout": init_rng}, *args, **(init_kwargs or {})
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        rng=state_rng,
        apply_fn=model.apply,
        tx=tx,
    )
