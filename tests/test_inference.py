"""End-to-end inference path + detection/pose quality metrics.

Covers VERDICT.md missing #1: model -> decode -> NMS -> boxes for a user,
and mAP/PCKh computed on synthetic fixtures with known answers.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # jit-heavy: excluded from the fast tier (`-m "not slow"`)

from deep_vision_tpu.core.detection_metrics import (

    DetectionEvaluator,
    pck,
    pckh,
)


class TestDetectionEvaluator:
    def test_perfect_detections_map_1(self):
        ev = DetectionEvaluator(num_classes=3)
        rng = np.random.RandomState(0)
        for _ in range(4):
            boxes = rng.rand(5, 2) * 0.4
            boxes = np.concatenate([boxes, boxes + 0.3], -1)
            classes = rng.randint(0, 3, size=5)
            ev.add(boxes, np.ones(5) * 0.9, classes, boxes, classes)
        out = ev.compute(iou_threshold=0.5)
        assert out["mAP"] == pytest.approx(1.0)

    def test_all_wrong_class_map_0(self):
        ev = DetectionEvaluator(num_classes=2)
        boxes = np.array([[0.1, 0.1, 0.4, 0.4]])
        ev.add(boxes, [0.9], [1], boxes, [0])
        out = ev.compute()
        assert out["mAP"] == 0.0

    def test_half_precision_known_ap(self):
        # 1 GT box; 2 detections: the higher-scored one misses, the lower hits.
        # all-point AP = precision at recall 1 = 1/2.
        ev = DetectionEvaluator(num_classes=1)
        gt = np.array([[0.1, 0.1, 0.5, 0.5]])
        preds = np.array([[0.6, 0.6, 0.9, 0.9], [0.1, 0.1, 0.5, 0.5]])
        ev.add(preds, [0.9, 0.8], [0, 0], gt, [0])
        out = ev.compute(iou_threshold=0.5)
        assert out["mAP"] == pytest.approx(0.5)

    def test_duplicate_detection_is_fp(self):
        # two detections on one GT: second match counts as FP (VOC protocol)
        ev = DetectionEvaluator(num_classes=1)
        gt = np.array([[0.1, 0.1, 0.5, 0.5]])
        preds = np.stack([gt[0], gt[0]])
        ev.add(preds, [0.9, 0.8], [0, 0], gt, [0])
        out = ev.compute(iou_threshold=0.5)
        # AP: TP at rank 1 (P=1, R=1), FP at rank 2 -> all-point AP = 1.0
        assert out["mAP"] == pytest.approx(1.0)
        # but precision fell; 11-point also 1.0 since max precision at R>=t is 1
        # instead verify the FP lowered nothing incorrectly:
        assert out["ap_per_class"][0] == pytest.approx(1.0)

    def test_padded_rows_ignored(self):
        ev = DetectionEvaluator(num_classes=1)
        gt = np.array([[0.1, 0.1, 0.5, 0.5], [0, 0, 0, 0]])
        preds = np.array([[0.1, 0.1, 0.5, 0.5], [0, 0, 0, 0]])
        ev.add(preds, [0.9, 0.0], [0, -1], gt, [0, 0])
        out = ev.compute()
        assert out["mAP"] == pytest.approx(1.0)

    def test_coco_sweep_monotone(self):
        ev = DetectionEvaluator(num_classes=1)
        gt = np.array([[0.1, 0.1, 0.5, 0.5]])
        # slightly offset box: IoU ~ 0.68 -> hits at 0.5, misses at 0.9
        pred = np.array([[0.13, 0.13, 0.53, 0.53]])
        ev.add(pred, [0.9], [0], gt, [0])
        out = ev.compute_coco()
        assert out["mAP@.5"] == pytest.approx(1.0)
        assert 0.0 < out["mAP@[.5:.95]"] < 1.0


class TestPck:
    def test_exact_keypoints(self):
        gt = np.random.RandomState(0).rand(3, 16, 2)
        vis = np.ones((3, 16), bool)
        out = pckh(gt, gt, vis, head_sizes=np.full(3, 0.1))
        assert out["PCKh@0.5"] == pytest.approx(1.0)

    def test_known_fraction(self):
        gt = np.zeros((1, 4, 2))
        pred = np.zeros((1, 4, 2))
        pred[0, :2, 0] = 0.04  # within 0.5 * 0.1
        pred[0, 2:, 0] = 0.2  # outside
        out = pck(pred, gt, np.ones((1, 4), bool), [0.1], alpha=0.5)
        assert out["PCK@0.5"] == pytest.approx(0.5)
        assert out["per_joint"][0] == pytest.approx(1.0)
        assert out["per_joint"][3] == pytest.approx(0.0)

    def test_invisible_excluded(self):
        gt = np.zeros((1, 2, 2))
        pred = np.ones((1, 2, 2))  # both wrong
        vis = np.array([[True, False]])
        out = pck(pred, gt, vis, [1.0])
        assert out["num_visible"] == 1


class TestYoloInference:
    def test_decode_and_nms_shapes(self):
        """Tiny YoloV3 -> decode -> NMS end-to-end, fixed shapes out."""
        from deep_vision_tpu.inference import make_yolo_detector
        from deep_vision_tpu.models import get_model

        model = get_model("yolov3", num_classes=4)
        x = jnp.zeros((2, 64, 64, 3), jnp.float32)
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        detect = make_yolo_detector(model, max_detections=10,
                                    score_threshold=0.05)
        out = detect(variables, x)
        assert out["boxes"].shape == (2, 10, 4)
        assert out["scores"].shape == (2, 10)
        assert out["classes"].shape == (2, 10)
        assert out["num"].shape == (2,)
        assert int(out["num"].max()) <= 10
        # padding convention: classes -1 where invalid
        invalid = np.asarray(out["scores"]) == 0
        assert np.all(np.asarray(out["classes"])[invalid] == -1)

    def test_synthetic_peak_detected(self):
        """Craft raw head outputs with one hot box; decode+NMS must find it."""
        from deep_vision_tpu.inference import yolo_decode_outputs
        from deep_vision_tpu.ops.anchors import YOLO_ANCHORS, YOLO_ANCHOR_MASKS
        from deep_vision_tpu.ops.nms import non_maximum_suppression

        g = 4
        c = 3
        outputs = []
        for _ in range(3):
            outputs.append(np.full((1, g, g, 3, 5 + c), -8.0, np.float32))
        # scale 0, cell (1, 2), anchor 1, class 2: strong positive
        outputs[0][0, 1, 2, 1, 4] = 8.0  # objectness
        outputs[0][0, 1, 2, 1, 5 + 2] = 8.0
        outputs[0][0, 1, 2, 1, 0:2] = 0.0  # sigmoid -> 0.5: center of cell
        outputs[0][0, 1, 2, 1, 2:4] = 0.0  # wh = anchor size
        outputs = [jnp.asarray(o) for o in outputs]
        boxes, scores = yolo_decode_outputs(outputs)
        best_c = jnp.argmax(scores, -1)
        best_s = jnp.max(scores, -1)
        ob, os_, oc, n = non_maximum_suppression(
            boxes, best_s, best_c, max_detections=5, score_threshold=0.5
        )
        assert int(n[0]) == 1
        assert int(oc[0, 0]) == 2
        box = np.asarray(ob[0, 0])
        cx, cy = (box[0] + box[2]) / 2, (box[1] + box[3]) / 2
        assert cx == pytest.approx((2 + 0.5) / g, abs=1e-5)
        assert cy == pytest.approx((1 + 0.5) / g, abs=1e-5)
        anchor = YOLO_ANCHORS[YOLO_ANCHOR_MASKS[0][1]]
        assert box[2] - box[0] == pytest.approx(anchor[0], rel=1e-4)

    def test_e2e_map_on_fixture(self):
        """Detector output -> evaluator: mAP on a crafted fixture is 1.0."""
        from deep_vision_tpu.core.detection_metrics import DetectionEvaluator
        from deep_vision_tpu.ops.nms import non_maximum_suppression

        gt_boxes = np.array([[0.2, 0.2, 0.6, 0.6], [0.1, 0.6, 0.3, 0.9]])
        gt_classes = np.array([0, 1])
        # detector candidates: GT boxes + jittered dupes at lower score
        cand = np.concatenate([gt_boxes, gt_boxes + 0.01], 0)[None]
        scores = np.array([[0.9, 0.95, 0.6, 0.55]])
        classes = np.array([[0, 1, 0, 1]])
        ob, os_, oc, n = non_maximum_suppression(
            jnp.asarray(cand), jnp.asarray(scores), jnp.asarray(classes),
            max_detections=4, iou_threshold=0.5, score_threshold=0.3,
        )
        ev = DetectionEvaluator(num_classes=2)
        ev.add(np.asarray(ob[0]), np.asarray(os_[0]), np.asarray(oc[0]),
               gt_boxes, gt_classes)
        out = ev.compute(iou_threshold=0.5)
        assert int(n[0]) == 2  # NMS removed the jittered dupes
        assert out["mAP"] == pytest.approx(1.0)


class TestCenternetInference:
    def test_peak_decode(self):
        from deep_vision_tpu.inference import centernet_decode

        h = w = 8
        c = 2
        heat = np.full((1, h, w, c), -8.0, np.float32)
        heat[0, 3, 5, 1] = 8.0  # single confident peak
        wh = np.zeros((1, h, w, 2), np.float32)
        wh[0, 3, 5] = [2.0, 4.0]  # in feature-map cells
        off = np.zeros((1, h, w, 2), np.float32)
        off[0, 3, 5] = [0.5, 0.5]
        out = centernet_decode(
            {"heatmap": jnp.asarray(heat), "wh": jnp.asarray(wh),
             "offset": jnp.asarray(off)},
            max_detections=5, score_threshold=0.5,
        )
        assert int(out["num"][0]) == 1
        assert int(out["classes"][0, 0]) == 1
        box = np.asarray(out["boxes"][0, 0])
        assert (box[0] + box[2]) / 2 == pytest.approx((5 + 0.5) / w)
        assert (box[1] + box[3]) / 2 == pytest.approx((3 + 0.5) / h)
        assert box[2] - box[0] == pytest.approx(2.0 / w)
        assert box[3] - box[1] == pytest.approx(4.0 / h)

    def test_model_wiring(self):
        from deep_vision_tpu.inference import make_centernet_detector
        from deep_vision_tpu.models import get_model

        model = get_model("objects_as_points", num_classes=3, num_stack=1)
        x = jnp.zeros((1, 128, 128, 3), jnp.float32)
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        detect = make_centernet_detector(model, max_detections=8)
        out = detect(variables, x)
        assert out["boxes"].shape == (1, 8, 4)
        assert out["num"].shape == (1,)


class TestPoseInference:
    def test_heatmap_argmax(self):
        from deep_vision_tpu.inference import heatmaps_to_keypoints

        hm = np.zeros((1, 16, 16, 2), np.float32)
        hm[0, 4, 7, 0] = 1.0
        hm[0, 12, 2, 1] = 0.8
        kpts = np.asarray(heatmaps_to_keypoints(jnp.asarray(hm)))
        assert kpts.shape == (1, 2, 3)
        assert kpts[0, 0, 0] == pytest.approx(7 / 16)
        assert kpts[0, 0, 1] == pytest.approx(4 / 16)
        assert kpts[0, 1, 2] == pytest.approx(0.8)

    def test_pose_estimator_wiring(self):
        from deep_vision_tpu.inference import make_pose_estimator
        from deep_vision_tpu.models import get_model

        model = get_model("hourglass", num_stack=1, num_heatmap=4)
        x = jnp.zeros((1, 64, 64, 3), jnp.float32)
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        estimate = make_pose_estimator(model)
        kpts = estimate(variables, x)
        assert kpts.shape == (1, 4, 3)
