"""ObjectsAsPoints / CenterNet (Zhou 2019): hourglass backbone + center
heatmap / size / offset heads.

Parity target: ObjectsAsPoints/tensorflow/model.py — HourglassModule with a
per-order filter table (:17-32,94-127), DetectionHead producing
(class-heatmap, wh, offset) (:81-91), 2-stack default (:130-179). The
reference's trainer and losses were never finished (train.py:35,248 —
SURVEY.md §2.9); the complete focal+L1 loss lives in losses/centernet.py.

Head convention per stack: dict with
  'heatmap': (B, H/4, W/4, num_classes)  raw logits (sigmoid in loss/decode)
  'wh':      (B, H/4, W/4, 2)
  'offset':  (B, H/4, W/4, 2)
"""
from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from deep_vision_tpu.models import register_model
from deep_vision_tpu.models.hourglass import HgBottleneck
from deep_vision_tpu.nn.layers import FusedBatchNorm

# per-depth channel table, model.py:17-32 flavor
_CURR_DIMS = (256, 256, 384, 384, 384, 512)


class CenterHourglassModule(nn.Module):
    order: int  # 5 at the top

    @nn.compact
    def __call__(self, x, train: bool = True):
        curr = _CURR_DIMS[5 - self.order]
        nxt = _CURR_DIMS[5 - self.order + 1]
        up = HgBottleneck(curr)(x, train)
        up = HgBottleneck(curr)(up, train)
        low = nn.max_pool(x, (2, 2), strides=(2, 2))
        low = HgBottleneck(nxt)(low, train)
        low = HgBottleneck(nxt)(low, train)
        if self.order > 1:
            low = CenterHourglassModule(self.order - 1)(low, train)
        else:
            low = HgBottleneck(nxt)(low, train)
        low = HgBottleneck(curr)(low, train)
        low = HgBottleneck(curr)(low, train)
        low = jnp.repeat(jnp.repeat(low, 2, axis=1), 2, axis=2)
        return up + low


class DetectionHead(nn.Module):
    """3x3 conv + 1x1 per output branch (model.py:81-91)."""

    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = True):
        def branch(ch, bias_init=0.0):
            y = nn.Conv(256, (3, 3))(x)
            y = nn.relu(y)
            return nn.Conv(
                ch, (1, 1), bias_init=nn.initializers.constant(bias_init)
            )(y)

        # heatmap bias init -2.19 = -log((1-0.1)/0.1): focal-loss prior
        return {
            "heatmap": branch(self.num_classes, bias_init=-2.19),
            "wh": branch(2),
            "offset": branch(2),
        }


class ObjectsAsPoints(nn.Module):
    """Returns a list of per-stack head dicts (intermediate supervision)."""

    num_classes: int = 20
    num_stack: int = 2
    features: int = 256

    @nn.compact
    def __call__(self, x, train: bool = True):
        # stem: /4 resolution (model.py:130-140)
        x = nn.Conv(128, (7, 7), strides=(2, 2), use_bias=False)(x)
        x = nn.relu(FusedBatchNorm(use_running_average=not train, momentum=0.9)(x))
        x = HgBottleneck(self.features)(x, train)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = HgBottleneck(self.features)(x, train)

        outputs = []
        for stack in range(self.num_stack):
            inter = CenterHourglassModule(5)(x, train)
            inter = HgBottleneck(self.features)(inter, train)
            outputs.append(DetectionHead(self.num_classes)(inter, train))
            if stack < self.num_stack - 1:
                x = x + nn.Conv(self.features, (1, 1), use_bias=False)(inter)
        return outputs


@register_model("objects_as_points")
def objects_as_points(num_classes: int = 20, num_stack: int = 2, **_):
    return ObjectsAsPoints(num_classes=num_classes, num_stack=num_stack)
