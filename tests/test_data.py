"""Data layer tests: Example codec, record framing, datasets, transforms,
DataLoader. The codec/framing tests cross-check against TensorFlow's own
implementations when TF is importable (byte-level format parity with the
shard files the reference's converters produce)."""
import os
import struct

import numpy as np
import pytest

from deep_vision_tpu.data import (
    Compose,
    DataLoader,
    MnistDataset,
    RecordDataset,
    decode_example,
    encode_example,
    read_records,
    write_records,
)
from deep_vision_tpu.data import transforms as T

try:
    import tensorflow as tf

    HAS_TF = True
except Exception:
    HAS_TF = False


FEATS = {
    "image/encoded": [b"\x00\x01jpegbytes\xff"],
    "image/width": [416],
    "image/object/bbox/xmin": [0.125, 0.5],
    "name": [b"img_001"],
}


def test_example_codec_roundtrip():
    out = decode_example(encode_example(FEATS))
    assert out["image/encoded"] == FEATS["image/encoded"]
    assert out["image/width"] == [416]
    assert out["name"] == [b"img_001"]
    np.testing.assert_allclose(
        out["image/object/bbox/xmin"], FEATS["image/object/bbox/xmin"], rtol=1e-6
    )


def test_example_codec_negative_int_and_empty():
    out = decode_example(encode_example({"a": [-5, 3], "b": []}))
    assert out["a"] == [-5, 3]
    assert out["b"] == []


def test_example_codec_numpy_scalars():
    # values sourced from numpy arrays must encode like their Python twins
    out = decode_example(encode_example({
        "f32": list(np.array([0.25, 0.5], np.float32)),
        "f64": list(np.array([1.5], np.float64)),
        "i64": list(np.array([-5, 3], np.int64)),
        "i32": list(np.array([7], np.int32)),
        "u8": list(np.array([255], np.uint8)),
    }))
    np.testing.assert_allclose(out["f32"], [0.25, 0.5], rtol=1e-6)
    np.testing.assert_allclose(out["f64"], [1.5], rtol=1e-6)
    assert out["i64"] == [-5, 3]
    assert out["i32"] == [7]
    assert out["u8"] == [255]


@pytest.mark.skipif(not HAS_TF, reason="tensorflow unavailable")
def test_example_codec_tf_cross_parity():
    # our encoder -> TF parser
    parsed = tf.train.Example.FromString(encode_example(FEATS))
    f = parsed.features.feature
    assert f["image/encoded"].bytes_list.value[0] == FEATS["image/encoded"][0]
    assert list(f["image/width"].int64_list.value) == [416]
    np.testing.assert_allclose(
        list(f["image/object/bbox/xmin"].float_list.value), [0.125, 0.5]
    )
    # TF encoder -> our parser
    ex = tf.train.Example(
        features=tf.train.Features(
            feature={
                "label": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[7])
                ),
                "xy": tf.train.Feature(
                    float_list=tf.train.FloatList(value=[0.25, -1.5])
                ),
                "raw": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[b"abc"])
                ),
            }
        )
    )
    out = decode_example(ex.SerializeToString())
    assert out["label"] == [7]
    np.testing.assert_allclose(out["xy"], [0.25, -1.5])
    assert out["raw"] == [b"abc"]


def test_records_roundtrip(tmp_path):
    path = str(tmp_path / "a.tfrecord")
    payloads = [b"first", b"", b"x" * 1000]
    assert write_records(path, payloads) == 3
    assert list(read_records(path)) == payloads


def test_records_corruption_detected(tmp_path):
    path = str(tmp_path / "a.tfrecord")
    write_records(path, [b"hello world"])
    with open(path, "r+b") as f:
        f.seek(14)  # inside payload
        f.write(b"X")
    with pytest.raises(IOError):
        list(read_records(path))
    # verify=False skips the check
    assert len(list(read_records(path, verify=False))) == 1


@pytest.mark.skipif(not HAS_TF, reason="tensorflow unavailable")
def test_records_tf_cross_parity(tmp_path):
    ours = str(tmp_path / "ours.tfrecord")
    theirs = str(tmp_path / "tf.tfrecord")
    payloads = [b"alpha", b"beta" * 100]
    write_records(ours, payloads)
    got = [bytes(r.numpy()) for r in tf.data.TFRecordDataset(ours)]
    assert got == payloads
    with tf.io.TFRecordWriter(theirs) as w:
        for p in payloads:
            w.write(p)
    assert list(read_records(theirs)) == payloads


def test_record_dataset_voc_schema(tmp_path):
    import cv2

    img = np.full((20, 30, 3), 128, np.uint8)
    ok, enc = cv2.imencode(".png", img)
    assert ok
    ex = encode_example(
        {
            "image/encoded": [enc.tobytes()],
            "image/object/bbox/xmin": [0.1],
            "image/object/bbox/ymin": [0.2],
            "image/object/bbox/xmax": [0.5],
            "image/object/bbox/ymax": [0.6],
            "image/object/class/label": [3],
        }
    )
    path = str(tmp_path / "voc-00000-of-00001.tfrecord")
    write_records(path, [ex, ex])
    ds = RecordDataset(str(tmp_path / "voc-*"), schema="voc")
    samples = list(ds)
    assert len(samples) == 2
    assert samples[0]["image"].shape == (20, 30, 3)
    np.testing.assert_allclose(samples[0]["boxes"], [[0.1, 0.2, 0.5, 0.6]])
    assert samples[0]["classes"].tolist() == [3]


def test_mnist_idx_dataset(tmp_path):
    imgs = (np.arange(3 * 28 * 28) % 255).astype(np.uint8).reshape(3, 28, 28)
    labels = np.array([5, 0, 9], np.uint8)
    ipath, lpath = str(tmp_path / "imgs.idx"), str(tmp_path / "labels.idx")
    with open(ipath, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 3))
        f.write(struct.pack(">3I", 3, 28, 28))
        f.write(imgs.tobytes())
    with open(lpath, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 1))
        f.write(struct.pack(">I", 3))
        f.write(labels.tobytes())
    ds = MnistDataset(ipath, lpath)
    assert len(ds) == 3
    s = ds[0]
    assert s["image"].shape == (32, 32, 1)  # 28 padded to 32
    assert s["label"] == 5
    np.testing.assert_array_equal(s["image"][2:-2, 2:-2, 0], imgs[0])


def test_rescale_aspect_preserving():
    rng = np.random.default_rng(0)
    s = {"image": np.zeros((100, 200, 3), np.uint8)}
    out = T.Rescale(50)(s, rng)
    assert out["image"].shape == (50, 100, 3)
    s = {"image": np.zeros((200, 100, 3), np.uint8)}
    out = T.Rescale(50)(s, rng)
    assert out["image"].shape == (100, 50, 3)


def test_crops_and_flip_boxes():
    rng = np.random.default_rng(0)
    img = np.arange(10 * 10).reshape(10, 10, 1).astype(np.uint8)
    out = T.CenterCrop(4)({"image": img}, rng)
    assert out["image"].shape == (4, 4, 1)
    np.testing.assert_array_equal(out["image"], img[3:7, 3:7])
    out = T.RandomCrop(4)({"image": img}, rng)
    assert out["image"].shape == (4, 4, 1)

    boxes = np.array([[0.1, 0.2, 0.4, 0.6]], np.float32)
    out = T.RandomHorizontalFlip(p=1.0)(
        {"image": img, "boxes": boxes}, rng
    )
    np.testing.assert_allclose(out["boxes"], [[0.6, 0.2, 0.9, 0.6]], atol=1e-6)
    np.testing.assert_array_equal(out["image"], img[:, ::-1])

    # all-zero padding rows must stay [0,0,0,0] (not become [1,0,1,0])
    padded = np.array([[0.1, 0.2, 0.4, 0.6], [0, 0, 0, 0]], np.float32)
    out = T.RandomHorizontalFlip(p=1.0)({"image": img, "boxes": padded}, rng)
    np.testing.assert_allclose(
        out["boxes"], [[0.6, 0.2, 0.9, 0.6], [0, 0, 0, 0]], atol=1e-6
    )


def test_random_crop_with_boxes_preserves_all_boxes():
    rng = np.random.default_rng(3)
    img = np.zeros((100, 100, 3), np.uint8)
    boxes = np.array(
        [[0.3, 0.3, 0.5, 0.5], [0.6, 0.2, 0.8, 0.4], [0, 0, 0, 0]], np.float32
    )
    for _ in range(20):
        out = T.RandomCropWithBoxes()({"image": img.copy(), "boxes": boxes.copy()}, rng)
        b = out["boxes"][:2]
        assert (b[:, 2] > b[:, 0]).all() and (b[:, 3] > b[:, 1]).all()
        assert (b >= 0).all() and (b <= 1).all()


def test_pad_boxes_fixed_shape():
    rng = np.random.default_rng(0)
    out = T.PadBoxes(5)(
        {"boxes": np.ones((2, 4), np.float32), "classes": np.array([1, 2])}, rng
    )
    assert out["boxes"].shape == (5, 4)
    assert out["classes"].tolist() == [1, 2, 0, 0, 0]


def test_colorjitter_preserves_uint8_for_downstream_tofloat():
    # regression: jitter between decode and ToFloat must not break the
    # 0-255 -> 0-1 rescale (imagenet train chain in train_cli.py)
    rng = np.random.default_rng(0)
    img = np.full((4, 4, 3), 200, np.uint8)
    out = T.ColorJitter(0.4, 0.4, 0.4)({"image": img}, rng)
    assert out["image"].dtype == np.uint8
    s = Compose([T.ColorJitter(0.4, 0.4, 0.4), T.ToFloat()])(
        {"image": img}, rng
    )
    assert s["image"].max() <= 1.0


def test_normalize_and_tofloat():
    rng = np.random.default_rng(0)
    img = np.full((4, 4, 3), 255, np.uint8)
    s = Compose([T.ToFloat(), T.Normalize()])({"image": img}, rng)
    np.testing.assert_allclose(
        s["image"][0, 0], (1.0 - T.IMAGENET_MEAN) / T.IMAGENET_STD, rtol=1e-5
    )
    g = T.ToFloat(expand_gray_to_rgb=True)({"image": np.zeros((4, 4), np.uint8)}, rng)
    assert g["image"].shape == (4, 4, 3)


class _SquaresDataset:
    def __len__(self):
        return 10

    def __getitem__(self, i):
        return {"image": np.full((4, 4, 1), i, np.float32), "label": np.int32(i)}


def test_dataloader_map_style_shuffle_and_batching():
    dl = DataLoader(_SquaresDataset(), batch_size=4, shuffle=True, seed=7,
                    num_workers=2)
    epoch1 = [b["label"].tolist() for b in dl]
    assert sorted(sum(epoch1, [])) == list(range(10))
    assert [len(x) for x in epoch1] == [4, 4, 2]  # remainder kept
    epoch2 = [b["label"].tolist() for b in dl]
    assert epoch1 != epoch2  # reshuffled per epoch

    dl2 = DataLoader(_SquaresDataset(), batch_size=4, shuffle=True, seed=7,
                     num_workers=2)
    assert [b["label"].tolist() for b in dl2] == epoch1  # seed-deterministic
    assert len(dl2) == 3


def test_dataloader_transform_applied_in_order():
    calls = []

    def t1(s, rng):
        s["image"] = s["image"] + 1
        return s

    dl = DataLoader(_SquaresDataset(), batch_size=10, transform=Compose([t1]),
                    num_workers=4, prefetch=0)
    (batch,) = list(dl)
    # order preserved despite parallel map
    np.testing.assert_allclose(batch["image"][:, 0, 0, 0], np.arange(10) + 1)


def test_dataloader_iterable_with_shuffle_buffer():
    def gen():
        for i in range(20):
            yield {"x": np.int32(i)}

    class It:
        def __iter__(self):
            return gen()

    dl = DataLoader(It(), batch_size=5, shuffle=True, shuffle_buffer=8, seed=1)
    vals = sum((b["x"].tolist() for b in dl), [])
    assert sorted(vals) == list(range(20))
    assert vals != list(range(20))  # actually shuffled


def test_dataloader_error_propagates():
    def boom(s, rng):
        raise RuntimeError("decode failed")

    dl = DataLoader(_SquaresDataset(), batch_size=4, transform=boom)
    with pytest.raises(RuntimeError, match="decode failed"):
        list(dl)


def test_mean_subtract_tf_variant():
    rng = np.random.default_rng(0)
    img = (np.ones((4, 4, 3)) * [130, 120, 110]).astype(np.uint8)
    # ToFloat(scale=False) keeps 0-255; MeanSubtract removes TF channel means
    out = T.ToFloat(expand_gray_to_rgb=True, scale=False)({"image": img}, rng)
    out = T.MeanSubtract()(out, rng)
    np.testing.assert_allclose(
        out["image"][0, 0], [130 - 123.68, 120 - 116.78, 110 - 103.94],
        atol=1e-4,
    )
    # grayscale input: expand first, then subtract 3-channel means
    gray = np.full((4, 4), 100, np.uint8)
    out = T.ToFloat(expand_gray_to_rgb=True, scale=False)({"image": gray}, rng)
    out = T.MeanSubtract()(out, rng)
    assert out["image"].shape == (4, 4, 3)
    # channel mismatch is an error, not silent broadcast
    import pytest as _pytest
    with _pytest.raises(ValueError):
        T.MeanSubtract()({"image": np.zeros((4, 4, 1), np.uint8)}, rng)


class TestFusedTransforms:
    def test_colorjitter_matches_sequential(self):
        """The single-pass affine fold must equal the sequential b/c/s ops."""
        import numpy as np
        from deep_vision_tpu.data import transforms as T

        rng_img = np.random.RandomState(0)
        img = (rng_img.rand(32, 32, 3) * 255).astype(np.uint8)
        jit = T.ColorJitter(0.4, 0.4, 0.4)
        rng = np.random.default_rng(7)
        out = jit({"image": img.copy()}, rng)["image"]

        # sequential reference with the SAME factor draws
        rng2 = np.random.default_rng(7)
        fb = jit._factor(rng2, 0.4)
        fc = jit._factor(rng2, 0.4)
        fs = jit._factor(rng2, 0.4)
        x = img.astype(np.float32) * fb
        luma = np.array([0.299, 0.587, 0.114], np.float32)
        m = (x @ luma).mean()
        x = (x - m) * fc + m
        g = x @ luma
        x = (x - g[..., None]) * fs + g[..., None]
        want = np.clip(x, 0, 255).astype(np.uint8)
        np.testing.assert_allclose(out.astype(np.int16), want.astype(np.int16),
                                   atol=1)

    def test_tofloat_normalize_fused_matches_pair(self):
        import numpy as np
        from deep_vision_tpu.data import transforms as T

        img = (np.random.RandomState(1).rand(16, 16, 3) * 255).astype(np.uint8)
        rng = np.random.default_rng(0)
        fused = T.ToFloatNormalize()({"image": img.copy()}, rng)["image"]
        pair = T.Normalize()(
            T.ToFloat()({"image": img.copy()}, rng), rng
        )["image"]
        np.testing.assert_allclose(fused, pair, rtol=1e-5, atol=1e-5)

    def test_tofloat_normalize_gray_expand(self):
        import numpy as np
        from deep_vision_tpu.data import transforms as T

        img = (np.random.RandomState(2).rand(8, 8) * 255).astype(np.uint8)
        out = T.ToFloatNormalize(expand_gray_to_rgb=True)(
            {"image": img}, None
        )["image"]
        assert out.shape == (8, 8, 3)


class TestProcessLoader:
    def _records(self, tmp_path, n_shards=4, per_shard=8):
        import numpy as np
        from deep_vision_tpu.data.example_codec import encode_example
        from deep_vision_tpu.data.records import RecordWriter

        rng = np.random.RandomState(0)
        for s in range(n_shards):
            with RecordWriter(str(tmp_path / f"train-{s}")) as w:
                for i in range(per_shard):
                    w.write(encode_example({
                        "image/encoded": [b""],
                        "image/class/label": [int(s * per_shard + i + 1)],
                    }))
        return str(tmp_path / "train-*")

    def test_record_dataset_split_disjoint_and_complete(self, tmp_path):
        from deep_vision_tpu.data import RecordDataset

        pattern = self._records(tmp_path)
        full = RecordDataset(pattern, schema=lambda f: {
            "label": f["image/class/label"][0]})
        parts = [full.split(i, 3) for i in range(3)]
        all_files = sorted(f for p in parts for f in p.files)
        assert all_files == sorted(full.files)
        seen = [s["label"] for p in parts for s in p]
        assert sorted(seen) == sorted(s["label"] for s in full)

    @pytest.mark.slow
    def test_num_procs_loader_yields_everything(self, tmp_path):
        from deep_vision_tpu.data import DataLoader, RecordDataset

        pattern = self._records(tmp_path)
        ds = RecordDataset(pattern, schema=_label_schema)
        dl = DataLoader(ds, batch_size=4, transform=_add_one,
                        shuffle=True, shuffle_buffer=8, num_procs=2,
                        drop_remainder=False)
        labels = []
        for batch in dl:
            labels.extend(batch["label"].tolist())
        assert sorted(labels) == list(range(2, 34))  # 32 samples, +1 each

    def test_num_procs_requires_splittable(self):
        from deep_vision_tpu.data import DataLoader

        with pytest.raises(TypeError):
            DataLoader([{"x": 1}], batch_size=1, num_procs=2)


def _label_schema(feats):
    return {"label": feats["image/class/label"][0]}


def _add_one(sample, rng):
    sample["label"] = sample["label"] + 1
    return sample


class TestCropRoi:
    """Golden tests vs hand-computed crops (crop_roi parity,
    Hourglass/tensorflow/preprocess.py:43-88)."""

    def _sample(self, h=100, w=200):
        # two visible joints at px (50, 20) and (150, 80); one invisible
        kp = np.array([[50 / 200, 20 / 100],
                       [150 / 200, 80 / 100],
                       [-1 / 200, -1 / 100]], np.float32)
        vis = np.array([1.0, 1.0, 0.0], np.float32)
        img = np.arange(h * w * 3, dtype=np.uint8).reshape(h, w, 3)
        return {"image": img, "keypoints": kp, "visibility": vis}

    def test_hand_computed_crop_with_scale(self):
        s = self._sample()
        s["scale"] = 0.5  # body height = 100 px -> pad = 0.2 * 100 = 20 px
        out = T.CropRoi(margin=0.2)(s, np.random.default_rng(0))
        # extent x:[50,150] y:[20,80]; padded x:[30,170] y:[0,100]
        assert out["image"].shape == (100, 140, 3)
        # keypoint 0 remaps to ((50-30)/140, (20-0)/100)
        np.testing.assert_allclose(
            out["keypoints"][0], [20 / 140, 20 / 100], atol=1e-6)
        np.testing.assert_allclose(
            out["keypoints"][1], [120 / 140, 80 / 100], atol=1e-6)
        # invisible joint rides along, lands outside [0,1]
        assert out["keypoints"][2, 0] < 0

    def test_extent_fallback_without_scale(self):
        s = self._sample()
        out = T.CropRoi(margin=0.2)(s, np.random.default_rng(0))
        # body height = ymax - ymin = 60 -> pad 12: x:[38,162] y:[8,92]
        assert out["image"].shape == (84, 124, 3)

    def test_margin_range_is_sampled(self):
        shapes = set()
        for seed in range(8):
            s = self._sample()
            s["scale"] = 0.5
            out = T.CropRoi(margin=(0.1, 0.3))(s, np.random.default_rng(seed))
            shapes.add(out["image"].shape)
        assert len(shapes) > 1  # random margin really varies the crop

    def test_no_visible_joints_is_noop(self):
        s = self._sample()
        s["visibility"] = np.zeros((3,), np.float32)
        out = T.CropRoi(margin=0.2)(s, np.random.default_rng(0))
        assert out["image"].shape == (100, 200, 3)

    def test_crop_pixels_match_slice(self):
        s = self._sample()
        s["scale"] = 0.5
        orig = s["image"].copy()
        out = T.CropRoi(margin=0.2)(s, np.random.default_rng(0))
        np.testing.assert_array_equal(out["image"], orig[0:100, 30:170])


def test_pose_flip_swaps_left_right_identities():
    """Mirroring moves the left ankle to the right ankle's position; the
    channel identities must swap with it (the bug that made the reference
    disable its flip, preprocess.py:31-40)."""
    kp = np.zeros((16, 2), np.float32)
    kp[0] = [0.2, 0.9]   # r ankle
    kp[5] = [0.8, 0.9]   # l ankle
    vis = np.zeros((16,), np.float32)
    vis[0], vis[5] = 1.0, 2.0
    s = {"image": np.zeros((8, 8, 3), np.uint8), "keypoints": kp,
         "visibility": vis}
    out = T.RandomHorizontalFlip(p=1.0, keypoint_swap_pairs=T.MPII_FLIP_PAIRS)(
        s, np.random.default_rng(0))
    # old l-ankle (0.8 -> flipped 0.2) is now channel 0 (r ankle)
    np.testing.assert_allclose(out["keypoints"][0], [0.2, 0.9], atol=1e-6)
    np.testing.assert_allclose(out["keypoints"][5], [0.8, 0.9], atol=1e-6)
    assert out["visibility"][0] == 2.0 and out["visibility"][5] == 1.0
