"""Shared CLI exit-status contract for the repo's gate tools.

sysexits.h-style: callers (and make) can tell a bad input file from a
bad invocation. Used by `python -m deep_vision_tpu.lint` and
`tools/check_journal.py` — one definition so the two contracts cannot
drift.
"""
from __future__ import annotations

import argparse
import sys

EXIT_OK = 0
EXIT_INVALID = 2
EXIT_USAGE = 64


class UsageErrorParser(argparse.ArgumentParser):
    """argparse exits 2 on bad usage, which collides with 'invalid file';
    remap to EX_USAGE (64)."""

    def error(self, message):
        self.print_usage(sys.stderr)
        print(f"{self.prog}: error: {message}", file=sys.stderr)
        raise SystemExit(EXIT_USAGE)
