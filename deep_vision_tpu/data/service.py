"""Shared dataset service: decode/augment out-of-process, batches over sockets.

The second half of the production data plane (ROADMAP item 4, the
tf.data-service / Grain pattern): decoding and augmentation move out of
the trainer process into a worker-pool service that serves pre-decoded,
pre-collated, fixed-shape batches over local sockets — so several
consumers (a trainer and its eval pass, two trainers, a high-RPS eval
fleet) share ONE pipeline instead of each burning host cores on a
private copy, and the trainer's step loop never pays decode on its
critical path.

Topology::

    worker procs (spawn; disjoint dataset slices; decode+augment)
        -> sample queue -> pump thread (global shuffle buffer, collate,
           encode) -> bounded batch queue
        -> accept thread -> per-client handler threads (frame I/O)

    DataServiceClient(addr).batches(n)  # any number of clients

Wire format: the record container's framing over a TCP stream —
``uint64 len | crc32c(len) | payload | crc32c(payload)`` (records.py's
masked crc) — with payloads encoded by `example_codec`, so the service
speaks the repo's one serialization dialect end to end. A batch frame
carries each array as raw bytes + dtype + shape features.

Epoch semantics are client-side: the service runs a CONTINUOUS stream
(each worker-pool epoch reshuffles shard order and reseeds transforms
from (seed, epoch); the global shuffle buffer carries across the
boundary), and clients impose their own epoch windows by step count
(`client.batches(steps_per_epoch)`) — the tf.data-service `repeat()`
contract that keeps N consumers from needing a distributed epoch
barrier. Batches are always exactly `batch_size` rows (drop-remainder
at the stream tail), so every consumer compiles once.

Resilience contracts (all CPU-testable, `make data-smoke`):

* worker death: a SIGKILLed/OOM-killed worker is detected by the pump's
  watchdog, journaled as a typed `data_worker_lost` event, and respawned
  over its slice with the already-delivered prefix skipped
  (`data_worker_recovered`) — the serve/pool.py `replica_lost` shape at
  the data plane. A spent restart budget fails the service loudly.
* client reconnect: a dropped connection (server restart, injected
  `data.service` io_error at the frame boundary) is absorbed by the
  client's `resilience.RetryPolicy` — reconnect, re-request, counted in
  `data_service_reconnects_total`. Requests are idempotent pops of a
  shared stream, so a retried `get` never duplicates a batch unless the
  failure hit AFTER the server popped it (at-most-once delivery per
  frame; a lost in-flight batch costs one batch of data, never a hang).
* `resilience.faults` point `data.service` (io_error/crash) fires at
  both frame boundaries and in the worker body (env-inherited), making
  every path above deterministically injectable.

Per-host sharding: `shard_for_host(host_id, num_hosts)` is the
assignment rule multi-host training feeds (`multihost.host_shard` →
one service per host over its disjoint shard slice); with a file list
it returns the actual slice. Disjointness and coverage are tested.

Metrics (the host-pipeline gauges re-homed at the service boundary):
`data_service_batches_total{role=}`, `data_service_starved_total`,
`data_service_reconnects_total`, `data_service_queue_depth`.

jax-free, like the rest of data/: the service host needs no accelerator.
"""
from __future__ import annotations

import os
import queue
import socket
import struct
import sys
import threading
import time
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from deep_vision_tpu.data.example_codec import decode_example, encode_example
from deep_vision_tpu.data.pipeline import _buffer_shuffle, collate, worker_put
from deep_vision_tpu.data.records import _masked_crc
from deep_vision_tpu.obs import locksmith, propagate
from deep_vision_tpu.resilience import RetryPolicy, faults


class DataServiceError(RuntimeError):
    """Terminal service failure surfaced to a client (worker restart
    budget spent, server-side pipeline error)."""


# -- framing (records.py's container framing, over a stream socket) ----------

def send_frame(sock: socket.socket, payload: bytes) -> None:
    """One length-prefixed crc-checked frame; the `data.service` fault
    point fires here (io_error = dropped connection mid-protocol)."""
    faults.fire("data.service")
    header = struct.pack("<Q", len(payload))
    sock.sendall(header + struct.pack("<I", _masked_crc(header))
                 + payload + struct.pack("<I", _masked_crc(payload)))


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None  # peer closed
        buf += chunk
    return buf


def recv_frame(sock: socket.socket) -> Optional[bytes]:
    """Read one frame; None on clean EOF, IOError on corruption (a torn
    stream must not be decoded as a batch)."""
    header = _recv_exact(sock, 8)
    if header is None:
        return None
    rest = _recv_exact(sock, 4)
    if rest is None:
        raise IOError("data.service: stream died inside a frame header")
    (length,) = struct.unpack("<Q", header)
    (hcrc,) = struct.unpack("<I", rest)
    if _masked_crc(header) != hcrc:
        raise IOError("data.service: corrupt frame header")
    payload = _recv_exact(sock, length)
    tail = _recv_exact(sock, 4) if payload is not None else None
    if payload is None or tail is None:
        raise IOError("data.service: stream died inside a frame")
    if _masked_crc(payload) != struct.unpack("<I", tail)[0]:
        raise IOError("data.service: corrupt frame payload")
    faults.fire("data.service")
    return payload


# -- batch <-> Example encoding ----------------------------------------------

def encode_batch(batch: dict) -> bytes:
    """Collated numpy batch dict -> one Example payload: per key, the
    array's raw bytes + dtype + shape (the pre-decoded, pre-collated
    shape a consumer device_puts without touching a decoder)."""
    feats: dict = {"__kind__": [b"batch"]}
    for k in sorted(batch):
        v = np.ascontiguousarray(np.asarray(batch[k]))
        feats[f"t/{k}/data"] = [v.tobytes()]
        feats[f"t/{k}/dtype"] = [str(v.dtype).encode()]
        feats[f"t/{k}/shape"] = [int(d) for d in v.shape]
    return encode_example(feats)


def decode_batch(payload: bytes) -> dict:
    feats = decode_example(payload)
    kind = feats.get("__kind__", [b""])[0]
    if kind == b"err":
        raise DataServiceError(feats.get("error", [b"?"])[0].decode())
    if kind != b"batch":
        raise IOError(f"data.service: unexpected frame kind {kind!r}")
    out = {}
    for key, vals in feats.items():
        if not key.startswith("t/") or not key.endswith("/data"):
            continue
        name = key[2:-5]
        dtype = np.dtype(feats[f"t/{name}/dtype"][0].decode())
        shape = tuple(int(d) for d in feats[f"t/{name}/shape"])
        out[name] = np.frombuffer(vals[0], dtype).reshape(shape)
    return out


def _control(kind: str, **fields) -> bytes:
    feats = {"__kind__": [kind.encode()]}
    for k, v in fields.items():
        feats[k] = [v.encode() if isinstance(v, str) else v]
    return encode_example(feats)


# -- per-host shard assignment -----------------------------------------------

def shard_for_host(host_id: int, num_hosts: int,
                   files: Optional[Sequence[str]] = None):
    """Deterministic, disjoint, covering shard assignment per host.

    Without `files`, returns the (shard_index, num_shards) pair that
    `RecordDataset`/`record_iterator` consume — the value
    `multihost.host_shard()` produces, validated. With `files`, returns
    the host's round-robin slice of the list. Every shard lands on
    exactly one host (tests/test_data_service.py proves disjointness +
    coverage), which is what keeps a multi-host epoch from double-
    visiting data.

    Elastic worlds re-call this per generation: after an N→M resize the
    surviving hosts pass their NEW (rank, world_size) from
    `multihost.host_shard()` and the assignment re-derives — disjoint
    and covering at every world size (tests/test_rendezvous.py proves
    the property across arbitrary N→M), journaled by the trainer as a
    typed `data_reshard` event. No state carries over: the slice is a
    pure function of the generation, which is what makes the reshard
    safe to recompute.
    """
    if num_hosts < 1:
        raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
    if not 0 <= host_id < num_hosts:
        raise ValueError(
            f"host_id {host_id} outside [0, num_hosts={num_hosts})")
    if files is None:
        return host_id, num_hosts
    return list(files)[host_id::num_hosts]


# -- worker body ---------------------------------------------------------------

def _service_worker(dataset, transform, seed, wid, out_q, stop_evt,
                    skip: int = 0, respawn: bool = False,
                    start_epoch: int = 0):
    """Spawned PERSISTENT worker: decode+augment its dataset slice epoch
    after epoch in one process (every per-epoch random decision derives
    from (seed + epoch, wid), shard order via set_epoch), shipping
    `(wid, sample)` tuples and an `("__epoch__", wid)` marker at each
    epoch boundary. Persistence is the point: a pool respawned per
    epoch stalls the stream for a full python startup every pass over
    the data — workers here only ever restart on death.

    The `data.service` fault point fires per sample (env-inherited, so
    an injected crash kills a real worker process exactly the way OOM
    does). Respawned workers do NOT fire it: a replacement re-inherits
    the same spec, and an @N crash rule would re-kill every respawn
    forever — a permanently poisoned slot models nothing real. One
    injected crash = one worker death; injectable RESPAWN failure is
    the serve.replica point's territory."""
    import numpy as np

    def put(item) -> bool:
        return worker_put(out_q, stop_evt, item)

    epoch = start_epoch
    try:
        while not stop_evt.is_set():
            if hasattr(dataset, "set_epoch"):
                dataset.set_epoch(epoch)
            rng = np.random.default_rng((seed + epoch, wid))
            produced = 0
            for k, sample in enumerate(dataset):
                if stop_evt.is_set():
                    return
                if k < skip:
                    continue  # already delivered by the life this
                    #           worker replaces (parent-counted)
                if not respawn:
                    faults.fire("data.service")
                if transform is not None:
                    sample = transform(sample, rng)
                if not put((wid, sample)):
                    return
                produced += 1
            skip = 0
            if not put(("__epoch__", wid)):
                return
            epoch += 1
            if produced == 0:
                # an empty slice (datasets the clamp above cannot size)
                # must not hot-loop epoch markers at full CPU
                time.sleep(0.5)
    except BaseException as e:  # noqa: BLE001 - surfaced in the parent
        put(("__error__", repr(e)))


# -- the service ---------------------------------------------------------------

class DataService:
    """One shared input pipeline serving collated batches over sockets.

    dataset must expose `.split(i, n)` (the DataLoader num_procs
    contract: RecordDataset does) and be picklable along with
    `transform`. `port=0` binds an ephemeral port — read `.address`
    after `start()`.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        transform: Optional[Callable] = None,
        num_workers: int = 2,
        shuffle: bool = True,
        shuffle_buffer: int = 512,
        seed: int = 0,
        queue_depth: int = 16,
        host: str = "127.0.0.1",
        port: int = 0,
        name: str = "default",
        journal=None,
        registry=None,
        worker_restarts: int = 2,
        worker_poll_s: float = 5.0,
        collate_fn: Callable = collate,
    ):
        if not hasattr(dataset, "split"):
            raise TypeError(
                f"DataService needs a dataset with .split(i, n); "
                f"{type(dataset).__name__} has none")
        self.dataset = dataset
        self.batch_size = batch_size
        self.transform = transform
        self.num_workers = max(1, num_workers)
        files = getattr(dataset, "files", None)
        if files is not None and not files:
            # an empty per-host slice would clamp to zero workers and
            # start a service that can never serve — clients would hang
            # to a misleading retry timeout instead of reading this
            raise ValueError(
                "dataset has no shards for this service (empty per-host "
                "slice? fewer shards than num_hosts)")
        if files is not None and self.num_workers > len(files):
            # more workers than shards hands the surplus EMPTY slices:
            # each would hot-loop epoch markers at full CPU forever
            print(f"data_service: clamping num_workers "
                  f"{self.num_workers} -> {len(files)} (one shard "
                  f"minimum per worker)", file=sys.stderr)
            self.num_workers = len(files)
        self.shuffle = shuffle
        self.shuffle_buffer = shuffle_buffer
        self.seed = seed
        self.name = name
        self.journal = journal
        self.worker_restarts = worker_restarts
        self.worker_poll_s = worker_poll_s
        self.collate_fn = collate_fn
        self._host, self._port = host, port
        self._stop = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._batches: "queue.Queue[bytes]" = queue.Queue(maxsize=queue_depth)
        self._threads: List[threading.Thread] = []
        self._handlers: List[threading.Thread] = []  # accept-loop only
        # shared across pump/handler/accept threads; one lock, held only
        # for counter math — journal writes always happen OUTSIDE it
        self._lock = locksmith.lock("data.service")
        self._served = 0
        self._produced = 0
        self._lost = 0
        self._recovered = 0
        self._clients: List[socket.socket] = []
        self._failed: Optional[str] = None
        if registry is None:
            from deep_vision_tpu.obs.registry import get_registry

            registry = get_registry()
        labels = {"service": name}
        self._c_batches = registry.counter(
            "data_service_batches_total",
            "batches served to clients", labels=dict(labels, role="server"))
        self._c_starved = registry.counter(
            "data_service_starved_total",
            "client gets that found the batch queue empty", labels=labels)
        self._g_depth = registry.gauge(
            "data_service_queue_depth",
            "encoded batches ready when a client asked", labels=labels)

    # -- lifecycle ---------------------------------------------------------

    @property
    def address(self) -> str:
        return f"{self._host}:{self._port}"

    def start(self) -> "DataService":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self._host, self._port))
        self._port = self._sock.getsockname()[1]
        self._sock.listen(32)
        self._sock.settimeout(0.25)  # accept loop stays stop-responsive
        for target, tname in ((self._pump_loop, "data-service-pump"),
                              (self._accept_loop, "data-service-accept")):
            t = threading.Thread(target=target, name=tname, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def close(self) -> None:
        """Stop workers + threads, close sockets, journal the summary."""
        if self._stop.is_set():
            return
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._lock:
            clients = list(self._clients)
        for c in clients:
            try:
                c.close()
            except OSError:
                pass
        with self._lock:
            handlers = list(self._handlers)
        for t in self._threads + handlers:
            t.join(timeout=10)
        with self._lock:
            served, produced = self._served, self._produced
            lost, recovered = self._lost, self._recovered
        if self.journal is not None:
            # produced - served = batches buffered but never consumed
            # (the residue a drain leaves behind)
            self.journal.write(
                "data_service", role="server", service=self.name,
                batches=int(served), produced=int(produced),
                workers=int(self.num_workers),
                workers_lost=int(lost), workers_recovered=int(recovered))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # -- producer side -----------------------------------------------------

    def _journal(self, event: str, **fields) -> None:
        if self.journal is not None:
            try:
                self.journal.write(event, **fields)
            except Exception:
                pass  # telemetry must never kill the pipeline it observes

    def _worker_stream(self) -> Iterator[dict]:
        """The continuous merged sample stream off the persistent worker
        pool: spawn once, supervise, respawn on death.

        A dead worker is `data_worker_lost{worker, attempt, error}` then
        (within budget) `data_worker_recovered{worker, attempt}` after
        the respawn over the same slice at its current epoch with the
        delivered prefix skipped — the serve/pool.py replica shape at
        the data plane.

        Each worker LIFE owns a private mp.Queue. A shared queue is a
        trap here: a SIGKILLed writer dies holding the queue's shared
        write lock, and every surviving/respawned worker then blocks on
        it forever — the whole service starves off one death (observed,
        not hypothetical). With one single-writer queue per life, a
        death poisons only its own queue, which is simply abandoned
        unread: samples left in it were never counted in `delivered`,
        so the replacement (started with skip=delivered) re-produces
        exactly those — the consumer stream sees no loss and no
        duplicates."""
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        stop = ctx.Event()
        n = self.num_workers
        shards: list = []
        procs: list = [None] * n
        queues: list = [None] * n

        def spawn(wid: int, skip: int = 0, respawn: bool = False,
                  start_epoch: int = 0):
            q: "mp.Queue" = ctx.Queue(maxsize=64)
            saved = os.environ.get("JAX_PLATFORMS")
            os.environ["JAX_PLATFORMS"] = "cpu"  # workers never touch a chip
            try:
                p = ctx.Process(
                    target=_service_worker,
                    args=(shards[wid], self.transform, self.seed, wid,
                          q, stop, skip, respawn, start_epoch),
                    daemon=True,
                )
                p.start()
                return p, q
            finally:
                if saved is None:
                    os.environ.pop("JAX_PLATFORMS", None)
                else:
                    os.environ["JAX_PLATFORMS"] = saved

        try:
            for i in range(n):
                shards.append(self.dataset.split(i, n))
                procs[i], queues[i] = spawn(i)
            epochs = [0] * n      # each worker's current epoch
            delivered = [0] * n   # samples merged from its CURRENT epoch
            restarts = [0] * n
            last_check = time.monotonic()
            while not self._stop.is_set():
                got_any = False
                for i in range(n):
                    # bounded drain burst per worker so one fast worker
                    # cannot starve the others' queues of service
                    for _ in range(64):
                        try:
                            item = queues[i].get_nowait()
                        except (queue.Empty, EOFError, OSError):
                            break
                        got_any = True
                        if isinstance(item, tuple) and len(item) == 2 \
                                and item[0] == "__error__":
                            raise DataServiceError(
                                f"data service worker failed: {item[1]}")
                        if isinstance(item, tuple) and len(item) == 2 \
                                and item[0] == "__epoch__":
                            epochs[i] += 1
                            delivered[i] = 0
                            continue
                        delivered[i] += 1
                        yield item[1]
                now = time.monotonic()
                if now - last_check < self.worker_poll_s:
                    if not got_any:
                        time.sleep(0.05)
                    continue
                # liveness runs on the poll cadence even while OTHER
                # workers keep producing: a dead worker next to a healthy
                # one would otherwise never be detected (every sweep
                # would short-circuit on got_any) and its shard slice
                # would silently vanish from the stream
                last_check = now
                for wid in [i for i in range(n)
                            if not procs[i].is_alive()]:
                    restarts[wid] += 1
                    with self._lock:
                        self._lost += 1
                    self._journal(
                        "data_worker_lost", worker=int(wid),
                        attempt=int(restarts[wid]),
                        error="worker process died (OOM-killed or "
                              "crashed)",
                        service=self.name)
                    if restarts[wid] > self.worker_restarts:
                        raise DataServiceError(
                            f"data service worker {wid} died "
                            f"{restarts[wid]}x; restart budget "
                            f"({self.worker_restarts}) spent")
                    # fresh queue, dead one abandoned (see docstring)
                    procs[wid], queues[wid] = spawn(
                        wid, skip=delivered[wid], respawn=True,
                        start_epoch=epochs[wid])
                    with self._lock:
                        self._recovered += 1
                    self._journal(
                        "data_worker_recovered", worker=int(wid),
                        attempt=int(restarts[wid]), service=self.name)
        finally:
            stop.set()
            # drain the live queues so workers blocked in put() observe
            # the stop (dead workers' queues stay untouched — poisoned
            # locks must not be re-acquired from here)
            for i, q in enumerate(queues):
                if procs[i] is not None and procs[i].is_alive():
                    try:
                        while True:
                            q.get_nowait()
                    except (queue.Empty, EOFError, OSError):
                        pass
            for p in procs:
                if p is None:
                    continue
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()

    def _pump_loop(self) -> None:
        """samples -> global shuffle -> collate -> encode -> batch queue."""
        try:
            samples: Iterator[dict] = self._worker_stream()
            if self.shuffle:
                samples = _buffer_shuffle(
                    samples, self.shuffle_buffer,
                    np.random.default_rng(self.seed))
            buf: List[dict] = []
            for s in samples:
                if self._stop.is_set():
                    return
                buf.append(s)
                if len(buf) < self.batch_size:
                    continue
                payload = encode_batch(self.collate_fn(buf))
                buf = []
                while not self._stop.is_set():
                    try:
                        self._batches.put(payload, timeout=0.25)
                        with self._lock:
                            self._produced += 1
                        break
                    except queue.Full:
                        continue
            # stream tail (< batch_size rows): dropped — every served
            # batch keeps the one compiled shape
        except BaseException as e:  # noqa: BLE001 - latched for clients
            with self._lock:
                self._failed = f"{type(e).__name__}: {e}"
            self._journal("note", note="data_service pump failed",
                          error=self._failed, service=self.name)

    # -- consumer side -----------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed by close()
            with self._lock:
                self._clients.append(conn)
            t = threading.Thread(target=self._serve_client, args=(conn,),
                                 name="data-service-client", daemon=True)
            t.start()
            # handlers are tracked separately from the pump/accept threads
            # and pruned as they finish: a reconnect-heavy client churns
            # one handler per connection, and an ever-growing list would
            # leak for the service's lifetime
            with self._lock:
                self._handlers.append(t)
                self._handlers = [h for h in self._handlers
                                  if h.is_alive()]

    def _serve_client(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    req = recv_frame(conn)
                except (OSError, IOError):
                    return  # client died mid-request; it will reconnect
                if req is None:
                    return  # clean client close
                feats = decode_example(req)
                kind = feats.get("__kind__", [b""])[0]
                # a traced get carries the client hop's context over the
                # wire; this hop becomes its child. Untraced gets (the
                # steady-state training stream) carry nothing and journal
                # nothing per-request — tracing is sampled at ingress,
                # not paid on every batch
                remote = propagate.from_traceparent(
                    feats.get("traceparent", [b""])[0])
                ctx = remote.child() if remote is not None else None
                if kind == b"stats":
                    with self._lock:
                        served = self._served
                    send_frame(conn, _control(
                        "stats", served=[served],
                        depth=[self._batches.qsize()]))
                    continue
                if kind != b"get":
                    send_frame(conn, _control(
                        "err", error=f"unknown command {kind!r}"))
                    continue
                payload = self._pop_batch()
                if payload is None:
                    with self._lock:
                        failed = self._failed
                    send_frame(conn, _control(
                        "err", error=failed or "service stopping"))
                    return
                send_frame(conn, payload)
                self._c_batches.inc()
                with self._lock:
                    self._served += 1
                if ctx is not None and self.journal is not None:
                    self.journal.write(
                        "data_service", role="server", service=self.name,
                        batches=1, op="get", **ctx.fields())
        except (OSError, IOError):
            # a frame-boundary failure (incl. the injected io_error) is
            # request-scoped: THIS connection dies, the client reconnects,
            # every other client keeps streaming
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._clients:
                    self._clients.remove(conn)

    def _pop_batch(self) -> Optional[bytes]:
        depth = self._batches.qsize()
        self._g_depth.set(depth)
        if depth == 0:
            self._c_starved.inc()  # consumer out-ran the pipeline
        while not self._stop.is_set():
            with self._lock:
                if self._failed:
                    return None
            try:
                return self._batches.get(timeout=0.25)
            except queue.Empty:
                continue
        return None

    # -- live plane (obs/telemetry.py sources) -----------------------------

    def healthz(self):
        """Telemetry health source: serving iff not stopped and the
        pump has not latched a terminal failure."""
        with self._lock:
            failed = self._failed
        ok = not self._stop.is_set() and not failed
        detail = {"service": self.name, "stopped": self._stop.is_set(),
                  "workers": int(self.num_workers)}
        if failed:
            detail["failed"] = failed
        return ok, detail

    def telemetry_status(self) -> dict:
        """Telemetry status source: the serving ledger for /statusz."""
        with self._lock:
            out = {"service": self.name, "served": int(self._served),
                   "produced": int(self._produced),
                   "workers": int(self.num_workers),
                   "workers_lost": int(self._lost),
                   "workers_recovered": int(self._recovered),
                   "clients": len(self._clients),
                   "failed": self._failed}
        out["queue_depth"] = self._batches.qsize()
        return out


# -- the client ----------------------------------------------------------------

class DataServiceClient:
    """Iterable consumer of a DataService: `batches(n)` yields n decoded
    batch dicts, reconnecting through a `resilience.RetryPolicy` when the
    connection drops (server restart, injected `data.service` fault)."""

    def __init__(self, address: str, name: str = "client",
                 journal=None, registry=None,
                 retry: Optional[RetryPolicy] = None,
                 timeout_s: float = 60.0):
        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.name = name
        self.journal = journal
        self.timeout_s = timeout_s
        self._retry = retry or RetryPolicy(
            name="data.service", max_attempts=5, base_delay_s=0.05,
            max_delay_s=1.0, journal=journal)
        self._sock: Optional[socket.socket] = None
        self.batches_received = 0
        self.reconnects = 0
        if registry is None:
            from deep_vision_tpu.obs.registry import get_registry

            registry = get_registry()
        labels = {"service": name}
        self._c_batches = registry.counter(
            "data_service_batches_total", "batches served to clients",
            labels=dict(labels, role="client"))
        self._c_reconnects = registry.counter(
            "data_service_reconnects_total",
            "client reconnects after a dropped service connection",
            labels=labels)

    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(
                self._addr, timeout=self.timeout_s)
        return self._sock

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def get(self) -> dict:
        """One batch; reconnects under the retry policy. DataServiceError
        (a server-side terminal failure) is NOT retried — the service
        itself said it cannot continue."""
        # batch ingress: a caller that installed a trace context
        # (propagate.use at the real ingress — a traced request, a smoke)
        # gets this fetch recorded as its child hop and propagated to the
        # service over the frame protocol; the steady-state stream stays
        # untraced and pays nothing
        parent = propagate.current()
        ctx = parent.child() if parent is not None else None
        frame = (_control("get", traceparent=ctx.to_traceparent())
                 if ctx is not None else _control("get"))
        out: List[dict] = []
        tries = 0
        for attempt in self._retry.attempts():
            with attempt:
                tries += 1
                if tries > 1:
                    # the previous attempt dropped the connection: this
                    # one is a reconnect, the metric the smoke asserts
                    self.reconnects += 1
                    self._c_reconnects.inc()
                sock = self._connect()
                try:
                    send_frame(sock, frame)
                    payload = recv_frame(sock)
                except (OSError, IOError) as e:
                    self._drop()
                    raise OSError(f"data.service connection lost: {e}")
                if payload is None:
                    self._drop()
                    raise OSError("data.service closed the connection")
                out.append(decode_batch(payload))  # DataServiceError: no retry
        if not out:
            raise OSError("data.service retry loop yielded no batch")
        self.batches_received += 1
        self._c_batches.inc()
        if ctx is not None and self.journal is not None:
            self.journal.write("data_service", role="client",
                               service=self.name, batches=1, op="get",
                               reconnects=int(tries - 1), **ctx.fields())
        return out[0]

    def batches(self, n: int) -> Iterator[dict]:
        """A client-side epoch: exactly n fixed-shape batches."""
        for _ in range(n):
            yield self.get()

    def close(self) -> None:
        """Idempotent: registered as a journal closer AND called on the
        clean path — the summary event must land exactly once."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        self._drop()
        if self.journal is not None:
            self.journal.write(
                "data_service", role="client", service=self.name,
                batches=int(self.batches_received),
                reconnects=int(self.reconnects))
