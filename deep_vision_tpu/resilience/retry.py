"""Shared retry policy: exponential backoff + jitter, deadline, typed events.

Production checkpoint/data systems treat storage and transport as
unreliable by design (Check-N-Run, NSDI '22; Varuna, EuroSys '22); until
this module the repo's only retry logic was a bespoke loop inside
bench.py (grown after BENCH_r02 lost its perf number to ONE transient
tunnel error). `RetryPolicy` is the one implementation every I/O
boundary shares — bench's rebuild-replay loop, the checkpoint sidecar
writer, and shard opens in the tolerant record reader all consult it —
so backoff behavior, exception classification, and the `retry` journal
event schema cannot drift between callers.

Three usage shapes:

    policy = RetryPolicy(name="ckpt.sidecar", max_attempts=4)

    # 1. driver: call through the policy
    policy.call(write_file, path, data)

    # 2. decorator
    @policy
    def write_file(path, data): ...

    # 3. attempt loop (tenacity-style), for bodies that need local state
    for attempt in policy.attempts():
        with attempt:
            write_file(path, data)

Every failed-then-retried attempt emits a typed `retry` journal event
(when a journal is attached) and bumps `retry_attempts_total{policy=}`;
a giveup bumps `retry_giveups_total{policy=}` and re-raises the last
exception unchanged (callers keep their existing except clauses).
Jitter is drawn from a policy-owned seeded RNG so tests are
deterministic; pass `jitter=0` to disable entirely.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type, Union

_RetryOn = Union[Type[BaseException], Tuple[Type[BaseException], ...]]

#: the default classification: transient-looking I/O and transport errors.
#: RuntimeError is NOT here — jax wraps both transient tunnel failures and
#: genuine program bugs in it; callers that know better (bench) pass
#: retry_on=Exception explicitly.
DEFAULT_RETRY_ON: Tuple[Type[BaseException], ...] = (
    OSError,  # includes IOError, ConnectionError, TimeoutError(OSError)
    TimeoutError,
)


class RetryPolicy:
    """Backoff schedule + retryable-exception classification + budget.

    name:          labels journal events and metrics counters.
    max_attempts:  total tries including the first (<=0 means "no retries").
    base_delay_s / multiplier / max_delay_s: exponential backoff envelope
                   (delay before retry k is base * multiplier**(k-1), capped).
    jitter:        +-fraction applied to each delay (0.5 -> 50%-150%).
    deadline_s:    wall budget for one call()/attempts() session; when the
                   NEXT delay would cross it, give up instead of sleeping.
    retry_on:      exception class(es) considered transient.
    retry_if:      optional predicate(exc) -> bool consulted when the class
                   check fails (e.g. match "UNAVAILABLE" in the message).
    journal:       obs.RunJournal (or None) for typed `retry` events.
    registry:      obs Registry; defaults to the process-wide one, lazily.
    sleep/clock:   injectable for tests.
    """

    def __init__(
        self,
        name: str = "default",
        max_attempts: int = 5,
        base_delay_s: float = 0.5,
        multiplier: float = 2.0,
        max_delay_s: float = 30.0,
        jitter: float = 0.5,
        deadline_s: Optional[float] = None,
        retry_on: _RetryOn = DEFAULT_RETRY_ON,
        retry_if: Optional[Callable[[BaseException], bool]] = None,
        journal=None,
        registry=None,
        seed: int = 0,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.name = name
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.multiplier = float(multiplier)
        self.max_delay_s = float(max_delay_s)
        self.jitter = float(jitter)
        self.deadline_s = deadline_s
        self.retry_on = retry_on
        self.retry_if = retry_if
        self.journal = journal
        self._registry = registry
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._clock = clock

    # -- classification / schedule (pure; shared by all three shapes) -------

    def classify(self, exc: BaseException) -> bool:
        """Is this exception retryable under the policy?"""
        if isinstance(exc, (KeyboardInterrupt, SystemExit)):
            return False  # never eat an operator interrupt or a crash fault
        if isinstance(exc, self.retry_on):
            return True
        return bool(self.retry_if is not None and self.retry_if(exc))

    def delay(self, attempt: int) -> float:
        """Backoff before retry number `attempt` (1-based), jittered."""
        d = self.base_delay_s * self.multiplier ** max(0, attempt - 1)
        d = min(d, self.max_delay_s)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, d)

    def should_retry(self, attempt: int, exc: BaseException) -> bool:
        """Budget + classification in one check: `attempt` failures so far."""
        return attempt < self.max_attempts and self.classify(exc)

    def backoff(self, attempt: int) -> float:
        """Sleep the schedule's delay for retry `attempt`; returns it."""
        d = self.delay(attempt)
        if d > 0:
            self._sleep(d)
        return d

    # -- event plumbing ------------------------------------------------------

    def _counter(self, which: str):
        reg = self._registry
        if reg is None:
            from deep_vision_tpu.obs.registry import get_registry

            reg = get_registry()
        return reg.counter(f"retry_{which}_total",
                           f"RetryPolicy {which}", labels={"policy": self.name})

    def note(self, attempt: int, exc: BaseException, outcome: str,
             delay_s: float = 0.0) -> None:
        """Emit one typed `retry` journal event + the matching counter.

        outcome: 'retrying' (will try again), 'gave_up' (budget/classifier
        stopped it), 'recovered' (a later attempt succeeded).
        """
        which = {"retrying": "attempts", "gave_up": "giveups",
                 "recovered": "recoveries"}[outcome]
        try:
            self._counter(which).inc()
        except Exception:
            pass  # metrics must never turn a retry into a crash
        if self.journal is not None:
            self.journal.write(
                "retry", name=self.name, attempt=int(attempt),
                error=f"{type(exc).__name__}: {exc}"[:500],
                outcome=outcome, delay_s=round(float(delay_s), 3),
            )

    # -- drivers -------------------------------------------------------------

    def call(self, fn: Callable, *args, **kwargs):
        """Run fn(*args, **kwargs) under the policy; the terminal exception
        (non-retryable, or budget/deadline exhausted) re-raises unchanged."""
        start = self._clock()
        attempt = 0
        while True:
            try:
                result = fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 - classified below
                attempt += 1
                if not self.should_retry(attempt, e):
                    self.note(attempt, e, "gave_up")
                    raise
                d = self.delay(attempt)
                if (self.deadline_s is not None
                        and self._clock() - start + d > self.deadline_s):
                    self.note(attempt, e, "gave_up")
                    raise
                self.note(attempt, e, "retrying", delay_s=d)
                if d > 0:
                    self._sleep(d)
                continue
            if attempt:
                self.note(attempt, _Recovered(), "recovered")
            return result

    def __call__(self, fn: Callable) -> Callable:
        """Decorator form: `@policy` wraps fn in call()."""
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        wrapped.retry_policy = self
        return wrapped

    def attempts(self) -> Iterator["_Attempt"]:
        """Attempt-loop form: yields context managers until one succeeds.

        The with-block's exception is swallowed while the policy admits a
        retry, re-raised otherwise; a block that exits cleanly ends the loop.
        """
        start = self._clock()
        attempt = 0
        while True:
            a = _Attempt()
            yield a
            if a.succeeded:
                if attempt:
                    self.note(attempt, _Recovered(), "recovered")
                return
            exc = a.exc
            attempt += 1
            if not self.should_retry(attempt, exc):
                self.note(attempt, exc, "gave_up")
                raise exc
            d = self.delay(attempt)
            if (self.deadline_s is not None
                    and self._clock() - start + d > self.deadline_s):
                self.note(attempt, exc, "gave_up")
                raise exc
            self.note(attempt, exc, "retrying", delay_s=d)
            if d > 0:
                self._sleep(d)


class _Recovered(Exception):
    """Placeholder 'exception' for the recovered event (no live error)."""

    def __str__(self):
        return "recovered"


class _Attempt:
    """One try of an attempts() loop; captures the body's exception."""

    def __init__(self):
        self.exc: Optional[BaseException] = None
        self.succeeded = False

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.succeeded = True
            return False
        self.exc = exc
        return True  # swallowed; attempts() decides whether to re-raise
