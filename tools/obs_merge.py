"""Merge per-host run journals into one timeline with straggler detection.

    PYTHONPATH=. python tools/obs_merge.py run.jsonl.p0 run.jsonl.p1 ...
    PYTHONPATH=. python tools/obs_merge.py --auto run.jsonl   # glob .p*
        [-o merged.jsonl] [--gap-ms 25] [--rel 0.5]

The CLI over obs/merge.py: a multi-host run writes one journal per
process (`<path>.pN`); this stitches them into ONE chronological JSONL
(every event annotated with `host`) and synthesizes typed `straggler`
events wherever a step's max−median cross-host step-time gap exceeds
the thresholds — the signal a fragmenting host hides inside the lockstep
collective. Render the output with `tools/obs_report.py --merged`. The
merge is schema-valid under `tools/check_journal.py`; note that
`--strict` additionally demands a clean terminal `exit`, so a merged
postmortem of a crashed run flags there by design.

Exit status 0 = merged; 2 = no usable events; 64 = usage error.
"""
from __future__ import annotations

import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deep_vision_tpu.cli import (  # noqa: E402
    EXIT_INVALID,
    EXIT_OK,
    UsageErrorParser,
)
from deep_vision_tpu.obs.merge import merge_journal_files  # noqa: E402


def main(argv=None) -> int:
    p = UsageErrorParser(description=__doc__.splitlines()[0])
    p.add_argument("journals", nargs="+",
                   help="per-host journal files (or, with --auto, the "
                        "base path whose .p* siblings are globbed)")
    p.add_argument("--auto", action="store_true",
                   help="treat each positional as a base path and expand "
                        "<path>.p* (what a multi-host run wrote)")
    p.add_argument("-o", "--out", default=None, metavar="PATH",
                   help="merged JSONL path (default: <first base>.merged)")
    p.add_argument("--gap-ms", type=float, default=25.0,
                   help="absolute straggler floor: flag a step only when "
                        "max-median exceeds this many ms (default 25)")
    p.add_argument("--rel", type=float, default=0.5,
                   help="relative straggler floor: ... and exceeds this "
                        "fraction of the median (default 0.5)")
    args = p.parse_args(argv)

    if args.auto:
        paths = []
        for base in args.journals:
            hits = sorted(glob.glob(base + ".p*"))
            if not hits and os.path.exists(base):
                hits = [base]  # single-process run: pass it through
            paths.extend(hits)
        out_default = args.journals[0] + ".merged"
    else:
        paths = list(args.journals)
        out_default = paths[0] + ".merged"
    if not paths:
        print("no journal files found", file=sys.stderr)
        return EXIT_INVALID

    out = args.out or out_default
    summary = merge_journal_files(paths, out, gap_ms=args.gap_ms,
                                  rel=args.rel)
    if not summary["events"]:
        print("no events found in " + ", ".join(paths), file=sys.stderr)
        return EXIT_INVALID
    stragglers = summary["stragglers"]
    print(f"merged {len(paths)} journal(s), hosts {summary['hosts']}, "
          f"{summary['events']} events -> {out}")
    if stragglers:
        worst = max(stragglers, key=lambda s: s["gap_ms"])
        print(f"stragglers: {len(stragglers)} step(s) flagged; worst gap "
              f"{worst['gap_ms']:.1f} ms at step {worst['step']} "
              f"(host {worst['host']})")
    else:
        print("stragglers: none detected")
    print("render: python tools/obs_report.py --merged " + out)
    return EXIT_OK


if __name__ == "__main__":
    raise SystemExit(main())
