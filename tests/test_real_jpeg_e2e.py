"""Real-photograph end-to-end: the full data path on actual camera JPEGs.

Every other data test synthesizes its images; this one drives the seam the
reference exercises with real files (`ResNet/pytorch/data_load.py:53-54`
cv2-decodes dataset JPEGs; the demo notebooks classify real photos):
converter -> record shards -> Example codec -> DataLoader (decode +
augment + batch) -> one jitted train step -> the inference CLI, all on the
three license-clean photographs in tests/fixtures/real_photos/.

Fast tier: the train step uses the slim BottleneckBlock ResNet (the
dryrun flagship) on 64px crops, so the whole chain jits in seconds.
"""
import os
import shutil

import jax
import jax.numpy as jnp
import numpy as np

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "real_photos")
PHOTOS = ("grace_hopper.jpg", "china.jpg", "flower.jpg")
SYNSETS = ("n10000001", "n10000002", "n10000003")


def _flattened_imagenet_dir(tmp_path):
    """Real photos in the converter's flattened nXXXXXXXX_*.JPEG layout."""
    root = tmp_path / "flat"
    os.makedirs(root)
    for synset, photo in zip(SYNSETS, PHOTOS):
        shutil.copy(os.path.join(FIXTURES, photo),
                    root / f"{synset}_{photo.replace('.jpg', '.JPEG')}")
    synsets = tmp_path / "synsets.txt"
    synsets.write_text("".join(s + "\n" for s in SYNSETS))
    return str(root), str(synsets)


def test_real_photos_through_converter_records_loader_and_train_step(tmp_path):
    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.data import Compose, DataLoader, RecordDataset
    from deep_vision_tpu.data import transforms as T
    from deep_vision_tpu.losses.classification import classification_loss_fn
    from deep_vision_tpu.models.resnet import BottleneckBlock, ResNet
    from deep_vision_tpu.tools.converters import (
        build_shards,
        imagenet_annotations,
        imagenet_example,
    )
    from deep_vision_tpu.train.optimizers import build_optimizer

    root, synsets = _flattened_imagenet_dir(tmp_path)
    annos = imagenet_annotations(root, synsets)
    assert len(annos) == 3 and {a["label"] for a in annos} == {1, 2, 3}

    records = tmp_path / "records"
    build_shards(annos, imagenet_example, str(records), "train", num_shards=1)

    ds = RecordDataset(str(records / "*"), "imagenet")
    chain = Compose([
        T.Rescale(72), T.RandomHorizontalFlip(), T.RandomCrop(64),
        T.ToFloatNormalize(expand_gray_to_rgb=True),
    ])
    dl = DataLoader(ds, batch_size=3, transform=chain, shuffle=True,
                    drop_remainder=True)
    batch = next(iter(dl))
    # real JPEG content survived the trip: natural photos have non-trivial
    # per-image variance and three distinct images
    assert batch["image"].shape == (3, 64, 64, 3)
    assert batch["image"].dtype == np.float32
    # the dataset maps the converter's 1-based record labels (0=background)
    # to 0-based model labels
    assert sorted(batch["label"].tolist()) == [0, 1, 2]
    per_image_std = batch["image"].reshape(3, -1).std(axis=1)
    assert (per_image_std > 0.1).all(), per_image_std

    model = ResNet(stage_sizes=(1, 1, 1, 1), block=BottleneckBlock,
                   width=16, num_classes=4)
    tx = build_optimizer("sgd", learning_rate=0.1, momentum=0.9)
    state = create_train_state(model, tx, jnp.ones((2, 64, 64, 3)))

    @jax.jit
    def train_step(state, batch):
        def loss_fn(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            outputs, new_state = state.apply_fn(
                variables, batch["image"], train=True,
                rngs={"dropout": jax.random.PRNGKey(0)},
                mutable=["batch_stats"],
            )
            loss, metrics = classification_loss_fn(outputs, batch)
            return loss, (metrics, new_state["batch_stats"])

        grads, (metrics, bs) = jax.grad(loss_fn, has_aux=True)(state.params)
        return state.apply_gradients(grads).replace(batch_stats=bs), metrics

    state, metrics = train_step(
        state, {k: jnp.asarray(v) for k, v in batch.items()}
    )
    assert np.isfinite(float(metrics["loss"]))


def test_infer_cli_classifies_and_renders_real_photo(tmp_path, capsys):
    """The inference CLI end-to-end on a real photograph: decode, classify
    (fresh-init lenet5 — the render path, not the weights, is under test),
    and write the labeled display copy."""
    from deep_vision_tpu.tools.infer import main

    labels = tmp_path / "names.txt"
    labels.write_text("".join(f"name_{i}\n" for i in range(10)))
    photo = os.path.join(FIXTURES, "grace_hopper.jpg")
    rc = main(["-m", "lenet5", "-o", str(tmp_path / "out"), "--render",
               "--labels", str(labels), photo])
    assert rc == 0
    out = capsys.readouterr().out
    assert "name_" in out
    dst = tmp_path / "out" / "grace_hopper_classified.jpg"
    assert dst.exists()
    # the overlay is a real JPEG that still decodes
    from deep_vision_tpu.data.datasets import decode_image

    with open(dst, "rb") as f:
        img = decode_image(f.read())
    assert img.ndim == 3 and img.shape[2] == 3
