"""Performance attribution plane (obs/costmodel.py + obs/perfwatch.py +
tools/perf_gate.py + tools/trace_digest.py).

XLA cost extraction on a real compiled step, collective-inventory
parsing checked against the gradient-tree size it predicts (the sharded
ViT all-reduce bill), the crc-manifested perf ledger (append, corrupt-
row quarantine, rotation), the noise-aware MAD gate across its verdict
space, step-time decomposition of a real CPU profiler capture, the
obs_report / telemetry renderings with their byte-unchanged gates, and
the schema drift-guards that pin the emitters to check_journal.
"""
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from deep_vision_tpu.obs import costmodel, perfwatch  # noqa: E402
from deep_vision_tpu.obs.journal import RunJournal, read_journal  # noqa: E402
from deep_vision_tpu.obs.registry import Registry  # noqa: E402

from tools.check_journal import (  # noqa: E402
    EVENT_FIELDS,
    PERF_COLLECTIVE_KINDS,
    check_journal,
)
from tools.perf_gate import (  # noqa: E402
    GATE_VERDICTS,
    PerfLedger,
    default_env,
    env_key,
    gate_result,
    mad_gate,
    metric_direction,
)


@pytest.fixture(autouse=True)
def _fresh_perfwatch():
    perfwatch._reset_for_tests()
    yield
    perfwatch._reset_for_tests()


def _compiled_matmul():
    def f(x, w):
        return jnp.tanh(x @ w).sum()

    x = jnp.ones((32, 64), jnp.float32)
    w = jnp.ones((64, 64), jnp.float32)
    return jax.jit(f).lower(x, w).compile()


# ---------------------------------------------------------------- costmodel


def test_cost_summary_real_compiled_step():
    cost = costmodel.cost_summary(_compiled_matmul())
    # 32x64 @ 64x64 is 2*32*64*64 flops before fusion slack
    assert cost["flops"] and cost["flops"] >= 2 * 32 * 64 * 64
    assert cost["bytes_accessed"] and cost["bytes_accessed"] > 0
    assert cost["argument_bytes"] == 32 * 64 * 4 + 64 * 64 * 4


def test_collective_inventory_parses_hlo_forms():
    # one instruction per line, the shape compiled HLO as_text() emits
    hlo = (
        "  %ar = f32[64,128]{1,0} all-reduce(f32[64,128] %p), channel_id=1,"
        " replica_groups=[1,8]<=[8], use_global_device_ids=true\n"
        "  %ag-start = (f32[16]{0}, f32[128]{0}) all-gather-start(f32[16]"
        " %q), replica_groups={{0,1},{2,3}}, dimensions={0}\n"
        "  %ag-done = f32[128]{0} all-gather-done((f32[16], f32[128])"
        " %ag-start)\n"
        "  %rs = bf16[32]{0} reduce-scatter(bf16[256] %r), replica_groups={}\n"
    )
    inv = costmodel.collective_inventory(hlo)
    kinds = sorted(i["kind"] for i in inv)
    # the -done half of an async pair must not double-count
    assert kinds == ["all-gather", "all-reduce", "reduce-scatter"]
    ar = next(i for i in inv if i["kind"] == "all-reduce")
    assert ar["bytes"] == 64 * 128 * 4
    assert ar["group_size"] == 8
    ag = next(i for i in inv if i["kind"] == "all-gather")
    assert ag["group_size"] == 2
    rs = next(i for i in inv if i["kind"] == "reduce-scatter")
    assert rs["bytes"] == 32 * 2  # result shape, bf16
    assert costmodel.predicted_collective_bytes(inv) == sum(
        i["bytes"] for i in inv)
    assert costmodel.predicted_collective_bytes(inv, "all-reduce") \
        == ar["bytes"]


def test_collective_inventory_empty_on_single_device_hlo():
    hlo = costmodel.hlo_text(_compiled_matmul())
    assert hlo  # compiled text must be available on this jax
    assert costmodel.collective_inventory(hlo) == []


def test_sharded_vit_allreduce_matches_grad_tree():
    """The acceptance check: on a pure-DP mesh the grad all-reduce bill
    parsed out of the compiled HLO must equal the gradient tree size
    within 5%."""
    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.losses.classification import classification_loss_fn
    from deep_vision_tpu.models.vit import ViT
    from deep_vision_tpu.parallel.mesh import create_mesh, data_sharding
    from deep_vision_tpu.parallel.shardmap import VIT_RULES
    from deep_vision_tpu.train.optimizers import build_optimizer

    mesh = create_mesh(data=len(jax.devices()), model=1)
    model = ViT(depth=2, dim=16, num_heads=2, patch=8, num_classes=8)
    state = create_train_state(model, build_optimizer("sgd", 0.1),
                               jnp.ones((2, 16, 16, 3), jnp.float32))
    shardings, _ = VIT_RULES.resolve(state, mesh)
    state = jax.device_put(state, shardings)
    batch = {
        "image": jax.device_put(
            np.ones((16, 16, 16, 3), np.float32), data_sharding(mesh, 4)),
        "label": jax.device_put(
            np.zeros((16,), np.int32), data_sharding(mesh, 1)),
    }

    def train_step(state, batch):
        def loss_fn(params):
            logits = state.apply_fn({"params": params}, batch["image"],
                                    train=False)
            loss, _ = classification_loss_fn(logits, batch)
            return loss

        grads = jax.grad(loss_fn)(state.params)
        return state.apply_gradients(grads)

    compiled = jax.jit(train_step).lower(state, batch).compile()
    inv = costmodel.collective_inventory(costmodel.hlo_text(compiled))
    ar = costmodel.predicted_collective_bytes(inv, "all-reduce")
    grad = costmodel.tree_bytes(state.params)
    assert ar > 0
    assert abs(ar - grad) / grad <= 0.05


# ---------------------------------------------------------------- perfwatch


def test_profile_compiled_journals_and_gauges(tmp_path):
    path = str(tmp_path / "j.jsonl")
    reg = Registry()
    with RunJournal(path, kind="test") as j:
        j.manifest()
        prof = perfwatch.profile_compiled("test/matmul", _compiled_matmul(),
                                          journal=j, registry=reg)
    assert prof is not None
    assert prof["cost"]["flops"] > 0
    assert prof["collective_bytes"] == 0  # single-device program
    events = [e for e in read_journal(path) if e["event"] == "perf_profile"]
    assert len(events) == 1
    assert events[0]["name"] == "test/matmul"
    assert check_journal(path, strict=True) == []
    snap = reg.snapshot()  # flat {name+labels: value}
    assert snap["perfwatch_profiles_total"] == 1
    assert any(k.startswith("perfwatch_flops") for k in snap)


def test_profile_compiled_never_raises_on_garbage():
    assert perfwatch.profile_compiled("x", object()) is not None


def test_telemetry_status_surfaces_last_profile_gate_digest():
    perfwatch.profile_compiled("t/step", _compiled_matmul())
    perfwatch.note_gate({"verdict": "pass", "metric": "m"})
    perfwatch.note_digest({"compute_ms": 1.0})
    perfwatch.set_quantile_source(
        lambda: {"step_time_ms_p50": 3.0, "step_time_ms_p95": 9.0})
    st = perfwatch.telemetry_status()
    assert st["step_time_ms_p50"] == 3.0
    assert st["gate"]["verdict"] == "pass"
    assert st["digest"]["compute_ms"] == 1.0
    assert st["last_profile"]["name"] == "t/step"
    assert isinstance(st.get("recompiles"), int)
    json.dumps(st)  # the /statusz scraper must be able to serialize it


# ------------------------------------------------------------------ ledger


def test_ledger_append_read_roundtrip(tmp_path):
    led = PerfLedger(str(tmp_path / "led.jsonl"))
    led.append({"metric": "m", "value": 1.0, "verdict": "pass"})
    led.append({"metric": "m", "value": 2.0, "verdict": "pass"})
    rows = led.read()
    assert [r["value"] for r in rows] == [1.0, 2.0]
    assert all("crc" in r and "ts" in r for r in rows)


def test_ledger_quarantines_corrupt_rows(tmp_path):
    led = PerfLedger(str(tmp_path / "led.jsonl"))
    for v in (1.0, 2.0, 3.0):
        led.append({"metric": "m", "value": v})
    with open(led.path, "a") as f:
        f.write('{"metric": "tampered", "value": 9, "crc": 1}\n')
        f.write("not json\n")
    rows = led.read()
    assert [r["value"] for r in rows] == [1.0, 2.0, 3.0]
    assert os.path.exists(led.quarantine_path)
    quarantined = open(led.quarantine_path).read()
    assert "tampered" in quarantined and "not json" in quarantined
    # the main file was rewritten clean: a second read quarantines nothing
    assert [r["value"] for r in led.read()] == [1.0, 2.0, 3.0]


def test_ledger_rotation_spills_oldest(tmp_path):
    led = PerfLedger(str(tmp_path / "led.jsonl"), max_rows=6, keep_rows=3)
    for v in range(8):
        led.append({"metric": "m", "value": float(v)})
    live = [r["value"] for r in led.read()]
    assert len(live) <= 6
    assert live[-1] == 7.0
    assert os.path.exists(led.rotated_path)
    spilled = [json.loads(line)["value"]
               for line in open(led.rotated_path) if line.strip()]
    assert spilled[0] == 0.0
    assert sorted(spilled + live) == [float(v) for v in range(8)]


# ---------------------------------------------------------------- MAD gate


def test_mad_gate_verdicts():
    hist = [10.0, 10.2, 9.8, 10.1, 9.9]
    out = mad_gate(hist, 10.05, direction="lower")
    assert out["verdict"] == "pass"
    out = mad_gate(hist, 30.0, direction="lower")
    assert out["verdict"] == "fail"
    assert out["baseline"] == pytest.approx(10.0)
    assert out["threshold"] > 0
    # a big IMPROVEMENT must not fail a lower-is-better gate
    assert mad_gate(hist, 1.0, direction="lower")["verdict"] == "pass"
    # higher-is-better flips the failing side
    assert mad_gate(hist, 1.0, direction="higher")["verdict"] == "fail"
    assert mad_gate(hist, 30.0, direction="higher")["verdict"] == "pass"
    assert mad_gate([10.0], 30.0, direction="lower")["verdict"] \
        == "insufficient_history"
    # identical history (MAD=0): the relative floor absorbs jitter
    flat = [10.0] * 5
    assert mad_gate(flat, 10.2, direction="lower")["verdict"] == "pass"
    assert mad_gate(flat, 11.0, direction="lower")["verdict"] == "fail"


def test_metric_direction_heuristic():
    assert metric_direction("step_time_ms", None) == "lower"
    assert metric_direction("x", "ms_per_step") == "lower"
    assert metric_direction("resnet50_images_per_sec", None) == "higher"
    assert metric_direction("multichip_scaling", "efficiency_fraction") \
        == "higher"


def test_gate_result_excludes_failed_rows_and_blesses(tmp_path):
    led = PerfLedger(str(tmp_path / "led.jsonl"))
    env = default_env()
    kw = dict(unit="ms", env=env, min_history=2, journal=None)
    for v in (10.0, 10.1, 9.9):
        gate_result(led, "m", v, **kw)
    out = gate_result(led, "m", 50.0, **kw)
    assert out["verdict"] == "fail"
    # the failed row must not drag the baseline: a clean run still passes
    assert gate_result(led, "m", 10.0, **kw)["verdict"] == "pass"
    # bless re-anchors at the new level; the next run gates against it
    assert gate_result(led, "m", 50.0, bless=True, **kw)["verdict"] \
        == "blessed"
    assert gate_result(led, "m", 50.5, **kw)["verdict"] == "pass"
    assert gate_result(led, "m", 90.0, **kw)["verdict"] == "fail"


def test_gate_result_journals_regression(tmp_path):
    led = PerfLedger(str(tmp_path / "led.jsonl"))
    path = str(tmp_path / "j.jsonl")
    kw = dict(unit="ms", env=default_env(), min_history=2)
    with RunJournal(path, kind="perf_gate") as j:
        j.manifest()
        for v in (1.0, 1.01, 1.02):
            gate_result(led, "m", v, journal=j, **kw)
        out = gate_result(led, "m", 99.0, journal=j, **kw)
    assert out["verdict"] == "fail"
    events = [e for e in read_journal(path)
              if e["event"] == "perf_regression"]
    assert len(events) == 1
    assert events[0]["observed"] == 99.0
    assert events[0]["metric"] == "m"
    assert check_journal(path, strict=True) == []
    # the verdict also lands on the /statusz perf section
    assert perfwatch.telemetry_status()["gate"]["verdict"] == "fail"


def test_env_key_separates_mesh_shapes():
    a = default_env(mesh_shape={"data": 8, "model": 1})
    b = default_env(mesh_shape={"data": 4, "model": 2})
    assert env_key(a) != env_key(b)
    assert env_key(a) == env_key(dict(a))


# ------------------------------------------------------------ trace digest


def test_trace_digest_on_real_cpu_capture(tmp_path):
    from tools.trace_digest import digest, find_xplanes, render_digest

    @jax.jit
    def f(x, w):
        return jnp.tanh(x @ w).sum()

    x = jnp.ones((16, 32))
    w = jnp.ones((32, 32))
    f(x, w).block_until_ready()
    cap = str(tmp_path / "cap")
    with jax.profiler.trace(cap):
        for _ in range(3):
            f(x, w).block_until_ready()
    assert find_xplanes(cap), "profiler wrote no xplane capture"
    d = digest(cap)
    assert "error" not in d
    assert d["totals"]["compute_ms"] > 0
    assert d["totals"]["collective_ms"] == 0  # single-device program
    ops = {r["op"]: r for r in d["ops"]}
    assert "dot" in ops and ops["dot"]["category"] == "compute"
    assert ops["dot"]["count"] == 3
    assert any(r["category"] == "host" for r in d["ops"])
    text = render_digest(d)
    assert "step-time decomposition" in text and "dot" in text
    # the in-process run surfaces on /statusz
    assert perfwatch.telemetry_status()["digest"]["compute_ms"] > 0


def test_trace_digest_missing_capture_degrades(tmp_path):
    from tools.trace_digest import digest, render_digest

    d = digest(str(tmp_path))
    assert d["error"]
    assert "no .xplane.pb" in render_digest(d)


# ------------------------------------------------------------- renderings


def test_obs_report_perf_section_renders(tmp_path):
    from tools.obs_report import render, summarize_run

    path = str(tmp_path / "j.jsonl")
    with RunJournal(path, kind="test") as j:
        j.manifest()
        j.write("perf_profile", name="trainer/train", flops=1e9,
                bytes_accessed=2e6, argument_bytes=1, output_bytes=1,
                temp_bytes=0, collective_count=2, collective_bytes=33024)
        j.write("perf_collective", name="trainer/train", kind="all-reduce",
                dtype="f32", ops=2, bytes=33024, group_size=8)
        j.write("perf_regression", metric="step_ms", baseline=1.0,
                observed=9.0, threshold=0.5, direction="lower")
    text = render(summarize_run(read_journal(path)))
    assert "perf trainer/train" in text
    assert "all-reduce f32 x2" in text
    assert "PERF REGRESSION" in text and "step_ms" in text


def test_obs_report_unchanged_without_perf_events(tmp_path):
    from tools.obs_report import render, summarize_run

    path = str(tmp_path / "j.jsonl")
    with RunJournal(path, kind="test") as j:
        j.manifest()
        j.write("note", note="nothing perf-shaped here")
    events = read_journal(path)
    text = render(summarize_run(events))
    assert "perf" not in text.lower() or "perf" not in text
    from tools.obs_report import summarize_perf

    assert summarize_perf(events) is None


def test_obs_report_ledger_trajectory(tmp_path):
    from tools.obs_report import render_ledger

    led = PerfLedger(str(tmp_path / "led.jsonl"))
    kw = dict(unit="ms", env=default_env(), min_history=2)
    for v in (10.0, 10.5, 9.5, 10.2):
        gate_result(led, "step_ms", v, **kw)
    text = render_ledger(led.path)
    assert "step_ms" in text
    assert "[pass]" in text
    assert "(n=4)" in text
    # empty ledger renders a stub, not a crash
    assert "empty" in render_ledger(str(tmp_path / "missing.jsonl"))


# ------------------------------------------------------------ drift guards


def test_collective_kind_enums_stay_in_sync():
    assert set(costmodel.COLLECTIVE_KINDS) == PERF_COLLECTIVE_KINDS


# (the old perf-event registration walk lives in lint now: DV204 fails
# any journal.write whose event type has no check_journal schema, and
# tests/test_distlint.py parametrizes that walk over every emitter)


def test_gate_verdicts_cover_gate_outputs():
    assert set(GATE_VERDICTS) == {"pass", "fail", "insufficient_history",
                                  "blessed"}


def test_emitters_satisfy_required_schema(tmp_path):
    """Every field check_journal requires must actually be emitted —
    the strict gate and the emitters drift together or not at all."""
    path = str(tmp_path / "j.jsonl")
    led = PerfLedger(str(tmp_path / "led.jsonl"))
    with RunJournal(path, kind="test") as j:
        j.manifest()
        perfwatch.profile_compiled("t", _compiled_matmul(), journal=j)
        kw = dict(unit="ms", env=default_env(), min_history=2, journal=j)
        for v in (1.0, 1.0, 1.0):
            gate_result(led, "m", v, **kw)
        gate_result(led, "m", 99.0, **kw)
    by_event = {}
    for e in read_journal(path):
        by_event.setdefault(e["event"], []).append(e)
    assert "perf_profile" in by_event
    assert "perf_regression" in by_event
    for ev, rows in by_event.items():
        for row in rows:
            for field in EVENT_FIELDS.get(ev, ()):
                assert field in row, (ev, field)
    assert check_journal(path, strict=True) == []
