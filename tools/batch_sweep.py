"""Batch-size scaling curve for the flagship train step (round 4).

Round 3 found batch 512 ~6% slower PER IMAGE on-device than 256
(artifacts/dispatch_r03.json) and left it unexplained. This sweep measures
device time, wall time, XLA cost-analysis bytes, and XLA memory-analysis
peak HBM for batch in {128, 192, 256, 320, 384, 512} in ONE process with
interleaved windows (session drift is +-4%).

The capacity hypothesis: ResNet-50/224 bf16 saves ~46 MB of activations per
image for the backward pass; at batch 512 that alone is ~23 GB against the
v5e's 16 GB HBM, so XLA must rematerialize/spill — visible as bytes/image
and time/image going UP while memory-analysis pins near the HBM limit.

Writes artifacts/batch_scaling_r04.json. Run solo on the chip.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

BATCHES = [128, 192, 256, 320, 384, 512]
REPS = 3
STEPS_PER_WINDOW_IMAGES = 256 * 20  # equal IMAGE count per window


def _log(m):
    print(f"batch_sweep: {m}", file=sys.stderr, flush=True)


def main(out_path="artifacts/batch_scaling_r04.json"):
    art = {"what": __doc__.split("\n")[0], "batches": BATCHES, "reps": REPS}
    rows = {}
    built = {}
    for b in BATCHES:
        try:
            t0 = time.perf_counter()
            step, state, batch, batch_size, n_chips, devices = (
                bench.build_bench(b, 1)
            )
            row = {"batch_per_chip": b,
                   "compile_s": round(time.perf_counter() - t0, 1)}
            try:
                ca = step.cost_analysis()
                ca = ca[0] if isinstance(ca, (list, tuple)) else ca
                row["bytes_gb_per_step"] = round(
                    float(ca["bytes accessed"]) / 1e9, 3
                )
                row["bytes_mb_per_image"] = round(
                    float(ca["bytes accessed"]) / 1e6 / b, 1
                )
                row["gflops_per_image"] = round(float(ca["flops"]) / 1e9 / b,
                                                2)
            except Exception as e:
                row["bytes_gb_per_step"] = None
                _log(f"b{b} cost_analysis: {e}")
            try:
                ma = step.memory_analysis()
                for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                          "output_size_in_bytes", "alias_size_in_bytes"):
                    v = getattr(ma, k, None)
                    if v is not None:
                        row[k.replace("_in_bytes", "_gb")] = round(v / 1e9, 2)
            except Exception as e:
                _log(f"b{b} memory_analysis: {e}")
            # warmup
            for _ in range(3):
                state, loss = step(state, batch)
            float(loss)
            built[b] = [step, state, batch, row, []]
            _log(f"b{b}: compiled {row['compile_s']}s, "
                 f"bytes/img {row.get('bytes_mb_per_image')} MB, "
                 f"temp {row.get('temp_size_gb')} GB")
        except KeyboardInterrupt:
            raise
        except Exception as e:
            _log(f"b{b} FAILED: {type(e).__name__}: {e}")
            rows[b] = {"batch_per_chip": b,
                       "error": f"{type(e).__name__}: {e}"}
    for rep in range(REPS):
        for b, (step, state, batch, row, dts) in list(built.items()):
            n_steps = max(1, STEPS_PER_WINDOW_IMAGES // b)
            try:
                t0 = time.perf_counter()
                for _ in range(n_steps):
                    state, loss = step(state, batch)
                float(loss)
                dts.append((time.perf_counter() - t0) / n_steps)
                built[b][1] = state
                _log(f"rep {rep} b{b}: {dts[-1] * 1e3:.2f} ms/step "
                     f"({b / dts[-1]:.0f} img/s)")
            except KeyboardInterrupt:
                raise
            except Exception as e:
                _log(f"rep {rep} b{b} dropped: {type(e).__name__}: {e}")
                row["error"] = f"{type(e).__name__}: {e}"
                del built[b]
    for b, (step, state, batch, row, dts) in built.items():
        if dts:
            wall_ms = float(np.median(dts)) * 1e3
            row["wall_ms_per_step"] = round(wall_ms, 2)
            row["wall_images_per_sec"] = round(b / wall_ms * 1e3, 1)
        dev = bench._device_step_ms(step, state, batch, 1)
        if dev:
            row["device_ms_per_step"] = round(dev, 2)
            row["device_images_per_sec"] = round(b / dev * 1e3, 1)
            row["device_ms_per_256_images"] = round(dev * 256 / b, 2)
        rows[b] = row
        _log(f"b{b}: wall {row.get('wall_ms_per_step')} ms, device "
             f"{row.get('device_ms_per_step')} ms "
             f"({row.get('device_images_per_sec')} img/s device)")
    art["rows"] = [rows[b] for b in BATCHES if b in rows]
    good = [r for r in art["rows"] if r.get("device_images_per_sec")]
    if good:
        best = max(good, key=lambda r: r["device_images_per_sec"])
        art["recommended_batch_per_chip"] = best["batch_per_chip"]
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(art, f, indent=2)
    _log(f"wrote {out_path}")


if __name__ == "__main__":
    # usage: batch_sweep.py [out.json] [b1,b2,...]
    if len(sys.argv) > 2:
        BATCHES = [int(b) for b in sys.argv[2].split(",")]
    main(sys.argv[1] if len(sys.argv) > 1 else
         "artifacts/batch_scaling_r04.json")
