"""Golden-value and behavioral tests for task losses."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.losses.classification import (
    classification_loss_fn,
    cross_entropy_loss,
)
from deep_vision_tpu.losses.heatmap import (
    centernet_focal_loss,
    centernet_loss_fn,
    hourglass_loss_fn,
)
from deep_vision_tpu.losses.yolo import yolo_loss_fn, yolo_loss_per_scale
from deep_vision_tpu.ops import YOLO_ANCHORS, assign_anchors_to_grid


def test_cross_entropy_golden():
    # uniform logits over 4 classes -> CE = log(4)
    logits = jnp.zeros((3, 4))
    labels = jnp.array([0, 1, 2])
    assert cross_entropy_loss(logits, labels) == pytest.approx(np.log(4), abs=1e-5)


def test_cross_entropy_masked_ignores_padding():
    logits = jnp.array([[10.0, 0.0], [0.0, 10.0]])
    labels = jnp.array([0, 0])  # second row is wrong on purpose
    w = jnp.array([1.0, 0.0])  # ...but masked out
    assert cross_entropy_loss(logits, labels, weights=w) == pytest.approx(0.0, abs=1e-3)


def test_classification_aux_heads_add_loss():
    labels = jnp.array([0, 1])
    logits = jnp.zeros((2, 4))
    loss_no_aux, _ = classification_loss_fn(logits, {"label": labels})
    loss_aux, _ = classification_loss_fn(
        (logits, logits, logits), {"label": labels}
    )
    assert loss_aux == pytest.approx(float(loss_no_aux) * 1.6, rel=1e-5)  # 1 + 2*0.3


def _yolo_batch(g=13, num_classes=5):
    boxes = jnp.array([[[0.5, 0.5, 0.4, 0.35], [0.0, 0.0, 0.0, 0.0]]])
    classes = jnp.array([[3, 0]])
    targets = jax.vmap(
        lambda b, c: assign_anchors_to_grid(b, c, (13, 26, 52), num_classes=num_classes)
    )(boxes, classes)
    return {"labels": tuple(targets), "boxes": boxes}


def test_yolo_loss_perfect_prediction_near_zero_regression():
    """A prediction that decodes exactly to the target has ~zero xy/wh/class loss."""
    num_classes = 5
    batch = _yolo_batch()
    target = batch["labels"][0]  # (1, 13, 13, 3, 10)
    anchors = jnp.asarray(YOLO_ANCHORS[[6, 7, 8]])

    from deep_vision_tpu.ops.boxes import encode_yolo_boxes

    t = encode_yolo_boxes(target[..., 0:4], anchors, 13)
    # build raw logits that reproduce the target exactly where obj=1
    eps = 1e-6
    t_xy = jnp.clip(t[..., 0:2], eps, 1 - eps)
    raw_xy = jnp.log(t_xy / (1 - t_xy))  # inverse sigmoid
    raw = jnp.concatenate(
        [
            raw_xy,
            t[..., 2:4],
            jnp.where(target[..., 4:5] > 0, 20.0, -20.0),  # obj logits
            jnp.where(target[..., 5:] > 0, 20.0, -20.0),   # class logits
        ],
        axis=-1,
    )
    losses = yolo_loss_per_scale(raw, target, batch["boxes"], anchors)
    assert float(losses["xy"]) == pytest.approx(0.0, abs=1e-3)
    assert float(losses["wh"]) == pytest.approx(0.0, abs=1e-3)
    assert float(losses["class"]) == pytest.approx(0.0, abs=1e-3)
    assert float(losses["obj"]) == pytest.approx(0.0, abs=1e-3)
    assert float(losses["total"]) < 0.01


def test_yolo_loss_fn_decreases_with_better_obj():
    batch = _yolo_batch()
    preds_bad = tuple(jnp.zeros((1, g, g, 3, 10)) for g in (13, 26, 52))
    loss_bad, metrics = yolo_loss_fn(preds_bad, batch)
    assert np.isfinite(float(loss_bad))
    assert "loss_large" in metrics
    # objectness logits that match the GT obj mask must lower the loss
    preds_good = tuple(
        p.at[..., 4].set(jnp.where(t[..., 4] > 0, 20.0, -20.0))
        for p, t in zip(preds_bad, batch["labels"])
    )
    loss_good, _ = yolo_loss_fn(preds_good, batch)
    assert float(loss_good) < float(loss_bad)


def test_hourglass_loss_foreground_weighting():
    gt = jnp.zeros((1, 8, 8, 2)).at[0, 4, 4, 0].set(1.0)
    # same squared error magnitude, but a foreground miss costs 82x
    pred_bg_err = [gt.at[0, 0, 0, 0].set(1.0)]  # perfect fg, 1.0 err at bg
    pred_fg_err = [gt.at[0, 4, 4, 0].set(0.0)]  # 1.0 err at the fg pixel
    loss_bg, _ = hourglass_loss_fn(pred_bg_err, {"heatmap": gt})
    loss_fg, _ = hourglass_loss_fn(pred_fg_err, {"heatmap": gt})
    assert float(loss_fg) == pytest.approx(float(loss_bg) * 82.0, rel=1e-4)


def test_centernet_focal_confident_correct_is_small():
    gt = jnp.zeros((1, 8, 8, 3)).at[0, 4, 4, 1].set(1.0)
    good = jnp.full((1, 8, 8, 3), -10.0).at[0, 4, 4, 1].set(10.0)
    bad = jnp.full((1, 8, 8, 3), -10.0).at[0, 4, 4, 1].set(-10.0)
    assert float(centernet_focal_loss(good, gt)) < 0.01
    assert float(centernet_focal_loss(bad, gt)) > 1.0


def test_centernet_loss_fn_complete():
    """The loss ObjectsAsPoints never got (reference train.py:35): runs + finite."""
    h = w = 8
    batch = {
        "heatmap": jnp.zeros((1, h, w, 3)).at[0, 4, 4, 1].set(1.0),
        "wh": jnp.zeros((1, h, w, 2)).at[0, 4, 4].set(jnp.array([2.0, 3.0])),
        "offset": jnp.zeros((1, h, w, 2)).at[0, 4, 4].set(jnp.array([0.3, 0.7])),
        "mask": jnp.zeros((1, h, w)).at[0, 4, 4].set(1.0),
    }
    outputs = [
        {
            "heatmap": jnp.zeros((1, h, w, 3)),
            "wh": jnp.zeros((1, h, w, 2)),
            "offset": jnp.zeros((1, h, w, 2)),
        }
    ]
    loss, metrics = centernet_loss_fn(outputs, batch)
    assert np.isfinite(float(loss))
    assert metrics["wh_loss"] == pytest.approx(5.0)  # |2|+|3| over 1 object
    assert metrics["offset_loss"] == pytest.approx(1.0)  # 0.3+0.7


def test_aux_penalty_name_collision_raises():
    """Reserved metric keys would silently swallow an aux penalty's metric
    while still adding it to the loss (ADVICE r2) — refuse loudly."""
    logits = jnp.zeros((4, 8))
    batch = {"label": np.zeros((4,), np.int32)}
    with pytest.raises(ValueError, match="reserved"):
        classification_loss_fn((logits, {"loss": jnp.float32(1.0)}), batch)


def test_aux_duplicate_name_collision_raises():
    """A '_'-prefixed diagnostic and a same-named penalty (or repeats across
    aux dicts) must not silently last-writer-win in metrics (ADVICE r4)."""
    logits = jnp.zeros((4, 8))
    batch = {"label": np.zeros((4,), np.int32)}
    one = jnp.float32(1.0)
    # diagnostic '_x' surfaces as 'x'; penalty 'x' then collides
    with pytest.raises(ValueError, match="duplicate"):
        classification_loss_fn((logits, {"_x": one, "x": one}), batch)
    # same surfaced name across two aux dicts
    with pytest.raises(ValueError, match="duplicate"):
        classification_loss_fn((logits, {"_x": one}, {"_x": one}), batch)
