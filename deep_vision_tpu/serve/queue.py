"""BatchingQueue: coalesce in-flight requests under a max-wait/max-batch
policy.

The serving latency/throughput dial: a request never waits longer than
`max_wait_ms` for company (the latency bound), and a batch never exceeds
`max_batch` = the largest warmed bucket (the shape bound). Between the
two, the dispatcher takes whatever has accumulated — bucket rounding and
padding happen downstream (serve/buckets.py), so the queue stays a pure
host-side coalescer with no jax anywhere near it.

Drain semantics are first-class: `close()` stops producers (submit
raises `QueueClosed`), wakes the dispatcher, and switches `next_batch`
to flush-immediately mode — remaining requests come back in max_batch
slices with no max-wait lingering, then `None` tells the dispatcher to
exit. SIGTERM drain (serve/router.py) is exactly this switch.
"""
from __future__ import annotations

import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, List, Optional

from deep_vision_tpu.obs import locksmith


class QueueClosed(RuntimeError):
    """submit() after close(): the server is draining or stopped."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before dispatch: it was shed, not
    executed (serve/router.py checks at batch pickup; the front door —
    serve/transport.py — maps this to HTTP 504). Retrying is pointless
    by definition: the CLIENT's budget expired, not the server."""


class Request:
    """One in-flight request: the payload, its promise, and its clock.

    `accounted` latches once the router has counted this request toward
    completed/errors/cancelled — a request must land in exactly one
    bucket no matter which path (resolve, batch failure, client cancel)
    reaches it first.

    `ctx` carries the request's trace context (obs/propagate.py) from
    the submitting thread to the dispatcher thread — the ambient
    thread-local slot cannot make that hop, so the context rides the
    request object itself.

    `deadline_ts` (perf_counter seconds, or None) is the client's
    budget: the dispatcher sheds the request instead of executing it
    when pickup happens past this instant — work whose answer nobody
    will read must not occupy a batch slot.
    """

    __slots__ = ("model", "image", "future", "t_submit", "accounted", "ctx",
                 "deadline_ts")

    def __init__(self, model: str, image):
        self.model = model
        self.image = image
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.accounted = False
        self.ctx = None
        self.deadline_ts = None


class BatchingQueue:
    """Thread-safe request coalescer for one model.

    Producers call `submit` from any thread; one dispatcher thread loops
    on `next_batch`. `on_depth` (the slo.py queue-depth gauge hook) is
    called with the post-change depth under no lock contention concerns —
    registry gauges take their own lock.
    """

    def __init__(self, max_batch: int, max_wait_ms: float = 5.0,
                 on_depth: Optional[Callable[[int], None]] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self._on_depth = on_depth
        self._q: deque = deque()
        # one lock ROLE for every per-model queue (locksmith checks lock
        # ordering between roles, not instances — lockdep lock classes)
        self._cond = locksmith.condition("serve.queue")
        self._closed = False

    # -- producer side -----------------------------------------------------

    def submit(self, request: Request) -> None:
        with self._cond:
            if self._closed:
                raise QueueClosed(
                    f"queue for {request.model!r} is draining/closed")
            self._q.append(request)
            depth = len(self._q)
            self._cond.notify_all()
        if self._on_depth is not None:
            self._on_depth(depth)

    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    # -- dispatcher side ---------------------------------------------------

    def next_batch(self) -> Optional[List[Request]]:
        """Block until a batch is ready; None = closed AND empty (exit).

        A batch is ready when `max_batch` requests are waiting, when the
        OLDEST request has waited `max_wait_ms`, or immediately once the
        queue is closed (drain flushes, it never lingers).
        """
        with self._cond:
            while not self._q and not self._closed:
                self._cond.wait()
            if not self._q:
                return None  # closed and drained: dispatcher exits
            if not self._closed:
                # the max-wait window is anchored on the oldest request:
                # later arrivals ride it, they do not extend it
                deadline = self._q[0].t_submit + self.max_wait_s
                while len(self._q) < self.max_batch and not self._closed:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
            take = min(len(self._q), self.max_batch)
            batch = [self._q.popleft() for _ in range(take)]
            depth = len(self._q)
        if self._on_depth is not None:
            self._on_depth(depth)
        return batch

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Stop accepting; flush what remains. Idempotent."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
