"""MobileNet V1 (Howard 2017) with width multiplier alpha.

Parity targets: MobileNet/pytorch/models/mobilenet_v1.py (DepthwiseSeparableConv
stack, alpha at mobilenet_v1.py:17, depthwise via groups=in_channels at
:109-122) and the Keras twin MobileNet/tensorflow/models/mobilenet_v1.py:7-26.
Depthwise lowers to lax.conv_general_dilated with feature_group_count — the
TPU-native grouped conv.
"""
from __future__ import annotations

import flax.linen as nn

from deep_vision_tpu.models import register_model
from deep_vision_tpu.nn.layers import ConvBN, DepthwiseSeparableConv, global_avg_pool

# (features, stride) after the stem; features are pre-alpha
_CFG = [
    (64, 1),
    (128, 2),
    (128, 1),
    (256, 2),
    (256, 1),
    (512, 2),
    (512, 1),
    (512, 1),
    (512, 1),
    (512, 1),
    (512, 1),
    (1024, 2),
    (1024, 1),
]


class MobileNetV1(nn.Module):
    num_classes: int = 1000
    alpha: float = 1.0
    dropout: float = 0.001  # keras MobileNet default; reference uses none (PT)

    @nn.compact
    def __call__(self, x, train: bool = True):
        def scaled(ch):
            return max(8, int(ch * self.alpha))

        x = ConvBN(scaled(32), (3, 3), strides=(2, 2))(x, train)
        for features, stride in _CFG:
            x = DepthwiseSeparableConv(scaled(features), strides=(stride, stride))(
                x, train
            )
        x = global_avg_pool(x)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


@register_model("mobilenet1")
def mobilenet_v1(num_classes: int = 1000, alpha: float = 1.0, **_):
    return MobileNetV1(num_classes=num_classes, alpha=alpha)
