"""Name the wall-vs-device gap mechanism on the tunneled chip (round 4).

Round 3 measured a ~5 ms/step wall-minus-device gap that an 8-step
`lax.scan` superstep did NOT remove (artifacts/dispatch_r03.json), which
contradicted the "per-dispatch relay turnaround" story. But the same rows
hide a cleaner pattern: gap_per_step x steps_per_window is ~constant
(108.6 / 110.2 / 114.2 / 112.1 ms across all four configs) — i.e. the
overhead looks *per host synchronization* (the `float(loss)` fetch that
closes each timed window), not per step and not per dispatch.

This probe decides it:

1. **Window-length sweep**: wall time of windows of N in {5,10,20,50,100,200}
   steps (one fetch per window), interleaved round-robin across reps to beat
   the rig's +-4% session drift. Least-squares fit wall(N) = a + b*N:
   - a ~= per-sync overhead (ms), b ~= true per-step time (ms).
   - Per-sync hypothesis: a ~ 110, b ~ device step time (97.9).
   - Per-step-overhead hypothesis: a ~ 0, b ~ 103.3.
2. **Per-enqueue timing**: perf_counter around every step() call in a
   window — proves dispatches are async (fast enqueue, cost concentrated in
   the closing fetch) or sync (each call blocks ~one step).
3. **Pure sync RTT**: float() fetch of a trivial jitted computation —
   the floor any synchronization pays through the relay.
4. **Device timeline**: module-event START timestamps from a profiler trace
   of one 20-dispatch window — inter-module idle gaps on the device tell
   whether the chip itself ever waits between dispatches.

Writes artifacts/dispatch_r04.json. Run solo (no concurrent host load:
a CPU-heavy cotenant inflated a 74 ms step to 174 ms in round 3).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

WINDOW_SIZES = [5, 10, 20, 50, 100, 200]
REPS = 3


def _log(msg):
    print(f"probe: {msg}", file=sys.stderr, flush=True)


def pure_sync_rtt_ms(n=5):
    """Dispatch + scalar-fetch round trip for a trivial kernel."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.float32(0.0)
    float(f(x))  # compile
    dts = []
    for _ in range(n):
        t0 = time.perf_counter()
        float(f(x))
        dts.append((time.perf_counter() - t0) * 1e3)
    return dts


def device_timeline(step, state, batch, dispatches=20):
    """(module_durations_ms, inter_module_gaps_ms) from one traced window
    (bench._trace_module_events does the trace + xplane parse).

    CONSUMES `state`: the step donates its input, so the caller's handle is
    dead after this returns — call it last."""
    events = bench._trace_module_events(step, state, batch, dispatches)
    # ps -> ms (1 ms = 1e9 ps)
    durs_ms = [d / 1e9 for _, d in events]
    gaps_ms = [
        (events[i + 1][0] - (events[i][0] + events[i][1])) / 1e9
        for i in range(len(events) - 1)
    ]
    return durs_ms, gaps_ms


def main(out_path="artifacts/dispatch_r04.json"):
    art = {"what": __doc__.split("\n")[0],
           "window_sizes": WINDOW_SIZES, "reps": REPS}

    _log("building step (batch 256, k=1)")
    step, state, batch, batch_size, n_chips, devices = bench.build_bench(
        256, 1
    )
    art["device_kind"] = devices[0].device_kind
    # warmup
    t0 = time.perf_counter()
    for _ in range(5):
        state, loss = step(state, batch)
    float(loss)
    _log(f"warmup {time.perf_counter() - t0:.1f}s")

    # 3. pure sync RTT (cheap, do first on the warm session)
    art["pure_sync_rtt_ms"] = [round(v, 2) for v in pure_sync_rtt_ms()]
    _log(f"pure sync RTT ms: {art['pure_sync_rtt_ms']}")

    # 2. per-enqueue timing: one 20-step window, clock every call
    enq = []
    t0 = time.perf_counter()
    for _ in range(20):
        t1 = time.perf_counter()
        state, loss = step(state, batch)
        enq.append((time.perf_counter() - t1) * 1e3)
    t2 = time.perf_counter()
    float(loss)
    fetch_ms = (time.perf_counter() - t2) * 1e3
    wall_ms = (time.perf_counter() - t0) * 1e3
    art["per_enqueue"] = {
        "enqueue_ms": [round(v, 2) for v in enq],
        "closing_fetch_ms": round(fetch_ms, 1),
        "window_wall_ms": round(wall_ms, 1),
        "note": "async dispatch = small enqueues, cost in the fetch; "
                "sync dispatch = each enqueue ~ one step",
    }
    _log(f"enqueue ms: med {np.median(enq):.2f} max {max(enq):.1f}; "
         f"closing fetch {fetch_ms:.0f} of {wall_ms:.0f} wall")

    # 1. window-length sweep, interleaved
    walls = {n: [] for n in WINDOW_SIZES}
    for rep in range(REPS):
        for n in WINDOW_SIZES:
            t0 = time.perf_counter()
            for _ in range(n):
                state, loss = step(state, batch)
            float(loss)
            dt = (time.perf_counter() - t0) * 1e3
            walls[n].append(dt)
            _log(f"rep {rep} N={n}: {dt:.0f} ms ({dt / n:.2f} ms/step)")
    med = {n: float(np.median(v)) for n, v in walls.items()}
    ns = np.array(WINDOW_SIZES, dtype=np.float64)
    ws = np.array([med[n] for n in WINDOW_SIZES])
    b, a = np.polyfit(ns, ws, 1)  # wall = a + b*N
    resid = ws - (a + b * ns)
    art["window_sweep"] = {
        "wall_ms_per_window": {str(n): [round(v, 1) for v in walls[n]]
                               for n in WINDOW_SIZES},
        "median_wall_ms": {str(n): round(med[n], 1) for n in WINDOW_SIZES},
        "fit_per_sync_overhead_ms": round(float(a), 1),
        "fit_per_step_ms": round(float(b), 3),
        "fit_max_residual_ms": round(float(np.abs(resid).max()), 1),
    }
    _log(f"fit: wall = {a:.1f} + {b:.2f}*N ms "
         f"(max residual {np.abs(resid).max():.1f} ms)")

    # 4. device timeline
    try:
        durs, gaps = device_timeline(step, state, batch)  # consumes state
        art["device_timeline"] = {
            "module_ms": [round(d, 2) for d in durs],
            "inter_module_gap_us": [round(g * 1e3, 1) for g in gaps],
            "median_module_ms": round(float(np.median(durs)), 2),
            "median_gap_us": round(float(np.median(gaps)) * 1e3, 1)
            if gaps else None,
        }
        _log(f"device: module med {np.median(durs):.2f} ms, "
             f"gap med {np.median(gaps) * 1e3:.1f} us")
    except Exception as e:
        art["device_timeline"] = {"error": f"{type(e).__name__}: {e}"}
        _log(f"device timeline failed: {e}")

    # verdict, mechanically derived
    per_step_gap_20 = med[20] / 20 - art["window_sweep"]["fit_per_step_ms"]
    art["conclusion"] = {
        "per_sync_overhead_ms": art["window_sweep"]["fit_per_sync_overhead_ms"],
        "true_per_step_ms": art["window_sweep"]["fit_per_step_ms"],
        "r03_20step_window_gap_explained_ms_per_step": round(
            float(per_step_gap_20), 2
        ),
    }
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(art, f, indent=2)
    _log(f"wrote {out_path}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "artifacts/dispatch_r04.json")
