"""Production inference serving: dynamic batching over an AOT-warmed
multi-model engine, with SLO accounting and drain semantics.

The "millions of users" leg of the roadmap: `inference.py`'s per-call
predictors become a server —

- `buckets`: the anti-recompile contract — coalesced requests round up
  to a small fixed menu of batch shapes and zero-pad the tail
  (`bucket_for`, `pad_batch`, `split_rows`).
- `queue`: `BatchingQueue`, max-wait/max-batch request coalescing with
  first-class drain (close -> flush-immediately -> None).
- `engine`: `Engine`, AOT `jax.jit(...).lower().compile()` of every
  (model, bucket) pair at startup, images donated on the inference
  path; `run()` refuses to compile at request time.
- `router`: `Server`, one queue+dispatcher per model over one device,
  request-scoped failure (`data.read` fault boundary), health-policy
  wiring, SIGTERM drain that flushes in-flight requests and dumps a
  `preempt` flight bundle.
- `slo`: `SLOTracker`, p50/p95/p99 request latency from the obs
  registry histograms plus queue-depth / batch-occupancy /
  padding-waste gauges, offered-vs-admitted accounting, and the
  per-replica depth gauges the pool routes by.

The fleet layer above one Router (the "millions of users" shape):

- `pool`: `ReplicaPool`, N in-process replicas each owning a warmed
  Engine + Server, load-aware routing, `warming/serving/draining/dead`
  health states, replica-death detection with request-scoped failure
  and supervised respawn (`replica_lost` / `replica_recovered` events).
- `admission`: `AdmissionController` + `TokenBucket` — bounded
  per-model queues and request budgets; overload sheds by policy
  (typed `serve_shed` events, `ShedError` to the client) instead of
  collapsing the latency tail.
- `swap`: `SwapController` — zero-downtime canary weight swap: load via
  the cross-mesh checkpoint restore, bind a shadow engine over the SAME
  warmed executables (weights are a runtime argument — zero recompiles,
  counter-verified), canary x% of live traffic, auto-promote or
  auto-rollback (`serve_swap` events).

Journal events: `serve_request`, `serve_batch`, `serve_drain`,
`serve_shed`, `serve_swap`, `replica_lost`, `replica_recovered`
(schemas in obs/README.md, validated by tools/check_journal.py). Trace
spans: `serve/warmup`, `serve/batch`, `serve/drain`. The CI teeth are
`make serve-smoke` (tools/serve_smoke.py), `make fleet-smoke`
(tools/loadgen.py), tests/test_serve.py and tests/test_serve_pool.py.
"""
from deep_vision_tpu.serve.admission import (
    AdmissionController,
    ShedError,
    TokenBucket,
)
from deep_vision_tpu.serve.buckets import (
    DEFAULT_BUCKETS,
    bucket_for,
    normalize_buckets,
    pad_batch,
    split_rows,
)
from deep_vision_tpu.serve.engine import Engine, ModelEntry, ServeError
from deep_vision_tpu.serve.pool import REPLICA_STATES, ReplicaLost, ReplicaPool
from deep_vision_tpu.serve.quantize import (
    QuantizationRejected,
    QuantizedModel,
    calibrate_and_quantize,
    quantize_variables,
    quantized_fn,
)
from deep_vision_tpu.serve.procpool import ProcReplicaPool
from deep_vision_tpu.serve.queue import (
    BatchingQueue,
    DeadlineExceeded,
    QueueClosed,
    Request,
)
from deep_vision_tpu.serve.router import Server, ServerClosed
from deep_vision_tpu.serve.slo import SHED_REASONS, SLOTracker
from deep_vision_tpu.serve.transport import (
    DEADLINE_HEADER,
    STATUS_BY_REASON,
    TRANSPORT_OUTCOMES,
    Transport,
)
from deep_vision_tpu.serve.swap import SWAP_OUTCOMES, SWAP_PHASES, SwapController

__all__ = [
    "AdmissionController",
    "BatchingQueue",
    "DEFAULT_BUCKETS",
    "Engine",
    "ModelEntry",
    "QuantizationRejected",
    "DEADLINE_HEADER",
    "DeadlineExceeded",
    "ProcReplicaPool",
    "QuantizedModel",
    "QueueClosed",
    "REPLICA_STATES",
    "ReplicaLost",
    "ReplicaPool",
    "Request",
    "SHED_REASONS",
    "STATUS_BY_REASON",
    "SLOTracker",
    "SWAP_OUTCOMES",
    "SWAP_PHASES",
    "ServeError",
    "Server",
    "ServerClosed",
    "ShedError",
    "SwapController",
    "TRANSPORT_OUTCOMES",
    "TokenBucket",
    "Transport",
    "bucket_for",
    "calibrate_and_quantize",
    "normalize_buckets",
    "pad_batch",
    "quantize_variables",
    "quantized_fn",
    "split_rows",
]
