"""Step-time decomposition from profiler captures: where did the step go.

    PYTHONPATH=. python tools/trace_digest.py artifacts/autoprof/cap-000-spike
    PYTHONPATH=. python tools/trace_digest.py <dir> --top 20 --json

The autoprof policy (obs/autoprof.py) and the static capture window both
write TensorBoard xplane protos (`plugins/profile/<ts>/<host>.xplane.pb`).
This tool reads them back WITHOUT TensorBoard: every XLA op execution on
the device lines, aggregated per op and classified compute vs collective
vs host, rendered as a top-k time table. That is step-time decomposition
v2 — v1 (obs_report --trace) sees only the Python-side spans the journal
chose to stamp; this sees every op the compiled executable actually ran,
so "the step got slower" decomposes into "which op" and "compute or
comm" directly from the capture a spike already triggered.

Consumed three ways: this CLI, `obs_report --digest <dir>` (the same
table inside the postmortem report), and — when called in-process —
`perfwatch.note_digest` so the telemetry /statusz perf section carries
the last decomposition next to the live step-time quantiles.

Parsing needs the pure-python protobuf fallback (the xplane pb2 modules
ship without C extensions here); the env var is set before any protobuf
import, and a missing/foreign proto degrades to an explanatory error,
never a crash.
"""
from __future__ import annotations

import os

# must precede the first protobuf import anywhere in the process; a
# setdefault so an operator's explicit choice wins
os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

import argparse  # noqa: E402
import json  # noqa: E402
import re  # noqa: E402
import sys  # noqa: E402
from typing import Dict, List, Optional  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

__all__ = ["find_xplanes", "digest", "render_digest", "CATEGORIES"]

CATEGORIES = ("compute", "collective", "host")

#: op-name tokens that mark a device op as communication rather than
#: math — the hyphen/underscore-normalized spelling of
#: obs/costmodel.COLLECTIVE_KINDS plus the send/recv pair fusion emits
_COLLECTIVE_TOKENS = ("all-reduce", "all-gather", "reduce-scatter",
                      "all-to-all", "collective-permute", "send", "recv")

# `fusion.123` / `all-reduce.5` -> the base op name the table keys on
_OP_SUFFIX_RE = re.compile(r"\.\d+$")


def _classify(op: str, device_line: bool) -> str:
    # HLO op names never contain "::" — runtime C++ methods interleaved
    # on the XLA client line (ThunkExecutor, ThreadpoolListener) are
    # host machinery, not executed ops
    if not device_line or "::" in op:
        return "host"
    norm = op.replace("_", "-").lower()
    for tok in _COLLECTIVE_TOKENS:
        if tok in norm:
            return "collective"
    return "compute"


def find_xplanes(path: str) -> List[str]:
    """Every .xplane.pb under `path` (a capture dir, its plugins/profile
    tree, or a direct .pb file), newest session first."""
    if os.path.isfile(path):
        return [path] if path.endswith(".xplane.pb") else []
    found: List[str] = []
    for root, _dirs, files in os.walk(path):
        for f in files:
            if f.endswith(".xplane.pb"):
                found.append(os.path.join(root, f))
    # session dirs are timestamp-named; newest capture first so the
    # single-capture default digests the most recent profile
    return sorted(found, reverse=True)


def _load_xspace(path: str):
    """Parsed XSpace proto, or None with a reason when the proto stack
    can't read it (missing dep / truncated file / foreign format)."""
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception:
        try:  # older tensorboard_plugin_profile layouts
            from tensorboard_plugin_profile.protobuf import xplane_pb2
        except Exception:
            return None, "no xplane proto bindings available"
    space = xplane_pb2.XSpace()
    try:
        with open(path, "rb") as f:
            space.ParseFromString(f.read())
    except Exception as e:
        return None, f"unreadable xplane proto: {e}"
    return space, None


def digest(path: str, *, top_k: int = 12) -> dict:
    """Per-op time decomposition of the newest capture under `path`.

    Returns {"source", "ops": [{"op", "category", "count", "total_ms",
    "mean_us"}...] top-k by total time, "totals": {compute_ms,
    collective_ms, host_ms}, "op_count", and "error" instead when the
    capture can't be parsed}. Device planes are `/device:*` (TPU/GPU)
    plus the XLA CPU client line of the host plane; everything else on
    the host plane is host-side Python/runtime time.
    """
    planes = find_xplanes(path)
    if not planes:
        return {"source": path, "error": "no .xplane.pb captures found"}
    src = planes[0]
    space, err = _load_xspace(src)
    if space is None:
        return {"source": src, "error": err}
    agg: Dict[str, dict] = {}
    for plane in space.planes:
        meta = {mid: m.name for mid, m in plane.event_metadata.items()}
        plane_is_device = plane.name.startswith("/device:")
        for line in plane.lines:
            # the CPU backend runs XLA executables on a host-plane line
            # named after the PjRt client; those are device ops too
            device_line = plane_is_device or line.name.startswith("tf_XLA")
            for ev in line.events:
                op = meta.get(ev.metadata_id, "?")
                if not device_line and op.startswith("$"):
                    # Python-tracer stack frames ($file.py:line fn) nest:
                    # summing them counts the same wall time once per
                    # stack depth, drowning the runtime host events
                    continue
                cat = _classify(op, device_line)
                key = _OP_SUFFIX_RE.sub("", op) if device_line else op
                row = agg.setdefault(
                    f"{cat}:{key}",
                    {"op": key, "category": cat, "count": 0, "total_ms": 0.0})
                row["count"] += 1
                row["total_ms"] += ev.duration_ps / 1e9
    ops = sorted(agg.values(), key=lambda r: -r["total_ms"])
    for r in ops:
        r["total_ms"] = round(r["total_ms"], 4)
        r["mean_us"] = round(r["total_ms"] * 1e3 / max(1, r["count"]), 2)
    totals = {f"{c}_ms": round(sum(r["total_ms"] for r in ops
                                   if r["category"] == c), 3)
              for c in CATEGORIES}
    out = {"source": src, "op_count": len(ops), "totals": totals,
           "ops": ops[:max(1, int(top_k))]}
    try:  # surface the decomposition on the live /statusz perf section
        from deep_vision_tpu.obs import perfwatch

        perfwatch.note_digest({"source": src, **totals})
    except Exception:
        pass
    return out


def render_digest(d: dict) -> str:
    if d.get("error"):
        return f"trace digest {d.get('source', '?')}: {d['error']}"
    t = d["totals"]
    lines = [f"-- step-time decomposition: {d['source']} --",
             f"compute {t['compute_ms']:.2f} ms  "
             f"collective {t['collective_ms']:.2f} ms  "
             f"host {t['host_ms']:.2f} ms  "
             f"({d['op_count']} distinct ops, top {len(d['ops'])} shown)"]
    if d["ops"]:
        w = max(len(r["op"]) for r in d["ops"])
        lines.append(f"{'op':<{w}}  {'class':<10}  {'count':>6}  "
                     f"{'total ms':>9}  {'mean us':>9}")
        for r in d["ops"]:
            lines.append(f"{r['op']:<{w}}  {r['category']:<10}  "
                         f"{r['count']:>6}  {r['total_ms']:>9.3f}  "
                         f"{r['mean_us']:>9.2f}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("captures", nargs="+",
                   help="capture dir(s) (autoprof cap-* / --profile-dir) "
                        "or direct .xplane.pb path(s)")
    p.add_argument("--top", type=int, default=12,
                   help="rows in the per-op table (default 12)")
    p.add_argument("--json", action="store_true",
                   help="emit the digest dict(s) as JSON lines")
    args = p.parse_args(argv)
    bad = 0
    for path in args.captures:
        d = digest(path, top_k=args.top)
        if args.json:
            print(json.dumps(d, sort_keys=True))
        else:
            print(render_digest(d))
        bad += 1 if d.get("error") else 0
    return 1 if bad == len(args.captures) else 0


if __name__ == "__main__":
    raise SystemExit(main())
