"""SLO accounting: the numbers an operator pages on.

Rides the existing obs registry (PR 1) rather than inventing a second
metrics surface: request latency lands in the same log-scale Histogram
type the trainer's step times use, so p50/p95/p99 come from
`Histogram.quantile` exactly like every other tail in the repo, and one
Prometheus export carries training and serving side by side.

Tracked per model:

  serve_request_latency_ms{model=}   submit -> result, histogram
  serve_queue_wait_ms{model=}        oldest-request coalescing wait
  serve_exec_ms{model=}              device execute + host fetch
  serve_requests_total{model=,outcome=}  ok / error / rejected
  serve_queue_depth{model=}          gauge, updated on every transition
  serve_batch_occupancy_pct{model=}  last batch: real rows / bucket rows
  serve_padding_waste_pct{model=}    last batch: padded rows / bucket rows
  serve_batches_total{model=}
  serve_batch_slots_total{model=} / serve_padded_slots_total{model=}
                                     lifetime aggregate occupancy
  serve_slo_violations_total{model=} requests over the p99 target
                                     (when an slo_ms target is set)

`report()` collapses all of it into one dict per model (the serving
summary `tools/obs_report.py` renders from the journal has the same
shape, so live metrics and postmortem journals read identically).
"""
from __future__ import annotations

from typing import Dict, Optional

from deep_vision_tpu.obs.registry import Registry, get_registry

OUTCOMES = ("ok", "error", "rejected", "cancelled")


class SLOTracker:
    """Per-model serving metrics over one obs registry."""

    def __init__(self, registry: Optional[Registry] = None,
                 slo_ms: Optional[float] = None):
        self.registry = registry or get_registry()
        self.slo_ms = slo_ms
        self._models: Dict[str, dict] = {}

    def _m(self, model: str) -> dict:
        m = self._models.get(model)
        if m is None:
            r = self.registry
            lbl = {"model": model}
            m = {
                "latency": r.histogram(
                    "serve_request_latency_ms",
                    "request latency, submit -> result", labels=lbl),
                "queue_wait": r.histogram(
                    "serve_queue_wait_ms",
                    "oldest-request wait before dispatch", labels=lbl),
                "exec": r.histogram(
                    "serve_exec_ms", "batch execute + host fetch",
                    labels=lbl),
                "requests": {o: r.counter(
                    "serve_requests_total", "requests by outcome",
                    labels={"model": model, "outcome": o})
                    for o in OUTCOMES},
                "depth": r.gauge(
                    "serve_queue_depth", "requests waiting to batch",
                    labels=lbl),
                "occupancy": r.gauge(
                    "serve_batch_occupancy_pct",
                    "last batch: real rows / bucket rows", labels=lbl),
                "waste": r.gauge(
                    "serve_padding_waste_pct",
                    "last batch: padded rows / bucket rows", labels=lbl),
                "batches": r.counter(
                    "serve_batches_total", "batches dispatched", labels=lbl),
                "slots": r.counter(
                    "serve_batch_slots_total", "bucket rows dispatched",
                    labels=lbl),
                "padded": r.counter(
                    "serve_padded_slots_total", "bucket rows that were pad",
                    labels=lbl),
                "violations": r.counter(
                    "serve_slo_violations_total",
                    "requests over the slo_ms target", labels=lbl),
            }
            self._models[model] = m
        return m

    # -- recording hooks (router calls these) -------------------------------

    def queue_depth(self, model: str, depth: int) -> None:
        self._m(model)["depth"].set(depth)

    def request_done(self, model: str, latency_ms: float,
                     outcome: str = "ok") -> None:
        m = self._m(model)
        m["requests"][outcome if outcome in OUTCOMES else "error"].inc()
        if outcome == "ok":
            m["latency"].observe(latency_ms)
            if self.slo_ms is not None and latency_ms > self.slo_ms:
                m["violations"].inc()

    def batch_done(self, model: str, bucket: int, size: int,
                   queue_wait_ms: float, exec_ms: float) -> None:
        m = self._m(model)
        m["batches"].inc()
        m["slots"].inc(bucket)
        m["padded"].inc(bucket - size)
        m["occupancy"].set(100.0 * size / bucket)
        m["waste"].set(100.0 * (bucket - size) / bucket)
        m["queue_wait"].observe(queue_wait_ms)
        m["exec"].observe(exec_ms)

    # -- reading back --------------------------------------------------------

    def report(self) -> Dict[str, dict]:
        """model -> {requests, errors, p50/p95/p99_ms, occupancy_pct,
        padding_waste_pct, batches, slo_violations}. Quantiles are
        bucket-resolution (Histogram.quantile): upper bound of the bucket
        holding the q-th observation, same contract as every other obs
        tail in the repo."""
        out: Dict[str, dict] = {}
        for model, m in sorted(self._models.items()):
            slots = m["slots"].value
            out[model] = {
                "requests": int(m["requests"]["ok"].value),
                "errors": int(m["requests"]["error"].value),
                "rejected": int(m["requests"]["rejected"].value),
                "cancelled": int(m["requests"]["cancelled"].value),
                "p50_ms": m["latency"].quantile(0.5),
                "p95_ms": m["latency"].quantile(0.95),
                "p99_ms": m["latency"].quantile(0.99),
                "mean_ms": m["latency"].mean,
                "batches": int(m["batches"].value),
                "occupancy_pct": (100.0 * (slots - m["padded"].value) / slots
                                  if slots else 0.0),
                "padding_waste_pct": (100.0 * m["padded"].value / slots
                                      if slots else 0.0),
                "slo_violations": int(m["violations"].value),
            }
        return out

    def render(self) -> str:
        """One aligned text block (the `serve_smoke` / operator view)."""
        rep = self.report()
        if not rep:
            return "slo: no serving traffic recorded"
        lines = []
        for model, r in rep.items():
            lines.append(
                f"{model}: {r['requests']} ok, {r['errors']} err  "
                f"latency mean {r['mean_ms']:.2f}ms "
                f"p50 {r['p50_ms']:.2f} p95 {r['p95_ms']:.2f} "
                f"p99 {r['p99_ms']:.2f}  "
                f"batches {r['batches']} "
                f"occupancy {r['occupancy_pct']:.1f}% "
                f"waste {r['padding_waste_pct']:.1f}%"
                + (f"  slo>{self.slo_ms:g}ms: {r['slo_violations']}"
                   if self.slo_ms is not None else ""))
        return "\n".join(lines)
