"""Finding record + baseline file format.

A baseline entry deliberately carries no line number: line drift from
unrelated edits must not resurrect an accepted finding, so matching is
on (code, path, symbol, message) with multiplicity — two identical
findings in one function need two baseline entries.
"""
from __future__ import annotations

import dataclasses
import json
from collections import Counter
from typing import Dict, List, Tuple

BASELINE_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Finding:
    code: str  # "DV001"
    message: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    severity: str  # "error" | "warning"
    symbol: str = ""  # enclosing function qualname, "" at module level

    def key(self) -> Tuple[str, str, str, str]:
        return (self.code, self.path, self.symbol, self.message)

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        where = f" (in {self.symbol})" if self.symbol else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.code} "
                f"[{self.severity}] {self.message}{where}")


def load_baseline(path: str) -> Counter:
    """Baseline file -> Counter of finding keys (missing file = empty)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return Counter()
    except json.JSONDecodeError as e:
        raise ValueError(f"{path}: baseline is not valid JSON: {e}")
    if not isinstance(doc, dict) or doc.get("version") != BASELINE_VERSION:
        raise ValueError(f"{path}: not a v{BASELINE_VERSION} jaxlint baseline")
    counts: Counter = Counter()
    for i, row in enumerate(doc.get("findings", [])):
        if not isinstance(row, dict) or \
                any(k not in row for k in ("code", "path", "message")):
            raise ValueError(
                f"{path}: findings[{i}] is missing code/path/message; "
                "regenerate with `make lint-baseline`")
        counts[(row["code"], row["path"], row.get("symbol", ""),
                row["message"])] += 1
    return counts


def save_baseline(path: str, findings: List[Finding]) -> None:
    rows = [
        {"code": f.code, "path": f.path, "symbol": f.symbol,
         "message": f.message}
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.code))
    ]
    with open(path, "w") as f:
        json.dump({"version": BASELINE_VERSION, "findings": rows}, f,
                  indent=2, sort_keys=False)
        f.write("\n")


def split_baselined(findings: List[Finding],
                    baseline: Counter) -> Tuple[List[Finding], List[Finding]]:
    """-> (new findings, baselined findings); consumes baseline entries so
    N accepted occurrences admit exactly N findings."""
    budget = Counter(baseline)
    fresh, accepted = [], []
    for f in findings:
        if budget[f.key()] > 0:
            budget[f.key()] -= 1
            accepted.append(f)
        else:
            fresh.append(f)
    return fresh, accepted
