"""Production inference serving: dynamic batching over an AOT-warmed
multi-model engine, with SLO accounting and drain semantics.

The "millions of users" leg of the roadmap: `inference.py`'s per-call
predictors become a server —

- `buckets`: the anti-recompile contract — coalesced requests round up
  to a small fixed menu of batch shapes and zero-pad the tail
  (`bucket_for`, `pad_batch`, `split_rows`).
- `queue`: `BatchingQueue`, max-wait/max-batch request coalescing with
  first-class drain (close -> flush-immediately -> None).
- `engine`: `Engine`, AOT `jax.jit(...).lower().compile()` of every
  (model, bucket) pair at startup, images donated on the inference
  path; `run()` refuses to compile at request time.
- `router`: `Server`, one queue+dispatcher per model over one device,
  request-scoped failure (`data.read` fault boundary), health-policy
  wiring, SIGTERM drain that flushes in-flight requests and dumps a
  `preempt` flight bundle.
- `slo`: `SLOTracker`, p50/p95/p99 request latency from the obs
  registry histograms plus queue-depth / batch-occupancy /
  padding-waste gauges.

Journal events: `serve_request`, `serve_batch`, `serve_drain` (schemas
in obs/README.md, validated by tools/check_journal.py). Trace spans:
`serve/warmup`, `serve/batch`, `serve/drain`. The CI teeth are
`make serve-smoke` (tools/serve_smoke.py) and tests/test_serve.py.
"""
from deep_vision_tpu.serve.buckets import (
    DEFAULT_BUCKETS,
    bucket_for,
    normalize_buckets,
    pad_batch,
    split_rows,
)
from deep_vision_tpu.serve.engine import Engine, ModelEntry, ServeError
from deep_vision_tpu.serve.queue import BatchingQueue, QueueClosed, Request
from deep_vision_tpu.serve.router import Server, ServerClosed
from deep_vision_tpu.serve.slo import SLOTracker

__all__ = [
    "BatchingQueue",
    "DEFAULT_BUCKETS",
    "Engine",
    "ModelEntry",
    "QueueClosed",
    "Request",
    "SLOTracker",
    "ServeError",
    "Server",
    "ServerClosed",
    "bucket_for",
    "normalize_buckets",
    "pad_batch",
    "split_rows",
]
