"""jaxlint engine: walk files, run rules, apply suppressions + baseline.

Per file: parse once, resolve the jit context once (lint/jitctx.py),
then every enabled rule runs over the shared ModuleCtx. Findings are
filtered through inline suppressions (`# jaxlint: disable=DVnnn`) and
then the checked-in baseline; only what survives both gates the exit
code.
"""
from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Set

from deep_vision_tpu.lint.findings import Finding
from deep_vision_tpu.lint.jitctx import JitContext, jax_random_aliases
from deep_vision_tpu.lint.rules import RULES

# `# jaxlint: disable=DV001` / `disable=DV001,DV005` / `disable=all`,
# optionally followed by `-- reason` (the reason is required by review
# convention, not enforced by the parser)
_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable=([A-Za-z0-9,_ ]+|all)(?:\s*--\s*(.*))?")


class ModuleCtx:
    """Everything the rules need about one parsed file."""

    def __init__(self, relpath: str, source: str, tree: ast.Module):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.jit = JitContext(tree)
        self.jax_random_aliases = jax_random_aliases(tree)
        self._symbols: Dict[int, str] = {}
        self._index_symbols(tree, "")

    def _index_symbols(self, node: ast.AST, qual: str) -> None:
        # every node maps to its innermost enclosing def/class qualname
        for child in ast.iter_child_nodes(node):
            self._symbols[id(child)] = qual
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                self._index_symbols(
                    child, f"{qual}.{child.name}" if qual else child.name)
            else:
                self._index_symbols(child, qual)

    def symbol_at(self, node: ast.AST) -> str:
        return self._symbols.get(id(node), "")

    def top_level_functions(self):
        """Function scopes that are not nested inside another function
        (methods included); nested defs are analyzed as part of their
        enclosing scope so closures share PRNG-key state."""
        out = []

        def rec(node, in_function: bool):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    if not in_function:
                        out.append(child)
                    rec(child, True)
                else:
                    rec(child, in_function)

        rec(self.tree, False)
        return out


def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """line number -> set of suppressed codes ('all' suppresses any).

    Tokenized, not line-scanned: a docstring that merely QUOTES the pragma
    syntax must not register a live suppression and punch a hole in the
    gate."""
    out: Dict[int, Set[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out  # unparseable files already fail the gate via DV000
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        raw = m.group(1).strip()
        if raw == "all":
            codes = {"all"}
        else:
            codes = {c.strip().upper() for c in raw.split(",") if c.strip()}
        i = tok.start[0]
        out.setdefault(i, set()).update(codes)
        # a pragma on its own line acknowledges the statement BELOW it; a
        # trailing pragma covers only its own line, so a new violation
        # added under it still fails the gate
        if not tok.line[:tok.start[1]].strip():
            out.setdefault(i + 1, set()).update(codes)
    return out


def _suppressed(f: Finding, supp: Dict[int, Set[str]]) -> bool:
    codes = supp.get(f.line)
    return bool(codes) and ("all" in codes or f.code in codes)


def lint_source(source: str, relpath: str,
                select: Optional[Iterable[str]] = None,
                disable: Optional[Iterable[str]] = None):
    """-> (findings, suppressed_findings). Parse errors come back as a
    single DV000 error finding so a syntax-broken file fails the gate
    rather than silently passing it."""
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError as e:
        return [Finding("DV000", f"file does not parse: {e.msg}", relpath,
                        e.lineno or 0, (e.offset or 0), "error")], []
    ctx = ModuleCtx(relpath, source, tree)
    enabled = set(select) if select else set(RULES)
    if disable:
        enabled -= set(disable)
    findings: List[Finding] = []
    for code in sorted(enabled):
        if code not in RULES:
            continue
        _, _, check, _ = RULES[code]
        findings.extend(check(ctx))
    findings.sort(key=lambda f: (f.line, f.col, f.code))
    supp = parse_suppressions(source)
    kept = [f for f in findings if not _suppressed(f, supp)]
    dropped = [f for f in findings if _suppressed(f, supp)]
    return kept, dropped


def iter_python_files(paths: Iterable[str],
                      exclude: Iterable[str] = (),
                      root: Optional[str] = None) -> List[str]:
    """Expand files/dirs into a sorted .py file list, skipping caches and
    any path whose `root`-relative form starts with an exclude prefix
    (so `tools` excludes tools/ but not deep_vision_tpu/tools/)."""
    out: List[str] = []
    root = os.path.abspath(root or os.getcwd())
    exclude = tuple(os.path.normpath(e).replace(os.sep, "/")
                    for e in exclude)

    def excluded(p: str) -> bool:
        rel = os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/")
        return any(rel == e or rel.startswith(e + "/") for e in exclude)

    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py") and not excluded(path):
                out.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__" and not d.startswith(".")]
            if excluded(dirpath):
                dirnames[:] = []
                continue
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    full = os.path.join(dirpath, fn)
                    if not excluded(full):
                        out.append(full)
    return out


def lint_paths(paths: Iterable[str], root: Optional[str] = None,
               select: Optional[Iterable[str]] = None,
               disable: Optional[Iterable[str]] = None,
               exclude: Iterable[str] = (),
               cache=None):
    """-> (findings, suppressed, n_files). Paths in findings are relative
    to `root` (default cwd) with forward slashes, so baselines are
    machine-portable. `cache` (lint/cache.py LintCache) short-circuits
    files whose (content, rule-pack) key already has a verdict."""
    root = os.path.abspath(root or os.getcwd())
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    for p in paths:
        if not os.path.exists(p):
            # a typo'd [tool.jaxlint] path must not silently disable the
            # gate by linting zero files
            rel = os.path.relpath(os.path.abspath(p), root).replace(
                os.sep, "/")
            findings.append(Finding(
                "DV000", "configured lint path does not exist", rel, 0, 0,
                "error"))
    files = iter_python_files(paths, exclude, root=root)
    for path in files:
        rel = os.path.relpath(os.path.abspath(path), root).replace(
            os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding("DV000", f"unreadable: {e}", rel, 0, 0,
                                    "error"))
            continue
        cached = cache.get(rel, source) if cache is not None else None
        if cached is not None:
            kept, dropped = cached
        else:
            kept, dropped = lint_source(source, rel, select=select,
                                        disable=disable)
            if cache is not None:
                cache.put(rel, source, kept, dropped)
        findings.extend(kept)
        suppressed.extend(dropped)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings, suppressed, len(files)
