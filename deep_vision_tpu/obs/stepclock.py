"""Step-time breakdown + recompile and HBM tracking.

Under JAX's async dispatch the wall time around a `train_step` call
measures *enqueue*, not compute — the reference's examples/sec print
(YOLO/tensorflow/train.py:217-223) and any naive timer conflate host
data-wait, dispatch, and device work. StepClock separates them:

  data_wait_ms   host blocked in the data iterator's next()
  dispatch_ms    host time to trace/shard/enqueue the step
  step_time_ms   full wall time of the step iteration (wait + dispatch)
  sync_ms        on sampled steps only: block_until_ready fence closing
                 the device pipeline — dispatch_ms + sync_ms on those
                 steps is the true per-step cost

The fence runs every `sample_every` steps (default 16) so steady-state
throughput stays async and unperturbed; between fences the device queue
absorbs the timing. Recompiles are counted process-wide from the
`/jax/core/compile/backend_compile_duration` monitoring event (fires per
backend compile, silent on cache hits — verified against jit cache
behavior in tests), HBM from `device.memory_stats()` where the backend
provides it (TPU yes, CPU None).
"""
from __future__ import annotations

import threading
import time
from typing import Iterable, Iterator, Optional

from deep_vision_tpu.obs.registry import Registry, get_registry

# -- recompile tracking ------------------------------------------------------

_compile_lock = threading.Lock()
_compile_events = 0
_compile_seconds = 0.0
_listener_installed = False


def _install_compile_listener() -> None:
    """Idempotent: jax.monitoring listeners cannot be individually removed,
    so exactly one module-level listener feeds a process-wide counter."""
    global _listener_installed
    with _compile_lock:
        if _listener_installed:
            return
        import jax

        def _on_duration(event: str, duration: float, **kw) -> None:
            global _compile_events, _compile_seconds
            if "backend_compile" in event:
                with _compile_lock:
                    _compile_events += 1
                    _compile_seconds += float(duration)

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _listener_installed = True


def recompile_count() -> int:
    """Backend compiles observed process-wide since the listener was
    installed (first StepClock construction or first explicit call)."""
    _install_compile_listener()
    return _compile_events


def compile_seconds() -> float:
    """Wall seconds the process spent in backend compiles, from the same
    monitoring listener as `recompile_count`. The goodput plane's
    compile feed: each step journal row carries the delta since the
    previous committed step as `compile_ms`, so offline attribution
    (obs/goodput.py) can carve compile time out of step gaps without a
    live listener."""
    _install_compile_listener()
    with _compile_lock:
        return _compile_seconds


def hbm_stats(device=None) -> "tuple[Optional[int], Optional[int]]":
    """(bytes_in_use, peak_bytes_in_use) for one device; None where the
    backend has no stats (CPU).

    The peak matters more than the instant: OOMs and fragmentation are
    high-water phenomena, an autoprof HBM trigger keyed on the
    instantaneous value would miss a transient allocation spike that
    freed before the sampled fence, and a postmortem wants the worst the
    run ever did — not where it happened to be when it died.
    """
    try:
        import jax

        dev = device or jax.local_devices()[0]
        stats = dev.memory_stats()
        if not stats:
            return None, None
        in_use = int(stats.get("bytes_in_use", stats.get("bytes_in_use_", 0)))
        peak = stats.get("peak_bytes_in_use")
        return in_use, (int(peak) if peak is not None else None)
    except Exception:
        return None, None


def hbm_bytes_in_use(device=None) -> Optional[int]:
    """Live device memory, or None where the backend has no stats (CPU)."""
    return hbm_stats(device)[0]


class StepClock:
    """Per-step timing harness around a host training loop.

    Usage (what Trainer._run_epoch does):

        clock.start_epoch()
        for batch in clock.iter_data(data):      # times next() = data wait
            with clock.step(batch_size=n) as rec:  # times dispatch
                out = train_step(batch)
                rec.fence_on(out)                # sampled block_until_ready
            journal fields: rec.fields()

    All timing is host-side perf_counter; the only device interaction is
    the sampled fence, and `examples_per_sec` is computed from the wall
    step time so it matches what an operator observes end to end.
    """

    def __init__(self, registry: Optional[Registry] = None,
                 journal=None, name: str = "train",
                 sample_every: int = 16, track_memory: bool = True):
        self.registry = registry or get_registry()
        self.journal = journal
        self.name = name
        self.sample_every = max(1, int(sample_every))
        self.track_memory = track_memory
        self._steps_seen = 0
        self._sync_samples = 0
        self._last_data_wait_ms = 0.0
        self._recompiles_at_start: Optional[int] = None
        _install_compile_listener()
        # compile-seconds high-water at construction: step rows carry the
        # delta since the previous committed step, so a clock built after
        # another run's compiles never re-attributes them
        self._compile_s_last = compile_seconds()

        r = self.registry
        self._g_data_wait = r.gauge(f"{name}_data_wait_ms",
                                    "host ms blocked on the data iterator")
        self._g_step = r.gauge(f"{name}_step_time_ms",
                               "wall ms per step (wait + dispatch)")
        self._g_eps = r.gauge(f"{name}_examples_per_sec",
                              "wall-clock examples/sec")
        self._g_recompiles = r.gauge("jit_recompiles_total",
                                     "backend compiles observed this process")
        self._g_hbm = r.gauge("hbm_bytes_in_use",
                              "device bytes in use (0 where unavailable)")
        self._g_hbm_peak = r.gauge(
            "hbm_peak_bytes_in_use",
            "device high-water bytes (0 where unavailable)")
        self._h_step = r.histogram(f"{name}_step_ms",
                                   "per-step wall ms distribution")
        self._h_wait = r.histogram(f"{name}_data_wait_ms_hist",
                                   "per-step data-wait ms distribution")
        self._c_steps = r.counter(f"{name}_steps_total", "steps executed")
        self._c_examples = r.counter(f"{name}_examples_total",
                                     "examples consumed")
        self._c_starved = r.counter(
            f"{name}_data_starved_steps_total",
            "steps whose data wait exceeded their dispatch time")

    # -- data-wait side ----------------------------------------------------

    def iter_data(self, data: Iterable) -> Iterator:
        """Wrap a batch iterable, timing each next() as data wait.

        With device_prefetch armed the iterable is the prefetcher's
        consumer side: next() blocks only until a device-placed batch is
        queued, so the producer thread's device_put time — overlapped
        with the previous step's compute — is hidden from this timer by
        construction. That is the goodput contract: those seconds are
        already inside the overlapped step's `step_time_ms`
        (productive), never double-counted as data_wait
        (tests/test_goodput.py pins this with a depth-2 prefetcher)."""
        it = iter(data)
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            self._last_data_wait_ms = (time.perf_counter() - t0) * 1e3
            yield batch

    # -- step side ---------------------------------------------------------

    def step(self, batch_size: int = 0,
             auto_commit: bool = True) -> "_StepRecord":
        """`auto_commit=False` defers the registry/journal write to an
        explicit `rec.commit(step=..., metrics=...)` AFTER the with-block,
        so host-side device fetches (optimizer step, LR) the caller makes
        between dispatch and logging count toward step_time_ms but never
        pollute dispatch_ms."""
        self._steps_seen += 1
        do_sample = (self._steps_seen % self.sample_every) == 0
        return _StepRecord(self, batch_size, self._last_data_wait_ms,
                           do_sample, auto_commit)

    def _finish(self, rec: "_StepRecord") -> None:
        self._c_steps.inc()
        if rec.batch_size:
            self._c_examples.inc(rec.batch_size)
        self._g_data_wait.set(rec.data_wait_ms)
        self._g_step.set(rec.step_time_ms)
        self._h_step.observe(rec.step_time_ms)
        self._h_wait.observe(rec.data_wait_ms)
        if rec.examples_per_sec is not None:
            self._g_eps.set(rec.examples_per_sec)
        if rec.data_wait_ms > rec.dispatch_ms:
            self._c_starved.inc()
        cs = compile_seconds()
        if cs > self._compile_s_last:
            rec.compile_ms = (cs - self._compile_s_last) * 1e3
            self._compile_s_last = cs
        if rec.sampled:
            self._sync_samples += 1
            n = recompile_count()
            self._g_recompiles.set(n)
            rec.recompiles = n
            if self.track_memory:
                hbm, peak = hbm_stats()
                if hbm is not None:
                    self._g_hbm.set(hbm)
                    rec.hbm_bytes = hbm
                if peak is not None:
                    self._g_hbm_peak.set(peak)
                    rec.hbm_peak_bytes = peak
        if self.journal is not None:
            self.journal.step(rec.step if rec.step is not None
                              else self._steps_seen, **rec.fields())

    @property
    def sync_samples(self) -> int:
        return self._sync_samples

    @property
    def steps_seen(self) -> int:
        return self._steps_seen


class _StepRecord:
    """Context manager for one step; collects the timing fields."""

    def __init__(self, clock: StepClock, batch_size: int,
                 data_wait_ms: float, sampled: bool, auto_commit: bool):
        self._clock = clock
        self.batch_size = batch_size
        self.data_wait_ms = data_wait_ms
        self.sampled = sampled
        self.step: Optional[int] = None  # caller may set the optimizer step
        self.metrics: dict = {}
        self.extra: dict = {}  # caller-supplied journal fields (e.g. the
                               # multistep width of a scan superstep)
        self.dispatch_ms = 0.0
        self.sync_ms: Optional[float] = None
        self.step_time_ms = 0.0
        self.examples_per_sec: Optional[float] = None
        self.recompiles: Optional[int] = None
        self.compile_ms: Optional[float] = None
        self.hbm_bytes: Optional[int] = None
        self.hbm_peak_bytes: Optional[int] = None
        self._t0 = 0.0
        self._fenced = None
        self._auto_commit = auto_commit
        self._committed = False

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def fence_on(self, out) -> None:
        """Hand the step's output here; on sampled steps it is fenced with
        block_until_ready so sync_ms captures the device pipeline drain."""
        self._fenced = out

    def __exit__(self, exc_type, exc, tb):
        self.dispatch_ms = (time.perf_counter() - self._t0) * 1e3
        if self.sampled and self._fenced is not None and exc_type is None:
            import jax

            t1 = time.perf_counter()
            jax.block_until_ready(self._fenced)
            self.sync_ms = (time.perf_counter() - t1) * 1e3
        if exc_type is None and self._auto_commit:
            self.commit()
        return False

    def commit(self, step: Optional[int] = None,
               metrics: Optional[dict] = None,
               extra: Optional[dict] = None) -> None:
        """Close the record and write registry/journal. step_time_ms spans
        enter -> commit, so deferred-commit callers fold their post-dispatch
        host fetches into the step total without widening dispatch_ms.
        `extra` fields ride the journal step event verbatim (unknown step
        fields are forward-compatible by the check_journal schema)."""
        if self._committed:
            return
        self._committed = True
        if step is not None:
            self.step = step
        if metrics is not None:
            self.metrics = metrics
        if extra:
            self.extra.update(extra)
        self.step_time_ms = self.data_wait_ms + (
            time.perf_counter() - self._t0) * 1e3
        if self.batch_size and self.step_time_ms > 0:
            self.examples_per_sec = self.batch_size / self.step_time_ms * 1e3
        self._clock._finish(self)

    def fields(self) -> dict:
        out = {
            "step_time_ms": round(self.step_time_ms, 3),
            "data_wait_ms": round(self.data_wait_ms, 3),
            "dispatch_ms": round(self.dispatch_ms, 3),
        }
        if self.examples_per_sec is not None:
            out["examples_per_sec"] = round(self.examples_per_sec, 2)
        if self.sync_ms is not None:
            out["sync_ms"] = round(self.sync_ms, 3)
        if self.recompiles is not None:
            out["recompiles"] = self.recompiles
        if self.compile_ms is not None:
            out["compile_ms"] = round(self.compile_ms, 3)
        if self.hbm_bytes is not None:
            out["hbm_bytes"] = self.hbm_bytes
        if self.hbm_peak_bytes is not None:
            out["hbm_peak_bytes"] = self.hbm_peak_bytes
        if self.extra:
            out.update(self.extra)
        if self.metrics:
            out["metrics"] = {k: float(v) for k, v in self.metrics.items()}
        return out
