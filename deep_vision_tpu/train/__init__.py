from deep_vision_tpu.train.optimizers import build_optimizer, ReduceLROnPlateau
from deep_vision_tpu.train.trainer import Trainer
