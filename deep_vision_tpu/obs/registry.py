"""Metrics registry: counters, gauges, log-scale histograms.

Dependency-free by design (like core/tensorboard.py): no prometheus_client,
no jax at import time. Metrics are plain host-side objects safe to touch
from data-loader threads; exporters render the whole registry as
Prometheus text exposition format or as one JSONL snapshot line, and both
writers are process-0-only so a multi-host run produces one file, not N.
"""
from __future__ import annotations

import json
import math
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple


def is_primary_host() -> bool:
    """True when this process should own file writers (process 0).

    Lazy jax import: the registry is also used from spawned data workers
    where importing jax would drag in a backend.
    """
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


def process_suffix() -> str:
    """'.pN' when this process is part of a multi-process run, else ''.

    The multi-host observability contract (journal/trace/flight): with
    more than one `jax.process_count()` every host writes its OWN file at
    `<path>.p<index>` — a follower's telemetry must survive the follower,
    and a shared file would interleave hosts mid-line. Single-process runs
    keep the plain path, so nothing changes for the common case. Lazy jax
    import, like is_primary_host: data workers must not drag in a backend.
    """
    try:
        import jax

        if jax.process_count() > 1:
            return f".p{jax.process_index()}"
    except Exception:
        pass
    return ""


def _fmt_labels(labels: Optional[dict]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    # finiteness first: int(NaN) raises, and a NaN gauge at export time
    # must render (Prometheus accepts the NaN token), not crash the export
    if not math.isfinite(v):
        if v != v:
            return "NaN"
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def default_log_buckets(lo: float = 1e-3, hi: float = 1e5,
                        per_decade: int = 3) -> List[float]:
    """Log-spaced bucket upper bounds covering [lo, hi]."""
    n = int(round(math.log10(hi / lo) * per_decade))
    return [lo * 10 ** (i / per_decade) for i in range(n + 1)]


class Counter:
    """Monotonically increasing count (Prometheus counter semantics)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", labels: Optional[dict] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def to_prometheus(self) -> List[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} {_fmt_value(self._value)}"]

    def snapshot(self):
        return self._value


class Gauge:
    """Point-in-time value (may go up or down)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "", labels: Optional[dict] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value

    def to_prometheus(self) -> List[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} {_fmt_value(self._value)}"]

    def snapshot(self):
        return self._value


class Histogram:
    """Cumulative-bucket histogram with log-scale default bounds.

    Step times, data waits, and request latencies span 4+ decades across
    models and hosts — linear buckets would waste resolution at one end;
    the default is 3 buckets per decade from 1e-3 to 1e5 (ms scale).
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Iterable[float]] = None,
                 labels: Optional[dict] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        bounds = sorted(buckets) if buckets else default_log_buckets()
        self.bounds: List[float] = list(bounds)
        self._counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        # linear scan: bucket lists are ~25 long and observe() is host-side
        # once per step/request, far off any hot path
        i = 0
        while i < len(self.bounds) and v > self.bounds[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile estimate (upper bound of the bucket
        holding the q-th observation)."""
        if not self._count:
            return 0.0
        target = q * self._count
        seen = 0
        for i, c in enumerate(self._counts):
            seen += c
            if seen >= target:
                return self.bounds[i] if i < len(self.bounds) else math.inf
        return math.inf

    def to_prometheus(self) -> List[str]:
        lines = []
        cumulative = 0
        for bound, c in zip(self.bounds, self._counts):
            cumulative += c
            lb = dict(self.labels, le=_fmt_value(bound))
            lines.append(f"{self.name}_bucket{_fmt_labels(lb)} {cumulative}")
        lb = dict(self.labels, le="+Inf")
        lines.append(f"{self.name}_bucket{_fmt_labels(lb)} {self._count}")
        lines.append(
            f"{self.name}_sum{_fmt_labels(self.labels)} {_fmt_value(self._sum)}"
        )
        lines.append(
            f"{self.name}_count{_fmt_labels(self.labels)} {self._count}"
        )
        return lines

    def snapshot(self):
        # quantiles above the top bucket are +Inf, which json.dumps would
        # emit as the non-standard `Infinity` token; None keeps the JSONL
        # strict-parser clean (jq, JSON.parse)
        def finite(v):
            return v if math.isfinite(v) else None

        return {
            "count": self._count,
            "sum": self._sum,
            "mean": self.mean,
            "p50": finite(self.quantile(0.5)),
            "p99": finite(self.quantile(0.99)),
        }


class Registry:
    """Named metric store with get-or-create accessors and exporters."""

    def __init__(self):
        self._metrics: Dict[Tuple[str, Tuple], object] = {}
        # the registry-level get-or-create lock is locksmith-named; the
        # per-metric leaf locks (Counter/Gauge/Histogram) stay raw
        # threading.Locks on purpose — they guard single arithmetic ops on
        # the hottest paths, never nest, and carry no ordering information
        from deep_vision_tpu.obs import locksmith

        self._lock = locksmith.lock("obs.registry")

    def _get_or_create(self, cls, name: str, help: str,
                       labels: Optional[dict], **kw):
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help=help, labels=labels, **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}"
                )
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[dict] = None) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[dict] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Iterable[float]] = None,
                  labels: Optional[dict] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def metrics(self) -> List[object]:
        with self._lock:
            return list(self._metrics.values())

    # -- exporters ---------------------------------------------------------

    def to_prometheus(self) -> str:
        """Prometheus text exposition format: one HELP/TYPE block per
        metric family with ALL its label variants contiguous under it —
        the spec forbids a family's lines being interleaved with another's
        (creation order would do that, e.g. latency{task=a}, requests,
        latency{task=b})."""
        families: Dict[str, List[object]] = {}
        for m in self.metrics():
            families.setdefault(m.name, []).append(m)
        lines: List[str] = []
        for name, members in families.items():
            head = members[0]
            if head.help:
                lines.append(f"# HELP {name} {head.help}")
            lines.append(f"# TYPE {name} {head.kind}")
            for m in members:
                lines.extend(m.to_prometheus())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        out: dict = {}
        for m in self.metrics():
            key = m.name + _fmt_labels(m.labels)
            out[key] = m.snapshot()
        return out

    def write_prometheus(self, path: str) -> bool:
        """Atomic-ish whole-file write; process-0-only. Returns written."""
        if not is_primary_host():
            return False
        import os

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_prometheus())
        os.replace(tmp, path)
        return True

    def append_jsonl_snapshot(self, path: str, **extra) -> bool:
        """Append one snapshot line (timestamped); process-0-only."""
        if not is_primary_host():
            return False
        import os

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        row = {"ts": time.time(), "metrics": self.snapshot()}
        row.update(extra)
        with open(path, "a") as f:
            f.write(json.dumps(row) + "\n")
        return True


_DEFAULT = Registry()


def get_registry() -> Registry:
    """The process-wide default registry (trainer, data, inference all
    report here unless handed an explicit one)."""
    return _DEFAULT
