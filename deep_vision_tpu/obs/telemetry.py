"""Live telemetry plane: per-process HTTP /metrics, /healthz, /statusz, /varz.

Everything obs/ built so far is post-hoc — files you read after the
run dies. The `TelemetryServer` is the live half: a stdlib
`http.server` on a daemon thread inside every process that wants to be
watched (trainer, serve router/pool, data service), serving:

    GET /metrics   Prometheus text exposition (Registry.to_prometheus)
    GET /varz      JSON metrics snapshot (Registry.snapshot)
    GET /healthz   200/503 readiness verdict aggregated over pluggable
                   health sources (HealthMonitor state, rendezvous
                   lease freshness, serve drain state, ...)
    GET /statusz   JSON (or ?format=html) status page: run manifest,
                   per-source status sections (step/epoch, generation,
                   replica states), excache ledger, last N journal
                   events from the flight recorder's ring
    GET /alertz    JSON state of the attached obs/alerts.py AlertEngine
                   (set_alerts): active alerts, fired->resolved
                   history, rule inventory — empty lists when no
                   engine is attached

Discovery: the server binds port 0 by default (auto-assign), journals
the bound port as a typed `telemetry_server` event, and writes a
discovery file `telemetry-<role>-<pid>.json` under the run dir so
`tools/obs_poll.py` (and any launcher) can find every process of a run
without configuration.

Contracts, enforced by tests/test_telemetry.py:
- stdlib only, no jax at import time, and nothing here may touch a
  device: every handler reads host-side state (registry objects,
  journal ring copies, plain callables), so a scrape can never hold
  the registry lock across a device fence or force a sync;
- telemetry must degrade, never kill the run it observes: a broken
  status/health source renders as an error entry (and flips /healthz
  to 503 — a probe you cannot evaluate is not a passing probe), it
  does not 500 the whole page or propagate into the training loop;
- registration is pluggable and idempotent by name, so a respawned
  serve replica re-registers over its dead predecessor's slot and the
  endpoint survives the respawn.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from deep_vision_tpu.obs import locksmith

__all__ = ["TelemetryServer", "TELEMETRY_OUTCOMES", "validate_prometheus"]

# outcomes of the typed `telemetry_server` journal event — kept in sync
# with tools/check_journal.py by a drift-guard test
TELEMETRY_OUTCOMES = ("started", "stopped", "failed")

DISCOVERY_PREFIX = "telemetry-"

# a health source: () -> (ok, detail-dict); a status source: () -> dict
HealthSource = Callable[[], Tuple[bool, dict]]
StatusSource = Callable[[], dict]


class TelemetryServer:
    """One process's live observability endpoint.

    Construction wires what exists; anything absent just leaves its
    section empty (a data worker has no flight recorder, a bare test
    has no journal). `start()` binds and journals; `close()` is
    idempotent and removes the discovery file.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 role: str = "process", registry=None, journal=None,
                 flight=None, discovery_dir: Optional[str] = None,
                 tail_n: int = 32):
        self.role = str(role)
        self.registry = registry
        self.journal = journal
        self.flight = flight
        self.discovery_dir = discovery_dir
        self.tail_n = int(tail_n)
        self._want_host = host
        self._want_port = int(port)
        # sources are registered from trainer/pool/service threads and
        # read from handler threads — locksmith-named like every other
        # cross-thread obs structure
        self._lock = locksmith.lock("obs.telemetry")
        self._health: Dict[str, HealthSource] = {}
        self._status: Dict[str, StatusSource] = {}
        self._alerts = None  # AlertEngine (obs/alerts.py) via set_alerts
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._discovery_path: Optional[str] = None
        self._t_start: Optional[float] = None
        self._closed = False

    # -- registration (idempotent by name) --------------------------------

    def add_health(self, name: str, fn: HealthSource) -> None:
        """Register/replace a readiness probe. Replacing is the respawn
        story: a fresh replica (or a fresh HealthMonitor after an
        aborted run) takes over its predecessor's slot by name."""
        with self._lock:
            self._health[str(name)] = fn

    def add_status(self, name: str, fn: StatusSource) -> None:
        with self._lock:
            self._status[str(name)] = fn

    def remove(self, name: str) -> None:
        with self._lock:
            self._health.pop(str(name), None)
            self._status.pop(str(name), None)

    def set_alerts(self, engine) -> None:
        """Attach an obs/alerts.py AlertEngine: `/alertz` serves its
        state, and the "alerts" health source fails while any
        page-severity alert is firing — a burning error budget flips
        /healthz exactly like a failing readiness probe. Idempotent by
        the same replace-on-respawn story as add_health."""
        with self._lock:
            self._alerts = engine
        self.add_health("alerts", self._alerts_health)

    def _alerts_health(self) -> Tuple[bool, dict]:
        with self._lock:
            engine = self._alerts
        if engine is None:
            return True, {"active": 0}
        active = engine.active()
        paging = [a["rule"] for a in active
                  if a.get("severity") == "page"]
        return (not paging,
                {"active": len(active), "paging": paging})

    def alertz(self) -> dict:
        """The /alertz body: the engine's event-time state (active
        alerts, fired->resolved history, rule inventory). An endpoint
        with no engine answers with empty lists — pollable either way."""
        with self._lock:
            engine = self._alerts
        if engine is None:
            return {"now": None, "active": [], "history": [], "rules": []}
        return _jsonable(engine.alertz())

    # -- lifecycle ---------------------------------------------------------

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def host(self) -> str:
        return self._want_host

    @property
    def address(self) -> Optional[str]:
        return f"{self.host}:{self.port}" if self._httpd else None

    def start(self) -> "TelemetryServer":
        if self._httpd is not None:
            return self
        try:
            httpd = ThreadingHTTPServer(
                (self._want_host, self._want_port), _Handler)
        except OSError as e:
            self._journal_event("failed", port=self._want_port,
                               error=f"{type(e).__name__}: {e}")
            raise
        httpd.daemon_threads = True
        httpd.telemetry = self  # handler backref
        self._httpd = httpd
        self._t_start = time.time()
        self._thread = threading.Thread(
            target=httpd.serve_forever, name=f"telemetry-{self.role}",
            daemon=True)
        self._thread.start()
        self._journal_event("started", port=self.port)
        self._write_discovery()
        return self

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        port = httpd.server_address[1]
        try:
            httpd.shutdown()
            httpd.server_close()
        except Exception:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self._discovery_path:
            try:
                os.remove(self._discovery_path)
            except OSError:
                pass
        self._journal_event("stopped", port=port)

    def _journal_event(self, outcome: str, port: int, **extra) -> None:
        assert outcome in TELEMETRY_OUTCOMES
        if self.journal is not None:
            self.journal.write("telemetry_server", host=self._want_host,
                               port=int(port), outcome=outcome,
                               role=self.role, pid=os.getpid(), **extra)

    def _write_discovery(self) -> None:
        if not self.discovery_dir:
            return
        try:
            os.makedirs(self.discovery_dir, exist_ok=True)
            path = os.path.join(
                self.discovery_dir,
                f"{DISCOVERY_PREFIX}{self.role}-{os.getpid()}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"host": self.host, "port": self.port,
                           "pid": os.getpid(), "role": self.role,
                           "run_id": getattr(self.journal, "run_id", None),
                           "ts": time.time()}, f)
            os.replace(tmp, path)
            self._discovery_path = path
        except OSError:
            # telemetry degrades: the endpoint still answers, it is
            # just not discoverable from the run dir
            self._discovery_path = None

    # -- endpoint bodies (called from handler threads) ---------------------

    def metrics_text(self) -> str:
        if self.registry is None:
            return ""
        return self.registry.to_prometheus()

    def varz(self) -> dict:
        return self.registry.snapshot() if self.registry is not None else {}

    def healthz(self) -> Tuple[bool, dict]:
        """Aggregate verdict: every registered source must pass. A
        source that raises counts as failing — an unevaluable probe is
        not a passing probe."""
        with self._lock:
            sources = list(self._health.items())
        checks: Dict[str, dict] = {}
        ok_all = True
        for name, fn in sources:
            try:
                ok, detail = fn()
                entry = dict(detail or {})
                entry["ok"] = bool(ok)
            except Exception as e:
                entry = {"ok": False,
                         "error": f"{type(e).__name__}: {e}"}
            checks[name] = entry
            ok_all = ok_all and entry["ok"]
        return ok_all, {"ok": ok_all, "role": self.role, "checks": checks}

    def statusz(self) -> dict:
        with self._lock:
            sources = list(self._status.items())
        status: Dict[str, dict] = {}
        for name, fn in sources:
            try:
                status[name] = _jsonable(fn())
            except Exception as e:
                status[name] = {"error": f"{type(e).__name__}: {e}"}
        ok, health = self.healthz()
        out = {
            "role": self.role,
            "pid": os.getpid(),
            "address": self.address,
            "run_id": getattr(self.journal, "run_id", None),
            "uptime_s": (round(time.time() - self._t_start, 3)
                         if self._t_start else None),
            "healthy": ok,
            "health": health,
            "status": status,
            "excache": self._excache_ledger(),
            "manifest": self._manifest(),
            "recent_events": self._recent_events(),
        }
        return out

    def _manifest(self) -> Optional[dict]:
        fn = getattr(self.journal, "manifest_row", None)
        return fn() if callable(fn) else None

    def _excache_ledger(self) -> dict:
        """The executable-cache hit ledger, pulled from the registry by
        name — the cache reports there already, so statusz needs no
        direct handle on the cache object."""
        if self.registry is None:
            return {}
        snap = self.registry.snapshot()
        return {k: v for k, v in snap.items() if k.startswith("excache_")}

    def _recent_events(self) -> List[dict]:
        if self.flight is None:
            return []
        tail = getattr(self.flight, "tail", None)
        if not callable(tail):
            return []
        try:
            return [_jsonable(r) for r in tail(self.tail_n)]
        except Exception:
            return []


def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        if isinstance(v, dict):
            return {str(k): _jsonable(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [_jsonable(x) for x in v]
        return repr(v)


class _Handler(BaseHTTPRequestHandler):
    """Route table for the four endpoints. Every handler body reads
    host-side state only — no jax, no device syncs, no blocking on the
    training loop."""

    server_version = "dvt-telemetry/1"
    protocol_version = "HTTP/1.1"

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        tele: TelemetryServer = self.server.telemetry
        parsed = urlparse(self.path)
        route = parsed.path.rstrip("/") or "/"
        try:
            if route == "/metrics":
                self._send(200, "text/plain; version=0.0.4",
                           tele.metrics_text())
            elif route == "/varz":
                self._send_json(200, tele.varz())
            elif route == "/healthz":
                ok, body = tele.healthz()
                self._send_json(200 if ok else 503, body)
            elif route == "/alertz":
                self._send_json(200, tele.alertz())
            elif route == "/statusz":
                body = tele.statusz()
                fmt = parse_qs(parsed.query).get("format", ["json"])[0]
                if fmt == "html":
                    self._send(200, "text/html; charset=utf-8",
                               _statusz_html(body))
                else:
                    self._send_json(200, body)
            elif route == "/":
                self._send(200, "text/plain",
                           "endpoints: /metrics /varz /healthz /statusz "
                           "/alertz\n")
            else:
                self._send(404, "text/plain", f"no such page: {route}\n")
        except Exception as e:
            # last-resort guard: a handler bug must answer 500, not
            # wedge the client or kill the serving thread
            try:
                self._send(500, "text/plain",
                           f"telemetry error: {type(e).__name__}: {e}\n")
            except Exception:
                pass

    def _send(self, code: int, ctype: str, body: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code: int, obj) -> None:
        self._send(code, "application/json",
                   json.dumps(obj, indent=1, default=repr) + "\n")

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


def _statusz_html(body: dict) -> str:
    """Minimal human view: headings + pre-formatted JSON per section.
    Operators curl the JSON; the HTML exists for a browser glance."""
    verdict = "HEALTHY" if body.get("healthy") else "UNHEALTHY"
    color = "#2a7" if body.get("healthy") else "#c33"
    parts = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        f"<title>statusz — {body.get('role')}</title></head>",
        "<body style='font-family:monospace'>",
        f"<h1>{body.get('role')} @ {body.get('address')} "
        f"<span style='color:{color}'>[{verdict}]</span></h1>",
        f"<p>pid {body.get('pid')} · run {body.get('run_id')} · "
        f"up {body.get('uptime_s')}s</p>",
    ]
    for section in ("status", "health", "excache", "manifest",
                    "recent_events"):
        parts.append(f"<h2>{section}</h2><pre>"
                     + _escape(json.dumps(body.get(section), indent=1,
                                          default=repr))
                     + "</pre>")
    parts.append("</body></html>")
    return "".join(parts)


def _escape(s: str) -> str:
    return s.replace("&", "&amp;").replace("<", "&lt;").replace(">", "&gt;")


# -- Prometheus text validation (shared by tests and live_smoke) -----------

_PROM_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? \S+$")
_PROM_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}


def validate_prometheus(text: str) -> List[str]:
    """Sanity-check Prometheus text exposition format. Returns a list
    of problems (empty = parses). Not a full spec parser — it enforces
    what our exporter promises: well-formed sample lines with numeric
    values, known TYPE tokens, and family lines contiguous under one
    TYPE block (the spec forbids interleaving)."""
    problems: List[str] = []
    typed: Dict[str, str] = {}
    seen_families: List[str] = []
    current_family: Optional[str] = None
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            bits = line.split(None, 3)
            if len(bits) < 4 or bits[3] not in _PROM_TYPES:
                problems.append(f"line {i}: bad TYPE line: {line!r}")
                continue
            family = bits[2]
            if family in typed:
                problems.append(
                    f"line {i}: duplicate TYPE for family {family!r} "
                    "(families must be contiguous)")
            typed[family] = bits[3]
            seen_families.append(family)
            current_family = family
            continue
        if line.startswith("#"):
            continue
        m = _PROM_SAMPLE_RE.match(line)
        if not m:
            problems.append(f"line {i}: unparseable sample: {line!r}")
            continue
        name = line.split("{", 1)[0].split(" ", 1)[0]
        value = line.rsplit(" ", 1)[1]
        if value not in ("NaN", "+Inf", "-Inf"):
            try:
                float(value)
            except ValueError:
                problems.append(f"line {i}: non-numeric value {value!r}")
        base = current_family
        if base is None or not name.startswith(base):
            problems.append(
                f"line {i}: sample {name!r} outside its family's TYPE "
                f"block (current family: {base!r})")
    return problems


def read_discovery(run_dir: str) -> List[dict]:
    """Parse every discovery file under `run_dir` (non-recursive).
    Unreadable/garbled files are skipped — a process that died mid-write
    must not break discovery of its siblings."""
    out: List[dict] = []
    try:
        names = sorted(os.listdir(run_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith(DISCOVERY_PREFIX)
                and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(run_dir, name)) as f:
                row = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(row, dict) and row.get("port"):
            row["discovery_file"] = name
            out.append(row)
    return out
