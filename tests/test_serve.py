"""serve/ tier-1 suite: bucket/padding correctness, the zero-recompile
contract, max-wait flush timing, drain semantics (including SIGTERM +
flight bundle), and request-scoped fault degradation.

Runs on a pure-jnp toy model so the whole stack (queue -> bucket ->
AOT engine -> router -> slo/journal) exercises in CPU-tier time; the
real YOLO/pose router is `make serve-smoke` (tools/serve_smoke.py).
"""
import json
import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.obs import RunJournal, read_journal
from deep_vision_tpu.obs.registry import Registry
from deep_vision_tpu.obs.stepclock import recompile_count
from deep_vision_tpu.resilience import FaultInjected, faults
from deep_vision_tpu.serve import (
    BatchingQueue,
    Engine,
    Request,
    ServeError,
    Server,
    ServerClosed,
    bucket_for,
    normalize_buckets,
    pad_batch,
    split_rows,
)

IMG = (4, 4, 1)


def toy_fn(variables, images):
    flat = images.reshape((images.shape[0], -1))
    return {"scores": flat @ variables["w"],
            "mean": images.mean(axis=(1, 2, 3))}


def toy_variables(seed=0):
    w = np.random.RandomState(seed).randn(16, 3).astype(np.float32)
    return {"w": jnp.asarray(w)}


def make_engine(buckets=(1, 2, 4), registry=None, journal=None, seed=0):
    eng = Engine(registry=registry or Registry(), journal=journal)
    eng.register("toy", toy_fn, toy_variables(seed), input_shape=IMG,
                 buckets=buckets)
    return eng


def images(n, seed=1):
    rng = np.random.RandomState(seed)
    return [rng.rand(*IMG).astype(np.float32) for _ in range(n)]


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.install(None)
    os.environ.pop(faults.ENV_SPEC, None)
    os.environ.pop(faults.ENV_SEED, None)


@pytest.fixture
def journal(tmp_path):
    j = RunJournal(str(tmp_path / "serve.jsonl"), kind="serve")
    yield j
    if not j._closed:
        j.close()


def strict_errors(path):
    from tools.check_journal import check_journal

    return check_journal(path, strict=True)


# -- buckets -----------------------------------------------------------------

class TestBuckets:
    def test_bucket_for_rounds_up(self):
        buckets = (1, 2, 4, 8)
        assert bucket_for(1, buckets) == 1
        assert bucket_for(2, buckets) == 2
        assert bucket_for(3, buckets) == 4
        assert bucket_for(5, buckets) == 8
        assert bucket_for(8, buckets) == 8
        assert bucket_for(9, buckets) is None

    def test_normalize_rejects_garbage(self):
        assert normalize_buckets([4, 1, 4, 2]) == (1, 2, 4)
        with pytest.raises(ValueError):
            normalize_buckets([])
        with pytest.raises(ValueError):
            normalize_buckets([0, 2])

    def test_pad_batch_contents_and_padding(self):
        ims = images(3)
        arr = pad_batch(ims, 4)
        assert arr.shape == (4,) + IMG
        for i, im in enumerate(ims):
            np.testing.assert_array_equal(arr[i], im)
        np.testing.assert_array_equal(arr[3], np.zeros(IMG, np.float32))

    def test_pad_batch_rejects_overflow_and_mixed_shapes(self):
        with pytest.raises(ValueError):
            pad_batch(images(5), 4)
        with pytest.raises(ValueError):
            pad_batch([np.zeros(IMG, np.float32),
                       np.zeros((2, 2, 1), np.float32)], 4)
        with pytest.raises(ValueError):
            pad_batch([], 4)

    def test_split_rows_drops_padding(self):
        tree = {"a": np.arange(8).reshape(4, 2), "b": np.arange(4)}
        rows = split_rows(tree, 3)
        assert len(rows) == 3
        np.testing.assert_array_equal(rows[1]["a"], [2, 3])
        assert rows[2]["b"] == 2


# -- engine ------------------------------------------------------------------

class TestEngine:
    def test_warmup_compiles_every_pair_exactly_once(self):
        eng = make_engine(buckets=(1, 2, 4))
        stats = eng.warmup()
        assert stats["pairs"] == 3
        # the AOT contract: one backend compile per (model, bucket) pair,
        # nothing eager slipping in at trace time
        assert stats["backend_compiles"] == 3
        assert sorted(eng.warmed_buckets("toy")) == [1, 2, 4]

    def test_padded_equals_unpadded_reference(self):
        eng = make_engine(buckets=(4,))
        eng.warmup()
        ims = images(3)
        out = jax.device_get(eng.run("toy", pad_batch(ims, 4)))
        ref = jax.device_get(
            toy_fn(toy_variables(), jnp.asarray(np.stack(ims))))
        np.testing.assert_allclose(out["scores"][:3], ref["scores"],
                                   rtol=1e-6)
        np.testing.assert_allclose(out["mean"][:3], ref["mean"], rtol=1e-6)

    def test_zero_recompiles_after_warmup(self):
        eng = make_engine(buckets=(1, 2, 4))
        eng.warmup()
        c0 = recompile_count()
        for n in (1, 2, 4, 2, 1, 4):
            eng.run("toy", pad_batch(images(n), n))
        assert recompile_count() == c0, \
            "serving mixed warmed shapes must never touch the compiler"

    def test_unwarmed_bucket_refuses_to_compile(self):
        eng = make_engine(buckets=(1, 2))
        eng.warmup()
        with pytest.raises(ServeError, match="no warmed bucket"):
            eng.run("toy", np.zeros((3,) + IMG, np.float32))

    def test_unknown_model_and_late_register(self):
        eng = make_engine()
        with pytest.raises(ServeError, match="unknown model"):
            eng.entry("nope")
        eng.warmup()
        with pytest.raises(ServeError, match="after warmup"):
            eng.register("late", toy_fn, toy_variables(), IMG)

    def test_start_before_warmup_refused(self):
        with pytest.raises(ServeError, match="warmup"):
            Server(make_engine()).start()


# -- batching queue ----------------------------------------------------------

class TestBatchingQueue:
    def test_coalesces_to_max_batch(self):
        q = BatchingQueue(max_batch=4, max_wait_ms=5000)
        for _ in range(6):
            q.submit(Request("m", None))
        t0 = time.perf_counter()
        batch = q.next_batch()
        # max_batch reached: no max-wait lingering
        assert time.perf_counter() - t0 < 1.0
        assert len(batch) == 4
        assert q.depth == 2

    def test_max_wait_flushes_partial_batch(self):
        q = BatchingQueue(max_batch=8, max_wait_ms=40)
        q.submit(Request("m", None))
        t0 = time.perf_counter()
        batch = q.next_batch()
        elapsed = time.perf_counter() - t0
        assert len(batch) == 1
        # lower bound is the contract (a request waits for company up to
        # max_wait); the upper bound is loose for CI schedulers
        assert 0.02 <= elapsed < 5.0

    def test_close_flushes_immediately_then_none(self):
        q = BatchingQueue(max_batch=4, max_wait_ms=60_000)
        for _ in range(2):
            q.submit(Request("m", None))
        q.close()
        t0 = time.perf_counter()
        assert len(q.next_batch()) == 2
        assert q.next_batch() is None
        assert time.perf_counter() - t0 < 1.0, "drain must not linger"
        with pytest.raises(Exception):
            q.submit(Request("m", None))


# -- server ------------------------------------------------------------------

class TestServer:
    def _server(self, journal=None, registry=None, **kw):
        eng = make_engine(buckets=(1, 2, 4), registry=registry,
                          journal=journal)
        eng.warmup()
        kw.setdefault("max_wait_ms", 3.0)
        srv = Server(eng, journal=journal, registry=registry, **kw)
        srv.start()
        return srv

    def test_round_trip_matches_reference(self, journal):
        srv = self._server(journal=journal)
        try:
            ims = images(5)
            futs = [srv.submit("toy", im) for im in ims]
            rows = [f.result(timeout=30) for f in futs]
            ref = jax.device_get(
                toy_fn(toy_variables(), jnp.asarray(np.stack(ims))))
            for i, row in enumerate(rows):
                np.testing.assert_allclose(row["scores"], ref["scores"][i],
                                           rtol=1e-6)
        finally:
            srv.close()
        journal.close()
        events = read_journal(journal.path)
        kinds = [e["event"] for e in events]
        assert kinds.count("serve_request") == 5
        assert "serve_batch" in kinds
        drain = next(e for e in events if e["event"] == "serve_drain")
        assert drain["reason"] == "close"
        assert drain["outcome"] == "flushed"
        assert drain["completed"] == 5
        assert strict_errors(journal.path) == []

    def test_zero_recompiles_through_server_path(self):
        srv = self._server()
        try:
            c0 = recompile_count()
            for burst in (1, 3, 2, 4, 1):
                futs = [srv.submit("toy", im) for im in images(burst)]
                for f in futs:
                    f.result(timeout=30)
            assert recompile_count() == c0
        finally:
            srv.close()

    def test_fault_degrades_one_request_not_the_server(self, journal):
        srv = self._server(journal=journal)
        try:
            # deterministic Nth-hit form: exactly the 2nd data.read fails
            faults.install_spec("data.read:io_error@2", seed=3,
                                journal=journal, export_env=False)
            futs = [srv.submit("toy", im) for im in images(3)]
            with pytest.raises(FaultInjected):
                futs[1].result(timeout=30)
            for f in (futs[0], futs[2]):
                assert f.result(timeout=30)["scores"].shape == (3,)
            faults.install(None)
            # the server keeps answering after the fault
            assert srv.submit(
                "toy", images(1)[0]).result(timeout=30) is not None
        finally:
            srv.close()
        journal.close()
        events = read_journal(journal.path)
        assert any(e["event"] == "fault" and e["point"] == "data.read"
                   for e in events)
        outcomes = [e["outcome"] for e in events
                    if e["event"] == "serve_request"]
        assert outcomes.count("error") == 1
        assert outcomes.count("ok") == 3
        assert strict_errors(journal.path) == []

    def test_bad_shape_fails_request_only(self):
        srv = self._server()
        try:
            bad = srv.submit("toy", np.zeros((2, 2, 1), np.float32))
            with pytest.raises(ServeError, match="request shape"):
                bad.result(timeout=30)
            ok = srv.submit("toy", images(1)[0])
            assert ok.result(timeout=30) is not None
        finally:
            srv.close()

    def test_cancelled_future_balances_the_books(self, journal):
        # a client that cancels its queued Future must not poison the
        # rest of the batch, and drain's accounting must still balance
        srv = self._server(journal=journal, max_wait_ms=200.0)
        try:
            futs = [srv.submit("toy", im) for im in images(3)]
            assert futs[1].cancel()  # still queued: cancel succeeds
            assert futs[0].result(timeout=30) is not None
            assert futs[2].result(timeout=30) is not None
        finally:
            summary = srv.close()
        assert summary["outcome"] == "flushed"
        assert summary["cancelled"] == 1
        assert summary["accepted"] == summary["completed"] \
            + summary["errors"] + summary["cancelled"]
        journal.close()
        outcomes = [e["outcome"] for e in read_journal(journal.path)
                    if e["event"] == "serve_request"]
        assert outcomes.count("cancelled") == 1
        assert outcomes.count("ok") == 2
        assert strict_errors(journal.path) == []

    def test_submit_before_start_refused(self):
        eng = make_engine()
        eng.warmup()
        srv = Server(eng)
        with pytest.raises(ServeError, match="before start"):
            srv.submit("toy", images(1)[0])
        assert srv.accepted == 0

    def test_unknown_model_fails_request_only(self):
        srv = self._server()
        try:
            with pytest.raises(ServeError, match="unknown model"):
                srv.submit("nope", images(1)[0]).result(timeout=30)
        finally:
            srv.close()

    def test_drain_flushes_in_flight_futures(self, journal):
        # a long max-wait keeps requests queued; drain must flush them
        # immediately instead of waiting out the window
        srv = self._server(journal=journal, max_wait_ms=60_000)
        futs = [srv.submit("toy", im) for im in images(3)]
        t0 = time.perf_counter()
        summary = srv.drain("close")
        assert time.perf_counter() - t0 < 10.0
        assert summary["outcome"] == "flushed"
        assert summary["completed"] == 3 and summary["pending"] == 0
        assert all(f.done() for f in futs)
        with pytest.raises(ServerClosed):
            srv.submit("toy", images(1)[0])
        # idempotent: the first drain's verdict sticks
        assert srv.drain("close")["outcome"] == "flushed"

    def test_sigterm_drain_dumps_preempt_flight_bundle(self, journal,
                                                       tmp_path):
        from deep_vision_tpu.obs import flight as flight_mod
        from deep_vision_tpu.obs.flight import (
            FlightRecorder,
            find_bundles,
            validate_bundle,
        )

        fr = FlightRecorder(str(tmp_path / "flight"),
                            run_id=journal.run_id)
        fr.attach(journal)
        flight_mod.set_flight(fr)
        srv = self._server(journal=journal, max_wait_ms=60_000)
        prev = signal.getsignal(signal.SIGTERM)
        try:
            srv.install_sigterm()
            futs = [srv.submit("toy", im) for im in images(2)]
            os.kill(os.getpid(), signal.SIGTERM)
            assert srv.wait_for_stop(timeout=10)
            with pytest.raises(ServerClosed):
                srv.submit("toy", images(1)[0])
            summary = srv.drain("sigterm")
            assert summary["outcome"] == "flushed"
            assert all(f.result(timeout=30) is not None for f in futs)
            bundles = find_bundles(str(tmp_path / "flight"))
            assert len(bundles) == 1 and "preempt" in bundles[0]
            assert validate_bundle(bundles[0]) == []
        finally:
            srv.uninstall_sigterm()
            signal.signal(signal.SIGTERM, prev)
            fr.close()
            flight_mod.set_flight(None)
        journal.close()
        events = read_journal(journal.path)
        drain = next(e for e in events if e["event"] == "serve_drain")
        assert drain["reason"] == "sigterm"
        assert any(e["event"] == "flight_dump" and e["reason"] == "preempt"
                   and e["outcome"] == "written" for e in events)
        assert strict_errors(journal.path) == []

    def test_nonfinite_outputs_journal_health_event(self, journal):
        registry = Registry()
        eng = Engine(registry=registry, journal=journal)
        nan_vars = {"w": jnp.full((16, 3), jnp.nan)}
        eng.register("toy", toy_fn, nan_vars, input_shape=IMG, buckets=(1,))
        eng.warmup()
        srv = Server(eng, journal=journal, registry=registry,
                     max_wait_ms=1.0, health_policy="abort")
        srv.start()
        try:
            fut = srv.submit("toy", images(1)[0])
            with pytest.raises(ServeError, match="non-finite"):
                fut.result(timeout=30)
        finally:
            srv.close()
        journal.close()
        events = read_journal(journal.path)
        health = [e for e in events if e["event"] == "health"]
        assert health and health[0]["kind"] == "non_finite"
        assert health[0]["monitor"] == "serve"
        assert strict_errors(journal.path) == []


# -- slo accounting ----------------------------------------------------------

class TestSLO:
    def test_report_and_render(self):
        from deep_vision_tpu.serve import SLOTracker

        slo = SLOTracker(registry=Registry(), slo_ms=50.0)
        for ms in (5, 8, 12, 200):
            slo.request_done("toy", ms, "ok")
        slo.request_done("toy", 1.0, "error")
        slo.batch_done("toy", bucket=4, size=3, queue_wait_ms=2.0,
                       exec_ms=6.0)
        rep = slo.report()["toy"]
        assert rep["requests"] == 4 and rep["errors"] == 1
        assert rep["p50_ms"] > 0
        assert rep["occupancy_pct"] == pytest.approx(75.0)
        assert rep["padding_waste_pct"] == pytest.approx(25.0)
        assert rep["slo_violations"] == 1
        text = slo.render()
        assert "toy" in text and "occupancy 75.0%" in text


# -- journal schema + report -------------------------------------------------

class TestServeJournalSchema:
    def test_strict_accepts_serve_events(self, tmp_path):
        j = RunJournal(str(tmp_path / "j.jsonl"), kind="serve")
        j.manifest()
        j.write("serve_request", model="toy", latency_ms=3.2, outcome="ok")
        j.write("serve_batch", model="toy", bucket=4, size=3,
                occupancy_pct=75.0, padding_waste_pct=25.0)
        j.write("serve_drain", reason="sigterm", outcome="flushed",
                accepted=3, completed=3, errors=0, pending=0)
        j.close()
        assert strict_errors(j.path) == []

    def test_strict_rejects_bad_enums_and_arithmetic(self, tmp_path):
        path = str(tmp_path / "bad.jsonl")
        rows = [
            {"event": "serve_request", "ts": 1.0, "run_id": "r",
             "model": "toy", "latency_ms": 1.0, "outcome": "maybe"},
            {"event": "serve_batch", "ts": 1.0, "run_id": "r",
             "model": "toy", "bucket": 2, "size": 3},
            {"event": "serve_drain", "ts": 1.0, "run_id": "r",
             "reason": "whim", "outcome": "flushed", "accepted": 1,
             "completed": 1},
            {"event": "serve_drain", "ts": 1.0, "run_id": "r",
             "reason": "close", "outcome": "flushed"},
            {"event": "exit", "ts": 2.0, "run_id": "r", "status": "clean"},
        ]
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        errs = strict_errors(path)
        assert any("serve_request outcome" in e for e in errs)
        assert any("outside [1, bucket=" in e for e in errs)
        assert any("serve_drain reason" in e for e in errs)
        assert any("missing field 'accepted'" in e for e in errs)

    def test_obs_report_renders_serving_summary(self, tmp_path, capsys):
        from tools.obs_report import main as report_main

        j = RunJournal(str(tmp_path / "j.jsonl"), kind="serve")
        j.manifest()
        for ms in (2.0, 3.0, 40.0):
            j.write("serve_request", model="toy", latency_ms=ms,
                    outcome="ok")
        j.write("serve_request", model="toy", latency_ms=1.0,
                outcome="error", error="FaultInjected: boom")
        j.write("serve_batch", model="toy", bucket=4, size=3)
        j.write("serve_drain", reason="close", outcome="flushed",
                accepted=4, completed=3, errors=1, pending=0)
        j.close()
        assert report_main([j.path]) == 0
        out = capsys.readouterr().out
        assert "serving toy" in out
        assert "3 ok, 1 err" in out
        assert "p99" in out
        assert "occupancy 75.0%" in out
        assert "close -> flushed" in out

    def test_obs_report_without_serving_unchanged(self, tmp_path, capsys):
        from tools.obs_report import main as report_main

        j = RunJournal(str(tmp_path / "j.jsonl"))
        j.manifest()
        j.step(1, step_time_ms=10.0, data_wait_ms=1.0)
        j.close()
        assert report_main([j.path]) == 0
        assert "serving" not in capsys.readouterr().out
