"""Gaussian heatmap generation for pose (Hourglass/MPII) and CenterNet.

Replaces the 7x7-patch scatter loop `generate_2d_guassian` at
Hourglass/tensorflow/preprocess.py:91-155 with a dense vectorized evaluation:
for K keypoints on an HxW grid, compute exp(-d^2 / 2sigma^2) over the whole
grid at once (one (H, W, K) broadcast — VPU-friendly, no scatter at all), and
take the per-pixel max over objects for CenterNet-style class heatmaps
(the penalty-reduced splatting of the ObjectsAsPoints paper, which the
reference stubbed out at ObjectsAsPoints/tensorflow/preprocess.py:129-147).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def gaussian_heatmaps(points, height: int, width: int, sigma=1.0, visible=None):
    """points: (K, 2) (x, y) in pixel coords of the output grid; -> (H, W, K).

    Invisible/padded keypoints (visible == 0 or coords < 0) produce zeros,
    matching the visibility-aware path at preprocess.py:158-173.
    """
    points = jnp.asarray(points, jnp.float32)
    k = points.shape[0]
    ys = jnp.arange(height, dtype=jnp.float32)[:, None, None]
    xs = jnp.arange(width, dtype=jnp.float32)[None, :, None]
    px = points[None, None, :, 0]
    py = points[None, None, :, 1]
    d2 = (xs - px) ** 2 + (ys - py) ** 2
    sigma = jnp.broadcast_to(jnp.asarray(sigma, jnp.float32), (k,))
    hm = jnp.exp(-d2 / (2.0 * sigma[None, None, :] ** 2))
    ok = (points[:, 0] >= 0) & (points[:, 1] >= 0)
    if visible is not None:
        ok = ok & (jnp.asarray(visible) > 0)
    return hm * ok[None, None, :].astype(hm.dtype)


def gaussian_radius(wh, min_overlap: float = 0.7):
    """CenterNet adaptive radius so a box shifted by r still has IoU>=min_overlap.

    wh: (..., 2) box sizes in output-grid pixels. Standard 3-case quadratic
    from the CornerNet/CenterNet papers.
    """
    w, h = wh[..., 0], wh[..., 1]
    a1 = 1.0
    b1 = h + w
    c1 = w * h * (1 - min_overlap) / (1 + min_overlap)
    r1 = (b1 - jnp.sqrt(jnp.maximum(b1**2 - 4 * a1 * c1, 0.0))) / 2

    a2 = 4.0
    b2 = 2 * (h + w)
    c2 = (1 - min_overlap) * w * h
    r2 = (b2 - jnp.sqrt(jnp.maximum(b2**2 - 4 * a2 * c2, 0.0))) / (2 * a2)

    a3 = 4.0 * min_overlap
    b3 = -2 * min_overlap * (h + w)
    c3 = (min_overlap - 1) * w * h
    r3 = (b3 + jnp.sqrt(jnp.maximum(b3**2 - 4 * a3 * c3, 0.0))) / (2 * a3)
    return jnp.maximum(jnp.minimum(jnp.minimum(r1, r2), r3), 1e-3)


def centernet_class_heatmap(centers, classes, wh, height: int, width: int,
                            num_classes: int):
    """Splat per-object Gaussians into (H, W, num_classes) with pixel-wise max.

    centers: (N, 2) (x, y) grid coords; classes: (N,); wh: (N, 2) grid sizes.
    Padded objects (wh == 0) contribute nothing. This is the label generator
    ObjectsAsPoints needed but never got (SURVEY.md §2.9).
    """
    valid = (wh[:, 0] > 0) & (wh[:, 1] > 0)
    radius = gaussian_radius(wh)
    sigma = jnp.maximum(radius / 3.0, 1e-3)
    pts = jnp.where(valid[:, None], centers, -1.0)
    hm = gaussian_heatmaps(pts, height, width, sigma=sigma)  # (H, W, N)
    onehot = jax.nn.one_hot(classes, num_classes, dtype=hm.dtype)  # (N, C)
    # per-class max over objects: (H, W, N, 1) * (N, C) -> max over N
    return jnp.max(hm[:, :, :, None] * onehot[None, None, :, :], axis=2)
