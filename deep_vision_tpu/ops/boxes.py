"""Box coordinate transforms, broadcast IoU, and YOLO box (de)coding.

Parity targets: YOLO/tensorflow/utils.py — `xywh_to_x1x2y1y2`, broadcast_iou
(:31-77); yolov3.py — `get_absolute_yolo_box` (:238-326) and
`get_relative_yolo_box` (:329-349). Everything is vectorized, static-shape,
NaN-safe, and differentiable where the loss needs it.

Conventions: boxes are (..., 4); 'xywh' = center x, center y, width, height;
'xyxy' = x1, y1, x2, y2. All normalized to [0, 1] image coordinates.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def xywh_to_xyxy(boxes):
    xy, wh = boxes[..., :2], boxes[..., 2:4]
    return jnp.concatenate([xy - wh / 2.0, xy + wh / 2.0], axis=-1)


def xyxy_to_xywh(boxes):
    mins, maxs = boxes[..., :2], boxes[..., 2:4]
    return jnp.concatenate([(mins + maxs) / 2.0, maxs - mins], axis=-1)


def broadcast_iou(box_a, box_b):
    """IoU of (..., N, 4) vs (..., M, 4) xyxy boxes -> (..., N, M).

    The (B, N, M) broadcast form of utils.py:31-77.
    """
    a = box_a[..., :, None, :]  # (..., N, 1, 4)
    b = box_b[..., None, :, :]  # (..., 1, M, 4)
    lt = jnp.maximum(a[..., :2], b[..., :2])
    rb = jnp.minimum(a[..., 2:4], b[..., 2:4])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[..., 2] - a[..., 0], 0.0) * jnp.clip(a[..., 3] - a[..., 1], 0.0)
    area_b = jnp.clip(b[..., 2] - b[..., 0], 0.0) * jnp.clip(b[..., 3] - b[..., 1], 0.0)
    union = area_a + area_b - inter
    return inter / jnp.maximum(union, 1e-9)


def _grid_offsets(gy: int, gx: int, dtype=jnp.float32):
    """(gy, gx, 1, 2) cell top-left offsets (the meshgrid at yolov3.py:272-281)."""
    ys = jnp.arange(gy, dtype=dtype)
    xs = jnp.arange(gx, dtype=dtype)
    gx_grid, gy_grid = jnp.meshgrid(xs, ys)  # each (gy, gx)
    return jnp.stack([gx_grid, gy_grid], axis=-1)[:, :, None, :]


def decode_yolo_boxes(pred, anchors):
    """Raw per-scale head output -> absolute boxes + probs.

    pred: (B, g, g, A, 5+C) raw; anchors: (A, 2) normalized w,h.
    Returns (boxes_xyxy (B,g,g,A,4), objectness (B,g,g,A,1), class_probs).
    bx = (sigmoid(tx) + cx) / g ; bw = pw * exp(tw)  (yolov3.py:238-326).
    """
    _, gy, gx, na, _ = pred.shape
    t_xy = pred[..., 0:2]
    t_wh = pred[..., 2:4]
    objectness = jax.nn.sigmoid(pred[..., 4:5])
    class_probs = jax.nn.sigmoid(pred[..., 5:])
    grid = _grid_offsets(gy, gx, pred.dtype)
    b_xy = (jax.nn.sigmoid(t_xy) + grid) / jnp.asarray([gx, gy], pred.dtype)
    b_wh = jnp.exp(jnp.clip(t_wh, -10.0, 10.0)) * anchors  # clip: stable exp
    boxes = xywh_to_xyxy(jnp.concatenate([b_xy, b_wh], axis=-1))
    return boxes, objectness, class_probs


def encode_yolo_boxes(boxes_xywh, anchors, grid_size):
    """Absolute xywh -> the (tx, ty, tw, th) regression targets.

    Inverse transform (get_relative_yolo_box, yolov3.py:329-349), with the
    log guarded against empty/padded boxes the way :344-346 NaN-guards.
    """
    g = grid_size
    b_xy, b_wh = boxes_xywh[..., :2], boxes_xywh[..., 2:4]
    scaled = b_xy * g
    cell = jnp.floor(scaled)
    t_xy = scaled - cell  # in (0,1) within the cell
    safe_wh = jnp.maximum(b_wh, 1e-9)
    t_wh = jnp.log(safe_wh / jnp.maximum(anchors, 1e-9))
    valid = (b_wh[..., 0] > 0) & (b_wh[..., 1] > 0)
    t_wh = jnp.where(valid[..., None], t_wh, 0.0)
    return jnp.concatenate([t_xy, t_wh], axis=-1)
