"""Training health monitor: NaN guard, divergence detector, hang watchdog.

The journal explains what a run did; this module explains why it died —
the three dominant production failure modes the reference had no answer
to (SURVEY §5: divergence burned the remaining epochs; a hung collective
just sat there):

- **Non-finite guard**: every checked step's host-fetched loss/grad-norm
  is tested for NaN/Inf. Policy:
    warn       log a typed `health` journal event and keep going
    skip_step  the jitted step itself discards the poisoned update
               (Trainer builds the step with a finiteness-select when
               this policy is active; see Trainer._train_step_impl) and
               the monitor counts the skip in the registry
    abort      write the `health` event, then raise — the journal's
               atexit hook stamps the crash marker after it, so the
               post-mortem reads: health(non_finite) -> crash
- **Divergence detector**: rolling-window z-score over recent losses
  flags spikes (`loss_spike`); `patience` consecutive spikes escalate to
  `divergence` and apply the policy.
- **Hang watchdog**: a daemon thread armed with a deadline; when no step
  (or eval batch) completes within it, every Python thread's stack is
  dumped into a `health` event (`kind=hang`) and to stderr — written
  BEFORE any crash marker, so a hung multi-host collective is
  diagnosable from the journal alone after the operator SIGKILLs it.

Host-side and jax-free at import, like the rest of obs/. All journal
writes go through RunJournal.write, which is lock-protected precisely
because the watchdog fires from its own thread.
"""
from __future__ import annotations

import math
import sys
import threading
import time
import traceback
from collections import deque
from typing import Optional

from deep_vision_tpu.obs import locksmith
from deep_vision_tpu.obs.registry import Registry, get_registry

POLICIES = ("warn", "skip_step", "abort")


class TrainingHealthError(FloatingPointError):
    """Raised by the `abort` policy (and by divergence escalation under
    it). Subclasses FloatingPointError so existing handlers for the
    epoch-level divergence check keep working."""


def dump_all_stacks() -> dict:
    """Every live Python thread's stack, keyed by thread name — what the
    watchdog writes when the train loop stops making progress."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for tid, frame in sys._current_frames().items():
        name = names.get(tid, f"tid-{tid}")
        stacks[f"{name} ({tid})"] = [
            line.rstrip() for line in traceback.format_stack(frame)
        ]
    return stacks


class HealthMonitor:
    """Per-run health guard wired between the host loop and the journal.

    Usage (what Trainer does):

        health.start_watchdog()                    # if a timeout is set
        for batch in data:
            metrics = train_step(batch)
            health.check_step(step, loss=..., grad_norm=...)
        health.stop()

    `check_step` doubles as the watchdog heartbeat; eval loops that run
    long without train steps call `beat()` per batch.
    """

    def __init__(
        self,
        policy: str = "warn",
        journal=None,
        registry: Optional[Registry] = None,
        window: int = 50,
        z_threshold: float = 6.0,
        min_history: int = 20,
        patience: int = 3,
        watchdog_timeout: Optional[float] = None,
        check_every: int = 1,
        name: str = "train",
        policy_explicit: bool = True,
    ):
        if policy not in POLICIES:
            raise ValueError(f"policy {policy!r} not in {POLICIES}")
        self.policy = policy
        # False when the policy is a default the user never chose (e.g.
        # --watchdog-timeout alone): pre-existing fatal checks like the
        # trainer's non-finite-epoch-mean abort must NOT be relaxed by an
        # implicit 'warn'
        self.policy_explicit = bool(policy_explicit)
        self.journal = journal
        self.registry = registry or get_registry()
        self.window = int(window)
        self.z_threshold = float(z_threshold)
        self.min_history = int(min_history)
        self.patience = int(patience)
        self.watchdog_timeout = watchdog_timeout
        self.check_every = max(1, int(check_every))
        self.name = name

        r = self.registry
        self._c_nonfinite = r.counter(
            "health_nonfinite_steps_total",
            "steps whose loss or grad norm was NaN/Inf")
        self._c_skipped = r.counter(
            "health_skipped_steps_total",
            "poisoned updates discarded by the skip_step policy")
        self._c_spikes = r.counter(
            "health_loss_spikes_total",
            "rolling-window z-score loss spikes")
        self._c_hangs = r.counter(
            "health_watchdog_fires_total",
            "watchdog deadline expiries (stack dumps written)")

        self._losses: deque = deque(maxlen=self.window)
        self._spike_streak = 0
        self._checks = 0
        # readiness latch for the telemetry /healthz probe: set just
        # before every abort-policy raise and never cleared — an aborted
        # run stays unhealthy until a FRESH monitor re-registers (a new
        # run is a new monitor, which is how /healthz flips back to 200)
        self.aborted = False
        self.abort_reason: Optional[str] = None

        # watchdog state: monotonic heartbeat + a fire latch so one stall
        # produces one stack dump, re-armed by the next heartbeat. The
        # latch is written by BOTH the train thread (beat) and the
        # watchdog thread (fire) — one lock covers the pair (concurlint
        # DV101: the un-guarded version loses the re-arm/fire race)
        self._wd_lock = locksmith.lock("obs.health.watchdog")
        self._last_beat = time.monotonic()
        self._wd_fired = False
        self._wd_thread: Optional[threading.Thread] = None
        self._wd_stop = threading.Event()

    # -- journal helper ----------------------------------------------------

    def _emit(self, kind: str, **fields) -> None:
        if self.journal is not None:
            # the flight recorder rides the journal's tap: one emit path
            self.journal.write("health", kind=kind, policy=self.policy,
                               monitor=self.name, **fields)
            return
        # journal-less runs still feed the black box: a hang or abort must
        # trigger the postmortem dump even when nobody asked for a journal
        try:
            from deep_vision_tpu.obs import flight

            fr = flight.get_flight()
            if fr is not None:
                fr.observe({"event": "health", "ts": round(time.time(), 3),
                            "kind": kind, "policy": self.policy,
                            "monitor": self.name, **fields})
        except Exception:
            pass

    # -- non-finite + divergence checks ------------------------------------

    def check_step(self, step: int, loss: Optional[float] = None,
                   grad_norm: Optional[float] = None,
                   skipped: bool = False) -> str:
        """Check one step's host-fetched scalars; returns the action taken
        ('ok' | 'warn' | 'skip' | 'spike'). Raises TrainingHealthError
        under the abort policy. `skipped` tells the monitor the jitted
        step already discarded this update (skip_step wiring)."""
        self.beat()
        self._checks += 1
        if self._checks % self.check_every != 0 and not skipped:
            return "ok"

        bad = [
            k for k, v in (("loss", loss), ("grad_norm", grad_norm))
            if v is not None and not math.isfinite(v)
        ]
        if bad or skipped:
            self._c_nonfinite.inc()
            action = {"warn": "warn", "skip_step": "skip",
                      "abort": "abort"}[self.policy]
            detail = {k: repr(v) for k, v in
                      (("loss", loss), ("grad_norm", grad_norm))
                      if v is not None}
            self._emit("non_finite", step=int(step), fields=bad or ["loss"],
                       action=action, **detail)
            if self.policy == "skip_step":
                self._c_skipped.inc()
                print(f"health: non-finite {'/'.join(bad) or 'loss'} at step "
                      f"{step} — update skipped", file=sys.stderr, flush=True)
                return "skip"
            if self.policy == "abort":
                raise self._abort(TrainingHealthError(
                    f"non-finite {'/'.join(bad) or 'loss'} at step {step} "
                    f"(loss={loss!r}, grad_norm={grad_norm!r}); aborting per "
                    "--health-policy abort"
                ))
            print(f"health: non-finite {'/'.join(bad)} at step {step} "
                  f"(loss={loss!r}, grad_norm={grad_norm!r})",
                  file=sys.stderr, flush=True)
            return "warn"

        if loss is None:
            return "ok"
        action = "ok"
        if len(self._losses) >= self.min_history:
            mean = sum(self._losses) / len(self._losses)
            var = sum((x - mean) ** 2 for x in self._losses) / len(self._losses)
            std = math.sqrt(var)
            # the 1e-9 floor keeps a perfectly flat window (synthetic
            # fixtures) from dividing by zero; any real window has spread
            z = (loss - mean) / max(std, 1e-9)
            if z > self.z_threshold:
                self._c_spikes.inc()
                self._spike_streak += 1
                escalate = self._spike_streak >= self.patience
                # an escalation under the abort policy carries the action
                # field: the flight recorder's tap keys its health_abort
                # dump on it (the raise below never returns control here)
                extra = ({"action": "abort"}
                         if escalate and self.policy == "abort" else {})
                self._emit("divergence" if escalate else "loss_spike",
                           step=int(step), loss=loss, window_mean=mean,
                           window_std=std, z=z, streak=self._spike_streak,
                           **extra)
                if escalate:
                    msg = (f"divergence: {self._spike_streak} consecutive "
                           f"loss spikes (z={z:.1f}, loss={loss:.4g} vs "
                           f"window mean {mean:.4g})")
                    if self.policy == "abort":
                        raise self._abort(TrainingHealthError(msg))
                    print("health: " + msg, file=sys.stderr, flush=True)
                # a spiking loss stays OUT of the window: admitting it
                # would inflate the std until the very spikes being
                # counted stop registering, resetting the streak before
                # patience can escalate (the window models the healthy
                # recent past, not whatever the run is doing now)
                return "spike"
            self._spike_streak = 0
        self._losses.append(loss)
        return action

    def check_summary(self, epoch: int, summary: dict) -> None:
        """Epoch-granularity guard for loops that keep metrics on device
        until epoch end (the GAN trainers): any non-finite summary value
        triggers the policy."""
        self.beat()
        bad = {k: v for k, v in summary.items()
               if isinstance(v, float) and not math.isfinite(v)}
        if not bad:
            return
        self._c_nonfinite.inc()
        self._emit("non_finite", epoch=int(epoch),
                   fields=sorted(bad), action=self.policy)
        if self.policy == "abort":
            raise self._abort(TrainingHealthError(
                f"non-finite epoch {epoch} summary: {bad}; aborting per "
                "--health-policy abort"
            ))
        print(f"health: non-finite epoch {epoch} summary {bad}",
              file=sys.stderr, flush=True)

    def _abort(self, err: TrainingHealthError) -> TrainingHealthError:
        """Latch the abort for /healthz, then hand the error back to its
        raise site (the latch must be set BEFORE the raise unwinds, so a
        probe racing the abort never sees healthy-but-dying)."""
        self.aborted = True
        self.abort_reason = str(err)
        return err

    def healthz(self):
        """Telemetry health source: (ok, detail) for TelemetryServer.
        Unhealthy once aborted or while the watchdog latch is up (the
        next heartbeat clears the latch — a recovered stall recovers the
        probe; an abort never does)."""
        with self._wd_lock:
            fired = self._wd_fired
            beat_age = time.monotonic() - self._last_beat
        ok = not self.aborted and not fired
        detail = {
            "policy": self.policy,
            "monitor": self.name,
            "aborted": self.aborted,
            "watchdog_fired": fired,
            "last_beat_age_s": round(beat_age, 3),
        }
        if self.abort_reason:
            detail["abort_reason"] = self.abort_reason
        return ok, detail

    @property
    def skip_nonfinite(self) -> bool:
        """True when the jitted train step should be built with the
        finiteness-select update guard."""
        return self.policy == "skip_step"

    # -- watchdog ----------------------------------------------------------

    def beat(self) -> None:
        """Heartbeat: any sign of forward progress re-arms the watchdog."""
        with self._wd_lock:
            self._last_beat = time.monotonic()
            self._wd_fired = False

    def start_watchdog(self) -> None:
        """Arm the hang detector (no-op without a timeout). Daemon thread:
        it must never keep a dying process alive."""
        if not self.watchdog_timeout or self._wd_thread is not None:
            return
        self.beat()
        self._wd_stop.clear()
        self._wd_thread = threading.Thread(
            target=self._watchdog_loop, name=f"health-watchdog-{self.name}",
            daemon=True,
        )
        self._wd_thread.start()
        self._emit("watchdog_started", timeout_s=float(self.watchdog_timeout))

    def _watchdog_loop(self) -> None:
        poll = min(max(self.watchdog_timeout / 4.0, 0.05), 10.0)
        while not self._wd_stop.wait(poll):
            # latch under the beat lock: a beat racing the fire either
            # re-arms before (no dump) or after (clean re-arm) — never a
            # lost latch. The stack dump and journal write run OUTSIDE
            # the lock: beat() is on the per-step hot path and must never
            # wait on a dump in progress.
            with self._wd_lock:
                stalled = time.monotonic() - self._last_beat
                if stalled < self.watchdog_timeout or self._wd_fired:
                    continue
                self._wd_fired = True
            self._c_hangs.inc()
            stacks = dump_all_stacks()
            self._emit("hang", stalled_s=round(stalled, 3),
                       timeout_s=float(self.watchdog_timeout),
                       stacks=stacks)
            print(f"health: WATCHDOG — no step completed in {stalled:.1f}s "
                  f"(deadline {self.watchdog_timeout}s); thread stacks:",
                  file=sys.stderr, flush=True)
            for name, frames in stacks.items():
                print(f"--- {name} ---", file=sys.stderr)
                print("".join(f"{ln}\n" for ln in frames),
                      file=sys.stderr, flush=True)

    def stop(self) -> None:
        """Disarm the watchdog; idempotent (journal closers may call it
        after train_cli already has)."""
        self._wd_stop.set()
        t, self._wd_thread = self._wd_thread, None
        if t is not None:
            t.join(timeout=5)

    close = stop

    def __enter__(self):
        self.start_watchdog()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False
