"""The driver's entry points, exercised the way the driver calls them.

Round 4 lost its multichip evidence because `dryrun_multichip` probed the
default backend and hung on a dead TPU tunnel; it is now hermetic (forces
the virtual host-CPU platform before any backend touch). These tests pin
that contract: a fresh process with NO helpful env vars — and even with a
hostile stale device-count flag — must complete the dry run on the virtual
CPU mesh.
"""
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # jit-heavy: full DP x TP step compile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(env_extra, n=4):
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, "__graft_entry__.py", str(n)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )


def test_dryrun_hermetic_with_no_env():
    proc = _run({})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout
    # all four parallelism flavors actually ran on the DP x TP mesh
    assert "tp_sharded_leaves=" in proc.stdout
    assert "ring_attn_err=" in proc.stdout and "ep_err=" in proc.stdout


def test_dryrun_overrides_stale_device_count_flag():
    """A leftover smaller --xla_force_host_platform_device_count must be
    replaced, not trusted (it would bring up a too-small backend)."""
    proc = _run({"XLA_FLAGS": "--xla_force_host_platform_device_count=2"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "mesh={'data': 2, 'model': 2}" in proc.stdout
