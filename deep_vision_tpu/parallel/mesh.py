"""Device mesh construction and sharding helpers.

This is the TPU-native replacement for the reference's three single-host
data-parallel wrappers (`nn.DataParallel` at ResNet/pytorch/train.py:353-355,
`tf.distribute.MirroredStrategy` at YOLO/tensorflow/train.py:281, and
`keras.utils.multi_gpu_model` at ResNet/tensorflow/train.py:249-251).

Instead of wrapping a model, we build a named `jax.sharding.Mesh` once and
express every parallelism flavor as a sharding of arrays over its axes:

- ``data``  : batch (data parallel; the only axis the reference ever used)
- ``model`` : tensor parallel (output features of wide layers)

Sequence/context parallelism for attention workloads reuses the ``data``
axis (see `parallel/ring_attention.py`) so long sequences shard over the
same mesh without a dedicated axis.  XLA's SPMD partitioner inserts the
all-reduce / all-gather / reduce-scatter collectives over ICI; cross-host
meshes ride DCN transparently (`jax.distributed.initialize` in
`parallel/multihost.py`).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """How to lay a device list out as a (data, model) mesh."""

    data: int = -1  # -1: all remaining devices
    model: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int]:
        model = max(1, self.model)
        if n_devices % model != 0:
            raise ValueError(f"model axis {model} does not divide {n_devices} devices")
        data = self.data if self.data > 0 else n_devices // model
        if data * model != n_devices:
            raise ValueError(
                f"mesh {data}x{model} != {n_devices} devices; pass data=-1 to infer"
            )
        return data, model


def create_mesh(
    spec: MeshSpec | None = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    data: int = -1,
    model: int = 1,
) -> Mesh:
    """Build a 2-D ('data', 'model') mesh over the given (default: all) devices.

    ``create_mesh()`` -> all devices on the data axis: pure data parallel,
    exactly mirroring the reference's `global_batch = batch * num_replicas`
    contract (YOLO/tensorflow/train.py:282).
    """
    if spec is None:
        spec = MeshSpec(data=data, model=model)
    if devices is None:
        devices = jax.devices()
    d, m = spec.resolve(len(devices))
    arr = np.asarray(devices).reshape(d, m)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def local_mesh_devices(mesh: Mesh) -> list[jax.Device]:
    """Devices of `mesh` that live on this host (for host-sharded input feed)."""
    procid = jax.process_index()
    return [d for d in mesh.devices.flat if d.process_index == procid]


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params/opt state in plain data parallel)."""
    return NamedSharding(mesh, P())


def data_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard the leading (batch) dimension over the 'data' axis."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def stacked_data_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Sharding for a (K, B, ...) stacked superstep batch (train/trainer.py
    multistep mode): the scan axis K replicates, the batch dim shards over
    'data' — each dispatch carries K microsteps' batches in one transfer."""
    return NamedSharding(mesh, P(None, DATA_AXIS, *([None] * (ndim - 2))))


def shard_batch(mesh: Mesh, batch):
    """Place a host batch (pytree of np/jnp arrays) with batch-dim sharding.

    The device boundary of the framework: everything before this call is
    host-side numpy; everything after is SPMD on the mesh.
    """

    def _place(x):
        if isinstance(x, jax.Array) and len(x.sharding.device_set) > 1:
            # already a globally-sharded array (multi-host callers build
            # batches with multihost.form_global_array — this host cannot
            # re-place an array whose shards live on other hosts)
            return x
        x = np.asarray(x)
        return jax.device_put(x, data_sharding(mesh, x.ndim))

    return jax.tree_util.tree_map(_place, batch)


def infer_tp_sharding(tree, mesh: Mesh, min_size: int = 4096):
    """Tensor-parallel sharding rule for a params/state pytree.

    Shards the output-feature (last) dim of large kernels over the 'model'
    axis when it divides evenly; everything else (biases, BN stats, scalars)
    is replicated. XLA's SPMD partitioner propagates the layout through the
    matmuls/convs and inserts the ICI collectives — the explicit Megatron-style
    plumbing the reference never had (its only parallelism was single-host DP,
    SURVEY.md §2.5) falls out of the sharding annotation alone.
    """
    m = mesh.shape[MODEL_AXIS]

    def rule(x):
        shape = getattr(x, "shape", ())
        size = int(np.prod(shape)) if shape else 0
        if (
            m > 1
            and len(shape) >= 2
            and shape[-1] % m == 0
            and size >= min_size
        ):
            return NamedSharding(mesh, P(*([None] * (len(shape) - 1) + [MODEL_AXIS])))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, tree)


def pad_batch_to(batch, multiple: int):
    """Pad the leading dim of every leaf up to `multiple` (TPU static shapes).

    Returns (padded_batch, valid_count). Needed for the final partial batch
    of an epoch: the reference simply let torch/TF handle ragged last batches
    (ResNet/pytorch/train.py:431-485); under jit we pad and mask instead.
    """
    leaves = jax.tree_util.tree_leaves(batch)
    if not leaves:
        return batch, 0
    n = leaves[0].shape[0]
    target = math.ceil(n / multiple) * multiple if n % multiple else n

    def _pad(x):
        if x.shape[0] == target:
            return x
        pad = [(0, target - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        return np.pad(np.asarray(x), pad)

    return jax.tree_util.tree_map(_pad, batch), n
