"""Pallas TPU kernels for profiled hotspots.

The reference has no custom kernels (its C++/CUDA lives inside torch/TF —
SURVEY.md §2); here the hot ops XLA can't fuse optimally get hand-written
TPU kernels with lax fallbacks for non-TPU platforms and interpret-mode
tests on CPU.
"""
from deep_vision_tpu.ops.pallas.flash_attention import flash_attention

__all__ = ["flash_attention"]
