"""Perf layer: fused Pallas kernels, scan-multistep Trainer, device
prefetch, bf16 optimizer state, roofline bench anchoring.

Kernel tests run the REAL Pallas kernels under interpret=True (the same
code path the TPU compiles), against pure-lax references. Multistep tests
prove the one-dispatch-per-K-steps contract the on-TPU bench banks on.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.ops.pallas.bn_act import (
    fused_scale_bias_act,
    reference_scale_bias_act,
)


def _xab(c, shape=(2, 4, 4), seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(*shape, c).astype(dtype))
    a = jnp.asarray((rng.rand(c) + 0.5).astype(np.float32))
    b = jnp.asarray(rng.randn(c).astype(np.float32))
    return x, a, b


# -- fused scale-bias-act kernel --------------------------------------------

@pytest.mark.parametrize("c", [64, 128, 256])  # 64: lane-tiled, others direct
@pytest.mark.parametrize("act", ["relu", None])
def test_bn_act_forward_parity(c, act):
    x, a, b = _xab(c)
    got = fused_scale_bias_act(x, a, b, act=act, interpret=True)
    want = reference_scale_bias_act(x, a, b, act=act)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_bn_act_residual_parity():
    x, a, b = _xab(128)
    r = jnp.asarray(np.random.RandomState(1).randn(*x.shape).astype(np.float32))
    got = fused_scale_bias_act(x, a, b, residual=r, act="relu",
                               interpret=True)
    want = reference_scale_bias_act(x, a, b, residual=r, act="relu")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_bn_act_grads_match_reference():
    x, a, b = _xab(128, shape=(2, 4, 4), seed=2)
    r = jnp.asarray(np.random.RandomState(3).randn(*x.shape).astype(np.float32))

    def f(fn):
        return lambda x, a, b, r: jnp.sum(
            fn(x, a, b, residual=r, act="relu") ** 2)

    g1 = jax.grad(f(lambda *args, **kw: fused_scale_bias_act(
        *args, interpret=True, **kw)), argnums=(0, 1, 2, 3))(x, a, b, r)
    g2 = jax.grad(f(reference_scale_bias_act), argnums=(0, 1, 2, 3))(x, a, b, r)
    for u, v, name in zip(g1, g2, ("x", "scale", "bias", "residual")):
        np.testing.assert_allclose(np.asarray(u), np.asarray(v),
                                   rtol=2e-5, atol=2e-5, err_msg=name)


def test_bn_act_bf16_io_keeps_dtype():
    x, a, b = _xab(128, dtype=np.float32)
    x = x.astype(jnp.bfloat16)
    got = fused_scale_bias_act(x, a, b, act="relu", interpret=True)
    assert got.dtype == jnp.bfloat16
    want = reference_scale_bias_act(x, a, b, act="relu")
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_bn_act_awkward_channels_fall_back():
    # 96 neither divides nor is divided by 128: lax fallback, same contract
    x, a, b = _xab(96)
    got = fused_scale_bias_act(x, a, b, act="relu", interpret=True)
    want = reference_scale_bias_act(x, a, b, act="relu")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_flag_resnet_block_forward_close(monkeypatch):
    """A real BottleneckBlock forward with the fusion forced on must match
    the unfused default path (tolerance: one fused-vs-sequential rounding)."""
    from deep_vision_tpu.models import get_model

    m = get_model("resnet50", num_classes=8)
    x = jnp.asarray(np.random.RandomState(0).rand(2, 32, 32, 3)
                    .astype(np.float32))
    v = m.init(jax.random.PRNGKey(0), x, train=False)
    monkeypatch.setenv("DVT_PALLAS_FUSED", "0")
    want = m.apply(v, x, train=False)
    monkeypatch.setenv("DVT_PALLAS_FUSED", "1")
    got = m.apply(v, x, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


# -- pallas NMS -------------------------------------------------------------

def _detections(seed, b=2, n=256):
    rng = np.random.RandomState(seed)
    xy = rng.rand(b, n, 2).astype(np.float32) * 0.8
    wh = rng.rand(b, n, 2).astype(np.float32) * 0.25 + 0.02
    boxes = jnp.asarray(np.concatenate([xy, xy + wh], -1))
    scores = jnp.asarray(rng.rand(b, n).astype(np.float32))
    classes = jnp.asarray(rng.randint(0, 6, size=(b, n)).astype(np.int32))
    return boxes, scores, classes


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pallas_nms_exact_parity(seed):
    from deep_vision_tpu.ops.nms import non_maximum_suppression

    boxes, scores, classes = _detections(seed)
    kw = dict(max_detections=25, iou_threshold=0.5, score_threshold=0.3)
    lax_out = non_maximum_suppression(boxes, scores, classes, impl="lax", **kw)
    pal_out = non_maximum_suppression(boxes, scores, classes, impl="pallas",
                                      **kw)
    for u, v, name in zip(lax_out, pal_out,
                          ("boxes", "scores", "classes", "valid")):
        np.testing.assert_array_equal(np.asarray(u), np.asarray(v),
                                      err_msg=f"seed {seed}: {name}")


def test_pallas_nms_under_jit_and_env_flag(monkeypatch):
    from deep_vision_tpu.ops.nms import non_maximum_suppression

    boxes, scores, classes = _detections(3)
    want = non_maximum_suppression(boxes, scores, classes, impl="lax",
                                   max_detections=10)
    # env flag forces the kernel for impl=None callers (inference paths)
    monkeypatch.setenv("DVT_NMS_IMPL", "pallas")
    f = jax.jit(lambda b, s, c: non_maximum_suppression(
        b, s, c, max_detections=10))
    got = f(boxes, scores, classes)
    for u, v in zip(want, got):
        np.testing.assert_array_equal(np.asarray(u), np.asarray(v))


def test_nms_impl_rejects_unknown():
    from deep_vision_tpu.ops.nms import non_maximum_suppression

    boxes, scores, _ = _detections(0)
    with pytest.raises(ValueError, match="unknown NMS impl"):
        non_maximum_suppression(boxes, scores, impl="cuda")


def test_nms_env_flag_typo_is_loud(monkeypatch):
    """A mistyped DVT_NMS_IMPL must raise, not silently run 'auto' —
    the disable flag exists for triage."""
    from deep_vision_tpu.ops.nms import non_maximum_suppression

    boxes, scores, _ = _detections(0)
    monkeypatch.setenv("DVT_NMS_IMPL", "LAX")
    with pytest.raises(ValueError, match="DVT_NMS_IMPL"):
        non_maximum_suppression(boxes, scores, max_detections=5)


# -- device prefetch --------------------------------------------------------

def test_device_prefetch_depth2_never_starves():
    from deep_vision_tpu.data.device_prefetch import (
        DevicePrefetcher, PlacedBatch)
    from deep_vision_tpu.obs.registry import Registry

    reg = Registry()
    pf = DevicePrefetcher(place_one=lambda b: PlacedBatch(b, 1, 1),
                          depth=2, name="t", registry=reg)
    seen = 0
    for item in pf(iter(range(16))):
        time.sleep(0.002)  # consumer slower than producer
        assert isinstance(item, PlacedBatch)
        seen += 1
    assert seen == 16
    assert reg.counter("device_prefetch_starved_total",
                       labels={"loader": "t"}).value == 0
    assert reg.counter("device_prefetch_batches_total",
                       labels={"loader": "t"}).value == 16


def test_device_prefetch_starvation_detected():
    from deep_vision_tpu.data.device_prefetch import (
        DevicePrefetcher, PlacedBatch)
    from deep_vision_tpu.obs.registry import Registry

    reg = Registry()

    def slow():
        for i in range(8):
            time.sleep(0.01)
            yield i

    pf = DevicePrefetcher(place_one=lambda b: PlacedBatch(b, 1, 1),
                          depth=1, name="s", registry=reg)
    list(pf(slow()))
    assert reg.counter("device_prefetch_starved_total",
                       labels={"loader": "s"}).value > 0


def test_device_prefetch_groups_and_tail():
    from deep_vision_tpu.data.device_prefetch import (
        DevicePrefetcher, PlacedBatch)
    from deep_vision_tpu.obs.registry import Registry

    pf = DevicePrefetcher(
        place_one=lambda b: PlacedBatch(("one", b), 1, 1),
        place_group=lambda bs: PlacedBatch(("grp", tuple(bs)), len(bs),
                                           len(bs)),
        depth=2, group=3, name="g", registry=Registry())
    items = list(pf(iter(range(7))))  # 2 full groups + 1-batch tail
    assert [it.group for it in items] == [3, 3, 1]
    assert items[0].data == ("grp", (0, 1, 2))
    assert items[2].data == ("one", 6)


def test_device_prefetch_propagates_source_error():
    from deep_vision_tpu.data.device_prefetch import (
        DevicePrefetcher, PlacedBatch)
    from deep_vision_tpu.obs.registry import Registry

    def bad():
        yield 1
        raise RuntimeError("decode exploded")

    pf = DevicePrefetcher(place_one=lambda b: PlacedBatch(b, 1, 1),
                          depth=2, name="e", registry=Registry())
    with pytest.raises(RuntimeError, match="decode exploded"):
        list(pf(bad()))


# -- scan-multistep Trainer -------------------------------------------------

def _lenet_trainer(mesh8, multistep=1, device_prefetch=0, journal=None,
                   registry=None, tx=None):
    from deep_vision_tpu.losses import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train import Trainer, build_optimizer

    model = get_model("lenet5", num_classes=4)
    tx = tx or build_optimizer("sgd", 0.05, momentum=0.9)
    return Trainer(model, tx, classification_loss_fn,
                   sample_input=jnp.zeros((8, 32, 32, 1)), mesh=mesh8,
                   multistep=multistep, device_prefetch=device_prefetch,
                   journal=journal, registry=registry)


def _mk_batches(n, bs=32, seed=0):
    rng = np.random.RandomState(seed)
    return [{"image": rng.rand(bs, 32, 32, 1).astype(np.float32),
             "label": rng.randint(0, 4, size=bs)} for _ in range(n)]


def test_multistep_superstep_equivalent_to_single_steps(mesh8):
    batches = _mk_batches(4)
    t1 = _lenet_trainer(mesh8, multistep=1)
    t4 = _lenet_trainer(mesh8, multistep=4)
    singles = [t1.train_step(b) for b in batches]
    stacked = t4.train_superstep(batches)
    # same RNG derivation, same update order: float-ulp agreement
    p1, p4 = jax.device_get((t1.state.params, t4.state.params))
    for u, v in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p4)):
        np.testing.assert_allclose(u, v, rtol=1e-6, atol=1e-6)
    for i in range(4):
        assert abs(float(singles[i]["loss"])
                   - float(stacked[i]["loss"])) <= 1e-5
    assert int(t1.state.step) == int(t4.state.step) == 4


def test_multistep_fit_tail_and_journal(mesh8, tmp_path):
    from deep_vision_tpu.obs.journal import RunJournal
    from deep_vision_tpu.obs.registry import Registry

    jpath = tmp_path / "ms.jsonl"
    batches = _mk_batches(7)  # 2 groups of 3 + 1 tail single
    with RunJournal(str(jpath), kind="train") as j:
        j.manifest(config={})
        t = _lenet_trainer(mesh8, multistep=3, journal=j,
                           registry=Registry())
        t.fit(lambda: iter(batches), epochs=1, handle_preemption=False)
        assert int(t.state.step) == 7
    rows = [json.loads(line) for line in open(jpath)]
    steps = [r for r in rows if r["event"] == "step"]
    assert [r.get("multistep") for r in steps] == [3, 3, None]
    assert [r["step"] for r in steps] == [3, 6, 7]
    # per-microstep series reach the logger: 7 rows, not 3
    assert len(t.logger.history["loss"]) == 1  # one epoch summary


def test_multistep_partial_batch_inside_full_group(mesh8):
    """A short final batch landing INSIDE a full K-group must be padded to
    the group's common size and masked, not crash np.stack."""
    batches = _mk_batches(2, bs=32) + _mk_batches(1, bs=8, seed=9)
    t = _lenet_trainer(mesh8, multistep=3)
    metrics = t.train_superstep(batches)  # group of [32, 32, 8]
    assert int(t.state.step) == 3
    assert all(np.isfinite(float(m["loss"])) for m in metrics)
    # and through fit with the device prefetcher grouping in its thread
    t2 = _lenet_trainer(mesh8, multistep=3, device_prefetch=2)
    t2.fit(lambda: iter(list(batches)), epochs=1, handle_preemption=False)
    assert int(t2.state.step) == 3


def test_multistep_logs_per_microstep_lr_under_schedule(mesh8):
    """With an LR schedule, each microstep's logged lr must be the
    schedule's value at that step, not the last microstep's."""
    import optax

    from deep_vision_tpu.losses import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train import Trainer

    sched = optax.linear_schedule(0.1, 0.0, 100)
    tx = optax.inject_hyperparams(optax.sgd)(learning_rate=sched)
    t = Trainer(get_model("lenet5", num_classes=4), tx,
                classification_loss_fn,
                sample_input=jnp.zeros((8, 32, 32, 1)), mesh=mesh8,
                multistep=4, lr_schedule=sched)
    seen = []
    orig = t.logger.log_step
    t.logger.log_step = lambda step, m, **kw: (
        seen.append((step, kw.get("lr"))), orig(step, m, **kw))
    t.fit(lambda: iter(_mk_batches(4)), epochs=1, handle_preemption=False)
    lrs = dict(seen)
    for step in (1, 2, 3, 4):
        assert lrs[step] == pytest.approx(float(sched(step - 1)), rel=1e-6)
    assert lrs[1] != lrs[4]  # the series actually moves within a dispatch


def test_multistep_with_device_prefetch_fit(mesh8):
    from deep_vision_tpu.obs.registry import Registry

    reg = Registry()
    batches = _mk_batches(8, seed=2)
    t = _lenet_trainer(mesh8, multistep=2, device_prefetch=2, registry=reg)
    t.fit(lambda: iter(batches), epochs=1, handle_preemption=False)
    assert int(t.state.step) == 8
    assert reg.counter("device_prefetch_batches_total",
                       labels={"loader": "train"}).value == 4  # 4 groups


def test_multistep_refuses_checkify_and_ema(mesh8):
    from deep_vision_tpu.losses import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train import Trainer, build_optimizer

    model = get_model("lenet5", num_classes=4)
    kw = dict(loss_fn=classification_loss_fn,
              sample_input=jnp.zeros((8, 32, 32, 1)), mesh=mesh8)
    with pytest.raises(ValueError, match="checkify"):
        Trainer(model, build_optimizer("sgd", 0.05), multistep=2,
                checkify_errors=True, **kw)
    with pytest.raises(ValueError, match="ema"):
        Trainer(model, build_optimizer("sgd", 0.05), multistep=2,
                ema_decay=0.99, **kw)


def test_superstep_rejects_wrong_group_size(mesh8):
    t = _lenet_trainer(mesh8, multistep=3)
    with pytest.raises(ValueError, match="superstep got 2"):
        t.train_superstep(_mk_batches(2))
    t1 = _lenet_trainer(mesh8, multistep=1)
    with pytest.raises(ValueError, match="multistep"):
        t1.train_superstep(_mk_batches(2))


def test_trainer_accepts_placed_batch(mesh8):
    t = _lenet_trainer(mesh8)
    b = _mk_batches(1)[0]
    placed = t._place_one(b)
    metrics = t.train_step(placed)
    assert np.isfinite(float(metrics["loss"]))
    assert placed.n == 32


# -- bf16 optimizer state ---------------------------------------------------

def test_bf16_opt_state_dtypes_and_training(mesh8):
    from deep_vision_tpu.train import build_optimizer

    tx = build_optimizer("sgd", 0.05, momentum=0.9, state_dtype="bfloat16")
    t = _lenet_trainer(mesh8, tx=tx)
    b = _mk_batches(1)[0]
    losses = [float(t.train_step(b)["loss"]) for _ in range(6)]
    assert losses[-1] < losses[0]  # still optimizes on the same batch
    dtypes = set()
    jax.tree_util.tree_map(
        lambda x: dtypes.add(str(x.dtype))
        if jnp.issubdtype(x.dtype, jnp.floating) else None,
        t.state.opt_state.inner_state)
    assert dtypes == {"bfloat16"}  # the big state rounds, nothing else
    # the injected LR stays f32 — plateau writes are unaffected
    assert t.state.opt_state.hyperparams["learning_rate"].dtype == jnp.float32


def test_bf16_opt_state_adam_moments():
    from deep_vision_tpu.train import build_optimizer

    tx = build_optimizer("adam", 1e-3, state_dtype="bfloat16")
    params = {"w": jnp.ones((4, 4))}
    state = tx.init(params)
    grads = {"w": jnp.full((4, 4), 0.1)}
    updates, state = tx.update(grads, state, params)
    dtypes = set()
    jax.tree_util.tree_map(
        lambda x: dtypes.add(str(x.dtype))
        if jnp.issubdtype(x.dtype, jnp.floating) else None,
        state.inner_state)
    assert dtypes == {"bfloat16"}
    assert updates["w"].dtype == jnp.float32  # updates stay full precision


# -- roofline bench anchoring ----------------------------------------------

def test_roofline_bench_position(tmp_path):
    from deep_vision_tpu.tools.roofline import (
        analytic_traffic, bench_position, load_bench_json, render_roofline)

    bench = {"metric": "resnet50_train_images_per_sec_per_chip",
             "value": 2477.9, "vs_baseline": 0.949, "batch_per_chip": 256,
             "multistep": 1, "model_flops_per_image": 24.05,
             "hbm_gbytes_per_step_per_chip": 77.86,
             "hbm_gbytes_per_sec_per_chip": 753.6,
             "device_images_per_sec_per_chip": 2615.3,
             "mfu_wall_pct": 30.2, "mfu_device_pct": 31.9}
    p = tmp_path / "bench.json"
    p.write_text(json.dumps({"parsed": bench}))  # driver wrapper form
    assert load_bench_json(str(p))["value"] == 2477.9
    pos = bench_position(bench, analytic_traffic(256))
    rows = {r["name"]: r for r in pos["rows"]}
    wall = rows["train_step (wall)"]
    # 2477.9 img/s * 24.05 GF = 59.6 TF/s achieved
    assert wall["achieved_tflops"] == pytest.approx(59.6, abs=0.1)
    assert wall["bound"] == "memory"  # intensity 79 f/B < ridge 240
    assert 0 < wall["pct_of_roofline"] <= 100
    assert wall["vs_30pct_mfu_baseline"] == pytest.approx(1.01, abs=0.02)
    # layers carry intensity-only placement
    assert any(r["name"].startswith("s") for r in pos["rows"])
    assert "30%-MFU baseline" in render_roofline(pos)


def test_roofline_rejects_non_bench_json(tmp_path):
    from deep_vision_tpu.tools.roofline import load_bench_json

    p = tmp_path / "x.json"
    p.write_text(json.dumps({"rows": []}))
    with pytest.raises(ValueError, match="not a bench result"):
        load_bench_json(str(p))


# -- bench result fields ----------------------------------------------------

def test_bench_stub_carries_multistep():
    import argparse

    import bench

    stub = bench.train_result_stub(
        argparse.Namespace(batch=128, multistep=4))
    assert stub["multistep"] == 4
    assert stub["batch_per_chip"] == 128


def test_bench_emit_journals_every_path(monkeypatch):
    """_emit (the one funnel for train/sweep/data/watchdog lines) must
    write the bench journal event exactly once."""
    import bench

    class Spy:
        def __init__(self):
            self.events, self.closed = [], False

        def bench(self, name, result):
            self.events.append((name, result))

        def close(self):
            self.closed = True

    spy = Spy()
    monkeypatch.setattr(bench, "_JOURNAL", spy)
    monkeypatch.setattr(bench, "_EMITTED", False)
    assert bench._emit({"metric": "dispatch_sweep", "rows": []})
    assert not bench._emit({"metric": "late_duplicate"})  # latched
    assert len(spy.events) == 1
    name, result = spy.events[0]
    assert name == "dispatch_sweep"
    assert result["metric"] == "dispatch_sweep" and result["rows"] == []
    # every emitted line carries the perf-ledger environment fingerprint
    # (tools/perf_gate.py keys baselines on it)
    assert result["env"]["jax"] and result["env_key"]
    assert spy.closed
