"""Span tracer: Chrome trace-event JSON that explains where time went.

The journal (journal.py) answers *what happened* per step; spans answer
*where inside the step the time went* — data fetch vs augment vs dispatch
vs eval vs checkpoint I/O — across every layer the journal touches. The
output is the Trace Event Format's complete-event ("ph": "X") list, so
one file loads directly in Perfetto / chrome://tracing and diffs across
PRs the same way journals do.

Design constraints, in order:

- **Zero cost when off.** Every instrumentation site calls the
  module-level `span(...)`; with no tracer installed it returns a shared
  no-op context manager (no allocation, no branching in callers). The
  data pipeline and spawned workers import this module, so it stays
  jax-free at import like registry.py.
- **Always-valid JSON on disk.** A hung or SIGKILLed run is exactly when
  the trace matters most, so flush() rewrites the whole file atomically
  (tmp + os.replace) instead of streaming an unterminated array. Spans
  buffer in memory and flush every `flush_every` completions and from an
  atexit hook.
- **Thread-safe, process-0-only.** Producer threads (data prefetch,
  watchdog) record spans concurrently with the train loop; each event
  carries its thread id and a one-time thread-name metadata event.
  Non-zero `jax.process_index()` hosts keep collecting (cheap) but never
  write.

Cross-referencing: the tracer carries the journal's `run_id` in the
trace metadata, and spans carry a `step` arg where the caller knows it,
so a Perfetto timeline and an obs_report table describe the same run.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from deep_vision_tpu.obs import locksmith, propagate
from deep_vision_tpu.obs.registry import is_primary_host, process_suffix

# Trace-event timestamps are microseconds. Use an epoch-anchored clock so
# trace ts and journal ts (unix seconds) cross-reference directly:
# perf_counter offsets from a wall-clock anchor keep monotonicity within
# the run while staying on the journal's time axis.
_ANCHOR_WALL = time.time()
_ANCHOR_PERF = time.perf_counter()


def _now_us() -> float:
    return (_ANCHOR_WALL + (time.perf_counter() - _ANCHOR_PERF)) * 1e6


class _NullSpan:
    """Shared do-nothing span: the off-switch for every call site."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args) -> None:
        pass


_NULL_SPAN = _NullSpan()


class _Span:
    """One in-flight span; records a complete ("X") event on exit."""

    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.args = args

    def set(self, **args) -> None:
        """Attach args discovered mid-span (e.g. the optimizer step, which
        is only known after the state fetch)."""
        self.args.update(args)

    def __enter__(self):
        self._t0 = _now_us()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.args.setdefault("error", exc_type.__name__)
        self._tracer._record(self.name, self._t0, _now_us(), self.args)
        return False


class Tracer:
    """Buffered Chrome trace-event writer for one run.

    Usage:

        tracer = Tracer("runs/train.trace.json", run_id=journal.run_id)
        with tracer.span("train/step", step=12):
            ...
        tracer.close()

    or install it process-wide (`set_tracer`) and use the module-level
    `span(...)` from any layer.
    """

    def __init__(self, path: str, run_id: Optional[str] = None,
                 flush_every: int = 256, max_events: int = 200_000,
                 per_process: bool = True):
        # multi-process runs: one trace file per host at `<path>.pN` (same
        # contract as the journal) — followers become writers of their own
        # file instead of silent collectors
        sfx = process_suffix() if per_process else ""
        self.path = path + sfx
        self.run_id = run_id
        self.flush_every = max(1, int(flush_every))
        # ring-buffer cap: a post-mortem wants the most RECENT window, and
        # an uncapped buffer on a week-long run is an OOM of its own
        self.max_events = max(1000, int(max_events))
        self._events: List[dict] = []
        self._dropped = 0
        self._lock = locksmith.lock("obs.trace.buffer")
        # flush serialization is separate from the buffer lock: the file
        # write must not block recorders, but two concurrent flushes with
        # one tmp name would publish a torn file
        self._flush_lock = locksmith.lock("obs.trace.flush")
        self._closed = False
        self._primary = is_primary_host() or bool(sfx)
        self._pid = os.getpid()
        self._thread_named: Dict[int, str] = {}  # ident -> last-seen name
        self._unflushed = 0
        if self._primary:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
        atexit.register(self._atexit)

    # -- recording ---------------------------------------------------------

    def span(self, name: str, **args) -> _Span:
        # cross-process causality: a span opened while a trace context is
        # installed (obs/propagate.py) carries the request's ids, so the
        # Perfetto view and the journal agree on which request this was
        ctx = propagate.current()
        if ctx is not None and "trace_id" not in args:
            args = dict(args, **ctx.fields())
        return _Span(self, name, args)

    def event(self, name: str, t0_us: float, t1_us: Optional[float] = None,
              **args) -> None:
        """Explicit complete event for callers that time a region that
        doesn't nest as a with-block (e.g. the data pipeline's per-batch
        assembly, which spans loop iterations)."""
        self._record(name, t0_us, t1_us if t1_us is not None else _now_us(),
                     args)

    def _record(self, name: str, t0_us: float, t1_us: float,
                args: dict) -> None:
        if self._closed or not self._primary:
            # followers never write a file, so buffering their events
            # would be a leak with no consumer
            return
        t = threading.current_thread()
        tid = t.ident or 0
        ev = {
            "name": name,
            "ph": "X",
            "ts": round(t0_us, 1),
            "dur": round(max(t1_us - t0_us, 0.0), 1),
            "pid": self._pid,
            "tid": tid,
        }
        if args:
            ev["args"] = {k: _arg(v) for k, v in args.items()}
        with self._lock:
            # keyed on ident AND name: the OS reuses thread ids, so a
            # short-lived worker's successor with the same ident still
            # gets its own metadata event (last-writer-wins in viewers)
            if self._thread_named.get(tid) != t.name:
                self._thread_named[tid] = t.name
                self._events.append({
                    "name": "thread_name", "ph": "M", "pid": self._pid,
                    "tid": tid, "args": {"name": t.name},
                })
            self._events.append(ev)
            if len(self._events) > self.max_events:
                # drop the oldest quarter in one slice (per-event pops
                # would be O(n) each); metadata reports the loss
                cut = len(self._events) // 4
                del self._events[:cut]
                self._dropped += cut
            self._unflushed += 1
            # adaptive cadence: every flush rewrites the whole file (the
            # price of always-valid JSON), so the interval grows with the
            # buffer — total I/O stays ~4x the final file size instead of
            # O(n^2/flush_every)
            do_flush = self._unflushed >= max(self.flush_every,
                                              len(self._events) // 4)
        if do_flush:
            self.flush()

    # -- persistence -------------------------------------------------------

    def flush(self) -> None:
        """Atomically rewrite the trace file with everything recorded so
        far; the on-disk file is valid Chrome trace JSON at all times."""
        if not self._primary:
            return
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
            self._unflushed = 0
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {"run_id": self.run_id, "pid": self._pid,
                         "dropped_events": dropped},
        }
        # serialized: concurrent flushes sharing one tmp name would
        # truncate each other mid-dump and publish a torn file
        with self._flush_lock:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)

    def _atexit(self) -> None:
        if not self._closed:
            self.close()

    def close(self) -> None:
        if self._closed:
            return
        self.flush()
        self._closed = True
        atexit.unregister(self._atexit)

    @property
    def num_events(self) -> int:
        with self._lock:
            return len(self._events)

    def tail(self, n: int = 256) -> List[dict]:
        """The most recent `n` buffered events (complete + metadata) — the
        span tail a flight-recorder bundle snapshots at dump time."""
        with self._lock:
            return [dict(e) for e in self._events[-max(0, int(n)):]]


def _arg(v):
    """Span args must never poison the JSON dump (same contract as
    journal._jsonable, minus containers — span args are flat)."""
    if isinstance(v, (str, int, bool)) or v is None:
        return v
    if isinstance(v, float):
        return v if v == v and abs(v) != float("inf") else repr(v)
    try:
        return float(v)
    except (TypeError, ValueError):
        return repr(v)


# -- process-wide active tracer ----------------------------------------------

_active: Optional[Tracer] = None


def set_tracer(tracer: Optional[Tracer]) -> None:
    """Install (or clear, with None) the process-wide tracer that the
    module-level `span`/`trace_event` report to."""
    global _active
    _active = tracer


def get_tracer() -> Optional[Tracer]:
    return _active


def span(name: str, **args):
    """A span on the active tracer, or a shared no-op when tracing is off.

    The instrumentation idiom used by every layer:

        with span("data/fetch", loader=self.name):
            batch = q.get()
    """
    t = _active
    if t is None:
        return _NULL_SPAN
    return t.span(name, **args)


def trace_event(name: str, t0_us: float, t1_us: Optional[float] = None,
                **args) -> None:
    """Explicit complete event on the active tracer (no-op when off)."""
    t = _active
    if t is not None:
        t.event(name, t0_us, t1_us, **args)


def now_us() -> float:
    """The tracer's clock, for callers building explicit trace_event()s."""
    return _now_us()


def traced(name: Optional[str] = None, **static_args) -> Callable:
    """Decorator: wrap a function in a span named after it.

        @traced("checkpoint/save")
        def save(...): ...
    """
    def deco(fn: Callable) -> Callable:
        span_name = name or fn.__qualname__

        def wrapper(*a, **kw):
            with span(span_name, **static_args):
                return fn(*a, **kw)

        wrapper.__name__ = fn.__name__
        wrapper.__qualname__ = fn.__qualname__
        wrapper.__doc__ = fn.__doc__
        wrapper.__wrapped__ = fn
        return wrapper

    return deco
