"""Pallas TPU kernels for profiled hotspots.

The reference has no custom kernels (its C++/CUDA lives inside torch/TF —
SURVEY.md §2); here the hot ops XLA can't fuse optimally get hand-written
TPU kernels with lax fallbacks for non-TPU platforms and interpret-mode
tests on CPU.
"""
from deep_vision_tpu.ops.pallas.bn_act import (
    fused_bn_act,
    fused_scale_bias_act,
    fusion_enabled,
    reference_scale_bias_act,
)
from deep_vision_tpu.ops.pallas.flash_attention import flash_attention
from deep_vision_tpu.ops.pallas.nms import pallas_nms

__all__ = [
    "flash_attention",
    "fused_bn_act",
    "fused_scale_bias_act",
    "fusion_enabled",
    "pallas_nms",
    "reference_scale_bias_act",
]
