"""Data layer: record IO, dataset readers, host-side transforms, device feed.

TPU-native replacement for the reference's two input stacks — the per-model
cv2/PIL python Datasets (ResNet/pytorch/data_load.py:14-69) and the
tf.data+TFRecord pipelines (YOLO/tensorflow/train.py:260-273,
ResNet/tensorflow/train.py:148-214). One layer, shared by every model:

- `records` / `example_codec`: TFRecord-compatible container + tf.train.Example
  wire codec, implemented natively (no TensorFlow dependency) so the same
  shard files the reference's converters produced remain readable; strict
  readers raise on corruption, `read_records_tolerant` + `BadRecordBudget`
  skip-and-dead-letter it under a bound (README: "Surviving bad data");
- `datasets`: MNIST idx, ImageNet folder, and record-backed datasets with the
  reference's Example schemas (ImageNet 9-field, VOC/COCO boxes, MPII joints);
- `transforms`: the hand-written numpy/PIL augmentation set
  (Rescale/RandomCrop/CenterCrop/Flip/ColorJitter/Normalize) plus the
  bbox-preserving detection augments;
- `pipeline`: threaded decode/augment workers -> fixed-shape batches ->
  `shard_batch` onto the mesh (the host->device boundary);
- `snapshot`: the input pipeline as a checkpoint citizen — a
  `DataLoaderState` (epoch, batches, shard cursor, budget spend) rides
  the checkpoint sidecar so a kill/resume replays a byte-identical
  batch stream instead of silently restarting from shard zero;
- `service`: the shared dataset service — decode/augment in a spawned
  worker pool serving pre-collated batches over local sockets to any
  number of trainers/evals, with worker-death supervision and
  client-side reconnect (README "The data plane").
"""
from deep_vision_tpu.data.example_codec import decode_example, encode_example
from deep_vision_tpu.data.records import (
    BadRecordBudget,
    BadRecordBudgetExceeded,
    RecordWriter,
    read_records,
    read_records_tolerant,
    record_iterator,
    write_records,
)
from deep_vision_tpu.data.datasets import (
    ImageFolderDataset,
    MnistDataset,
    RecordDataset,
)
from deep_vision_tpu.data import transforms
from deep_vision_tpu.data.pipeline import DataLoader, Compose
from deep_vision_tpu.data.device_prefetch import DevicePrefetcher, PlacedBatch
from deep_vision_tpu.data.service import (
    DataService,
    DataServiceClient,
    shard_for_host,
)
from deep_vision_tpu.data.snapshot import (
    DataLoaderState,
    SnapshotError,
    SnapshotMismatch,
    SnapshotUnsupported,
)

__all__ = [
    "DataLoaderState",
    "DataService",
    "DataServiceClient",
    "SnapshotError",
    "SnapshotMismatch",
    "SnapshotUnsupported",
    "shard_for_host",
    "DevicePrefetcher",
    "PlacedBatch",
    "BadRecordBudget",
    "BadRecordBudgetExceeded",
    "decode_example",
    "encode_example",
    "RecordWriter",
    "read_records",
    "read_records_tolerant",
    "record_iterator",
    "write_records",
    "ImageFolderDataset",
    "MnistDataset",
    "RecordDataset",
    "transforms",
    "DataLoader",
    "Compose",
]
