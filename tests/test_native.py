"""Native (C++) record reader vs the pure-Python twin.

Builds native/libdvtpu.so via make if missing; skips when no toolchain.
"""
import os
import shutil
import subprocess

import numpy as np
import pytest

from deep_vision_tpu.data.records import write_records, read_records

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")


@pytest.fixture(scope="module")
def native():
    lib = os.path.join(NATIVE_DIR, "libdvtpu.so")
    if not os.path.exists(lib):
        if shutil.which("make") is None or shutil.which("g++") is None:
            pytest.skip("no native toolchain")
        subprocess.run(["make", "-C", NATIVE_DIR], check=True,
                       capture_output=True)
    from deep_vision_tpu.data import native as native_mod

    assert native_mod.load_library() is not None
    return native_mod


def _shards(tmp_path, n_shards=3, n_records=50, size=1000):
    rng = np.random.RandomState(0)
    paths = []
    for s in range(n_shards):
        p = str(tmp_path / f"shard{s}.tfrecord")
        write_records(p, [rng.bytes(size) for _ in range(n_records)])
        paths.append(p)
    return paths


def test_native_single_file_matches_python(native, tmp_path):
    (path,) = _shards(tmp_path, n_shards=1)
    assert list(native.read_records_native(path)) == list(read_records(path))


def test_native_crc_matches_python(native, tmp_path):
    import ctypes

    from deep_vision_tpu.data.records import _masked_crc

    lib = native.load_library()
    for payload in (b"", b"x", b"hello world" * 100):
        arr = (ctypes.c_uint8 * len(payload))(*payload)
        assert lib.dv_masked_crc32c(arr, len(payload)) == _masked_crc(payload)


def test_native_detects_corruption(native, tmp_path):
    (path,) = _shards(tmp_path, n_shards=1)
    with open(path, "r+b") as f:
        f.seek(20)
        f.write(b"\xff\xff")
    with pytest.raises(IOError):
        list(native.read_records_native(path))
    # pool is sticky-corrupt too
    with pytest.raises(IOError):
        list(native.pool_records_native([path]))


def test_native_truncation_is_eof_error(native, tmp_path):
    # exception parity with the python reader: truncation -> EOFError
    (path,) = _shards(tmp_path, n_shards=1, n_records=3, size=500)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 100)
    with pytest.raises(EOFError):
        list(native.read_records_native(path))
    with pytest.raises(EOFError):
        list(read_records(path))  # python twin agrees
    with pytest.raises(EOFError):
        list(native.pool_records_native([path]))


def test_native_pool_complete_no_dups(native, tmp_path):
    paths = _shards(tmp_path, n_shards=4, n_records=100)
    expected = sorted(sum((list(read_records(p)) for p in paths), []))
    got = sorted(native.pool_records_native(paths, num_threads=4))
    assert got == expected


def test_native_missing_file(native, tmp_path):
    with pytest.raises(FileNotFoundError):
        list(native.read_records_native(str(tmp_path / "nope.tfrecord")))
    with pytest.raises(IOError):
        list(native.pool_records_native([str(tmp_path / "nope.tfrecord")]))


def test_native_empty_and_large_records(native, tmp_path):
    path = str(tmp_path / "mixed.tfrecord")
    payloads = [b"", b"a", np.random.RandomState(1).bytes(5_000_000)]
    write_records(path, payloads)
    assert list(native.read_records_native(path)) == payloads
