"""Zero-downtime canary weight swap: new weights under live traffic.

The third fleet failure mode after replica death and overload: updating
the model without stopping the fleet. The mechanism exploits the AOT
engine's shape — every compiled (model, bucket) executable takes the
variables as a RUNTIME argument (argnum 0, never donated), so new
weights with the same avals run on the executables already warmed at
startup. A swap therefore never touches the compiler; the controller
proves it with the backend-compile counter at every step.

The state machine (each transition a typed `serve_swap` journal event,
`phase` in warm/canary/promote/rollback, `outcome` in
started/ok/failed)::

    warm      load the checkpoint via the cross-mesh restore path
              (core/checkpoint.restore_tree(mesh=): arrays land placed
              for the serving mesh, resharded if the checkpoint was
              written on a different topology), validate avals against
              the serving weights, bind a SHADOW engine sharing the
              primary's executables (Engine.clone_with_variables), and
              probe every swapped model once — compile delta must be 0.
              Any failure here rolls back before a single user request
              touches the new weights. The `serve.replica` fault point
              fires at the load step, so a failed swap-restore is
              deterministically injectable.
    canary    mount the shadow as a canary replica taking x% of live
              traffic (ReplicaPool.add_canary; health_policy=abort so
              non-finite outputs become countable request errors), wait
              for `min_canary_requests` verdict samples.
    promote   canary healthy (error rate within budget, p99 within the
              SLO target, replica alive): hot-swap the new variables
              into every base replica's engine, then unmount the canary.
    rollback  canary unhealthy (errors / SLO violation / canary death)
              or warm failed: unmount, old weights never stopped
              serving. Auto — a 3am swap needs no operator.

Synthetic warm probes run on zeros: they prove plumbing, shapes, and
the zero-compile contract, NOT data-dependent health — weights can be
finite on a zero probe and explode on real traffic, which is exactly
why the canary phase exists and judges real requests.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from deep_vision_tpu.obs import locksmith
from deep_vision_tpu.obs.stepclock import recompile_count
from deep_vision_tpu.resilience import faults
from deep_vision_tpu.serve.engine import Engine, ServeError
from deep_vision_tpu.serve.pool import ReplicaPool

SWAP_PHASES = ("warm", "canary", "promote", "rollback")
SWAP_OUTCOMES = ("started", "ok", "failed")


class SwapController:
    """Drives one canary weight swap at a time over a ReplicaPool.

    Wire-up (what tools/loadgen.py's fleet smoke does)::

        swapper = SwapController(pool, journal=journal, canary_pct=25,
                                 min_canary_requests=8, slo_ms=500.0)
        verdict = swapper.swap("checkpoints/resnet50", step=1200)
        # {'outcome': 'promoted' | 'rolled_back', 'timeline': [...]}

    `swap()` blocks through the state machine; live traffic must keep
    flowing from client threads meanwhile — the canary verdict is
    sampled from real requests the pool diverts, not from synthetic
    probes.
    """

    def __init__(self, pool: ReplicaPool, journal=None,
                 canary_pct: int = 25, min_canary_requests: int = 8,
                 max_canary_error_rate: float = 0.0,
                 slo_ms: Optional[float] = None,
                 canary_timeout_s: float = 30.0,
                 poll_interval_s: float = 0.02,
                 clock=time.monotonic, sleep=time.sleep):
        self.pool = pool
        self.journal = journal
        self.canary_pct = int(canary_pct)
        self.min_canary_requests = int(min_canary_requests)
        self.max_canary_error_rate = float(max_canary_error_rate)
        self.slo_ms = slo_ms
        self.canary_timeout_s = float(canary_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self._clock = clock
        self._sleep = sleep
        self._swap_lock = locksmith.lock("serve.swap")
        self._swap_seq = 0

    # -- journal plumbing ----------------------------------------------------

    def _emit(self, timeline: list, swap_id: int, phase: str, outcome: str,
              **fields) -> None:
        row = {"swap": swap_id, "phase": phase, "outcome": outcome, **fields}
        timeline.append(row)
        if self.journal is not None:
            self.journal.write("serve_swap", **row)

    # -- the load + shadow-bind step -----------------------------------------

    def _load(self, source, step, models, mesh) -> Dict[str, object]:
        """Checkpoint -> {model: variables}, placed for the serving mesh.

        `source` is a core/checkpoint.CheckpointManager (or anything with
        its restore_tree contract) or a checkpoint directory path. The
        restore rides the cross-mesh path: the sidecar's sharding
        metadata re-places every leaf against `mesh`, so a checkpoint
        written by an 8-device trainer swaps into a 1-device serving
        replica (or vice versa) without a resave."""
        faults.fire("serve.replica")  # the injectable swap-restore boundary
        engine = self.pool.primary_engine()
        models = tuple(models or engine.models)
        template = {name: engine.entry(name).variables for name in models}
        owned = None
        try:
            if isinstance(source, str):
                from deep_vision_tpu.core.checkpoint import CheckpointManager

                owned = CheckpointManager(source, journal=self.journal)
                mgr = owned
            else:
                mgr = source
            tree, _host = mgr.restore_tree(template, step=step, mesh=mesh)
        finally:
            if owned is not None:
                owned.close()
        if tree is None:
            raise ServeError(
                f"no valid checkpoint to swap in from {source!r} "
                f"(step={step})")
        return {name: tree[name] for name in models}

    def _probe(self, shadow: Engine, models) -> int:
        """One zeros-batch per swapped model through the SHARED
        executables; returns the backend-compile delta (must be 0)."""
        c0 = recompile_count()
        for name in models:
            entry = shadow.entry(name)
            bucket = min(entry.buckets)
            shadow.run(name, np.zeros((bucket,) + entry.input_shape,
                                      entry.dtype))
        return recompile_count() - c0

    # -- the state machine ---------------------------------------------------

    def swap(self, source, step: Optional[int] = None, models=None,
             mesh=None) -> dict:
        """Run warm -> canary -> promote|rollback; returns the verdict
        dict {outcome, swap, timeline}. One swap at a time (a second
        concurrent call raises)."""
        if not self._swap_lock.acquire(blocking=False):
            raise ServeError("a swap is already in flight")
        try:
            self._swap_seq += 1
            swap_id = self._swap_seq
            timeline: list = []

            def emit(phase, outcome, **fields):
                self._emit(timeline, swap_id, phase, outcome, **fields)

            # -- warm ------------------------------------------------------
            emit("warm", "started", step=step)
            try:
                new_vars = self._load(source, step, models, mesh)
                shadow = self.pool.primary_engine().clone_with_variables(
                    new_vars)
                delta = self._probe(shadow, new_vars)
                if delta:
                    raise ServeError(
                        f"shadow warm compiled {delta} executable(s); a "
                        "hot swap must reuse the warmed menu — re-warm a "
                        "new pool for shape/structure changes")
            except Exception as e:
                emit("warm", "failed",
                     error=f"{type(e).__name__}: {e}"[:200])
                emit("rollback", "ok", reason="warm_failed")
                return {"outcome": "rolled_back", "swap": swap_id,
                        "reason": "warm_failed", "timeline": timeline}
            emit("warm", "ok", compile_delta=0, models=sorted(new_vars))

            # -- canary ----------------------------------------------------
            rid = self.pool.add_canary(shadow, self.canary_pct)
            emit("canary", "started", replica=rid, pct=self.canary_pct)
            verdict = self._watch_canary()
            if not verdict.pop("healthy"):
                emit("canary", "failed", replica=rid, **verdict)
                self.pool.remove_canary()
                emit("rollback", "ok", reason=verdict.get("reason", "?"))
                return {"outcome": "rolled_back", "swap": swap_id,
                        "reason": verdict.get("reason"),
                        "timeline": timeline}
            emit("canary", "ok", replica=rid, **verdict)

            # -- promote ---------------------------------------------------
            # base replicas first, canary unmounted after: at every
            # instant the whole request stream has a serving target
            self.pool.promote_variables(new_vars)
            self.pool.remove_canary()
            emit("promote", "ok", models=sorted(new_vars))
            return {"outcome": "promoted", "swap": swap_id,
                    "timeline": timeline}
        finally:
            self._swap_lock.release()

    def _watch_canary(self) -> dict:
        """Sample the canary until enough verdict traffic (or timeout /
        canary death). Healthy = alive, error rate within budget, p99
        within the SLO target."""
        deadline = self._clock() + self.canary_timeout_s
        status = self.pool.canary_status()
        while self._clock() < deadline:
            status = self.pool.canary_status()
            if status is None:
                return {"healthy": False, "reason": "canary_missing"}
            if status["state"] == "dead":
                return {"healthy": False, "reason": "replica_lost",
                        "canary_ok": status["completed"],
                        "canary_err": status["errors"]}
            done = (status["completed"] + status["errors"]
                    + status["cancelled"])
            if done >= self.min_canary_requests:
                break
            self._sleep(self.poll_interval_s)
        else:
            return {"healthy": False, "reason": "canary_timeout",
                    "canary_ok": status["completed"] if status else 0,
                    "canary_err": status["errors"] if status else 0}
        judged = status["completed"] + status["errors"]
        rate = status["errors"] / max(1, judged)
        out = {"canary_ok": status["completed"],
               "canary_err": status["errors"],
               "error_rate": round(rate, 4)}
        slo = status.get("slo") or {}
        p99 = max((r.get("p99_ms", 0.0) for r in slo.values()), default=0.0)
        if p99:
            out["p99_ms"] = round(p99, 3)
        if rate > self.max_canary_error_rate:
            return {"healthy": False, "reason": "errors", **out}
        if self.slo_ms is not None and p99 > self.slo_ms:
            return {"healthy": False, "reason": "slo", **out}
        return {"healthy": True, **out}
