"""Parallel scaling-efficiency measurement over data-axis sub-meshes.

The MULTICHIP evidence gap this closes: five rounds of multi-chip runs
proved `loss=OK` on a `{'data': 4, 'model': 2}` dryrun and nothing else —
no number ever said what the second through eighth chip BUY. This module
measures it: the same table-sharded train step timed at data={1,2,4,8}
sub-meshes of the available devices, reporting throughput, per-device
examples/s, and the efficiency fraction vs the 1-device baseline (1.0 =
linear scaling; the gap is the collective/dispatch cost).

Shared by `bench.py --multichip` (the journal/bench-JSON emitter, the
MULTICHIP_r0N artifact source), the `__graft_entry__.dryrun_multichip`
scaling section, and `make shard-smoke` — one measurement, three
consumers, so the numbers are comparable.

On a real multi-chip slice the rows are the scaling story; on a forced
virtual-CPU mesh (every "device" is the same host core) efficiency
honestly degrades toward 1/n — the MECHANISM is what the CPU runs prove,
the number is what the TPU runs report.
"""
from __future__ import annotations

import time
from typing import Optional, Sequence

__all__ = ["measure_scaling", "scaling_result", "format_rows"]

#: sub-mesh sizes the bench reports when enough devices exist
DEFAULT_SUB_SIZES = (1, 2, 4, 8)


def _build_step(devices, batch_per_device: int, rules):
    """(jitted step, placed state, placed batch): a slim flagship-family
    (BottleneckBlock ResNet) train step on a pure-DP mesh over
    `devices`, state placed per the declarative table. Slim for the
    same reason the dryrun's is: the scaling signal is per-step wall
    time, which extra depth inflates without adding information."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.losses.classification import classification_loss_fn
    from deep_vision_tpu.models.resnet import BottleneckBlock, ResNet
    from deep_vision_tpu.parallel.mesh import create_mesh, data_sharding
    from deep_vision_tpu.train.optimizers import build_optimizer

    n = len(devices)
    mesh = create_mesh(devices=devices, data=n, model=1)
    model = ResNet(stage_sizes=(1, 1), block=BottleneckBlock, width=16,
                   num_classes=32)
    tx = build_optimizer("sgd", learning_rate=0.1, momentum=0.9)
    sample = jnp.ones((2, 32, 32, 3), jnp.float32)
    state = create_train_state(model, tx, sample)
    shardings, _report = rules.resolve(state, mesh)
    state = jax.device_put(state, shardings)

    rng = np.random.RandomState(0)
    batch_size = batch_per_device * n
    batch = {
        "image": rng.rand(batch_size, 32, 32, 3).astype(np.float32),
        "label": (np.arange(batch_size) % 32).astype(np.int32),
    }
    batch = {k: jax.device_put(v, data_sharding(mesh, np.asarray(v).ndim))
             for k, v in batch.items()}

    def train_step(state, batch):
        step_rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            variables = {"params": params,
                         "batch_stats": state.batch_stats}
            outputs, new_model_state = state.apply_fn(
                variables, batch["image"], train=True,
                rngs={"dropout": step_rng}, mutable=["batch_stats"],
            )
            loss, _ = classification_loss_fn(outputs, batch)
            return loss, new_model_state["batch_stats"]

        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        return (state.apply_gradients(grads).replace(batch_stats=new_bs),
                loss)

    # AOT-compile instead of dispatch-compiling: the compiled artifact is
    # ALSO the evidence — its HLO names every collective the partitioner
    # inserted for this sub-mesh, which is the predicted comm bill each
    # scaling row carries next to its measured step time (obs/costmodel)
    import warnings

    with warnings.catch_warnings():
        # CPU has no donation support and warns once per lowering
        warnings.filterwarnings("ignore", message="Some donated buffers")
        step = jax.jit(train_step, donate_argnums=0).lower(
            state, batch).compile()
    return step, state, batch, batch_size


def _comm_profile(compiled, state) -> dict:
    """Predicted per-device comm bytes of one compiled scaling step, plus
    the gradient-tree size the all-reduce bytes are checked against."""
    from deep_vision_tpu.obs import costmodel

    hlo = costmodel.hlo_text(compiled)
    inv = costmodel.collective_inventory(hlo) if hlo else []
    return {
        "collective_ops": len(inv),
        "predicted_comm_bytes": costmodel.predicted_collective_bytes(inv),
        "predicted_allreduce_bytes": costmodel.predicted_collective_bytes(
            inv, "all-reduce"),
        "grad_tree_bytes": costmodel.tree_bytes(state.params),
    }


def measure_scaling(
    devices: Optional[Sequence] = None,
    sub_sizes: Sequence[int] = DEFAULT_SUB_SIZES,
    *,
    batch_per_device: int = 8,
    steps: int = 8,
    warmup: int = 2,
    rules=None,
) -> list:
    """Throughput rows at each data-parallel sub-mesh size.

    Each row: {"data": d, "examples_per_sec", "per_device_examples_per_sec",
    "efficiency", "wall_ms_per_step", "batch"}. `efficiency` is
    per-device examples/s over the 1-device row's (the fraction of
    linear scaling realized); the 1-device row anchors at 1.0. Sizes
    exceeding the device count are skipped, not faked.
    """
    import jax

    # degenerate knobs (BENCH_MULTICHIP_STEPS=0, warmup=0) would leave
    # `loss` unbound or divide by a zero baseline — clamp, don't crash
    steps = max(1, int(steps))
    warmup = max(1, int(warmup))
    if rules is None:
        from deep_vision_tpu.parallel.shardmap import RESNET_RULES

        rules = RESNET_RULES
    if devices is None:
        devices = jax.devices()
    sizes = [d for d in sub_sizes if d <= len(devices)]
    rows = []
    base_per_device = None
    base_wall_ms = None
    for d in sizes:
        step, state, batch, batch_size = _build_step(
            list(devices[:d]), batch_per_device, rules)
        comm = _comm_profile(step, state)
        for _ in range(warmup):
            state, loss = step(state, batch)
        float(loss)  # close warmup: a scalar fetch cannot return early
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = step(state, batch)
        float(loss)
        dt = time.perf_counter() - t0
        ex_s = batch_size * steps / dt
        per_dev = ex_s / d
        wall_ms = dt / steps * 1e3
        if base_per_device is None:
            base_per_device = per_dev
            base_wall_ms = wall_ms
        row = {
            "data": int(d),
            "batch": int(batch_size),
            "wall_ms_per_step": round(wall_ms, 3),
            "examples_per_sec": round(ex_s, 1),
            "per_device_examples_per_sec": round(per_dev, 1),
            "efficiency": round(per_dev / base_per_device, 4),
            # predicted comm bill (compiled HLO) next to what it cost in
            # wall time vs the 1-device baseline: the gap ROADMAP item 2's
            # comm/compute overlap work has to close
            "step_time_delta_ms": round(wall_ms - base_wall_ms, 3),
        }
        row.update(comm)
        rows.append(row)
    return rows


def scaling_result(rows: list, *, metric: str = "multichip_scaling") -> dict:
    """The bench-contract payload for a scaling run: headline `value` is
    the efficiency fraction at the LARGEST sub-mesh (the number the
    MULTICHIP_r0N trajectory tracks), rows carry the full curve."""
    import jax

    result = {
        "metric": metric,
        "value": float(rows[-1]["efficiency"]) if rows else 0.0,
        "unit": "efficiency_fraction",
        "rows": rows,
        "n_devices": len(jax.devices()),
    }
    try:
        result["device_kind"] = jax.devices()[0].device_kind
    except Exception:
        pass
    return result


def format_rows(rows: list) -> str:
    """Human lines for the dryrun tail / smoke stdout."""
    out = []
    for r in rows:
        line = (
            f"multichip_scaling: data={r['data']} "
            f"examples_per_sec={r['examples_per_sec']} "
            f"per_device={r['per_device_examples_per_sec']} "
            f"efficiency={r['efficiency']:.3f}")
        if r.get("predicted_comm_bytes") is not None:
            line += (f" comm_bytes={r['predicted_comm_bytes']} "
                     f"dt_ms={r.get('step_time_delta_ms', 0)}")
        out.append(line)
    return "\n".join(out)
