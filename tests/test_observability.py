"""SummaryWriter event-file format + MetricLogger integration + profiler hook
+ the obs/ subsystem (registry, journal, stepclock, trainer wiring)."""
import json
import os
import re

import numpy as np
import pytest

from deep_vision_tpu.core.metrics import MetricLogger
from deep_vision_tpu.core.tensorboard import SummaryWriter
from deep_vision_tpu.obs import (
    Registry,
    RunJournal,
    StepClock,
    read_journal,
    recompile_count,
)

try:
    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader,
    )

    HAS_TB = True
except Exception:
    HAS_TB = False


def test_summary_writer_records_parse(tmp_path):
    w = SummaryWriter(str(tmp_path))
    w.scalar("train/loss", 1.5, 10)
    w.scalar("val/top1", 0.75, 20)
    w.close()
    from deep_vision_tpu.data.records import read_records

    events = list(read_records(w.path))
    assert len(events) == 3  # file_version + 2 scalars
    assert b"brain.Event:2" in events[0]
    assert b"train/loss" in events[1]


@pytest.mark.skipif(not HAS_TB, reason="tensorboard package unavailable")
def test_summary_writer_tensorboard_cross_parity(tmp_path):
    w = SummaryWriter(str(tmp_path))
    w.scalar("loss", 2.25, 7)
    w.close()
    events = [e for e in EventFileLoader(w.path).Load()]
    scalar_events = [e for e in events if e.summary.value]
    assert len(scalar_events) == 1
    (e,) = scalar_events
    assert e.step == 7
    v = e.summary.value[0]
    assert v.tag == "loss"
    # the loader's data_compat pass migrates simple_value -> tensor.float_val
    got = v.simple_value or v.tensor.float_val[0]
    assert got == pytest.approx(2.25)


def test_metric_logger_writes_tb(tmp_path):
    w = SummaryWriter(str(tmp_path))
    lg = MetricLogger(tb_writer=w, name="train", print_every=0)
    lg.start_epoch()
    lg.log_step(1, {"loss": 3.0}, batch_size=4, epoch=0)
    summary = lg.end_epoch(0)
    w.close()
    assert summary["loss"] == pytest.approx(3.0)
    from deep_vision_tpu.data.records import read_records

    payload = b"".join(read_records(w.path))
    assert b"train/batch_loss" in payload
    assert b"train/epoch_loss" in payload


def test_trainer_profiler_hook(tmp_path, mesh8):
    import jax.numpy as jnp

    from deep_vision_tpu.losses import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train import Trainer, build_optimizer

    trainer = Trainer(
        get_model("lenet5", num_classes=4),
        build_optimizer("adam", 1e-3),
        classification_loss_fn,
        jnp.ones((2, 32, 32, 1)),
        mesh=mesh8,
        profile_dir=str(tmp_path / "trace"),
        profile_steps=(1, 3),
    )
    rng = np.random.RandomState(0)
    batch = {"image": rng.rand(8, 32, 32, 1).astype(np.float32),
             "label": rng.randint(0, 4, (8,)).astype(np.int32)}
    for _ in range(5):
        trainer.train_step(batch)
    assert not trainer._profiling
    # a trace directory with at least one .pb/.json artifact was produced
    found = []
    for root, _, files in os.walk(tmp_path / "trace"):
        found += files
    assert found, "profiler produced no trace files"


def test_model_summary_counts():
    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.core.summary import count_params, model_summary
    from deep_vision_tpu.models import get_model

    model = get_model("lenet5", num_classes=10)
    text = model_summary(model, jnp.ones((1, 32, 32, 1)))
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(0)},
        jnp.ones((1, 32, 32, 1)), train=False,
    )
    n = count_params(variables["params"])
    assert f"trainable params: {n:,}" in text
    # table lists every kernel with its shape
    assert "(5, 5, 1, 6)" in text  # LeNet-5 C1 conv kernel


def test_model_summary_resnet_is_abstract_and_fast():
    import jax.numpy as jnp

    from deep_vision_tpu.core.summary import model_summary
    from deep_vision_tpu.models import get_model

    # eval_shape: no real compute, so a 224x224 ResNet-50 summary is instant
    text = model_summary(
        get_model("resnet50", num_classes=1000), jnp.ones((2, 224, 224, 3)),
        max_rows=5,
    )
    assert "trainable params: 25,5" in text  # ~25.5M
    assert "... " in text  # truncation marker


# -- obs/registry ------------------------------------------------------------

# Prometheus text exposition grammar for the line formats we emit
_PROM_LINE = re.compile(
    r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})? "
    r"([0-9.eE+-]+|\+Inf|NaN))$"
)


def test_registry_roundtrip_prometheus_and_jsonl(tmp_path):
    reg = Registry()
    c = reg.counter("steps_total", "steps executed")
    c.inc()
    c.inc(4)
    g = reg.gauge("lr", "learning rate")
    g.set(0.1)
    h = reg.histogram("step_ms", "step wall ms")
    for v in (0.5, 5.0, 50.0, 50.0, 5000.0):
        h.observe(v)

    text = reg.to_prometheus()
    for line in text.strip().splitlines():
        assert _PROM_LINE.match(line), f"bad prometheus line: {line!r}"
    assert "steps_total 5" in text
    assert "# TYPE steps_total counter" in text
    assert "# TYPE step_ms histogram" in text
    assert 'step_ms_bucket{le="+Inf"} 5' in text
    assert "step_ms_count 5" in text
    # cumulative buckets are monotonically non-decreasing
    cum = [int(m.group(1)) for m in
           re.finditer(r'step_ms_bucket\{le="[^"]+"\} (\d+)', text)]
    assert cum == sorted(cum) and cum[-1] == 5

    # JSONL snapshot appends one parseable line per call
    path = tmp_path / "snap.jsonl"
    assert reg.append_jsonl_snapshot(str(path), tag="a")
    assert reg.append_jsonl_snapshot(str(path), tag="b")
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(rows) == 2 and rows[0]["tag"] == "a"
    assert rows[0]["metrics"]["steps_total"] == 5
    assert rows[0]["metrics"]["step_ms"]["count"] == 5
    assert rows[0]["metrics"]["step_ms"]["p50"] == pytest.approx(100, rel=1.1)

    # whole-file prometheus writer (process-0 path on CPU)
    prom = tmp_path / "m.prom"
    assert reg.write_prometheus(str(prom))
    assert prom.read_text() == text


def test_registry_writers_create_parent_dirs(tmp_path):
    # --metrics-export into a fresh runs/ dir must not crash a finished run
    reg = Registry()
    reg.counter("c").inc()
    assert reg.write_prometheus(str(tmp_path / "new" / "m.prom"))
    assert reg.append_jsonl_snapshot(str(tmp_path / "new2" / "s.jsonl"))
    assert (tmp_path / "new" / "m.prom").exists()


def test_prometheus_families_stay_contiguous():
    # creation order interleaves families (latency{a}, requests, latency{b});
    # the exposition format requires each family's lines in one block
    reg = Registry()
    reg.histogram("lat_ms", buckets=[1.0], labels={"task": "yolo"}).observe(0.5)
    reg.counter("reqs", labels={"task": "yolo"}).inc()
    reg.histogram("lat_ms", buckets=[1.0], labels={"task": "pose"}).observe(2.0)
    names = [l.split("# TYPE ")[1].split()[0] if l.startswith("# TYPE") else
             re.match(r"[a-zA-Z_:][a-zA-Z0-9_:]*", l).group(0)
             for l in reg.to_prometheus().strip().splitlines()
             if not l.startswith("# HELP")]
    fam = [re.sub(r"_(bucket|sum|count)$", "", n) for n in names]
    seen, last = set(), None
    for f in fam:
        if f != last:
            assert f not in seen, f"family {f} split across blocks: {fam}"
            seen.add(f)
        last = f


def test_prometheus_export_survives_nonfinite_gauges():
    reg = Registry()
    reg.gauge("maybe_nan").set(float("nan"))
    reg.gauge("neg_inf").set(float("-inf"))
    text = reg.to_prometheus()  # must not raise
    assert "maybe_nan NaN" in text
    assert "neg_inf -Inf" in text
    for line in text.strip().splitlines():
        if not line.startswith("#") and "Inf" not in line:
            assert _PROM_LINE.match(line), line


def test_histogram_snapshot_is_strict_json():
    reg = Registry()
    h = reg.histogram("t_ms", buckets=[1.0])
    h.observe(50.0)  # above the top bucket: quantiles land in +Inf
    snap = h.snapshot()
    assert snap["p50"] is None and snap["p99"] is None
    json.loads(json.dumps(snap, allow_nan=False))  # strict-parser clean


def test_registry_get_or_create_and_kind_conflict():
    reg = Registry()
    assert reg.counter("x") is reg.counter("x")
    assert reg.counter("x", labels={"a": "1"}) is not reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")
    with pytest.raises(ValueError):
        reg.counter("x").inc(-1)


def test_histogram_labels_render_with_le():
    reg = Registry()
    h = reg.histogram("lat_ms", buckets=[1.0, 10.0], labels={"task": "yolo"})
    h.observe(3.0)
    text = reg.to_prometheus()
    assert 'lat_ms_bucket{le="1",task="yolo"} 0' in text
    assert 'lat_ms_bucket{le="10",task="yolo"} 1' in text
    assert 'lat_ms_sum{task="yolo"} 3' in text


# -- obs/journal -------------------------------------------------------------

def test_journal_write_readback_clean_exit(tmp_path):
    path = str(tmp_path / "run.jsonl")
    with RunJournal(path, kind="train") as j:
        j.manifest(config={"name": "lenet5"})
        j.step(1, step_time_ms=12.5, data_wait_ms=0.1, examples_per_sec=100.0)
        j.write("checkpoint", step=1, saved=True)
    events = read_journal(path)
    kinds = [e["event"] for e in events]
    assert kinds == ["run_manifest", "step", "checkpoint", "exit"]
    assert events[0]["config"]["name"] == "lenet5"
    assert events[0]["jax_version"]
    assert events[1]["step_time_ms"] == 12.5
    assert events[-1]["status"] == "clean_exit"
    assert all(e["run_id"] == events[0]["run_id"] for e in events)


def test_journal_crash_marker_and_closer(tmp_path):
    path = str(tmp_path / "crash.jsonl")
    j = RunJournal(path, kind="train")
    j.step(1, step_time_ms=1.0)
    closed = []
    j.add_closer(lambda: closed.append(True))
    j._atexit()  # simulate interpreter shutdown without close()
    events = read_journal(path)
    assert events[-1]["event"] == "crash"
    assert closed == [True], "atexit crash path must run registered closers"
    # idempotent: a real atexit firing after this must not double-write
    j._atexit()
    assert len(read_journal(path)) == len(events)


def test_journal_tolerates_torn_final_line(tmp_path):
    path = tmp_path / "torn.jsonl"
    with RunJournal(str(path)) as j:
        j.step(1, step_time_ms=1.0)
    with open(path, "a") as f:
        f.write('{"event": "step", "truncat')  # crash mid-write
    events = read_journal(str(path))
    assert events[-1]["event"] == "_torn_line"
    assert events[0]["event"] == "step"


# -- obs/stepclock -----------------------------------------------------------

def test_stepclock_sampling_cadence(tmp_path):
    import jax.numpy as jnp

    path = str(tmp_path / "clock.jsonl")
    j = RunJournal(path)
    clock = StepClock(registry=Registry(), journal=j, name="t",
                      sample_every=4, track_memory=False)
    for i in range(8):
        with clock.step(batch_size=16) as rec:
            rec.fence_on(jnp.ones(()) * i)
    j.close()
    assert clock.steps_seen == 8
    assert clock.sync_samples == 2  # steps 4 and 8 only
    steps = [e for e in read_journal(path) if e["event"] == "step"]
    assert len(steps) == 8
    sampled = [e["step"] for e in steps if "sync_ms" in e]
    assert sampled == [4, 8]
    for e in steps:
        assert e["step_time_ms"] >= e["data_wait_ms"]
        assert e["examples_per_sec"] > 0


def test_stepclock_iter_data_times_waits():
    import time as _t

    clock = StepClock(registry=Registry(), name="t2", sample_every=100)

    def slow_data():
        for i in range(3):
            _t.sleep(0.02)
            yield i

    waits = []
    for _ in clock.iter_data(slow_data()):
        with clock.step(batch_size=1) as rec:
            pass
        waits.append(rec.data_wait_ms)
    assert len(waits) == 3
    assert all(w >= 15.0 for w in waits), waits


def test_recompile_count_tracks_backend_compiles():
    import jax
    import jax.numpy as jnp

    before = recompile_count()
    f = jax.jit(lambda x: x * 3 + 1)
    f(jnp.ones((3,)))
    mid = recompile_count()
    assert mid >= before + 1
    f(jnp.ones((3,)))  # cache hit: no new compile
    assert recompile_count() == mid
    f(jnp.ones((5,)))  # new shape: recompile
    assert recompile_count() >= mid + 1


# -- trainer wiring ----------------------------------------------------------

def _tiny_trainer(mesh8, **kw):
    import jax.numpy as jnp

    from deep_vision_tpu.losses import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train import Trainer, build_optimizer

    return Trainer(
        get_model("lenet5", num_classes=4),
        kw.pop("tx", build_optimizer("adam", 1e-3)),
        classification_loss_fn,
        jnp.ones((2, 32, 32, 1)),
        mesh=mesh8,
        **kw,
    )


def _tiny_batches(n=3, bs=8):
    rng = np.random.RandomState(0)
    return [
        {"image": rng.rand(bs, 32, 32, 1).astype(np.float32),
         "label": rng.randint(0, 4, (bs,)).astype(np.int32)}
        for _ in range(n)
    ]


def test_trainer_smoke_journal_and_recompile_gauge(tmp_path, mesh8):
    path = str(tmp_path / "train.jsonl")
    journal = RunJournal(path)
    journal.manifest()
    reg = Registry()
    trainer = _tiny_trainer(mesh8, journal=journal, registry=reg,
                            telemetry_sample_every=2)
    data = _tiny_batches()
    trainer.fit(lambda: data, epochs=1, handle_preemption=False)
    trainer.close()
    journal.close()
    events = read_journal(path)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_manifest" and kinds[-1] == "exit"
    steps = [e for e in events if e["event"] == "step"]
    assert len(steps) == 3
    for e in steps:
        assert "step_time_ms" in e and "data_wait_ms" in e
        assert "examples_per_sec" in e
        assert e["metrics"]["lr"] == pytest.approx(1e-3)
    assert any(e["event"] == "epoch" for e in events)
    # the sampled fence recorded the compile(s) of the jitted train step
    assert reg.gauge("jit_recompiles_total").value >= 1
    assert any("recompiles" in e for e in steps)


def test_trainer_close_stops_leaked_trace(tmp_path, mesh8):
    trainer = _tiny_trainer(
        mesh8, profile_dir=str(tmp_path / "trace"),
        profile_steps=(1, 10_000),  # stop gate unreachable in a short run
    )
    for batch in _tiny_batches(2):
        trainer.train_step(batch)
    assert trainer._profiling, "trace should be open mid-run"
    trainer.close()
    assert not trainer._profiling
    trainer.close()  # idempotent
    found = []
    for root, _, files in os.walk(tmp_path / "trace"):
        found += files
    assert found, "closed trace produced no artifacts"


def test_current_lr_falls_back_to_schedule(mesh8):
    import optax

    sched = optax.exponential_decay(0.1, transition_steps=10, decay_rate=0.5,
                                    staircase=True)
    # plain optax optimizer: no inject_hyperparams, so no opt_state.hyperparams
    trainer = _tiny_trainer(mesh8, tx=optax.sgd(sched), lr_schedule=sched)
    assert trainer.current_lr == pytest.approx(0.1)
    for batch in _tiny_batches(1):
        trainer.train_step(batch)
    assert trainer.current_lr == pytest.approx(float(sched(1)))
    # without the schedule hint the old NaN behavior remains
    t2 = _tiny_trainer(mesh8, tx=optax.sgd(0.1))
    assert np.isnan(t2.current_lr)


def test_metric_logger_perf_fields(tmp_path, capsys):
    reg = Registry()
    w = SummaryWriter(str(tmp_path))
    lg = MetricLogger(tb_writer=w, name="train", print_every=1, registry=reg)
    lg.start_epoch()
    lg.log_step(1, {"loss": 2.0}, batch_size=8, epoch=0, lr=0.1,
                data_wait_ms=3.25, examples_per_sec=123.0)
    w.close()
    out = capsys.readouterr().out
    assert "ex/s=123.0" in out
    assert "data_wait_ms=3.2" in out
    assert reg.gauge("train_loss").value == 2.0
    assert reg.gauge("train_learning_rate").value == pytest.approx(0.1)
    from deep_vision_tpu.data.records import read_records

    payload = b"".join(read_records(w.path))
    assert b"train/examples_per_sec" in payload
    assert b"train/data_wait_ms" in payload


def test_metric_logger_metric_slug():
    from deep_vision_tpu.core.metrics import _metric_slug

    assert _metric_slug("mAP@.5") == "mAP__5"
    assert re.match(r"^[a-zA-Z_][a-zA-Z0-9_]*$", "x_" + _metric_slug("mAP@[.5:.95]"))


# -- data pipeline + inference instrumentation -------------------------------

def test_dataloader_prefetch_metrics():
    from deep_vision_tpu.data.pipeline import DataLoader
    from deep_vision_tpu.obs.registry import get_registry

    reg = get_registry()
    labels = {"loader": "obs-test"}
    before = reg.counter("data_batches_total", labels=labels).value
    ds = [{"x": np.ones((2,), np.float32)} for _ in range(12)]
    dl = DataLoader(ds, batch_size=4, num_workers=1, prefetch=2,
                    name="obs-test")
    assert sum(1 for _ in dl) == 3
    assert reg.counter("data_batches_total", labels=labels).value == before + 3


def test_inference_latency_histogram(mesh8):
    from deep_vision_tpu.inference import make_pose_estimator
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.obs.registry import get_registry

    import jax
    import jax.numpy as jnp

    model = get_model("hourglass", num_stack=1, num_heatmap=4)
    images = jnp.ones((1, 64, 64, 3))
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        images, train=False,
    )
    est = make_pose_estimator(model)
    hist = get_registry().histogram("inference_latency_ms",
                                    labels={"task": "pose"})
    before = hist.count
    out = est({"params": variables["params"],
               **({"batch_stats": variables["batch_stats"]}
                  if "batch_stats" in variables else {})}, images)
    assert out.shape == (1, 4, 3)
    assert hist.count == before + 1
    assert hist.sum > 0


# -- obs_report + bench journal schema ---------------------------------------

def test_obs_report_renders_journal(tmp_path):
    from tools.obs_report import main as report_main, summarize_run

    path = str(tmp_path / "r.jsonl")
    with RunJournal(path, kind="train") as j:
        j.manifest(config={"name": "lenet5", "task": "classification"})
        for i in range(1, 5):
            j.step(i, step_time_ms=10.0 + i, data_wait_ms=0.5,
                   examples_per_sec=800.0, recompiles=2)
        j.write("eval", epoch=0, summary={"top1": 0.9})
    events = read_journal(path)
    s = summarize_run(events)
    assert s["steps"] == 4
    assert s["status"] == "clean_exit"
    assert s["step_time_ms"]["mean"] == pytest.approx(12.5)
    assert s["recompiles"] == 2
    assert report_main([path]) == 0


def test_obs_report_flags_crash(tmp_path):
    from tools.obs_report import summarize_run

    path = str(tmp_path / "c.jsonl")
    j = RunJournal(path)
    j.step(1, step_time_ms=1.0)
    j._atexit()
    s = summarize_run(read_journal(path))
    assert s["status"].startswith("CRASHED")


def test_bench_models_emits_journal_schema(tmp_path):
    from tools.bench_models import main as bench_main

    out = str(tmp_path / "bench.json")
    assert bench_main(["--out", out, "--skip-yolo", "--skip-flash"]) == 0
    events = read_journal(str(tmp_path / "bench.journal.jsonl"))
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_manifest" and kinds[-1] == "exit"
    assert events[0]["kind"] == "bench"
    assert events[0]["config"]["tool"] == "bench_models"
