"""Persistent compiled-executable cache: pay the XLA compiler once.

Every cold path in the system used to pay the compiler in full — serve
``warmup()`` compiled every (model, bucket) pair from scratch, the
elastic rebuild-replay re-jitted after backend loss, a re-exec'd host
recompiled its whole mesh program, and a replica respawned onto a fresh
device had no surviving engine to borrow executables from. This module
closes all four: a content-addressed on-disk store of AOT-serialized
executables (``jax.experimental.serialize_executable`` over the
``lowered.compile()`` artifact), keyed by

    sha256( stablehlo lowering text
          , jax version, jaxlib version
          , platform, platform_version, device kind, device count
          , mesh shape )

so a cache produced under a different compiler, topology, or libtpu
build can never satisfy a lookup — a skewed entry is a MISS by key
construction, and an entry whose *manifest* disagrees with the current
environment (a cache dir copied between machines, a tampered entry, a
hand-rolled key collision) journals a typed ``excache_invalid`` and
falls through to the compiler. Never load a stale executable.

Entries are written with the PR 4/5 file-integrity idiom: payload and
manifest both land tmp + fsync + rename, the manifest embeds the
payload's crc32c, and a corrupt or undeserializable entry is QUARANTINED
to ``<root>/quarantine/`` (so the bad bytes stop matching lookups but
stay inspectable) while the caller falls through to a fresh compile.
Concurrent warmers over one cache dir are safe by the same idiom: stores
race through ``os.replace`` (identical content, last rename wins) and a
reader can never observe a torn entry.

Observability: typed ``excache_hit`` / ``excache_miss`` /
``excache_store`` / ``excache_invalid`` journal events (schemas in
obs/README.md, validated by ``check_journal --strict``) and
``excache_{hits,misses,stores,invalid}_total`` counters.

DONATION CONTRACT: only donation-free lowerings may be cached. The
serialize round trip drops jax's donated-buffer bookkeeping, so a
deserialized DONATING executable silently aliases input buffers the
caller still owns — measured as params corruption and then a segfault
on the second call of a cached train step (the verify drive caught
it). Engine.warmup and the Trainer's cache-path jits therefore lower
without ``donate_argnums`` when a cache is attached; the trade is one
donated buffer's worth of transient memory per cached executable.

The supplementary half is :func:`install_jax_compilation_cache`: JAX's
own persistent compilation cache (``jax_compilation_cache_dir``) catches
the jit-traced compiles this module's explicit AOT entries don't cover
(the Trainer's eval step, one-off host utilities). Note its hits still
count as backend compiles on some backends — the ZERO-compile warmup
contract cache-smoke proves rides the explicit AOT entries only.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Optional, Tuple

import google_crc32c

from deep_vision_tpu.obs import locksmith

__all__ = [
    "ExecutableCache",
    "env_fingerprint",
    "install_jax_compilation_cache",
    "EXCACHE_INVALID_REASONS",
    "EXCACHE_ENV",
]

#: environment variable the CLIs read when --executable-cache is absent
EXCACHE_ENV = "DVT_EXCACHE"

#: why a present entry was refused (journaled as excache_invalid.reason)
EXCACHE_INVALID_REASONS = ("version_skew", "topology_skew", "corrupt",
                           "deserialize_failed")

#: manifest fields that indicate a stale COMPILER when they disagree
_VERSION_FIELDS = ("jax", "jaxlib", "platform_version")
#: manifest fields that indicate the wrong TOPOLOGY when they disagree
_TOPOLOGY_FIELDS = ("platform", "device_kind", "device_count", "mesh_shape")


def env_fingerprint(mesh_shape=None) -> dict:
    """The environment half of the cache key: everything that, if it
    changes, makes a serialized executable unloadable or — worse —
    silently wrong. Versions (the MULTICHIP_r01 skew axis), platform +
    device kind + device count (the topology axis), and the mesh shape
    when the caller compiles against one."""
    import jax
    import jaxlib

    devs = jax.devices()
    pv = str(getattr(getattr(devs[0], "client", None),
                     "platform_version", "") or "")
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": devs[0].platform,
        "platform_version": pv.splitlines()[0] if pv else "",
        "device_kind": devs[0].device_kind,
        "device_count": len(devs),
        "mesh_shape": list(int(d) for d in mesh_shape)
        if mesh_shape is not None else None,
    }


def install_jax_compilation_cache(path: str) -> None:
    """Point JAX's own persistent compilation cache at ``path`` (created
    if missing) and drop the min-compile-time/min-size gates so CPU CI
    exercises the same code path a TPU run does. Idempotent; call before
    the first compile."""
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    for knob, value in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                        ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, value)
        except Exception:
            pass  # knob renamed/absent on this jax: the dir alone suffices


class ExecutableCache:
    """Content-addressed store of AOT-serialized executables.

    Wire-up (what serve/engine.py warmup and the Trainer's cold paths
    do)::

        cache = ExecutableCache(root, journal=journal)
        lowered = jitted.lower(variables, spec)
        compiled, source = cache.get_or_compile(lowered, name="yolo/b4")
        # source == "cache": zero backend compiles; "compiled": stored
        # for the next cold start

    Every entry is two files under ``root``::

        <key>.exe    serialize_executable's payload bytes, written
                     tmp+fsync+rename (call PyTreeDefs are re-derived
                     from the caller's live lowering at load time — a
                     treedef's static aux may not pickle)
        <key>.json   manifest: payload crc32c + the env fingerprint the
                     entry was compiled under + name + created ts

    ``load`` re-validates the manifest against the CURRENT environment
    on every lookup, even though the fingerprint is hashed into the key:
    a copied cache dir or a tampered manifest must journal a typed
    ``excache_invalid`` and fall through to the compiler, never serve a
    stale executable.
    """

    def __init__(self, root: str, journal=None, registry=None,
                 mesh_shape=None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.journal = journal
        self.mesh_shape = mesh_shape
        # lazy: jax.devices() initializes the backend, and callers build
        # the cache object before deciding platform knobs
        self._fp: Optional[dict] = None
        self._lock = locksmith.lock("core.excache")
        if registry is None:
            from deep_vision_tpu.obs.registry import get_registry

            registry = get_registry()
        self._c_hits = registry.counter(
            "excache_hits_total", "executable cache hits")
        self._c_misses = registry.counter(
            "excache_misses_total", "executable cache misses")
        self._c_stores = registry.counter(
            "excache_stores_total", "executable cache stores")
        self._c_invalid = registry.counter(
            "excache_invalid_total",
            "present-but-refused executable cache entries")

    # -- keys ---------------------------------------------------------------

    @property
    def fingerprint(self) -> dict:
        with self._lock:
            if self._fp is None:
                self._fp = env_fingerprint(self.mesh_shape)
            return self._fp

    def key_for(self, lowered) -> str:
        """Content-addressed key: the stablehlo lowering text (shapes,
        dtypes, and the whole computation) + the env fingerprint."""
        text = lowered if isinstance(lowered, str) else lowered.as_text()
        h = hashlib.sha256()
        h.update(text.encode())
        h.update(json.dumps(self.fingerprint, sort_keys=True).encode())
        return h.hexdigest()[:32]

    def _paths(self, key: str) -> Tuple[str, str]:
        return (os.path.join(self.root, key + ".exe"),
                os.path.join(self.root, key + ".json"))

    # -- journal/counter plumbing -------------------------------------------

    def _event(self, event: str, key: str, **fields) -> None:
        if self.journal is not None:
            self.journal.write(event, key=key, **fields)

    def _quarantine(self, key: str, reason: str) -> None:
        """Move both files of a condemned entry aside so the bad bytes
        stop matching lookups but stay inspectable (the PR 4 checkpoint
        idiom). Best-effort: a cross-warmer race losing the rename is
        the same outcome — the entry is gone from the lookup path."""
        qdir = os.path.join(self.root, "quarantine")
        try:
            os.makedirs(qdir, exist_ok=True)
        except OSError:
            return
        for p in self._paths(key):
            if os.path.exists(p):
                try:
                    os.replace(p, os.path.join(
                        qdir, f"{os.path.basename(p)}.{reason}"))
                except OSError:
                    pass

    # -- load ---------------------------------------------------------------

    def _check_manifest(self, manifest: dict) -> Optional[str]:
        """None when the entry's recorded environment matches the current
        one; otherwise the invalid-reason. Version skew is checked before
        topology so a dir copied across BOTH axes reports the one that
        can never heal mid-run."""
        recorded = manifest.get("fingerprint")
        if not isinstance(recorded, dict):
            return "corrupt"
        current = self.fingerprint
        if any(recorded.get(f) != current.get(f) for f in _VERSION_FIELDS):
            return "version_skew"
        if any(recorded.get(f) != current.get(f) for f in _TOPOLOGY_FIELDS):
            return "topology_skew"
        return None

    def load(self, key: str, lowered, name: str = ""):
        """The compiled executable for ``key``, or None (journaling why).

        ``lowered`` is the live jax Lowered object the key was computed
        from: only the serialized executable PAYLOAD lives on disk, and
        the call trees are re-derived from ``lowered.args_info`` /
        ``out_info`` at load time — a PyTreeDef can carry unpicklable
        static aux (a TrainState's apply_fn/tx), so it must never be
        part of the entry.

        miss     -> no entry on disk
        invalid  -> entry present but version/topology-skewed (refused,
                    left in place: it may be valid for the env that wrote
                    it), or corrupt / undeserializable (quarantined)
        """
        exe_path, man_path = self._paths(key)
        if not (os.path.exists(exe_path) and os.path.exists(man_path)):
            self._c_misses.inc()
            self._event("excache_miss", key, name=name)
            return None
        try:
            with open(man_path) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError):
            self._quarantine(key, "corrupt")
            self._c_invalid.inc()
            self._event("excache_invalid", key, name=name, reason="corrupt",
                        detail="unreadable manifest")
            return None
        skew = self._check_manifest(manifest)
        if skew == "corrupt":
            self._quarantine(key, "corrupt")
            self._c_invalid.inc()
            self._event("excache_invalid", key, name=name, reason="corrupt",
                        detail="manifest carries no fingerprint")
            return None
        if skew is not None:
            # NOT quarantined: the entry may be perfectly valid for the
            # environment that wrote it (a shared cache mount serving two
            # pools mid-upgrade) — it is merely unusable HERE
            self._c_invalid.inc()
            self._event("excache_invalid", key, name=name, reason=skew,
                        recorded={f: manifest["fingerprint"].get(f)
                                  for f in _VERSION_FIELDS + _TOPOLOGY_FIELDS
                                  if manifest["fingerprint"].get(f)
                                  != self.fingerprint.get(f)})
            return None
        try:
            with open(exe_path, "rb") as f:
                blob = f.read()
        except OSError as e:
            self._c_misses.inc()
            self._event("excache_miss", key, name=name,
                        detail=f"{type(e).__name__}: {e}"[:200])
            return None
        if int(google_crc32c.value(blob)) != manifest.get("crc32c"):
            self._quarantine(key, "corrupt")
            self._c_invalid.inc()
            self._event("excache_invalid", key, name=name, reason="corrupt",
                        detail="payload crc32c mismatch")
            return None
        try:
            import jax.tree_util as jtu
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            compiled = deserialize_and_load(
                blob,
                jtu.tree_structure(lowered.args_info),
                jtu.tree_structure(lowered.out_info))
        except Exception as e:
            # crc-valid bytes the runtime refuses: a PJRT build drift the
            # fingerprint fields don't capture — condemn and recompile
            self._quarantine(key, "deserialize_failed")
            self._c_invalid.inc()
            self._event("excache_invalid", key, name=name,
                        reason="deserialize_failed",
                        detail=f"{type(e).__name__}: {e}"[:200])
            return None
        self._c_hits.inc()
        self._event("excache_hit", key, name=name, bytes=len(blob))
        return compiled

    # -- store --------------------------------------------------------------

    def store(self, key: str, compiled, name: str = "") -> bool:
        """Serialize + write one entry (payload first, manifest last, both
        tmp+fsync+rename). Never raises: a backend that cannot serialize
        executables degrades to compile-every-time with a journaled note,
        not a crashed warmup."""
        try:
            from jax.experimental.serialize_executable import serialize

            # payload bytes ONLY: the in/out PyTreeDefs are re-derived
            # from the caller's live lowering at load time (their static
            # aux — e.g. a TrainState's apply_fn — does not pickle)
            blob = bytes(serialize(compiled)[0])
        except Exception as e:
            if self.journal is not None:
                self.journal.write(
                    "note", note="excache_serialize_unsupported", key=key,
                    name=name, error=f"{type(e).__name__}: {e}"[:200])
            return False
        exe_path, man_path = self._paths(key)
        manifest = {
            "key": key,
            "name": name,
            "crc32c": int(google_crc32c.value(blob)),
            "bytes": len(blob),
            "fingerprint": self.fingerprint,
            "created": time.time(),
        }
        try:
            # payload BEFORE manifest: a reader keys presence on the pair,
            # so the torn window (payload without manifest) reads as a
            # plain miss, never a corrupt entry
            import threading as _threading

            for path, data in ((exe_path, blob),
                               (man_path,
                                json.dumps(manifest).encode())):
                # pid+thread-unique tmp: same-process concurrent warmers
                # (threads) racing the same key must not truncate each
                # other's in-flight tmp file — a torn payload published
                # under a full-crc manifest would quarantine a good entry
                tmp = path + f".tmp-{os.getpid()}-{_threading.get_ident()}"
                with open(tmp, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
        except OSError as e:
            if self.journal is not None:
                self.journal.write(
                    "note", note="excache_store_failed", key=key, name=name,
                    error=f"{type(e).__name__}: {e}"[:200])
            return False
        self._c_stores.inc()
        self._event("excache_store", key, name=name, bytes=len(blob))
        return True

    # -- the one-call form ---------------------------------------------------

    def get_or_compile(self, lowered, name: str = ""):
        """(compiled, source): load ``lowered``'s executable from the
        cache, or compile and store it. source is "cache" (zero backend
        compiles) or "compiled" (the cold path, now paid forward)."""
        key = self.key_for(lowered)
        compiled = self.load(key, lowered, name=name)
        if compiled is not None:
            return compiled, "cache"
        compiled = lowered.compile()
        self.store(key, compiled, name=name)
        return compiled, "compiled"

    def entries(self) -> list:
        """Manifest dicts of every readable entry (diagnostics/preflight)."""
        out = []
        for fn in sorted(os.listdir(self.root)):
            if fn.endswith(".json"):
                try:
                    with open(os.path.join(self.root, fn)) as f:
                        out.append(json.load(f))
                except (OSError, json.JSONDecodeError):
                    continue
        return out
