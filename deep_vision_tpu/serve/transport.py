"""The serving front door: a socket-level HTTP transport over the fleet.

Everything below this module speaks Futures; everything above it speaks
HTTP. One `Transport` binds a stdlib `ThreadingHTTPServer` (the
obs/telemetry.py idiom — no new deps, daemon handler threads, port-0
auto-assign) in front of any backend exposing
`submit(model, image, deadline_ms=) -> Future` — a `serve.Server`, a
`ReplicaPool`, or a `ProcReplicaPool` — and turns in-process verdicts
into real status codes a production client can act on:

    POST /v1/<model>      body {"image": [...]}  ->  200 + outputs
    GET  /healthz         readiness (503 while draining)
    GET  /ledgerz         the transport request ledger (JSON)

The status-code contract (the shed path made visible):

    429  ShedError(rate_limited)          + Retry-After
    503  ShedError(queue_full|draining),  + Retry-After
         ServerClosed, ReplicaLost, no serving replicas
    504  deadline shed — at ADMISSION (the X-DVT-Deadline-Ms budget is
         already spent on arrival) or at DISPATCH (it expired while the
         request sat queued; serve/router.py refuses to execute it)
    400  undecodable body / wrong shape   404  unknown model/route

Deadlines are enforced twice by design: the front door sheds a request
whose budget is spent before admission ever sees it, and the remaining
budget rides into `submit(deadline_ms=...)` so the dispatcher sheds it
again at batch pickup if queueing ate the rest — a request that would
START past its deadline is never executed.

W3C `traceparent` rides the wire: an inbound header becomes the parent
of this hop's context (obs/propagate.py), every journal event the
request touches carries the trace ids, and the response echoes the
server-side context so a client can stitch its own journal to ours.

Fault surface (`serve.transport`, resilience/faults.py): `io_error`
tears the connection mid-frame (no response bytes — the client sees a
reset; exactly one request fails and the acceptor thread survives),
`corrupt` mangles the request body via `transform()` (a 400, not a
wedge), `crash` SIGKILLs the serving process (the procpool respawn
path). Journal events: `transport_server{port,outcome}` on
start/stop/fail, `transport_request{status,deadline_ms,outcome}` per
request (schemas in tools/check_journal.py --strict).
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Sequence

import numpy as np

from deep_vision_tpu.core import knobs
from deep_vision_tpu.obs import locksmith, propagate
from deep_vision_tpu.resilience import faults
from deep_vision_tpu.serve.admission import ShedError
from deep_vision_tpu.serve.engine import ServeError
from deep_vision_tpu.serve.queue import DeadlineExceeded, QueueClosed

__all__ = ["Transport", "TransportError", "DEADLINE_HEADER",
           "STATUS_BY_REASON", "TRANSPORT_OUTCOMES",
           "TRANSPORT_SERVER_OUTCOMES"]

#: the client's remaining budget in milliseconds, measured at SEND time
DEADLINE_HEADER = "X-DVT-Deadline-Ms"

#: ShedError reason -> status. 429 is "you, specifically, are over
#: budget" (token bucket); 503 is "the service, as a whole, cannot take
#: this right now" (bounded queue, drain) — both carry Retry-After.
STATUS_BY_REASON = {"rate_limited": 429, "queue_full": 503,
                    "draining": 503}

#: `transport_request` outcome enum (check_journal --strict pins it)
TRANSPORT_OUTCOMES = ("ok", "error", "shed", "deadline", "bad_request",
                      "torn")

#: `transport_server` outcome enum — same lifecycle verdicts as
#: `telemetry_server`, one convention for every socket the repo binds
TRANSPORT_SERVER_OUTCOMES = ("started", "stopped", "failed")


class TransportError(RuntimeError):
    """Transport lifecycle misuse (start twice, bind failure wrapper)."""


class Transport:
    """HTTP edge over one serving backend.

    Wire-up (what tools/fleetnet_smoke.py does)::

        tp = Transport(pool, journal=journal, registry=registry)
        tp.start()                       # binds 127.0.0.1:0, journals port
        ... clients POST /v1/<model> ...
        tp.close()

    The backend contract is three callables, all optional but the
    first: `submit(model, image, deadline_ms=) -> Future`,
    `healthz() -> (ok, detail)`, and — only when `admission` is given —
    `queue_depth(model) -> int` feeds the admission verdict. Backends
    that run their own admission (`ReplicaPool`) just raise `ShedError`
    from submit; the mapping below is the same either way.
    """

    def __init__(self, backend, host: str = "127.0.0.1", port: int = 0,
                 journal=None, registry=None, admission=None,
                 models: Optional[Sequence[str]] = None,
                 queue_depth: Optional[Callable[[str], int]] = None,
                 default_deadline_ms: Optional[float] = None,
                 retry_after_ms: Optional[float] = None,
                 result_timeout_s: float = 60.0,
                 controls: Optional[Dict[str, Callable[[dict],
                                                       dict]]] = None):
        self.backend = backend
        self.journal = journal
        self.admission = admission
        # the control plane (POST /control/<name>): named host-side
        # verbs a fleet parent drives on its replica processes (weight
        # promote, drain) — separate from the request ledger, which
        # counts user traffic only
        self.controls: Dict[str, Callable[[dict], dict]] = \
            dict(controls or {})
        self._models = tuple(models) if models is not None else None
        if queue_depth is None and hasattr(backend, "queue_depth"):
            queue_depth = backend.queue_depth  # the admission input most
            # backends already expose (Server, ProcReplicaPool)
        self._queue_depth = queue_depth
        self._want_host = host
        self._want_port = int(port)
        self.default_deadline_ms = float(
            knobs.get_float("DVT_TRANSPORT_DEADLINE_MS")
            if default_deadline_ms is None else default_deadline_ms)
        self.retry_after_ms = float(
            knobs.get_float("DVT_TRANSPORT_RETRY_AFTER_MS")
            if retry_after_ms is None else retry_after_ms)
        self.result_timeout_s = float(result_timeout_s)
        if registry is None:
            from deep_vision_tpu.obs.registry import get_registry

            registry = get_registry()
        self.registry = registry
        # the edge ledger: every offered request lands in exactly one
        # bucket, so offered == ok + error + shed + deadline + bad +
        # torn holds at any instant the lock is not held mid-increment
        self._lock = locksmith.lock("serve.transport")
        self.counts: Dict[str, int] = {
            "offered": 0, "ok": 0, "error": 0, "shed": 0, "deadline": 0,
            "bad_request": 0, "torn": 0}
        self.by_status: Dict[int, int] = {}
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    def add_control(self, name: str, fn: Callable[[dict], dict]) -> None:
        """Register/replace a control verb (idempotent by name, the
        telemetry-source convention)."""
        self.controls[str(name)] = fn

    # -- lifecycle ---------------------------------------------------------

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def address(self) -> Optional[str]:
        return f"{self._want_host}:{self.port}" if self._httpd else None

    def start(self) -> "Transport":
        if self._httpd is not None:
            return self
        try:
            httpd = ThreadingHTTPServer(
                (self._want_host, self._want_port), _Handler)
        except OSError as e:
            self._journal_server("failed", port=self._want_port,
                                 error=f"{type(e).__name__}: {e}")
            raise
        httpd.daemon_threads = True
        httpd.transport = self  # handler backref (telemetry idiom)
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="serve-transport",
            daemon=True)
        self._thread.start()
        self._journal_server("started", port=self.port)
        return self

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        port = httpd.server_address[1]
        try:
            httpd.shutdown()
            httpd.server_close()
        except Exception:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._journal_server("stopped", port=port)

    def _journal_server(self, outcome: str, port: int, **extra) -> None:
        assert outcome in TRANSPORT_SERVER_OUTCOMES
        if self.journal is not None:
            self.journal.write("transport_server", host=self._want_host,
                               port=int(port), outcome=outcome, **extra)

    # -- ledger ------------------------------------------------------------

    def ledger(self) -> dict:
        """One consistent snapshot; `balanced` is the fleet-edge
        invariant offered == ok + error + shed + deadline + bad + torn
        the smoke asserts across client, server, and journal."""
        with self._lock:
            counts = dict(self.counts)
            by_status = dict(self.by_status)
        counts["by_status"] = {str(k): v
                               for k, v in sorted(by_status.items())}
        counts["balanced"] = counts["offered"] == sum(
            counts[k] for k in ("ok", "error", "shed", "deadline",
                                "bad_request", "torn"))
        return counts

    def _account(self, outcome: str, status: int) -> None:
        with self._lock:
            self.counts[outcome] += 1
            self.by_status[status] = self.by_status.get(status, 0) + 1
        self.registry.counter(
            "transport_requests_total", "front-door requests by status",
            labels={"status": str(status)}).inc()

    # -- request handling (called from handler threads) --------------------

    def healthz(self):
        if self._closed or self._httpd is None:
            return False, {"draining": True}
        fn = getattr(self.backend, "healthz", None)
        if callable(fn):
            return fn()
        return True, {}

    def known_models(self) -> Optional[Sequence[str]]:
        if self._models is not None:
            return self._models
        eng = getattr(self.backend, "engine", None)
        if eng is None:
            fn = getattr(self.backend, "primary_engine", None)
            if callable(fn):
                try:
                    eng = fn()
                except Exception:
                    return None
        return getattr(eng, "models", None)

    def handle_request(self, model: str, body: bytes,
                       deadline_hdr: Optional[str],
                       traceparent: Optional[str]) -> "_Reply":
        """The whole front-door verdict for one POST, transport-neutral
        (the HTTP handler frames it; tests call it directly). Returns a
        `_Reply`; `outcome == "torn"` means write NOTHING and drop the
        connection."""
        t0 = time.perf_counter()
        with self._lock:
            self.counts["offered"] += 1
        # the frame boundary: io_error = the connection resets mid-frame
        # (one torn request, no response bytes, the acceptor thread
        # lives), crash = the serving process dies here, corrupt =
        # the body arrives mangled and must fail THIS request as a 400
        try:
            faults.fire("serve.transport")
        except faults.FaultInjected:
            return self._reply(None, 0, "torn", t0, 0.0,
                               error="injected connection reset")
        body = faults.transform("serve.transport", body)
        # inbound context: the wire's traceparent parents this hop
        parent = propagate.from_traceparent(traceparent) \
            if traceparent else None
        ctx = parent.child() if parent is not None else \
            propagate.new_trace()
        deadline_ms: Optional[float] = None
        if deadline_hdr is not None and str(deadline_hdr).strip():
            try:
                deadline_ms = float(deadline_hdr)
            except ValueError:
                return self._reply(
                    ctx, 400, "bad_request", t0, 0.0,
                    error=f"unparseable {DEADLINE_HEADER}: "
                          f"{deadline_hdr!r}")
        elif self.default_deadline_ms > 0:
            deadline_ms = self.default_deadline_ms
        known = self.known_models()
        if known is not None and model not in known:
            return self._reply(ctx, 404, "bad_request", t0, deadline_ms,
                               error=f"unknown model {model!r}")
        try:
            image = self._decode(body)
        except (ValueError, TypeError) as e:
            return self._reply(ctx, 400, "bad_request", t0, deadline_ms,
                               error=f"{type(e).__name__}: {e}")
        # deadline check ONE, at admission: a budget spent in flight
        # (or by the corrupt-frame read above) sheds before any queue
        # or token bucket is consulted — never execute, never admit
        remaining_ms = None
        if deadline_ms is not None:
            remaining_ms = deadline_ms - (time.perf_counter() - t0) * 1e3
            if remaining_ms <= 0:
                return self._reply(ctx, 504, "deadline", t0, deadline_ms,
                                   stage="admission")
        if self.admission is not None:
            depth = self._queue_depth(model) if self._queue_depth else 0
            reason = self.admission.admit(model, depth)
            if reason is not None:
                return self._shed_reply(ctx, reason, t0, deadline_ms)
        try:
            with propagate.use(ctx):
                fut = self.backend.submit(model, image,
                                          deadline_ms=remaining_ms)
        except ShedError as e:
            return self._shed_reply(ctx, e.reason, t0, deadline_ms)
        except QueueClosed:
            return self._shed_reply(ctx, "draining", t0, deadline_ms)
        except ServeError as e:
            # "no serving replicas" — a fleet failure, not a policy
            # verdict: 503 + Retry-After, the respawn will land shortly
            return self._reply(ctx, 503, "error", t0, deadline_ms,
                               error=f"{type(e).__name__}: {e}",
                               retry_after=True)
        timeout_s = self.result_timeout_s if remaining_ms is None \
            else remaining_ms / 1e3 + 10.0
        try:
            row = fut.result(timeout=timeout_s)
        except DeadlineExceeded:
            # deadline check TWO fired, at dispatch (serve/router.py):
            # the budget died in the queue, the request never executed
            return self._reply(ctx, 504, "deadline", t0, deadline_ms,
                               stage="dispatch")
        except ShedError as e:
            return self._shed_reply(ctx, e.reason, t0, deadline_ms)
        except TimeoutError:
            fut.cancel()
            return self._reply(ctx, 500, "error", t0, deadline_ms,
                               error="result timeout")
        except Exception as e:
            # typed, retryable process death (ReplicaLost) and drain
            # races answer 503 + Retry-After; everything else is a 500
            name = type(e).__name__
            retryable = name in ("ReplicaLost", "ServerClosed",
                                 "QueueClosed")
            return self._reply(ctx, 503 if retryable else 500, "error",
                               t0, deadline_ms, error=f"{name}: {e}",
                               retry_after=retryable)
        latency_ms = (time.perf_counter() - t0) * 1e3
        body_out = {"model": model,
                    "latency_ms": round(latency_ms, 3),
                    "outputs": _jsonable_outputs(row)}
        return self._finish(ctx, 200, "ok", t0, deadline_ms,
                            body=body_out)

    @staticmethod
    def _decode(body: bytes):
        obj = json.loads(body.decode("utf-8"))
        if not isinstance(obj, dict) or "image" not in obj:
            raise ValueError("request body must be a JSON object with "
                             "an 'image' field")
        return np.asarray(obj["image"], dtype=np.float32)

    def _shed_reply(self, ctx, reason: str, t0: float,
                    deadline_ms: Optional[float]) -> "_Reply":
        status = STATUS_BY_REASON.get(reason, 503)
        return self._reply(ctx, status, "shed", t0, deadline_ms,
                           reason=reason, retry_after=True)

    def _reply(self, ctx, status: int, outcome: str, t0: float,
               deadline_ms: Optional[float], reason: Optional[str] = None,
               stage: Optional[str] = None, error: Optional[str] = None,
               retry_after: bool = False) -> "_Reply":
        body = {"error": outcome, "status": status,
                "retryable": bool(retry_after)}
        if reason:
            body["reason"] = reason
        if stage:
            body["stage"] = stage
        if error:
            body["detail"] = error[:200]
        extra = {}
        if reason:
            extra["reason"] = reason
        if stage:
            extra["stage"] = stage
        if error:
            extra["error"] = error[:200]
        return self._finish(ctx, status, outcome, t0, deadline_ms,
                            body=body, retry_after=retry_after, **extra)

    def _finish(self, ctx, status: int, outcome: str, t0: float,
                deadline_ms: Optional[float], body: dict,
                retry_after: bool = False, **extra) -> "_Reply":
        assert outcome in TRANSPORT_OUTCOMES
        latency_ms = (time.perf_counter() - t0) * 1e3
        self._account(outcome, status)
        if self.journal is not None:
            if ctx is not None:
                extra.update(ctx.fields())
            self.journal.write(
                "transport_request", status=int(status),
                deadline_ms=round(float(deadline_ms or 0.0), 3),
                outcome=outcome, latency_ms=round(latency_ms, 3), **extra)
        headers = {}
        if ctx is not None:
            headers["traceparent"] = ctx.to_traceparent()
        if retry_after:
            headers["Retry-After"] = f"{self.retry_after_ms / 1e3:.3f}"
        return _Reply(status, outcome, body, headers)


class _Reply:
    """One framed verdict: status + JSON body + extra headers.
    `outcome == "torn"` instructs the handler to write nothing."""

    __slots__ = ("status", "outcome", "body", "headers")

    def __init__(self, status: int, outcome: str, body: dict,
                 headers: Dict[str, str]):
        self.status = status
        self.outcome = outcome
        self.body = body
        self.headers = headers


def _jsonable_outputs(row):
    """Device/host output pytree -> JSON-shippable nested lists."""
    if isinstance(row, dict):
        return {str(k): _jsonable_outputs(v) for k, v in row.items()}
    if isinstance(row, (list, tuple)):
        return [_jsonable_outputs(v) for v in row]
    tolist = getattr(row, "tolist", None)
    if callable(tolist):
        return tolist()
    if isinstance(row, (int, float, str, bool)) or row is None:
        return row
    return repr(row)


class _Handler(BaseHTTPRequestHandler):
    """Route table. POST bodies are length-framed (Content-Length);
    handler threads are daemons (ThreadingHTTPServer), so one slow or
    torn request never blocks accept()."""

    server_version = "dvt-transport/1"
    protocol_version = "HTTP/1.1"

    def do_POST(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        tp: Transport = self.server.transport
        route = self.path.rstrip("/")
        if route.startswith("/control/"):
            self._do_control(tp, route[len("/control/"):])
            return
        if not route.startswith("/v1/"):
            with tp._lock:
                tp.counts["offered"] += 1
            tp._account("bad_request", 404)
            self._send_json(404, {"error": "bad_request",
                                  "detail": f"no such route: {route}"})
            return
        model = route[len("/v1/"):]
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else b""
        except (OSError, ValueError):
            with tp._lock:
                tp.counts["offered"] += 1
            tp._account("torn", 0)
            self.close_connection = True
            return
        try:
            reply = tp.handle_request(
                model, body, self.headers.get(DEADLINE_HEADER),
                self.headers.get("traceparent"))
        except Exception as e:
            # last-resort guard: a transport bug answers 500 for THIS
            # request; it must never wedge or kill the acceptor
            tp._account("error", 500)
            try:
                self._send_json(500, {"error": "error",
                                      "detail": f"{type(e).__name__}: {e}"})
            except Exception:
                pass
            return
        if reply.outcome == "torn":
            # mid-frame reset: no status line, no body — the client
            # sees the connection die exactly as a real reset looks
            self.close_connection = True
            try:
                self.wfile.flush()
            except Exception:
                pass
            return
        try:
            self._send_json(reply.status, reply.body,
                            extra=reply.headers)
        except Exception:
            pass  # client went away mid-response: its request, its loss

    def _do_control(self, tp: Transport, name: str) -> None:
        """Control-plane verbs: off the request ledger (they are fleet
        operations, not user traffic), 404 on unknown names so a typo'd
        parent fails loudly."""
        fn = tp.controls.get(name)
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length).decode("utf-8")) \
                if length else {}
        except (OSError, ValueError):
            self._send_json(400, {"error": "bad_request",
                                  "detail": "undecodable control payload"})
            return
        if fn is None:
            self._send_json(404, {"error": "bad_request",
                                  "detail": f"no such control: {name}"})
            return
        try:
            self._send_json(200, {"ok": True, **(fn(payload) or {})})
        except Exception as e:
            try:
                self._send_json(500, {"ok": False, "error":
                                      f"{type(e).__name__}: {e}"})
            except Exception:
                pass

    def do_GET(self):  # noqa: N802
        tp: Transport = self.server.transport
        route = self.path.rstrip("/") or "/"
        try:
            if route == "/healthz":
                ok, detail = tp.healthz()
                self._send_json(200 if ok else 503,
                                {"ok": bool(ok), **dict(detail or {})})
            elif route == "/ledgerz":
                self._send_json(200, tp.ledger())
            elif route == "/statusz":
                body = {"ledger": tp.ledger()}
                for attr in ("counts", "telemetry_status"):
                    fn = getattr(tp.backend, attr, None)
                    if callable(fn):
                        try:
                            body[attr] = fn()
                        except Exception as e:
                            body[attr] = {"error":
                                          f"{type(e).__name__}: {e}"}
                self._send_json(200, body)
            elif route == "/":
                self._send_json(200, {"endpoints":
                                      ["/v1/<model> (POST)", "/healthz",
                                       "/ledgerz", "/statusz"]})
            else:
                self._send_json(404, {"error": "bad_request",
                                      "detail": f"no such page: {route}"})
        except Exception as e:
            try:
                self._send_json(500, {"error": "error",
                                      "detail": f"{type(e).__name__}: {e}"})
            except Exception:
                pass

    def _send_json(self, code: int, obj,
                   extra: Optional[Dict[str, str]] = None) -> None:
        data = (json.dumps(obj, default=repr) + "\n").encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass
