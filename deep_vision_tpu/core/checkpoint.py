"""Checkpoint/resume for the whole zoo.

Semantics preserved from the reference (SURVEY.md §2.6):
  (a) full training-state capture incl. optimizer + scheduler + metric history
      (torch dict at ResNet/pytorch/train.py:417-428);
  (b) resume-by-flag (`-c <ckpt>`, ResNet/pytorch/train.py:293-307);
  (c) best-val-only saving (YOLO/tensorflow/train.py:243-247);
  (d) keep-every vs max_to_keep policies (CycleGAN/tensorflow/train.py:142-143,
      DCGAN/tensorflow/main.py:40).

TPU-native mechanism: orbax async checkpointing of the TrainState pytree,
step-indexed directories, plus a small JSON sidecar for host-side state
(metric history, plateau-scheduler state) that must never enter jit.

Storage is treated as unreliable by design (Check-N-Run, NSDI '22): the
sidecar is written tmp+fsync+rename with an embedded crc32c so a crash
mid-write can never leave a half-written JSON that breaks `resume()`,
writes retry transient I/O errors through the shared
`resilience.RetryPolicy`, and `restore()` walks a fallback chain — a
step whose arrays fail to restore, whose sidecar is corrupt, or whose
sidecar is missing while sibling steps have one (the
killed-between-array-commit-and-sidecar signature) is QUARANTINED (moved
to `<dir>/quarantine/`, typed `ckpt_quarantine` journal event) and the
newest remaining valid step is restored instead of crashing the run.
`resilience.faults` injection points (`ckpt.save`, `ckpt.restore`,
`ckpt.sidecar` incl. the after-write torn window) make every one of
those paths testable on CPU.

The sidecar is also the input pipeline's checkpoint home: Trainer saves
the train DataLoader's `data/snapshot.py` DataLoaderState under the
`data_state` host-state key (epoch, batches consumed, shard cursor,
bad-record-budget spend), so `resume()` re-arms the batch stream at the
exact position the model state corresponds to — the PR 10 elastic
guarantees extended to the data plane (a resumed run must not silently
re-visit data the step counter says it already trained on).

Elastic (cross-mesh) restore: every save records leaf-level sharding
metadata in the sidecar (`resilience.elastic.sharding_meta` under the
reserved `__sharding__` key), so a run checkpointed on N hosts/devices
restores onto M — `restore(..., mesh=current_mesh)` re-places every
restored array against the *current* mesh's NamedShardings, re-resolving
each saved PartitionSpec per dimension and replicating whatever the new
topology cannot honor. Proven on CPU by saving under an 8-device mesh
and restoring under 4 and 1 (tests/test_elastic.py).
"""
from __future__ import annotations

import json
import os
import re
import sys
from typing import Any, Callable, List, Optional, Tuple

import google_crc32c
import jax
import orbax.checkpoint as ocp

from deep_vision_tpu.resilience import RetryPolicy, faults
from deep_vision_tpu.resilience import elastic

_SIDECAR_RE = re.compile(r"host_state_(\d+)\.json$")
_SIDECAR_FORMAT = 1


def state_arrays(state) -> dict:
    """The serializable slice of a TrainState: arrays only, no apply_fn/tx
    closures. THE single definition — CheckpointManager.save/restore and the
    GAN trainers all build their trees from it."""
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "rng": state.rng,
    }


class CheckpointCorruptError(RuntimeError):
    """An explicitly requested step failed validation (corrupt sidecar or
    unrestorable arrays). The latest-step path never raises this — it
    quarantines and falls back instead."""


class CheckpointManager:
    def __init__(
        self,
        directory: str,
        max_to_keep: Optional[int] = 3,
        save_interval_steps: int = 1,
        best_mode: Optional[str] = None,  # None | 'min' | 'max'
        best_metric: Optional[str] = None,
        journal=None,  # obs.RunJournal: ckpt_quarantine / retry events
        retry: Optional[RetryPolicy] = None,
    ):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._best_mode = best_mode
        self._best_metric = best_metric
        self._best_value = None
        self.journal = journal
        self._retry = retry or RetryPolicy(
            name="ckpt.sidecar", max_attempts=4, base_delay_s=0.05,
            max_delay_s=2.0, journal=journal,
        )
        # array restores retry transient I/O before the fallback chain may
        # judge a step corrupt: quarantining the newest good step over one
        # network-FS hiccup would be an irreversible answer to a
        # retryable question
        self._restore_retry = RetryPolicy(
            name="ckpt.restore", max_attempts=3, base_delay_s=0.2,
            max_delay_s=5.0, journal=journal,
        )
        #: did the last restore() place arrays itself (mesh= given)?
        #: Callers that blanket-replicate after a legacy restore consult
        #: this so they don't clobber a metadata-driven placement.
        self.last_restore_placed = False
        self._options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=True,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=self._options)

    # -- host-side sidecar -------------------------------------------------
    def _sidecar_path(self, step: int) -> str:
        return os.path.join(self.directory, f"host_state_{step}.json")

    def _sidecar_steps(self) -> List[int]:
        out = []
        for name in os.listdir(self.directory):
            m = _SIDECAR_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return out

    def _write_sidecar(self, step: int, host_state: dict) -> None:
        """Atomic, checksummed, retried sidecar write.

        The payload crc travels inside the file: a torn write (crash between
        the first byte and the rename — impossible now, but the file may
        also rot on disk or be fed through a corrupting transport) is
        detected at read time instead of surfacing as a JSONDecodeError
        inside resume()."""
        self._retry.call(self._write_sidecar_once, step, host_state)

    def _write_sidecar_once(self, step: int, host_state: dict) -> None:
        faults.fire("ckpt.sidecar")
        payload = json.dumps(host_state, sort_keys=True)
        doc = json.dumps({
            "__sidecar_format__": _SIDECAR_FORMAT,
            "crc32c": int(google_crc32c.value(payload.encode())),
            "payload": host_state,
        }, sort_keys=True)
        # the corrupt fault flips bytes AFTER checksumming — simulating rot
        # the checksum must catch, never corruption it would vouch for
        data = faults.transform("ckpt.sidecar", doc.encode())
        path = self._sidecar_path(step)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            faults.fire("ckpt.sidecar", stage="after_write")
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass

    def _read_sidecar(self, step: int) -> Tuple[Optional[dict], Optional[str]]:
        """(host_state, error). (None, None) = no sidecar on disk;
        (None, reason) = a sidecar exists but failed validation."""
        path = self._sidecar_path(step)
        if not os.path.exists(path):
            return None, None
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
            return None, f"sidecar unreadable: {type(e).__name__}: {e}"
        if not isinstance(doc, dict):
            return None, "sidecar is not a JSON object"
        if "__sidecar_format__" not in doc:
            return doc, None  # pre-checksum legacy sidecar: accept as-is
        payload = doc.get("payload")
        want = doc.get("crc32c")
        got = int(google_crc32c.value(
            json.dumps(payload, sort_keys=True).encode()))
        if want != got:
            return None, f"sidecar checksum mismatch (want {want}, got {got})"
        return payload, None

    def _gc_sidecars(self) -> None:
        """Drop sidecars whose array step was pruned by max_to_keep (they
        would otherwise accumulate forever AND make every pruned step look
        like an incomplete save to the fallback chain)."""
        keep = set(self._mgr.all_steps())
        if not keep:
            return
        for s in self._sidecar_steps():
            if s not in keep:
                try:
                    os.remove(self._sidecar_path(s))
                except OSError:
                    pass

    # -- quarantine + fallback restore -------------------------------------

    def _reload(self) -> None:
        try:
            self._mgr.reload()
        except Exception:  # older orbax: rebuild from the stored options
            self._mgr = ocp.CheckpointManager(
                self.directory, options=self._options)

    def _quarantine(self, step: int, reason: str) -> None:
        """Move a failed step (array dir + sidecar) under quarantine/ so the
        operator can post-mortem it, and make the manager forget it.

        Only process 0 moves files (same single-writer rule as the sidecar
        writes): the validation that CONDEMNED the step is deterministic
        over shared on-disk bytes, so every process walks to the same
        surviving step; letting each of them race os.replace on a shared
        checkpoint dir would not be."""
        qdir = os.path.join(self.directory, "quarantine")

        def unique(dst: str) -> str:
            out, n = dst, 1
            while os.path.exists(out):
                out = f"{dst}.{n}"
                n += 1
            return out

        moved = []
        if jax.process_index() == 0:
            os.makedirs(qdir, exist_ok=True)
            for src in (os.path.join(self.directory, str(step)),
                        self._sidecar_path(step)):
                if os.path.exists(src):
                    dst = unique(os.path.join(qdir, os.path.basename(src)))
                    try:
                        os.replace(src, dst)
                        moved.append(dst)
                    except OSError as e:
                        reason += f"; quarantine move failed: {e}"
        print(f"checkpoint: QUARANTINED step {step} ({reason}); "
              f"falling back to the newest valid step", file=sys.stderr)
        try:
            from deep_vision_tpu.obs.registry import get_registry

            get_registry().counter(
                "ckpt_quarantine_total", "checkpoint steps quarantined").inc()
        except Exception:
            pass
        if self.journal is not None:
            self.journal.write("ckpt_quarantine", step=int(step),
                               reason=reason, moved_to=moved)
        self._reload()

    def _restore_with_fallback(
        self, do_restore: Callable[[int, Optional[dict]], Any],
        step: Optional[int]
    ) -> Tuple[Optional[int], Any, Optional[dict]]:
        """(restored_step, value, host_state); (None, None, None) when no
        valid checkpoint remains. Explicit `step` = validate-or-raise (the
        operator pinned it; silently restoring a different one would be
        worse than failing); `step=None` = newest valid, quarantining
        losers along the way. `do_restore` receives the step's (already
        validated) host sidecar so a cross-mesh restorer can derive the
        target shardings BEFORE orbax places anything."""
        def attempt(s: int, host_state: Optional[dict]):
            # transient I/O (OSError family) is retried here, so only a
            # failure that SURVIVES the retry budget can condemn a step
            def once():
                faults.fire("ckpt.restore")
                return do_restore(s, host_state)

            return self._restore_retry.call(once)

        if step is not None:
            if step not in set(self._mgr.all_steps()):
                # fail BEFORE orbax sees the doomed restore: besides the
                # clearer error, a failed typed restore on a fresh manager
                # poisons its item-structure registry for later saves
                raise FileNotFoundError(
                    f"no checkpoint step {step} in {self.directory!r}")
            host_state, err = self._read_sidecar(step)
            if err is not None:
                raise CheckpointCorruptError(
                    f"checkpoint step {step} in {self.directory!r}: {err}")
            return step, attempt(step, host_state), host_state
        sidecar_steps = set(self._sidecar_steps())
        for s in sorted(self._mgr.all_steps(), reverse=True):
            host_state, err = self._read_sidecar(s)
            if (err is None and host_state is None
                    and sidecar_steps - {s}):
                # arrays committed, sidecar never landed, while sibling
                # steps do carry one: the process died between the array
                # commit and the sidecar rename — an incomplete save
                err = ("sidecar missing while other steps have one "
                       "(save died before the sidecar landed)")
            if err is None:
                try:
                    return s, attempt(s, host_state), host_state
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:
                    err = f"array restore failed: {type(e).__name__}: {e}"
            self._quarantine(s, err)
            sidecar_steps.discard(s)
        return None, None, None

    def _typed_restorer(self, template, mesh) -> Callable:
        """The do_restore closure shared by restore/restore_tree: with a
        `mesh`, the template is handed to orbax as ABSTRACT arrays whose
        shardings come from the step's sidecar metadata re-resolved
        against that mesh — each array lands once, already placed (no
        restore-then-re-place double transfer)."""
        def do_restore(s: int, host_state: Optional[dict]):
            tmpl = template
            if mesh is not None:
                meta = (host_state or {}).get(elastic.SHARDING_META_KEY)
                tmpl = elastic.abstract_template(template, meta, mesh)
            return self._mgr.restore(s, args=ocp.args.StandardRestore(tmpl))

        return do_restore

    # -- save/restore API ---------------------------------------------------

    def save(self, step: int, state, host_state: Optional[dict] = None, metrics=None):
        """Save TrainState (async) + JSON host state. Returns True if saved."""
        if self._best_mode and metrics is not None and self._best_metric in metrics:
            v = float(metrics[self._best_metric])
            better = (
                self._best_value is None
                or (self._best_mode == "min" and v < self._best_value)
                or (self._best_mode == "max" and v > self._best_value)
            )
            if not better:
                return False
            self._best_value = v
        faults.fire("ckpt.save")
        arrays = state_arrays(state)
        saved = self._mgr.save(step, args=ocp.args.StandardSave(arrays))
        # multi-host: orbax coordinates the array save across processes;
        # the JSON sidecar is host-side state, written once by the primary.
        # REQUIRES a shared checkpoint filesystem (the standard orbax
        # multi-host setup): non-primary hosts read the same sidecar on
        # restore. With per-host local directories they would see
        # host_state=None and resume with divergent plateau/LR state.
        # Every save now carries a sidecar: the leaf-level sharding
        # metadata it embeds is what lets a later restore re-place the
        # arrays on a DIFFERENT mesh (elastic cross-mesh resume).
        if saved and jax.process_index() == 0:
            self._write_sidecar(step, self._with_sharding(host_state, arrays))
            self._gc_sidecars()
        return saved

    @staticmethod
    def _with_sharding(host_state: Optional[dict], tree) -> dict:
        doc = dict(host_state) if host_state else {}
        try:
            doc[elastic.SHARDING_META_KEY] = elastic.sharding_meta(tree)
        except Exception:
            pass  # metadata is an upgrade, never a reason to fail a save
        return doc

    def _place_restored(self, found: int, restored, host_state, mesh):
        """Strip the sharding metadata out of the host sidecar and, when a
        `mesh` was given, re-place every restored leaf against it — the
        cross-mesh half of an elastic resume. Returns (tree, host_state)."""
        self.last_restore_placed = False
        meta = None
        if isinstance(host_state, dict):
            meta = host_state.pop(elastic.SHARDING_META_KEY, None)
        if mesh is None:
            return restored, host_state
        # the typed restorer already landed every array on its target
        # sharding (abstract template); this pass is a near-free identity
        # (device_put to an equal sharding short-circuits) that also
        # covers managers whose do_restore did not pre-place
        restored, stats = elastic.replace_on_mesh(restored, meta, mesh)
        self.last_restore_placed = True
        if self.journal is not None and meta:
            self.journal.write(
                "note", note="ckpt_resharded", step=int(found),
                saved_mesh=meta.get("mesh"),
                saved_devices=meta.get("device_count"),
                mesh={str(k): int(v) for k, v in mesh.shape.items()},
                **stats,
            )
        return restored, host_state

    def restore(self, state, step: Optional[int] = None, mesh=None):
        """Restore into the structure of `state`; returns (state, host_state).

        With `step=None`, walks the fallback chain: corrupt/incomplete
        steps are quarantined and the newest valid one wins. When nothing
        valid remains, returns the input state untouched (fresh start).

        With `mesh`, the restored arrays are re-placed against THAT mesh
        using the sharding metadata the save recorded — a checkpoint from
        an 8-device run restores onto 4 (or 1) with every leaf landing on
        the new topology (specs the new mesh cannot honor replicate)."""
        template = state_arrays(state)
        found, restored, host_state = self._restore_with_fallback(
            self._typed_restorer(template, mesh), step)
        if found is None:
            self.last_restore_placed = False
            return state, None
        restored, host_state = self._place_restored(
            found, restored, host_state, mesh)
        return state.replace(**restored), host_state

    def save_tree(self, step: int, tree, host_state: Optional[dict] = None):
        """Save an arbitrary array pytree (multi-model trainers: the GAN
        trainers save {'g': ..., 'd': ...} of per-state array dicts — the
        tf.train.Checkpoint(generator.., discriminator..) analog at
        CycleGAN/tensorflow/train.py:133-148)."""
        faults.fire("ckpt.save")
        saved = self._mgr.save(step, args=ocp.args.StandardSave(tree))
        if saved and jax.process_index() == 0:
            self._write_sidecar(step, self._with_sharding(host_state, tree))
            self._gc_sidecars()
        return saved

    def restore_tree(self, template, step: Optional[int] = None, mesh=None):
        """Restore a pytree saved by `save_tree` into `template`'s structure;
        returns (tree, host_state) or (None, None) when nothing valid is
        saved (same quarantine-and-fall-back and cross-mesh `mesh=`
        semantics as `restore`)."""
        found, restored, host_state = self._restore_with_fallback(
            self._typed_restorer(template, mesh), step)
        if found is None:
            self.last_restore_placed = False
            return None, None
        return self._place_restored(found, restored, host_state, mesh)

    def restore_variables(self, step: Optional[int] = None) -> dict:
        """Template-free restore of just the model variables.

        Inference/export flows (tools/infer.py, tools/export.py) must not
        need to reconstruct the exact optimizer + schedule state tree the
        trainer saved — orbax can restore with the on-disk structure, and
        only `params`/`batch_stats` are kept. Returns a flax variables dict.
        """
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory!r}")
        faults.fire("ckpt.restore")
        restored = self._mgr.restore(step)
        out = {"params": restored["params"]}
        if restored.get("batch_stats"):
            out["batch_stats"] = restored["batch_stats"]
        return out

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()
