from deep_vision_tpu.core.train_state import TrainState, create_train_state
from deep_vision_tpu.core.checkpoint import CheckpointManager
from deep_vision_tpu.core.metrics import MetricLogger, topk_accuracy
from deep_vision_tpu.core.summary import count_params, model_summary
