"""Eval-pipeline goldens: fixed inputs -> hand-computed metric values.

Hardware convergence artifacts prove the training recipes optimize; these
prove the EVAL MATH is right (VERDICT r2 weak #3): a fixed logits matrix has
a known top-1/top-5, a fixed set of detections a known VOC mAP, fixed
keypoints a known PCK — all derived by hand in the comments, so a regression
in the metric code cannot hide behind model noise. Parity targets:
`accuracy`/`validate` at ResNet/pytorch/train.py:488-538 and the VOC AP
protocol of the reference's eval notebooks.
"""
import numpy as np
import pytest

from deep_vision_tpu.core.detection_metrics import (
    DetectionEvaluator,
    pck,
    pckh,
)
from deep_vision_tpu.core.metrics import topk_accuracy


class TestTopkGolden:
    def test_known_matrix(self):
        # 4 samples, 6 classes. Correct class rank per row (by logit):
        # row 0: label 2 is argmax            -> top1 hit, top5 hit
        # row 1: label 0 ranks 2nd            -> top1 miss, top5 hit
        # row 2: label 5 ranks 6th (last)     -> top1 miss, top5 miss
        # row 3: label 1 ranks 5th            -> top1 miss, top5 hit
        logits = np.array([
            [0.1, 0.2, 0.9, 0.3, 0.4, 0.0],
            [0.8, 0.9, 0.1, 0.2, 0.3, 0.0],
            [0.9, 0.8, 0.7, 0.6, 0.5, 0.1],
            [0.9, 0.2, 0.8, 0.7, 0.6, 0.1],
        ], np.float32)
        labels = np.array([2, 0, 5, 1])
        acc = topk_accuracy(logits, labels)
        assert float(acc["top1"]) == pytest.approx(1 / 4)
        assert float(acc["top5"]) == pytest.approx(3 / 4)

    def test_mask_weights_exclude_padding(self):
        logits = np.array([[1.0, 0.0], [1.0, 0.0], [0.0, 1.0]], np.float32)
        labels = np.array([0, 1, 1])
        # row 2 is padding: top1 over rows {0 (hit), 1 (miss)} = 0.5
        acc = topk_accuracy(logits, labels, ks=(1,),
                            weights=np.array([1.0, 1.0, 0.0]))
        assert float(acc["top1"]) == pytest.approx(0.5)


class TestMapGolden:
    def test_single_class_hand_computed_ap(self):
        """3 detections, 2 GT boxes, one image. Score order d1(.9) d2(.8)
        d3(.7); d1 matches gt A (IoU 1.0), d2 misses (IoU < .5), d3 matches
        gt B. Precision/recall points: (1/1, .5), (1/2, .5), (2/3, 1.0) ->
        all-point interpolated AP = 0.5 * 1.0 + 0.5 * (2/3) = 0.8333."""
        ev = DetectionEvaluator(num_classes=1)
        gt = np.array([[0.0, 0.0, 0.2, 0.2], [0.5, 0.5, 0.7, 0.7]])
        preds = np.array([
            [0.0, 0.0, 0.2, 0.2],   # d1: exact match of gt A
            [0.25, 0.25, 0.4, 0.4],  # d2: overlaps nothing
            [0.5, 0.5, 0.7, 0.7],   # d3: exact match of gt B
        ])
        ev.add(preds, np.array([0.9, 0.8, 0.7]), np.zeros(3, int),
               gt, np.zeros(2, int))
        out = ev.compute(iou_threshold=0.5)
        assert out["mAP"] == pytest.approx(0.5 + 0.5 * 2 / 3, abs=1e-6)

    def test_duplicate_detection_is_false_positive(self):
        """Two detections on ONE gt: the lower-scored duplicate is a FP
        (greedy matching consumes the gt). AP = 1.0 * recall jump at the
        first det = 1.0 (precision 1 at recall 1), duplicate changes
        nothing after the gt is matched -> AP stays 1.0 under all-point
        interpolation? No: PR points are (1/1, 1.0) then (1/2, 1.0) — max
        precision at recall 1.0 is 1.0, so AP = 1.0."""
        ev = DetectionEvaluator(num_classes=1)
        gt = np.array([[0.0, 0.0, 0.2, 0.2]])
        preds = np.array([[0.0, 0.0, 0.2, 0.2], [0.01, 0.0, 0.21, 0.2]])
        ev.add(preds, np.array([0.9, 0.8]), np.zeros(2, int),
               gt, np.zeros(1, int))
        out = ev.compute(iou_threshold=0.5)
        assert out["mAP"] == pytest.approx(1.0, abs=1e-6)

    def test_two_class_mean(self):
        """Class 0: perfect single detection (AP 1). Class 1: one FP, one
        missed gt (AP 0). mAP = 0.5."""
        ev = DetectionEvaluator(num_classes=2)
        ev.add(np.array([[0.0, 0.0, 0.2, 0.2]]), np.array([0.9]),
               np.array([0]),
               np.array([[0.0, 0.0, 0.2, 0.2], [0.5, 0.5, 0.7, 0.7]]),
               np.array([0, 1]))
        ev.add(np.array([[0.1, 0.1, 0.3, 0.3]]), np.array([0.8]),
               np.array([1]),
               np.zeros((0, 4)), np.zeros((0,), int))
        out = ev.compute(iou_threshold=0.5)
        assert out["ap_per_class"][0] == pytest.approx(1.0)
        assert out["ap_per_class"][1] == pytest.approx(0.0)
        assert out["mAP"] == pytest.approx(0.5)


class TestPckGolden:
    def test_hand_computed_pck(self):
        """2 samples, 2 joints, norm 10, alpha 0.5 -> threshold 5 px.
        s0j0 off by 3 (hit), s0j1 off by 8 (miss), s1j0 off by 4.9 (hit),
        s1j1 invisible (excluded). PCK = 2/3."""
        gt = np.array([[[10.0, 10.0], [50.0, 50.0]],
                       [[20.0, 20.0], [60.0, 60.0]]])
        pred = gt.copy()
        pred[0, 0, 0] += 3.0
        pred[0, 1, 1] += 8.0
        pred[1, 0, 0] += 4.9
        pred[1, 1, 0] += 100.0  # invisible: must not count
        vis = np.array([[True, True], [True, False]])
        out = pck(pred, gt, vis, norm_lengths=np.array([10.0, 10.0]),
                  alpha=0.5)
        assert out["PCK@0.5"] == pytest.approx(2 / 3)
        assert out["num_visible"] == 3
        assert out["per_joint"][0] == pytest.approx(1.0)
        assert out["per_joint"][1] == pytest.approx(0.0)

    def test_pckh_per_sample_head_norm(self):
        """PCKh normalizes per sample: the SAME 6-px error passes under
        head size 20 (threshold 10) and fails under head size 8
        (threshold 4)."""
        gt = np.zeros((2, 1, 2))
        pred = gt + np.array([6.0, 0.0])
        vis = np.ones((2, 1), bool)
        out = pckh(pred, gt, vis, head_sizes=np.array([20.0, 8.0]))
        assert out["PCKh@0.5"] == pytest.approx(0.5)
