from deep_vision_tpu.losses.classification import (
    cross_entropy_loss,
    classification_loss_fn,
)
