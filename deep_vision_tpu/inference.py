"""End-to-end prediction paths: model output -> user-facing detections/keypoints.

The TPU-native analog of the reference's eval-mode wiring: the box-decode
Lambda appendix (YOLO/tensorflow/yolov3.py:224-235) + Postprocessor
(YOLO/tensorflow/postprocess.py:12-96) become one jitted function per task —
decode and NMS run on device with static shapes, and only the final
(max_detections,) padded results travel to the host.

Predictors:
  make_yolo_detector(model)        images -> boxes/scores/classes/valid
  make_centernet_detector(model)   heatmap peaks -> boxes/scores/classes/valid
  make_pose_estimator(model)       heatmaps -> (x, y, score) per joint
"""
from __future__ import annotations

import functools
import time
import warnings
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

# the predictors donate their image argument (see make_yolo_detector);
# backends without donation support (CPU) warn once per lowering, which
# is pure noise on every test/eval run — the donation is declared for
# the TPU path. Scoped to jax's lowering module so nothing else is
# silenced (serve/engine.py filters the same warning around its AOT
# compiles).
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable",
    module=r"jax\._src\.interpreters\.mlir")

from deep_vision_tpu.ops.anchors import YOLO_ANCHOR_MASKS, YOLO_ANCHORS
from deep_vision_tpu.ops.boxes import decode_yolo_boxes
from deep_vision_tpu.ops.nms import non_maximum_suppression


def _observed(fn: Callable, task: str) -> Callable:
    """Wrap a jitted predictor with a per-request latency histogram
    (obs registry, labeled by task). The wrapper fences with
    block_until_ready so the observation is end-to-end request latency,
    not enqueue time — predictors feed host-side evaluators/renderers
    that fetch the result immediately anyway."""
    from deep_vision_tpu.obs.registry import get_registry
    from deep_vision_tpu.obs.trace import span

    reg = get_registry()
    hist = reg.histogram("inference_latency_ms",
                         "per-request predictor latency, fenced",
                         labels={"task": task})
    count = reg.counter("inference_requests_total", "predictor calls",
                        labels={"task": task})

    def wrapped(variables, images):
        # per-request span: the same fenced region the histogram times,
        # so a Perfetto timeline and the latency quantiles agree
        with span(f"infer/{task}"):
            t0 = time.perf_counter()
            out = jax.block_until_ready(fn(variables, images))
            hist.observe((time.perf_counter() - t0) * 1e3)
        count.inc()
        return out

    return wrapped


def yolo_decode_outputs(outputs, anchors=YOLO_ANCHORS, anchor_masks=YOLO_ANCHOR_MASKS):
    """Raw 3-scale head outputs -> flat (B, N, 4) xyxy boxes + (B, N, C) scores.

    The Postprocessor concat at postprocess.py:12-36: per-scale decode, then
    flatten grid x anchor dims. Scores are objectness * class probability
    (multi-label, postprocess.py:58-63).
    """
    anchors = jnp.asarray(anchors)
    all_boxes, all_scores = [], []
    for pred, mask in zip(outputs, anchor_masks):
        boxes, obj, cls = decode_yolo_boxes(pred, anchors[jnp.asarray(mask)])
        b = boxes.shape[0]
        all_boxes.append(boxes.reshape(b, -1, 4))
        all_scores.append((obj * cls).reshape(b, -1, cls.shape[-1]))
    return jnp.concatenate(all_boxes, 1), jnp.concatenate(all_scores, 1)


def yolo_detect(
    variables,
    images,
    *,
    apply_fn: Callable,
    anchors=YOLO_ANCHORS,
    anchor_masks=YOLO_ANCHOR_MASKS,
    max_detections: int = 100,
    iou_threshold: float = 0.5,
    score_threshold: float = 0.5,
):
    """images (B, H, W, 3) in [0,1] -> NMS'd detections (all fixed-shape).

    Returns dict: boxes (B, D, 4) xyxy normalized, scores (B, D),
    classes (B, D) int (-1 = padding), num (B,).
    """
    outputs = apply_fn(variables, images, train=False)
    boxes, scores = yolo_decode_outputs(outputs, anchors, anchor_masks)
    # best class per candidate box; NMS is class-aware via the offset trick
    best_class = jnp.argmax(scores, axis=-1)
    best_score = jnp.max(scores, axis=-1)
    out_b, out_s, out_c, valid = non_maximum_suppression(
        boxes,
        best_score,
        best_class,
        max_detections=max_detections,
        iou_threshold=iou_threshold,
        score_threshold=score_threshold,
    )
    return {"boxes": out_b, "scores": out_s, "classes": out_c, "num": valid}


def yolo_predict_fn(
    model,
    *,
    anchors=YOLO_ANCHORS,
    anchor_masks=YOLO_ANCHOR_MASKS,
    max_detections: int = 100,
    iou_threshold: float = 0.5,
    score_threshold: float = 0.5,
) -> Callable:
    """The raw (variables, images) -> detections fn, un-jitted: what
    make_yolo_detector wraps per call and serve/engine.py AOT-compiles
    per bucket shape."""
    return functools.partial(
        yolo_detect,
        apply_fn=model.apply,
        anchors=anchors,
        anchor_masks=anchor_masks,
        max_detections=max_detections,
        iou_threshold=iou_threshold,
        score_threshold=score_threshold,
    )


def make_yolo_detector(
    model,
    *,
    anchors=YOLO_ANCHORS,
    anchor_masks=YOLO_ANCHOR_MASKS,
    max_detections: int = 100,
    iou_threshold: float = 0.5,
    score_threshold: float = 0.5,
):
    """Returns a jitted (variables, images) -> detections dict.

    Donation goes to the IMAGES (argnum 1), never the variables: eval
    paths reuse `variables` across every call (donating state here is a
    use-after-free — the DV003 exemption rationale), while a request's
    input buffer is dead once decode starts, so its HBM is reusable for
    the decode/NMS intermediates.
    """
    fn = yolo_predict_fn(
        model,
        anchors=anchors,
        anchor_masks=anchor_masks,
        max_detections=max_detections,
        iou_threshold=iou_threshold,
        score_threshold=score_threshold,
    )
    return _observed(jax.jit(fn, donate_argnums=1), "yolo")


def centernet_decode(
    head: dict,
    *,
    max_detections: int = 100,
    score_threshold: float = 0.1,
):
    """CenterNet head dict -> detections, the 'peaks are boxes' decode.

    Peak extraction is the 3x3 max-pool trick from the Objects-as-Points
    paper (the reference never finished its decode; cited intent is
    ObjectsAsPoints/tensorflow/model.py:81-91 heads + train.py's stub):
    a cell is a peak iff it equals its 3x3 neighborhood max. Top-K peaks
    become boxes via the wh and offset branches.
    """
    heatmap = jax.nn.sigmoid(head["heatmap"])  # (B, h, w, C)
    b, h, w, c = heatmap.shape
    pooled = jax.lax.reduce_window(
        heatmap, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 1, 1, 1), "SAME"
    )
    peaks = jnp.where(pooled == heatmap, heatmap, 0.0)
    flat = peaks.reshape(b, -1)  # index = (y * w + x) * c + class
    k = min(max_detections, flat.shape[-1])
    scores, idx = jax.lax.top_k(flat, k)
    if k < max_detections:  # keep the (B, max_detections) contract
        pad = max_detections - k
        scores = jnp.pad(scores, ((0, 0), (0, pad)))
        idx = jnp.pad(idx, ((0, 0), (0, pad)))
    cls = idx % c
    spatial = idx // c
    ys = (spatial // w).astype(jnp.float32)
    xs = (spatial % w).astype(jnp.float32)

    def gather_spatial(branch):  # (B, h, w, 2) -> (B, k, 2) at peak cells
        flat_b = branch.reshape(b, -1, branch.shape[-1])
        return jnp.take_along_axis(flat_b, spatial[..., None], axis=1)

    off = gather_spatial(head["offset"])
    wh = gather_spatial(head["wh"])
    cx = (xs + off[..., 0]) / w
    cy = (ys + off[..., 1]) / h
    bw = wh[..., 0] / w
    bh = wh[..., 1] / h
    boxes = jnp.stack([cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2], -1)
    keep = scores >= score_threshold
    return {
        "boxes": jnp.where(keep[..., None], boxes, 0.0),
        "scores": jnp.where(keep, scores, 0.0),
        "classes": jnp.where(keep, cls, -1),
        "num": keep.sum(-1).astype(jnp.int32),
    }


def centernet_predict_fn(model, *, max_detections: int = 100,
                         score_threshold: float = 0.1) -> Callable:
    """Raw (variables, images) -> detections fn (un-jitted; serve/ AOT
    path + make_centernet_detector share it)."""
    def detect(variables, images):
        outputs = model.apply(variables, images, train=False)
        return centernet_decode(
            outputs[-1],  # last stack's head
            max_detections=max_detections,
            score_threshold=score_threshold,
        )

    return detect


def make_centernet_detector(model, *, max_detections: int = 100,
                            score_threshold: float = 0.1):
    # donate images, not variables — see make_yolo_detector
    fn = centernet_predict_fn(model, max_detections=max_detections,
                              score_threshold=score_threshold)
    return _observed(jax.jit(fn, donate_argnums=1), "centernet")


def heatmaps_to_keypoints(heatmaps):
    """(B, h, w, J) heatmaps -> (B, J, 3) normalized (x, y, score).

    The demo-notebook argmax decode (Hourglass demo_hourglass_pose.ipynb's
    role), on-device and batched.
    """
    b, h, w, j = heatmaps.shape
    flat = heatmaps.transpose(0, 3, 1, 2).reshape(b, j, -1)
    idx = jnp.argmax(flat, axis=-1)
    score = jnp.max(flat, axis=-1)
    ys = (idx // w).astype(jnp.float32) / h
    xs = (idx % w).astype(jnp.float32) / w
    return jnp.stack([xs, ys, score], axis=-1)


def pose_predict_fn(model) -> Callable:
    """Raw (variables, images) -> (B, J, 3) keypoints fn (un-jitted;
    serve/ AOT path + make_pose_estimator share it)."""
    def estimate(variables, images):
        outputs = model.apply(variables, images, train=False)
        heatmaps = outputs[-1] if isinstance(outputs, (list, tuple)) else outputs
        return heatmaps_to_keypoints(heatmaps)

    return estimate


def make_pose_estimator(model):
    # donate images, not variables — see make_yolo_detector
    return _observed(jax.jit(pose_predict_fn(model), donate_argnums=1),
                     "pose")
