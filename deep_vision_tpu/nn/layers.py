"""Shared flax building blocks for the whole model zoo.

The reference re-implements these per model (e.g. `BasicConv2d` at
Inception/pytorch/models/inception_v1.py, `DarknetConv` at
YOLO/tensorflow/yolov3.py:23-41, custom `SeparableConv2D` at
MobileNet/tensorflow/models/mobilenet_v1.py:7-26). Here they are written once,
NHWC, TPU-native:

- depthwise/group conv lowers to `lax.conv_general_dilated` with
  `feature_group_count` (the XLA-native form of torch's `groups=`);
- BatchNorm under pjit computes batch statistics over the *global* batch
  (XLA inserts the cross-replica psum), i.e. synced BN by construction —
  resolving the DataParallel+BN pitfall the reference documents at
  ResNet/pytorch/train.py:348-349;
- LocalResponseNorm (AlexNet V1, alexnet_v1.py:33-89) is a vectorized
  channel-window sum, fused by XLA.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

INITIALIZERS = {
    "he_normal": nn.initializers.he_normal(),
    "he_uniform": nn.initializers.he_uniform(),
    "xavier_normal": nn.initializers.xavier_normal(),
    "xavier_uniform": nn.initializers.xavier_uniform(),
    "lecun_normal": nn.initializers.lecun_normal(),
    "normal02": nn.initializers.normal(0.02),  # DCGAN init
}


def global_avg_pool(x):
    """NHWC -> NC global average pool (replaces AdaptiveAvgPool2d(1))."""
    return jnp.mean(x, axis=(1, 2))


def channel_shuffle(x, groups: int):
    """ShuffleNet channel shuffle: (B,H,W,g*c) -> transpose group/channel.

    The reference never implemented this (shufflenet_v1.py is a 0-byte file,
    SURVEY.md §2.9); written from the ShuffleNet paper (sec 3.1).
    """
    b, h, w, c = x.shape
    assert c % groups == 0, f"channels {c} not divisible by groups {groups}"
    x = x.reshape(b, h, w, groups, c // groups)
    x = jnp.swapaxes(x, 3, 4)
    return x.reshape(b, h, w, c)


class LocalResponseNorm(nn.Module):
    """AlexNet V1's LRN (alexnet_v1.py:42,52): across-channel normalization."""

    size: int = 5
    alpha: float = 1e-4
    beta: float = 0.75
    k: float = 2.0

    @nn.compact
    def __call__(self, x):
        half = self.size // 2
        sq = jnp.square(x)
        # sum over a channel window via padded cumulative trick
        padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
        window = sum(
            jax.lax.dynamic_slice_in_dim(padded, i, x.shape[-1], axis=x.ndim - 1)
            for i in range(self.size)
        )
        return x / jnp.power(self.k + self.alpha * window, self.beta)


class ConvBN(nn.Module):
    """Conv + BatchNorm + activation, the universal CNN building block."""

    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: str | Sequence[Tuple[int, int]] = "SAME"
    groups: int = 1
    use_bn: bool = True
    use_bias: bool = False
    act: Optional[Callable] = nn.relu
    kernel_init: Callable = nn.initializers.he_normal()
    bn_momentum: float = 0.9
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(
            self.features,
            self.kernel,
            strides=self.strides,
            padding=self.padding,
            feature_group_count=self.groups,
            use_bias=self.use_bias or not self.use_bn,
            kernel_init=self.kernel_init,
            dtype=self.dtype,
        )(x)
        if self.use_bn:
            x = nn.BatchNorm(
                use_running_average=not train,
                momentum=self.bn_momentum,
                dtype=self.dtype,
            )(x)
        if self.act is not None:
            x = self.act(x)
        return x


class DepthwiseSeparableConv(nn.Module):
    """MobileNet's depthwise 3x3 + pointwise 1x1 (mobilenet_v1.py:109-122).

    Depthwise = grouped conv with feature_group_count == in_channels; XLA
    lowers this to a TPU-native depthwise convolution.
    """

    features: int  # pointwise output channels
    strides: Tuple[int, int] = (1, 1)
    act: Callable = nn.relu
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        in_ch = x.shape[-1]
        x = ConvBN(
            features=in_ch,
            kernel=(3, 3),
            strides=self.strides,
            groups=in_ch,
            act=self.act,
            dtype=self.dtype,
        )(x, train)
        x = ConvBN(
            features=self.features, kernel=(1, 1), act=self.act, dtype=self.dtype
        )(x, train)
        return x
