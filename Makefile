# Launch conventions: the analog of the reference's per-model Makefiles
# (ResNet/pytorch/Makefile train_*/resume_* nohup targets,
# CycleGAN/tensorflow/Makefile tb/ps monitor targets), over the single
# config-registry CLI instead of 12 per-model scripts.
#
#   make train MODEL=resnet50            # background train, log to file
#   make resume MODEL=resnet50           # resume from latest checkpoint
#   make train-fg MODEL=lenet5 ARGS=--fake-data
#   make tb                              # tensorboard on ./runs
#   make test / make bench / make dryrun

TIME := $(shell date "+%Y-%m-%dT%H-%M-%S")
MODEL ?= resnet50
DATA ?= ./dataset
ARGS ?=

train:
	mkdir -p checkpoints logs
	nohup python -u train.py -m $(MODEL) --data-dir $(DATA) \
	  --tensorboard-dir runs/$(MODEL)-$(TIME) $(ARGS) \
	  > logs/$(MODEL)-$(TIME).log 2>&1 &
	@echo "started; tail -f logs/$(MODEL)-$(TIME).log"

resume:
	mkdir -p checkpoints logs
	nohup python -u train.py -m $(MODEL) --data-dir $(DATA) -c auto \
	  --tensorboard-dir runs/$(MODEL)-$(TIME) $(ARGS) \
	  > logs/$(MODEL)-$(TIME).log 2>&1 &
	@echo "resumed; tail -f logs/$(MODEL)-$(TIME).log"

train-fg:
	python -u train.py -m $(MODEL) --data-dir $(DATA) $(ARGS)

test:
	python -m pytest tests/ -x -q

# static analysis (lint/): the review-time teeth behind the obs/ runtime
# signals — fails on any non-baselined DV001-DV007 (JAX/TPU contracts),
# DV101-DV104 (concurrency pack, lint/concur.py), or DV201-DV205
# (distributed-correctness pack, lint/distlint.py) finding, then audits
# the curated sharding tables semantically (tools/shard_check.py:
# coverage floors over abstract eval_shape trees — zero devices, zero
# compiles). Runs first in verify: it is the cheapest gate (warm lint
# cache ~0.1s; shard_check ~2s on a cold jax import)
lint:
	python -m deep_vision_tpu.lint
	JAX_PLATFORMS=cpu python tools/shard_check.py

# accept the current findings into the checked-in baseline (use after an
# intentional change; review the diff of .jaxlint-baseline.json like code)
lint-baseline:
	python -m deep_vision_tpu.lint --write-baseline

# the tier-1 gate, verbatim from ROADMAP.md: run before shipping any PR
# (bash, not sh: the command uses pipefail and PIPESTATUS); lint, then
# obs-smoke and chaos-smoke — the telemetry artifacts must validate and
# the resilience contracts must hold before the tests count
verify: SHELL := /bin/bash
verify: lint preflight perf-smoke obs-smoke chaos-smoke data-smoke host-smoke serve-smoke fleet-smoke fleetnet-smoke cache-smoke shard-smoke perf-gate live-smoke
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

# environment preflight: backend liveness + libtpu/client version
# handshake, device-count/mesh-shape sanity, and checkpoint-dir
# writability — the run-killers that used to burn minutes (MULTICHIP_r01
# died 4 minutes into its compile on a libtpu skew; the r04 dead tunnel
# hung to rc=124) now fail in seconds, before anything compiles. Also
# the first act of every train_cli run (--skip-preflight opts out)
preflight:
	JAX_PLATFORMS=cpu python -m deep_vision_tpu.tools.preflight \
	  --ckpt-dir artifacts/preflight_probe

# observability smoke: a tiny CPU train with tracing + health guard +
# flight recorder + a static profiler window on, then validate the
# journal/trace artifacts against the obs/ schemas (profile_capture
# events included) and assert the clean exit left NO flight bundle —
# the recorder must disarm on a healthy run
obs-smoke:
	rm -rf artifacts/obs_smoke
	mkdir -p artifacts/obs_smoke
	JAX_PLATFORMS=cpu python train.py -m lenet5 --fake-data --epochs 1 \
	  --ckpt-dir artifacts/obs_smoke/ckpt \
	  --journal artifacts/obs_smoke/journal.jsonl \
	  --trace artifacts/obs_smoke/trace.json \
	  --flight-dir artifacts/obs_smoke/flight \
	  --profile-dir artifacts/obs_smoke/prof --profile-window 1:3 \
	  --health-policy warn --watchdog-timeout 300
	python tools/check_journal.py artifacts/obs_smoke/journal.jsonl \
	  --trace artifacts/obs_smoke/trace.json --strict
	python tools/obs_report.py artifacts/obs_smoke/journal.jsonl \
	  --trace artifacts/obs_smoke/trace.json
	@if [ -n "$$(ls -A artifacts/obs_smoke/flight 2>/dev/null)" ]; then \
	  echo "obs-smoke: clean run left a flight bundle"; exit 1; fi

# serving smoke: a real multi-model CPU server (YOLO + pose @64x64)
# through the whole serve/ contract — AOT warmup compiles exactly the
# bucket menu, a mixed-size request stream causes ZERO additional
# compilations, injected data.read faults degrade single requests,
# clean shutdown passes check_journal --strict with no flight bundle,
# and a SIGTERM'd child flushes all accepted requests and leaves a
# crc-valid preempt bundle (tools/serve_smoke.py). The locksmith lock
# sanitizer (obs/locksmith.py) is armed throughout and must report
# zero lock_order_violation events
serve-smoke:
	JAX_PLATFORMS=cpu python tools/serve_smoke.py --workdir artifacts/serve_smoke

# fleet smoke: the serving layer at fleet shape (tools/loadgen.py) — a
# 3-replica pool under seeded load survives an injected replica death
# request-scoped (typed replica_lost/replica_recovered + supervised
# respawn), promotes a canary weight swap AND auto-rolls-back a
# poisoned one under live traffic, sheds an overload blast by policy
# (serve_shed accounting exact, p99 of admitted traffic held), drains
# clean with a balanced fleet ledger, and compiles NOTHING after
# warmup — including across both swaps. Locksmith armed throughout;
# journals pass check_journal --strict; no stray flight bundles
fleet-smoke:
	JAX_PLATFORMS=cpu python tools/loadgen.py --workdir artifacts/fleet_smoke

# front-door smoke: the socket transport + process-replica fleet
# (tools/fleetnet_smoke.py) — N spawned replica PROCESSES (each its
# own engine, HTTP endpoint, and rendezvous lease) behind the parent's
# HTTP front door; every replica warms at ZERO backend compiles off
# the parent-seeded executable cache; a mid-traffic SIGKILL fails only
# the dead process's in-flight requests (typed ReplicaLost behind
# retryable 503s) and the respawn rebirths from cache; a canary
# PROCESS serves shadow weights and promote hot-swaps the whole fleet
# over /control/promote; an overload blast gets real 429s with
# Retry-After that a retrying client honors; offered == ok+err+shed
# holds across client, transport ledger, and journal; strict
# check_journal on parent + every surviving child journal, with the
# SIGKILLed incarnation's journal flagged as the forensic record
fleetnet-smoke:
	JAX_PLATFORMS=cpu python tools/fleetnet_smoke.py --workdir artifacts/fleetnet_smoke

# cold-path smoke: the persistent executable cache + int8 quantization
# contracts (tools/cache_smoke.py) — run A compiles and populates the
# cache (one excache_store per pair), run B in a FRESH process warms
# with ZERO backend compiles (recompile-counter delta == 0, all
# excache_hit, bit-identical outputs), a deliberately version-skewed
# entry journals a typed excache_invalid and falls through to the
# compiler, and the int8 engine passes the accuracy-delta gate and
# serves the same traffic with SLO before/after printed (a poisoned
# calibration is REFUSED). Journals pass check_journal --strict
cache-smoke:
	JAX_PLATFORMS=cpu python tools/cache_smoke.py --workdir artifacts/cache_smoke

# shard smoke: declarative sharding on a forced 8-device CPU mesh
# (tools/shard_smoke.py) — ViT and the V-MoE variant train GENUINELY
# sharded multi-step (table-resolved NamedShardings on device, zero
# recompiles after warmup), tp_sharded_leaves clears each family's
# declared floor via the TABLE (and beats the size heuristic it
# replaces), a deliberately gutted table fails at startup NAMING the
# replicated leaves, scaling efficiency is measured at data={1,2,4,8}
# sub-meshes, and the journals (typed sharding_resolved + bench
# events) pass check_journal --strict with obs_report rendering the
# sharding section
shard-smoke:
	JAX_PLATFORMS=cpu python tools/shard_smoke.py --workdir artifacts/shard_smoke

# perf-attribution smoke: two seeded CPU bench runs build the crc-
# manifested ledger, a third run slowed through the fault-injection
# plane must FAIL the noise-aware MAD gate (CLI exits nonzero, typed
# perf_regression journaled, failed row excluded from future
# baselines), --bless re-anchors, corrupt ledger rows quarantine, and
# the sharded ViT step's parsed all-reduce inventory must match its
# gradient-tree bytes within 5% (tools/perf_gate.py --smoke)
perf-gate:
	JAX_PLATFORMS=cpu python tools/perf_gate.py --smoke --workdir artifacts/perf_gate

# live-telemetry smoke: a REAL train.py subprocess is scraped MID-RUN
# through its discovery file (/metrics parses as Prometheus, /healthz
# 200, /statusz shows a live step, obs_poll renders the one-liner); a
# data-service subprocess and an in-process client journal ONE traced
# request that obs_report --merged stitches into a single cross-process
# causal timeline; and a locksmith-armed probe proves concurrent
# scraping causes zero recompiles, zero lock-order violations, and
# <2% step-time overhead at a 1 Hz poll. Journals pass --strict with
# typed telemetry_server events (tools/live_smoke.py)
live-smoke:
	JAX_PLATFORMS=cpu python tools/live_smoke.py --workdir artifacts/live_smoke

# resilience smoke: a record-backed CPU train under injected faults
# (skipped bad records within budget, SIGKILL mid-checkpoint-save,
# quarantine-and-fall-back resume), journals validated --strict, plus a
# no-fault overhead probe on the injection points (tools/chaos_run.py).
# Children run with DVT_LOCKSMITH=1 (zero violations asserted), a forced
# A->B/B->A inversion must be detected at runtime, and the disabled
# locksmith wrapper is overhead-probed
chaos-smoke:
	JAX_PLATFORMS=cpu python tools/chaos_run.py --workdir artifacts/chaos_smoke

# host-churn smoke: the multi-host half of the elastic arc
# (tools/host_smoke.py) — three REAL processes (forced 2-device CPU
# worlds) rendezvous, train a checkpointed run at world 3, and one is
# SIGKILLed mid-epoch: the survivors must detect within the heartbeat
# deadline (typed host_lost, no collective hang), re-rendezvous at
# generation 1 / world 2, rebuild the mesh, resume at the EXACT
# checkpointed step via the cross-mesh restore, and re-derive a
# disjoint+covering host-shard assignment (typed data_reshard).
# Locksmith armed throughout (zero violations); surviving journals
# pass check_journal --strict; obs_report renders the membership
# timeline
host-smoke:
	JAX_PLATFORMS=cpu python tools/host_smoke.py --workdir artifacts/host_smoke

# data-plane smoke: the production data plane's contracts
# (tools/data_smoke.py) — a record-backed CPU train SIGKILLed mid-epoch
# resumes from the crc32c sidecar with a byte-identical batch stream
# (content hashes; typed data_resume event), and a 2-consumer shared
# dataset service streams with zero recompiles and zero starvation,
# absorbs an injected worker crash via supervised respawn
# (data_worker_lost/recovered) and a dropped connection via client
# reconnect; journals pass check_journal --strict
data-smoke:
	JAX_PLATFORMS=cpu python tools/data_smoke.py --workdir artifacts/data_smoke

# perf smoke: the CPU-provable proxies behind the MFU attack — fused
# Pallas kernels (bn_act, nms) match their lax references in interpret
# mode, a multistep=4 Trainer superstep is step-for-step equivalent to 4
# single dispatches with 4x fewer step events and ZERO recompiles after
# warmup, the depth-2 device prefetcher never starves a slower consumer,
# and check_journal --strict accepts the extended step/bench fields
# (tools/perf_smoke.py)
perf-smoke:
	JAX_PLATFORMS=cpu python tools/perf_smoke.py --workdir artifacts/perf_smoke

bench:
	python bench.py

# roofline anchored to the latest bench numbers: where the measured step
# and each analytic layer sit vs the 197 TF/s / 819 GB/s pins and the
# 30%-MFU baseline (deep_vision_tpu/tools/roofline.py --bench-json)
BENCH_JSON ?= BENCH_r03.json
roofline:
	python -m deep_vision_tpu.tools.roofline --analytic \
	  --bench-json $(BENCH_JSON) --out artifacts/roofline_bench.json

# perf-evidence suite: every README perf claim regenerates from these
bench-evidence:
	python tools/batch_sweep.py artifacts/batch_scaling_r04.json
	python tools/bench_ablate.py
	python tools/bench_models.py
	python tools/dispatch_probe.py

demo:
	python -m deep_vision_tpu.tools.convergence_run --model yolov3 \
	  --holdout --render-dir examples/output
	python -m deep_vision_tpu.tools.convergence_run --model hourglass \
	  --holdout --render-dir examples/output

demo-gan:
	python -m deep_vision_tpu.tools.convergence_run --model dcgan \
	  --render-dir examples/output --out artifacts/dcgan_convergence.json
	python -m deep_vision_tpu.tools.convergence_run --model cyclegan \
	  --render-dir examples/output --out artifacts/cyclegan_convergence.json

demo-real:
	python examples/real_photo_demo.py

dryrun:
	python __graft_entry__.py 8

tb:
	tensorboard --logdir=./runs

ps:
	ps -ef | grep python

native:
	$(MAKE) -C native

.PHONY: train resume train-fg test lint lint-baseline verify preflight obs-smoke chaos-smoke data-smoke host-smoke serve-smoke fleet-smoke fleetnet-smoke cache-smoke shard-smoke perf-gate live-smoke perf-smoke bench bench-evidence roofline demo demo-gan demo-real dryrun tb ps native
