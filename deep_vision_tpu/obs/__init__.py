"""Unified telemetry: metrics registry, run journal, step-time breakdown.

The observability layer every perf PR reports through (SURVEY.md §2.7
records the reference's instrumentation as one examples/sec print):

- `registry`: counters / gauges / log-scale histograms, exported as
  Prometheus text format or JSONL snapshots (`Registry`, `get_registry`).
- `journal`: append-only JSONL of typed run events — manifest, steps,
  evals, checkpoints, crash/exit markers (`RunJournal`, `read_journal`).
- `stepclock`: host data-wait vs dispatch vs device-compute breakdown
  with periodic `block_until_ready` fences, plus recompile and HBM
  tracking (`StepClock`, `recompile_count`, `hbm_bytes_in_use`).

All file writers are process-0-only under `jax.process_index()`; metric
*collection* runs on every host so counters stay meaningful if a
follower is later asked to dump state.
"""
from deep_vision_tpu.obs.journal import RunJournal, read_journal
from deep_vision_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
    is_primary_host,
)
from deep_vision_tpu.obs.stepclock import (
    StepClock,
    hbm_bytes_in_use,
    recompile_count,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "RunJournal",
    "StepClock",
    "get_registry",
    "hbm_bytes_in_use",
    "is_primary_host",
    "read_journal",
    "recompile_count",
]
