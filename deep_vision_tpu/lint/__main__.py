"""jaxlint CLI: `python -m deep_vision_tpu.lint` / `make lint`.

    python -m deep_vision_tpu.lint [paths...]
        [--format human|json] [--baseline PATH | --no-baseline]
        [--write-baseline] [--select DV001,DV002] [--disable DV006]
        [--fail-on-warn] [--list-rules]

Exit status: 0 = clean (or every error is baselined), 1 = new findings,
2 = invalid file (unreadable baseline), 64 = usage error — the same
contract as tools/check_journal.py. With no paths, the [tool.jaxlint]
section of pyproject.toml supplies them (defaults: deep_vision_tpu/,
tools/, train.py).
"""
from __future__ import annotations

import json
import os
import sys
from typing import List, Optional

from deep_vision_tpu.cli import EXIT_INVALID, EXIT_USAGE, UsageErrorParser
from deep_vision_tpu.lint.config import (
    find_pyproject,
    load_config,
    resolve_paths,
)
from deep_vision_tpu.lint.engine import lint_paths
from deep_vision_tpu.lint.findings import (
    load_baseline,
    save_baseline,
    split_baselined,
)
from deep_vision_tpu.lint.rules import RULES


def _codes(raw: Optional[str]) -> Optional[List[str]]:
    if raw is None:
        return None
    return [c.strip().upper() for c in raw.split(",") if c.strip()]


def main(argv: Optional[List[str]] = None) -> int:
    p = UsageErrorParser(
        prog="python -m deep_vision_tpu.lint",
        description="JAX/TPU-aware static analysis for deep_vision_tpu",
    )
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: [tool.jaxlint] paths)")
    p.add_argument("--format", choices=("human", "json"), default="human")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline file (default: [tool.jaxlint] baseline)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept all current findings into the baseline "
                        "and exit 0")
    p.add_argument("--select", default=None, metavar="CODES",
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--disable", default=None, metavar="CODES",
                   help="comma-separated rule codes to skip")
    p.add_argument("--fail-on-warn", action="store_true",
                   help="non-baselined warnings also fail the gate")
    p.add_argument("--config", default=None, metavar="PYPROJECT",
                   help="explicit pyproject.toml (default: nearest upward)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--knobs", action="store_true",
                   help="print the DVT_* environment-knob registry "
                        "(core/knobs.py) and exit")
    p.add_argument("--no-cache", action="store_true",
                   help="bypass the incremental lint cache "
                        "(artifacts/lint_cache/)")
    args = p.parse_args(argv)

    if args.list_rules:
        for code, (name, severity, _, doc) in sorted(RULES.items()):
            print(f"{code}  {name:<24} [{severity}]  {doc}")
        return 0

    if args.knobs:
        from deep_vision_tpu.core.knobs import format_knob_table

        print(format_knob_table())
        return 0

    # a typo'd code would otherwise run zero rules and report "clean"
    unknown = sorted({c for c in (_codes(args.select) or []) +
                      (_codes(args.disable) or []) if c not in RULES})
    if unknown:
        print(f"jaxlint: unknown rule code(s): {', '.join(unknown)} "
              f"(known: {', '.join(sorted(RULES))})", file=sys.stderr)
        return EXIT_USAGE

    pyproject = args.config or find_pyproject(
        args.paths[0] if args.paths else os.getcwd())
    try:
        cfg = load_config(pyproject)
    except ValueError as e:  # tomllib.TOMLDecodeError subclasses ValueError
        print(f"jaxlint: invalid [tool.jaxlint] config in {pyproject}: {e}",
              file=sys.stderr)
        return EXIT_INVALID
    paths = resolve_paths(cfg, args.paths)
    disable = {c.upper() for c in cfg["disable"]} | \
        set(_codes(args.disable) or [])
    bad_cfg = sorted(disable - set(RULES))
    if bad_cfg:
        print(f"jaxlint: unknown rule code(s) in [tool.jaxlint] disable: "
              f"{', '.join(bad_cfg)}", file=sys.stderr)
        return EXIT_INVALID
    # --select DV001 --disable DV001 would run zero rules and exit 0
    if not (set(_codes(args.select) or RULES) - disable):
        print("jaxlint: --select/--disable leave no rules enabled",
              file=sys.stderr)
        return EXIT_USAGE

    cache = None
    if not args.no_cache:
        from deep_vision_tpu.lint.cache import (
            DEFAULT_CACHE_DIR,
            LintCache,
            pack_fingerprint,
        )

        root = cfg.get("root", os.getcwd())
        enabled = set(_codes(args.select) or RULES) - disable
        cache = LintCache(os.path.join(root, DEFAULT_CACHE_DIR),
                          pack_fingerprint(enabled, root=root))

    findings, suppressed, n_files = lint_paths(
        paths,
        root=cfg.get("root"),
        select=_codes(args.select),
        disable=disable or None,
        exclude=cfg["exclude"],
        cache=cache,
    )

    baseline_path = args.baseline or os.path.join(
        cfg.get("root", os.getcwd()), cfg["baseline"])
    if args.write_baseline:
        # the baseline file holds the full-rule acceptance set: writing it
        # from a partial run would drop every other rule's accepted entries
        if args.select or args.disable:
            print("jaxlint: --write-baseline must run with all rules "
                  "enabled (drop --select/--disable)", file=sys.stderr)
            return EXIT_USAGE
        # same hazard as a partial rule run: findings outside the given
        # paths would be dropped from the acceptance set
        if args.paths:
            print("jaxlint: --write-baseline must run over the full "
                  "[tool.jaxlint] path set (drop the explicit paths)",
                  file=sys.stderr)
            return EXIT_USAGE
        # DV000 means the lint run itself is broken (missing path, syntax
        # error, unreadable file) — baselining it would permanently silence
        # the guard that exists to catch exactly that
        broken = [f for f in findings if f.code == "DV000"]
        if broken:
            for f in broken:
                print(f.render(), file=sys.stderr)
            print("jaxlint: refusing to write a baseline over DV000 "
                  "config/parse errors — fix them first", file=sys.stderr)
            return 1
        if n_files == 0:
            # an empty path set would silently truncate the acceptance
            # set to nothing and report success
            print("jaxlint: refusing to write a baseline: no Python "
                  "files were linted — check [tool.jaxlint] "
                  "paths/exclude", file=sys.stderr)
            return 1
        save_baseline(baseline_path, findings)
        print(f"jaxlint: baseline written to {baseline_path} "
              f"({len(findings)} finding(s) accepted)")
        return 0

    if n_files == 0:
        missing = [pt for pt in paths if not os.path.exists(pt)]
        detail = (f"path does not exist: {', '.join(missing)}" if missing
                  else f"no Python files found under {', '.join(paths)}")
        print(f"jaxlint: {detail} — check [tool.jaxlint] paths",
              file=sys.stderr)
        return 1

    if args.no_baseline:
        fresh, accepted = findings, []
    else:
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"jaxlint: unreadable baseline: {e}; regenerate with "
                  "`make lint-baseline`", file=sys.stderr)
            return EXIT_INVALID
        fresh, accepted = split_baselined(findings, baseline)

    errors = [f for f in fresh if f.severity == "error"]
    warnings = [f for f in fresh if f.severity == "warning"]
    failed = bool(errors) or (args.fail_on_warn and bool(warnings))

    if args.format == "json":
        print(json.dumps({
            "version": 1,
            "findings": [f.to_dict() for f in fresh],
            "baselined": [f.to_dict() for f in accepted],
            "summary": {
                "files": n_files,
                "errors": len(errors),
                "warnings": len(warnings),
                "baselined": len(accepted),
                "suppressed": len(suppressed),
                "failed": failed,
            },
        }, indent=2))
    else:
        for f in fresh:
            print(f.render())
        tail = (f"jaxlint: {len(errors)} error(s), {len(warnings)} "
                f"warning(s) in {n_files} files "
                f"({len(accepted)} baselined, {len(suppressed)} suppressed)")
        print(tail, file=sys.stderr if failed else sys.stdout)

    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
