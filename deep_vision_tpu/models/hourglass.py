"""Stacked Hourglass network for pose estimation (Newell 2016).

Parity target: Hourglass/tensorflow/hourglass104.py — BottleneckBlock (:19-67),
recursive HourglassModule (:70-98), StackedHourglassNetwork with intermediate
supervision: one heatmap head per stack plus re-injection of the head output
into the next stack's input (:113-159). Default 4 stacks, 16 MPII keypoints,
256x256 input -> 64x64x16 heatmaps per stack.
"""
from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from deep_vision_tpu.models import register_model
from deep_vision_tpu.nn.layers import FusedBatchNorm


class HgBottleneck(nn.Module):
    """Pre-activation bottleneck used throughout the hourglass."""

    features: int  # output channels

    @nn.compact
    def __call__(self, x, train: bool = True):
        def bn_relu(y):
            y = FusedBatchNorm(use_running_average=not train, momentum=0.9)(y)
            return nn.relu(y)

        residual = x
        y = bn_relu(x)
        y = nn.Conv(self.features // 2, (1, 1), use_bias=False)(y)
        y = bn_relu(y)
        y = nn.Conv(self.features // 2, (3, 3), use_bias=False)(y)
        y = bn_relu(y)
        y = nn.Conv(self.features, (1, 1), use_bias=False)(y)
        if residual.shape[-1] != self.features:
            residual = nn.Conv(self.features, (1, 1), use_bias=False)(x)
        return y + residual


class HourglassModule(nn.Module):
    """Recursive down-up module of `order` levels (hourglass104.py:70-98)."""

    order: int
    features: int = 256
    num_residual: int = 1

    @nn.compact
    def __call__(self, x, train: bool = True):
        # upper (skip) branch at current resolution
        up = x
        for _ in range(self.num_residual):
            up = HgBottleneck(self.features)(up, train)
        # lower branch: pool -> recurse -> upsample
        low = nn.max_pool(x, (2, 2), strides=(2, 2))
        for _ in range(self.num_residual):
            low = HgBottleneck(self.features)(low, train)
        if self.order > 1:
            low = HourglassModule(self.order - 1, self.features, self.num_residual)(
                low, train
            )
        else:
            for _ in range(self.num_residual):
                low = HgBottleneck(self.features)(low, train)
        for _ in range(self.num_residual):
            low = HgBottleneck(self.features)(low, train)
        b, h, w, c = low.shape
        low = jnp.repeat(jnp.repeat(low, 2, axis=1), 2, axis=2)  # nearest 2x
        return up + low


class StackedHourglass(nn.Module):
    """Returns a list of per-stack heatmaps [(B, 64, 64, K)] * num_stack."""

    num_stack: int = 4
    num_heatmap: int = 16
    features: int = 256
    num_residual: int = 1

    @nn.compact
    def __call__(self, x, train: bool = True):
        # stem: 256x256 -> 64x64 (hourglass104.py:120-128)
        x = nn.Conv(64, (7, 7), strides=(2, 2), use_bias=False)(x)
        x = nn.relu(FusedBatchNorm(use_running_average=not train, momentum=0.9)(x))
        x = HgBottleneck(128)(x, train)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = HgBottleneck(128)(x, train)
        x = HgBottleneck(self.features)(x, train)

        heatmaps = []
        for stack in range(self.num_stack):
            inter = HourglassModule(4, self.features, self.num_residual)(x, train)
            inter = HgBottleneck(self.features)(inter, train)
            inter = nn.Conv(self.features, (1, 1), use_bias=False)(inter)
            inter = nn.relu(
                FusedBatchNorm(use_running_average=not train, momentum=0.9)(inter)
            )
            hm = nn.Conv(self.num_heatmap, (1, 1))(inter)
            heatmaps.append(hm)
            # re-inject head output + features into the next stack (:144-157);
            # the last stack has no successor, so no re-injection params
            if stack < self.num_stack - 1:
                x = (
                    x
                    + nn.Conv(self.features, (1, 1), use_bias=False)(inter)
                    + nn.Conv(self.features, (1, 1), use_bias=False)(hm)
                )
        return heatmaps


@register_model("hourglass")
def hourglass(num_stack: int = 4, num_heatmap: int = 16, **_):
    return StackedHourglass(num_stack=num_stack, num_heatmap=num_heatmap)
