"""Host input pipeline: transform workers -> shuffle -> fixed-shape batches.

The TPU-feed replacement for both reference input stacks: torch DataLoader
with worker processes (ResNet/pytorch/train.py:218-257) and
tf.data map(AUTOTUNE)/shuffle/batch/prefetch chains
(YOLO/tensorflow/train.py:260-273). Decode+augment run on a thread pool
(cv2/PIL release the GIL for the heavy work), a sample-level shuffle buffer
reproduces `shuffle(512)`/`shuffle(10000)` semantics, and batches are
collated into fixed-shape numpy dicts ready for `shard_batch` onto the mesh.
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np


class Compose:
    """Chain of transforms, each `(sample, rng) -> sample`."""

    def __init__(self, transforms: Sequence[Callable]):
        self.transforms = list(transforms)

    def __call__(self, sample: dict, rng: np.random.Generator) -> dict:
        for t in self.transforms:
            sample = t(sample, rng)
        return sample


def collate(samples: List[dict]) -> dict:
    """Stack a list of sample dicts into one batch dict of arrays."""
    keys = samples[0].keys()
    return {k: np.stack([np.asarray(s[k]) for s in samples]) for k in keys}


class DataLoader:
    """dataset (+ transforms) -> iterator of batch dicts.

    dataset: __len__/__getitem__ map-style OR any iterable of sample dicts.
    Map-style datasets get a full index shuffle per epoch (torch DataLoader
    shuffle=True semantics); iterable datasets get a reservoir-style shuffle
    buffer (tf.data shuffle(buffer) semantics, YOLO/tensorflow/train.py:267).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        transform: Optional[Callable] = None,
        shuffle: bool = False,
        shuffle_buffer: int = 512,
        num_workers: int = 8,
        drop_remainder: bool = False,
        seed: int = 0,
        collate_fn: Callable = collate,
        prefetch: int = 2,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.transform = transform
        self.shuffle = shuffle
        self.shuffle_buffer = shuffle_buffer
        self.num_workers = max(1, num_workers)
        self.drop_remainder = drop_remainder
        self.seed = seed
        self.collate_fn = collate_fn
        self.prefetch = prefetch
        self._epoch = 0
        self._map_style = hasattr(dataset, "__getitem__") and hasattr(
            dataset, "__len__"
        )

    def __len__(self) -> int:
        if not self._map_style:
            raise TypeError("length unknown for iterable datasets")
        n = len(self.dataset)
        return n // self.batch_size if self.drop_remainder else -(-n // self.batch_size)

    # -- internals ---------------------------------------------------------

    def _samples(self, epoch_rng: np.random.Generator) -> Iterator[dict]:
        if self._map_style:
            idx = np.arange(len(self.dataset))
            if self.shuffle:
                epoch_rng.shuffle(idx)
            for i in idx:
                yield self.dataset[int(i)]
        else:
            it = iter(self.dataset)
            if not self.shuffle:
                yield from it
                return
            buf: List[dict] = []
            for s in it:
                if len(buf) < self.shuffle_buffer:
                    buf.append(s)
                    continue
                j = int(epoch_rng.integers(0, len(buf)))
                out, buf[j] = buf[j], s
                yield out
            epoch_rng.shuffle(buf)  # type: ignore[arg-type]
            yield from buf

    def _transformed(self, epoch_seed: int) -> Iterator[dict]:
        epoch_rng = np.random.default_rng(epoch_seed)
        samples = self._samples(epoch_rng)
        if self.transform is None:
            yield from samples
            return
        # ordered parallel map: worker i gets its own derived rng stream
        with ThreadPoolExecutor(self.num_workers) as pool:
            window: "queue.Queue" = queue.Queue()
            in_flight = 0
            max_in_flight = self.num_workers * 2

            def submit(sample, k):
                rng = np.random.default_rng((epoch_seed, k))
                return pool.submit(self.transform, sample, rng)

            k = 0
            for sample in samples:
                window.put(submit(sample, k))
                k += 1
                in_flight += 1
                if in_flight >= max_in_flight:
                    yield window.get().result()
                    in_flight -= 1
            while in_flight:
                yield window.get().result()
                in_flight -= 1

    def _batches(self) -> Iterator[dict]:
        epoch_seed = self.seed + self._epoch
        self._epoch += 1
        buf: List[dict] = []
        for s in self._transformed(epoch_seed):
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self.collate_fn(buf)
                buf = []
        if buf and not self.drop_remainder:
            yield self.collate_fn(buf)

    def __iter__(self) -> Iterator[dict]:
        """Yield batches, producing up to `prefetch` ahead on a thread."""
        if self.prefetch <= 0:
            yield from self._batches()
            return
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        err: List[BaseException] = []

        def producer():
            try:
                for b in self._batches():
                    q.put(b)
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        t.join()
        if err:
            raise err[0]
