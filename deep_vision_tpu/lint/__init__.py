"""jaxlint: JAX/TPU-aware static analysis for this framework.

The review-time teeth behind the obs/ runtime telemetry: an AST-based
rule engine (stdlib `ast`, no dependencies) that enforces the
performance and correctness contracts the hot paths rely on — no host
syncs or impurity inside jit, no reused PRNG keys, donated train-step
state, no jit-in-loop recompiles (DV001-DV007), plus the DV1xx
concurrency pack (lint/concur.py): thread-shared state without a lock,
lock-order inversions, signal-unsafe handlers, Future-protocol misuse.
Run as `python -m deep_vision_tpu.lint` or `make lint`; see
lint/README.md for the rule catalog and obs/locksmith.py for the
runtime half of the concurrency contracts.
"""
from deep_vision_tpu.lint.engine import (
    lint_paths,
    lint_source,
    parse_suppressions,
)
from deep_vision_tpu.lint.findings import (
    Finding,
    load_baseline,
    save_baseline,
    split_baselined,
)
from deep_vision_tpu.lint.rules import RULES

__all__ = [
    "Finding",
    "RULES",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "parse_suppressions",
    "save_baseline",
    "split_baselined",
]
