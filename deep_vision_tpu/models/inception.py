"""Inception V1 / GoogLeNet (Szegedy 2014) and Inception V3 (Szegedy 2015).

Parity targets: Inception/pytorch/models/inception_v1.py (InceptionModule,
two AuxiliaryClassifier heads active only in training, Xavier init at
inception_v1.py:116-124). The reference's V3 is a 6-line stub
(inception_v3.py, SURVEY.md §2.9) — ours is a real implementation from the
paper (factorized 7x7, grid-reduction blocks, aux head, label-smoothing
handled in the loss).

Training-mode output is `(logits, aux1_logits, aux2_logits)`; the trainer's
loss plumbing (losses/classification.py) weights aux heads by 0.3 as in the
paper — fixing the incompatibility the reference shipped (SURVEY.md §2.9,
inception_v1.py:112-114 vs train.py:449-452).
"""
from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

from deep_vision_tpu.models import register_model
from deep_vision_tpu.nn.layers import FusedBatchNorm, global_avg_pool

_XAVIER = nn.initializers.xavier_normal()


class BasicConv(nn.Module):
    """Conv + BN + ReLU with xavier init (BasicConv2d, inception_v1.py)."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.features, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False, kernel_init=_XAVIER)(x)
        x = FusedBatchNorm(use_running_average=not train, momentum=0.9)(x)
        return nn.relu(x)


class InceptionModule(nn.Module):
    """4-branch module (1x1 / 1x1-3x3 / 1x1-5x5 / pool-1x1)."""

    c1: int
    c3r: int
    c3: int
    c5r: int
    c5: int
    cp: int

    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = BasicConv(self.c1, (1, 1))(x, train)
        b2 = BasicConv(self.c3r, (1, 1))(x, train)
        b2 = BasicConv(self.c3, (3, 3))(b2, train)
        b3 = BasicConv(self.c5r, (1, 1))(x, train)
        b3 = BasicConv(self.c5, (5, 5))(b3, train)
        b4 = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = BasicConv(self.cp, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class AuxClassifier(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.avg_pool(x, (5, 5), strides=(3, 3))
        x = BasicConv(128, (1, 1))(x, train)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(1024, kernel_init=_XAVIER)(x))
        x = nn.Dropout(0.7, deterministic=not train)(x)
        return nn.Dense(self.num_classes, kernel_init=_XAVIER)(x)


class InceptionV1(nn.Module):
    num_classes: int = 1000

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = BasicConv(64, (7, 7), strides=(2, 2))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = BasicConv(64, (1, 1))(x, train)
        x = BasicConv(192, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = InceptionModule(64, 96, 128, 16, 32, 32)(x, train)    # 3a
        x = InceptionModule(128, 128, 192, 32, 96, 64)(x, train)  # 3b
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = InceptionModule(192, 96, 208, 16, 48, 64)(x, train)   # 4a
        aux1 = AuxClassifier(self.num_classes)(x, train) if train else None
        x = InceptionModule(160, 112, 224, 24, 64, 64)(x, train)  # 4b
        x = InceptionModule(128, 128, 256, 24, 64, 64)(x, train)  # 4c
        x = InceptionModule(112, 144, 288, 32, 64, 64)(x, train)  # 4d
        aux2 = AuxClassifier(self.num_classes)(x, train) if train else None
        x = InceptionModule(256, 160, 320, 32, 128, 128)(x, train)  # 4e
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = InceptionModule(256, 160, 320, 32, 128, 128)(x, train)  # 5a
        x = InceptionModule(384, 192, 384, 48, 128, 128)(x, train)  # 5b
        x = global_avg_pool(x)
        x = nn.Dropout(0.4, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, kernel_init=_XAVIER)(x)
        if train:
            return logits, aux1, aux2
        return logits


# ---------------------------------------------------------------------------
# Inception V3 (from the paper; reference stub only)
# ---------------------------------------------------------------------------


class InceptionA(nn.Module):
    pool_features: int

    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = BasicConv(64, (1, 1))(x, train)
        b2 = BasicConv(48, (1, 1))(x, train)
        b2 = BasicConv(64, (5, 5))(b2, train)
        b3 = BasicConv(64, (1, 1))(x, train)
        b3 = BasicConv(96, (3, 3))(b3, train)
        b3 = BasicConv(96, (3, 3))(b3, train)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = BasicConv(self.pool_features, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionA(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = BasicConv(384, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        b2 = BasicConv(64, (1, 1))(x, train)
        b2 = BasicConv(96, (3, 3))(b2, train)
        b2 = BasicConv(96, (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionB(nn.Module):
    """Factorized 7x7 module."""

    c7: int

    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = BasicConv(192, (1, 1))(x, train)
        b2 = BasicConv(self.c7, (1, 1))(x, train)
        b2 = BasicConv(self.c7, (1, 7))(b2, train)
        b2 = BasicConv(192, (7, 1))(b2, train)
        b3 = BasicConv(self.c7, (1, 1))(x, train)
        b3 = BasicConv(self.c7, (7, 1))(b3, train)
        b3 = BasicConv(self.c7, (1, 7))(b3, train)
        b3 = BasicConv(self.c7, (7, 1))(b3, train)
        b3 = BasicConv(192, (1, 7))(b3, train)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = BasicConv(192, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionB(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = BasicConv(192, (1, 1))(x, train)
        b1 = BasicConv(320, (3, 3), strides=(2, 2), padding="VALID")(b1, train)
        b2 = BasicConv(192, (1, 1))(x, train)
        b2 = BasicConv(192, (1, 7))(b2, train)
        b2 = BasicConv(192, (7, 1))(b2, train)
        b2 = BasicConv(192, (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    """Expanded-filter-bank output module."""

    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = BasicConv(320, (1, 1))(x, train)
        b2 = BasicConv(384, (1, 1))(x, train)
        b2 = jnp.concatenate(
            [BasicConv(384, (1, 3))(b2, train), BasicConv(384, (3, 1))(b2, train)],
            axis=-1,
        )
        b3 = BasicConv(448, (1, 1))(x, train)
        b3 = BasicConv(384, (3, 3))(b3, train)
        b3 = jnp.concatenate(
            [BasicConv(384, (1, 3))(b3, train), BasicConv(384, (3, 1))(b3, train)],
            axis=-1,
        )
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = BasicConv(192, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3Aux(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.avg_pool(x, (5, 5), strides=(3, 3))
        x = BasicConv(128, (1, 1))(x, train)
        x = BasicConv(768, x.shape[1:3], padding="VALID")(x, train)
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes, kernel_init=_XAVIER)(x)


class InceptionV3(nn.Module):
    num_classes: int = 1000

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: (B, 299, 299, 3)
        x = BasicConv(32, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        x = BasicConv(32, (3, 3), padding="VALID")(x, train)
        x = BasicConv(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = BasicConv(80, (1, 1))(x, train)
        x = BasicConv(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = InceptionA(32)(x, train)
        x = InceptionA(64)(x, train)
        x = InceptionA(64)(x, train)
        x = ReductionA()(x, train)
        x = InceptionB(128)(x, train)
        x = InceptionB(160)(x, train)
        x = InceptionB(160)(x, train)
        x = InceptionB(192)(x, train)
        aux = InceptionV3Aux(self.num_classes)(x, train) if train else None
        x = ReductionB()(x, train)
        x = InceptionC()(x, train)
        x = InceptionC()(x, train)
        x = global_avg_pool(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, kernel_init=_XAVIER)(x)
        if train:
            return logits, aux
        return logits


@register_model("inception1")
def inception_v1(num_classes: int = 1000, **_):
    return InceptionV1(num_classes=num_classes)


@register_model("inception3")
def inception_v3(num_classes: int = 1000, **_):
    return InceptionV3(num_classes=num_classes)
