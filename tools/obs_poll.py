"""Poll the live telemetry plane: one status line per process.

    PYTHONPATH=. python tools/obs_poll.py --run-dir checkpoints/lenet5
    PYTHONPATH=. python tools/obs_poll.py --run-dir ckpts --watch 2

Each process that serves telemetry (train.py --telemetry-port,
tools/data_service.py --telemetry-port, serve-side TelemetryServer)
drops a `telemetry-<role>-<pid>.json` discovery file under its run dir;
this tool reads those files (obs/telemetry.py read_discovery), hits
each process's /statusz + /healthz, and renders one line per process:

    train       pid 4242 @ 127.0.0.1:35411  OK      step 1840  ep 3  412.3 ex/s
    data_service pid 4250 @ 127.0.0.1:35500 OK      served 9211
    serve       pid 4260 @ 127.0.0.1:35600  UNHEALTHY(draining)  gen 2

A process whose endpoint no longer answers renders as GONE — a stale
discovery file from a crashed process, the poll's liveness signal.

`--once` (default) prints a single snapshot and exits 0 if every
discovered process is healthy, 1 otherwise (the scriptable form the
live smoke uses). `--watch SECONDS` loops forever. Each line also
carries a `gp NN%` goodput column (obs/goodput.py status source) and an
`ALERTS rule,rule` column from the process's /alertz endpoint when any
burn-rate rule is firing; `--strict-alerts` turns a firing alert into a
non-zero exit (and stops a --watch loop at the first firing snapshot) —
the scriptable "page me" form the fleet smoke uses.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fetch_json(host: str, port: int, path: str, timeout: float = 3.0):
    """GET http://host:port/path, parsed JSON — None on any failure."""
    url = f"http://{host}:{port}{path}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except (OSError, urllib.error.URLError, ValueError):
        return None


def _healthz(host: str, port: int, timeout: float = 3.0):
    """(ok, body) from /healthz — a 503 still carries the JSON verdict."""
    url = f"http://{host}:{port}/healthz"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return True, json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        try:
            return False, json.loads(e.read().decode("utf-8"))
        except (OSError, ValueError):
            return False, None
    except (OSError, urllib.error.URLError, ValueError):
        return None, None


def _unhealthy_names(body) -> str:
    if not isinstance(body, dict):
        return ""
    bad = [name for name, chk in (body.get("checks") or {}).items()
           if not chk.get("ok", False)]
    return ",".join(sorted(bad))


def format_line(rec: dict, status: dict, ok, health, alertz=None) -> str:
    """One line: role pid@host:port verdict + role-specific vitals."""
    role = str(rec.get("role", "?"))
    where = f"pid {rec.get('pid', '?')} @ {rec['host']}:{rec['port']}"
    if ok is None:
        return f"{role:<13}{where:<28} GONE"
    verdict = "OK" if ok else f"UNHEALTHY({_unhealthy_names(health)})"
    vitals = []
    for name, src in (status or {}).get("status", {}).items():
        if not isinstance(src, dict):
            continue
        if src.get("step") is not None:
            vitals.append(f"step {src['step']}")
        if src.get("epoch") is not None:
            vitals.append(f"ep {src['epoch']}")
        if src.get("examples_per_sec") is not None:
            vitals.append(f"{src['examples_per_sec']:.1f} ex/s")
        if src.get("generation") is not None:
            vitals.append(f"gen {src['generation']}")
        if src.get("served") is not None:
            vitals.append(f"served {src['served']}")
        if src.get("done") is not None:
            vitals.append(f"done {src['done']}")
        # perf status source (obs/perfwatch.py): rolling step-time
        # tail, last gate verdict, and the recompile count — the live
        # "is this process performance-healthy" vitals
        if src.get("step_time_ms_p50") is not None:
            line = f"p50 {src['step_time_ms_p50']:.1f}ms"
            if src.get("step_time_ms_p95") is not None:
                line += f"/p95 {src['step_time_ms_p95']:.1f}ms"
            vitals.append(line)
        gate = src.get("gate")
        if isinstance(gate, dict) and gate.get("verdict"):
            vitals.append(f"gate {gate['verdict']}")
        if src.get("recompiles") is not None:
            vitals.append(f"recompiles {src['recompiles']}")
        # goodput status source (obs/goodput.py): what fraction of this
        # process's wall clock went to productive work
        if src.get("goodput_frac") is not None:
            vitals.append(f"gp {float(src['goodput_frac']) * 100:.0f}%")
    # the /alertz column: which burn-rate rules are firing RIGHT NOW —
    # an empty active list renders nothing, keeping clean lines clean
    active = _active_alerts(alertz)
    if active:
        vitals.append("ALERTS " + ",".join(active))
    return f"{role:<13}{where:<28} {verdict:<10} " + "  ".join(vitals)


def _active_alerts(alertz) -> list:
    """Sorted active rule names out of a /alertz body; [] when none."""
    if not isinstance(alertz, dict):
        return []
    return sorted(str(a.get("rule", "?")) for a in
                  (alertz.get("active") or []) if isinstance(a, dict))


def poll_once(run_dir: str, timeout: float = 3.0):
    """(lines, all_ok, any_alert) for every discovery file under run_dir."""
    from deep_vision_tpu.obs.telemetry import read_discovery

    lines, all_ok, any_alert = [], True, False
    recs = read_discovery(run_dir)
    if not recs:
        return [f"no telemetry discovery files under {run_dir}"], False, False
    for rec in recs:
        host, port = rec["host"], rec["port"]
        ok, health = _healthz(host, port, timeout=timeout)
        status = fetch_json(host, port, "/statusz", timeout=timeout)
        alertz = fetch_json(host, port, "/alertz", timeout=timeout) \
            if ok is not None else None
        lines.append(format_line(rec, status, ok, health, alertz))
        if ok is not True:
            all_ok = False
        if _active_alerts(alertz):
            any_alert = True
    return lines, all_ok, any_alert


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--run-dir", required=True,
                   help="directory holding telemetry-*.json discovery files "
                        "(the run's checkpoint dir)")
    p.add_argument("--watch", type=float, default=None, metavar="SECONDS",
                   help="refresh every SECONDS instead of one snapshot")
    p.add_argument("--timeout", type=float, default=3.0,
                   help="per-endpoint HTTP timeout")
    p.add_argument("--strict-alerts", action="store_true",
                   help="exit non-zero while any burn-rate alert is "
                        "firing (/alertz active list non-empty); with "
                        "--watch the loop exits at the first firing "
                        "snapshot instead of running forever")
    args = p.parse_args(argv)

    while True:
        lines, all_ok, any_alert = poll_once(args.run_dir,
                                             timeout=args.timeout)
        for line in lines:
            print(line)
        if args.strict_alerts and any_alert:
            return 1
        if args.watch is None:
            return 0 if all_ok else 1
        print("--", flush=True)
        time.sleep(args.watch)


if __name__ == "__main__":
    raise SystemExit(main())
