"""Experiment config registry: configs are data, selected by name.

The reference keeps each paper's recipe in per-model `training_config` dicts
chosen by the `-m` CLI flag (ResNet/pytorch/train.py:26-215,
LeNet/pytorch/train.py:15-32, ResNet/tensorflow/train.py:21-62,
MobileNet/tensorflow/train.py:7-14, module constants at
YOLO/tensorflow/train.py:13-17 and CycleGAN/tensorflow/train.py:14-21).
This registry carries the union of all of them — one shared schema, every
hyperparameter value preserved (the paper-recipe comments in the reference
map to the fields here).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass
class ExperimentConfig:
    name: str
    task: str  # classification | detection | pose | centernet | dcgan | cyclegan
    model: str
    model_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    input_shape: Tuple[int, ...] = (224, 224, 3)
    num_classes: int = 1000
    batch_size: int = 128  # global batch (reference: per-replica x replicas)
    epochs: int = 90
    optimizer: Dict[str, Any] = dataclasses.field(
        default_factory=lambda: {"name": "sgd", "learning_rate": 0.01}
    )
    schedule: Optional[Dict[str, Any]] = None  # make_schedule kwargs
    plateau: Optional[Dict[str, Any]] = None  # ReduceLROnPlateau kwargs
    plateau_metric: str = "top1"
    dataset: Dict[str, Any] = dataclasses.field(
        default_factory=lambda: {"kind": "fake"}
    )
    loss_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    eval_crop: int = 224
    train_resize: int = 256

    def __post_init__(self):
        # One LR policy per recipe, as in every reference config
        # (ResNet/pytorch/train.py:26-215 picks either a torch scheduler OR
        # plateau, never both). Allowing both would be a silent no-op:
        # inject_hyperparams re-evaluates a scheduled LR every step,
        # overwriting whatever absolute value the plateau wrote between
        # epochs (train/trainer.py _set_lr).
        if self.schedule is not None and self.plateau is not None:
            raise ValueError(
                f"config '{self.name}' sets both 'schedule' and 'plateau': "
                "a scheduled learning rate is re-evaluated inside the jitted "
                "step and would silently override plateau scaling — pick one "
                "LR policy"
            )


CONFIG_REGISTRY: Dict[str, ExperimentConfig] = {}


def register_config(cfg: ExperimentConfig) -> ExperimentConfig:
    CONFIG_REGISTRY[cfg.name] = cfg
    return cfg


def get_config(name: str) -> ExperimentConfig:
    if name not in CONFIG_REGISTRY:
        raise KeyError(f"unknown config '{name}'; have {sorted(CONFIG_REGISTRY)}")
    return dataclasses.replace(CONFIG_REGISTRY[name])  # copy: callers mutate


# -- classifiers (ImageNet unless noted) ------------------------------------

register_config(ExperimentConfig(
    # LeNet/pytorch/train.py:15-32: Adam 1e-3, plateau(max, 0.1), batch 64
    name="lenet5", task="classification", model="lenet5",
    input_shape=(32, 32, 1), num_classes=10, batch_size=64, epochs=50,
    optimizer={"name": "adam", "learning_rate": 1e-3},
    plateau={"factor": 0.1, "mode": "max"},
    dataset={"kind": "mnist"},
))

for _name, _model, _bs, _wd in (
    # ResNet/pytorch/train.py:26-48 (alexnet1/2): SGD .01/.9/5e-4, plateau
    ("alexnet1", "alexnet1", 128, 5e-4),
    ("alexnet2", "alexnet2", 128, 5e-4),
):
    register_config(ExperimentConfig(
        name=_name, task="classification", model=_model,
        batch_size=_bs, epochs=90,
        optimizer={"name": "sgd", "learning_rate": 0.01, "momentum": 0.9,
                   "weight_decay": _wd},
        plateau={"factor": 0.1, "mode": "max"},
        dataset={"kind": "imagenet"},
    ))

for _name, _model, _bs in (("vgg16", "vgg16", 128), ("vgg19", "vgg19", 64)):
    # ResNet/pytorch/train.py:50-92: SGD .01/.9/5e-4, StepLR(10, 0.5)
    register_config(ExperimentConfig(
        name=_name, task="classification", model=_model,
        batch_size=_bs, epochs=90,
        optimizer={"name": "sgd", "learning_rate": 0.01, "momentum": 0.9,
                   "weight_decay": 5e-4},
        schedule={"kind": "step", "step_size_epochs": 10, "gamma": 0.5},
        dataset={"kind": "imagenet"},
    ))

register_config(ExperimentConfig(
    # ResNet/pytorch/train.py:94-140: SGD .01/.9/2e-4, poly decay sqrt
    name="inception1", task="classification", model="inception1",
    batch_size=128, epochs=90,
    optimizer={"name": "sgd", "learning_rate": 0.01, "momentum": 0.9,
               "weight_decay": 2e-4},
    schedule={"kind": "poly", "power": 0.5, "total_epochs": 60},
    dataset={"kind": "imagenet"},
    loss_kwargs={"aux_weight": 0.3},
))

register_config(ExperimentConfig(
    # finished properly here; reference stub is 6 lines (inception_v3.py)
    name="inception3", task="classification", model="inception3",
    input_shape=(299, 299, 3), batch_size=128, epochs=100,
    optimizer={"name": "rmsprop", "learning_rate": 0.045, "alpha": 0.9,
               "eps": 1.0},
    schedule={"kind": "step", "step_size_epochs": 2, "gamma": 0.94},
    dataset={"kind": "imagenet"}, train_resize=320, eval_crop=299,
))

for _name, _model, _mkw in (
    ("resnet34", "resnet34", {}),
    # flagship: space-to-depth stem (math-equal to conv7, ~3% faster on TPU;
    # models/resnet.py SpaceToDepthStem) — the config bench.py reproduces
    ("resnet50", "resnet50", {"stem": "s2d"}),
    ("resnet152", "resnet152", {}), ("resnet50v2", "resnet50v2", {}),
):
    # ResNet/pytorch/train.py:142-215: SGD .1/.9/1e-4, batch 256, plateau(max)
    register_config(ExperimentConfig(
        name=_name, task="classification", model=_model,
        model_kwargs=_mkw, batch_size=256, epochs=90,
        optimizer={"name": "sgd", "learning_rate": 0.1, "momentum": 0.9,
                   "weight_decay": 1e-4},
        plateau={"factor": 0.1, "mode": "max"},
        dataset={"kind": "imagenet"},
    ))

register_config(ExperimentConfig(
    # ResNet/pytorch/train.py:185-214: RMSprop .045/alpha .9/eps 1, StepLR(2,.94)
    name="mobilenet1", task="classification", model="mobilenet1",
    model_kwargs={"alpha": 1.0}, batch_size=128, epochs=90,
    optimizer={"name": "rmsprop", "learning_rate": 0.045, "alpha": 0.9,
               "eps": 1.0},
    schedule={"kind": "step", "step_size_epochs": 2, "gamma": 0.94},
    dataset={"kind": "imagenet"},
))

register_config(ExperimentConfig(
    # implemented for real here (reference ships a 0-byte file, SURVEY.md §2.9);
    # recipe from the ShuffleNet paper: SGD, linear decay
    name="shufflenet1", task="classification", model="shufflenet1",
    model_kwargs={"groups": 3}, batch_size=256, epochs=90,
    optimizer={"name": "sgd", "learning_rate": 0.1, "momentum": 0.9,
               "weight_decay": 4e-5},
    schedule={"kind": "poly", "power": 1.0, "total_epochs": 90},
    dataset={"kind": "imagenet"},
))

# -- detection / pose / generative ------------------------------------------

register_config(ExperimentConfig(
    # YOLO/tensorflow/train.py:13-17,46-47: Adam 1e-3, batch 16/replica,
    # 416 input, 80 classes (COCO), manual plateau on val loss :56-68
    name="yolov3_coco", task="detection", model="yolov3",
    input_shape=(416, 416, 3), num_classes=80, batch_size=16, epochs=300,
    optimizer={"name": "adam", "learning_rate": 1e-3},
    plateau={"factor": 0.3, "patience": 5, "mode": "min"},
    plateau_metric="loss",
    dataset={"kind": "records", "schema": "coco"},
))

register_config(ExperimentConfig(
    name="yolov3_voc", task="detection", model="yolov3",
    input_shape=(416, 416, 3), num_classes=20, batch_size=16, epochs=300,
    optimizer={"name": "adam", "learning_rate": 1e-3},
    plateau={"factor": 0.3, "patience": 5, "mode": "min"},
    plateau_metric="loss",
    dataset={"kind": "records", "schema": "voc"},
))

register_config(ExperimentConfig(
    # Hourglass/tensorflow/main.py:21-43 defaults: Adam, 64x64x16 heatmaps
    name="hourglass_mpii", task="pose", model="hourglass",
    model_kwargs={"num_stack": 4, "num_heatmap": 16},
    input_shape=(256, 256, 3), num_classes=16, batch_size=16, epochs=100,
    optimizer={"name": "adam", "learning_rate": 2.5e-4},
    plateau={"factor": 0.5, "patience": 5, "mode": "min"},
    plateau_metric="loss",
    dataset={"kind": "records", "schema": "mpii"},
))

register_config(ExperimentConfig(
    # ObjectsAsPoints completed (reference never finished the losses,
    # train.py:35): paper recipe Adam 1.25e-4
    name="centernet_coco", task="centernet", model="objects_as_points",
    model_kwargs={"num_stack": 2},
    input_shape=(512, 512, 3), num_classes=80, batch_size=32, epochs=140,
    optimizer={"name": "adam", "learning_rate": 1.25e-4},
    schedule={"kind": "step", "step_size_epochs": 90, "gamma": 0.1},
    dataset={"kind": "records", "schema": "coco"},
))

register_config(ExperimentConfig(
    # DCGAN/tensorflow/main.py:13-17,42-53: Adam 1e-4, batch 256, MNIST
    name="dcgan_mnist", task="dcgan", model="dcgan",
    input_shape=(28, 28, 1), batch_size=256, epochs=50,
    optimizer={"name": "adam", "learning_rate": 1e-4},
    dataset={"kind": "mnist"},
))

register_config(ExperimentConfig(
    # CycleGAN/tensorflow/train.py:14-21,126-131: Adam 2e-4 beta1 .5,
    # batch 1, 200 epochs, linear decay after 100
    name="cyclegan", task="cyclegan", model="cyclegan",
    input_shape=(256, 256, 3), batch_size=1, epochs=200,
    optimizer={"name": "adam", "learning_rate": 2e-4, "b1": 0.5},
    schedule={"kind": "linear_decay", "hold_epochs": 100, "total_epochs": 200},
    dataset={"kind": "records", "schema": "image_only"},
))

# -- attention family (net-new; no reference counterpart) -------------------

for _name, _model, _mkw in (
    ("vit_s16", "vit_s16", {}),
    ("vmoe_s16", "vmoe_s16", {}),
):
    # AdamW recipe (ViT paper, app. B.1 scaled to single-host): decoupled
    # weight decay, linear warmup + cosine decay via the schedule registry
    register_config(ExperimentConfig(
        name=_name, task="classification", model=_model,
        model_kwargs=_mkw, batch_size=256, epochs=90,
        optimizer={"name": "adamw", "learning_rate": 1e-3,
                   "weight_decay": 1e-4},
        schedule={"kind": "cosine", "warmup_epochs": 5,
                  "total_epochs": 90},
        dataset={"kind": "imagenet"},
    ))
