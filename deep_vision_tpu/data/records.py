"""TFRecord-compatible record container IO (no TensorFlow dependency).

The shard files every reference converter writes
(`Datasets/VOC2007/tfrecords.py:110-121`, `Datasets/MSCOCO/tfrecords.py`,
`build_imagenet_tfrecord.py`) use the TFRecord framing:

    uint64 length | uint32 masked_crc32c(length) | data | uint32 masked_crc32c(data)

crc32c comes from `google_crc32c` (C extension) so the Python reader sustains
record throughput; a C++ reader (`native/`) is the fast path for training.
"""
from __future__ import annotations

import glob as _glob
import os
import random
import struct
from typing import Iterable, Iterator, List, Optional, Sequence, Union

import google_crc32c

_MASK_DELTA = 0xA282EAD8


def _masked_crc(data: bytes) -> int:
    crc = google_crc32c.value(data)
    return ((crc >> 15 | crc << 17) + _MASK_DELTA) & 0xFFFFFFFF


class RecordWriter:
    """Append-only TFRecord-framing writer."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "wb")

    def write(self, record: bytes) -> None:
        header = struct.pack("<Q", len(record))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(record)
        self._f.write(struct.pack("<I", _masked_crc(record)))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path: str, records: Iterable[bytes]) -> int:
    n = 0
    with RecordWriter(path) as w:
        for r in records:
            w.write(r)
            n += 1
    return n


def read_records(path: str, verify: bool = True) -> Iterator[bytes]:
    """Yield raw record payloads from one file."""
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return
            if len(header) < 8:
                raise EOFError(f"truncated record header in {path}")
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", f.read(4))
            if verify and _masked_crc(header) != hcrc:
                raise IOError(f"corrupt record header in {path}")
            data = f.read(length)
            if len(data) < length:
                raise EOFError(f"truncated record in {path}")
            (dcrc,) = struct.unpack("<I", f.read(4))
            if verify and _masked_crc(data) != dcrc:
                raise IOError(f"corrupt record in {path}")
            yield data


def best_reader():
    """The fastest available single-file record reader: the native C++ one
    (native/libdvtpu.so, GIL-free IO+CRC) when built, else `read_records`.
    Both have identical iteration order and exception behavior."""
    try:
        from deep_vision_tpu.data.native import (
            native_available,
            read_records_native,
        )

        if native_available():
            return read_records_native
    except Exception:
        pass
    return read_records


def expand_shards(pattern: Union[str, Sequence[str]]) -> List[str]:
    """Glob pattern(s) -> sorted shard list (list_files analog, deterministic)."""
    patterns = [pattern] if isinstance(pattern, str) else list(pattern)
    files: List[str] = []
    for p in patterns:
        matched = sorted(_glob.glob(p)) if any(c in p for c in "*?[") else [p]
        files.extend(matched)
    if not files:
        raise FileNotFoundError(f"no record shards match {pattern!r}")
    return files


def record_iterator(
    pattern: Union[str, Sequence[str]],
    *,
    shuffle_shards: bool = False,
    seed: Optional[int] = None,
    shard_index: int = 0,
    num_shards: int = 1,
) -> Iterator[bytes]:
    """Iterate records across shards.

    `shard_index/num_shards` split the *file list* across hosts — the
    host-sharded input feed for multi-host training (each host reads only its
    shard subset, the pjit analog of `experimental_distribute_dataset` at
    YOLO/tensorflow/train.py:291-294).
    """
    files = expand_shards(pattern)
    files = files[shard_index::num_shards]
    if shuffle_shards:
        random.Random(seed).shuffle(files)
    reader = best_reader()
    for path in files:
        yield from reader(path)
