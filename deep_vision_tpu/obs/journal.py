"""Run journal: append-only JSONL of typed run events.

One file per run, one JSON object per line, `event` + `ts` on every line.
Event types (full schema in obs/README.md):

  run_manifest  config, argv, mesh, device/process topology, jax version
  step          per-step timing/metrics (step_time_ms, data_wait_ms, ...)
  epoch         MetricLogger epoch summaries
  eval          eval-pass summaries
  checkpoint    checkpoint saves/restores
  health        health monitor findings (obs/health.py: non_finite,
                loss_spike, divergence, hang with thread stacks)
  profile       profiler trace start/stop
  bench         one benchmark measurement (tools/bench_*.py)
  retry         one retried/abandoned I/O attempt (resilience/retry.py)
  fault         an injected fault fired (resilience/faults.py)
  data_skip     a bad record skipped under the bad-record budget
  ckpt_quarantine  a corrupt/incomplete checkpoint step quarantined
  lock_order_violation  runtime lock-order inversion (obs/locksmith.py)
  lock_contention  a lock hold/wait over the locksmith threshold
  note          free-form annotation
  crash         atexit marker: the process died without close()
  exit          clean close, with status

The writer appends with a flush per line (a crash loses at most the
in-flight line) and registers an atexit hook that stamps a `crash`
event — so a reader can always tell a finished run (`exit`) from a dead
one (`crash`, or no terminal event at all for SIGKILL). Single-process
runs write the plain path; multi-process runs write one file PER HOST at
`<path>.p<process_index>` (obs.registry.process_suffix) so host 7's last
seconds survive host 7 — `tools/obs_merge.py` stitches them back into
one timeline. Readers: `read_journal`, tools/obs_report.py.

Taps (`add_tap`) observe every event row after it is written — the
flight recorder (obs/flight.py) rides one to keep its postmortem ring
buffers current without a second instrumentation surface. A tap must be
cheap and must never raise into the run it observes (exceptions are
swallowed).
"""
from __future__ import annotations

import atexit
import json
import os
import platform
import sys
import threading
import time
from typing import Callable, List, Optional

from deep_vision_tpu.obs import locksmith, propagate
from deep_vision_tpu.obs.registry import is_primary_host, process_suffix


def _jsonable(v):
    """Best-effort conversion for numpy/jax scalars and containers."""
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (str, int, bool)) or v is None:
        return v
    if isinstance(v, float):
        return v if v == v and abs(v) != float("inf") else repr(v)
    try:
        return float(v)  # numpy/jax 0-d arrays and scalars
    except (TypeError, ValueError):
        return repr(v)


class RunJournal:
    """Append-only JSONL journal for one run (or one bench session)."""

    def __init__(self, path: str, run_id: Optional[str] = None,
                 kind: str = "train", per_process: bool = True,
                 writer: Optional[bool] = None):
        # multi-process runs: every host owns a suffixed file (`.pN`) so a
        # follower's telemetry outlives the follower; per_process=False
        # keeps the legacy process-0-only single shared path. writer=True
        # forces THIS process to write regardless of rank: elastic runs
        # name per-host files themselves (journal_<host>.jsonl) because a
        # rank-derived suffix would change across generations and strand
        # the pre-resize history in a terminal-less file
        sfx = process_suffix() if per_process else ""
        self.path = path + sfx
        self.kind = kind
        self.run_id = run_id or f"{kind}-{os.getpid()}-{int(time.time())}"
        self._closed = False
        self._closers: List[Callable[[], None]] = []
        self._taps: List[Callable[[dict], None]] = []
        self._primary = (bool(writer) if writer is not None
                         else (is_primary_host() or bool(sfx)))
        # writes come from the train loop AND side threads (the health
        # watchdog, data prefetch errors): one lock keeps lines whole.
        # locksmith-named: the runtime sanitizer checks nothing ever holds
        # this while taking a lock that can be held around a write()
        self._lock = locksmith.lock("obs.journal")
        self._manifest_row: Optional[dict] = None  # statusz identity card
        self._f = None
        self.dropped_lines = 0  # lines lost to journal I/O errors
        if self._primary:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._f = open(self.path, "a")
        # the crash marker: fires only if close() never ran
        atexit.register(self._atexit)

    # -- lifecycle ---------------------------------------------------------

    def add_tap(self, fn: Callable[[dict], None]) -> None:
        """Register an observer called with every event row after it is
        written (flight recorder, tests). Taps run outside the file lock
        and may themselves call write() (e.g. a flight dump journaling its
        own `flight_dump` event); a raising tap is swallowed — telemetry
        observers must never kill the run they observe."""
        self._taps.append(fn)

    def add_closer(self, fn: Callable[[], None]) -> None:
        """Register cleanup run by close() (and by the atexit crash path):
        e.g. Trainer.close so an unwinding run still stops an in-flight
        profiler trace and flushes writers."""
        self._closers.append(fn)

    def _run_closers(self) -> None:
        closers, self._closers = self._closers, []
        for fn in closers:
            try:
                fn()
            except Exception as e:  # a failing closer must not mask the rest
                self.write("note", note=f"closer {fn!r} failed: {e!r}")

    def _atexit(self) -> None:
        if self._closed:
            return
        self._run_closers()
        self.write("crash", reason="process exited without journal.close()")
        self._closed = True
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def close(self, status: str = "clean_exit") -> None:
        if self._closed:
            return
        self._run_closers()
        self.write("exit", status=status)
        self._closed = True
        atexit.unregister(self._atexit)
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close("clean_exit" if exc_type is None
                   else f"exception: {exc_type.__name__}")

    # -- writers -----------------------------------------------------------

    def write(self, event: str, **fields) -> None:
        row = {"event": event, "ts": round(time.time(), 3),
               "run_id": self.run_id}
        # cross-process causality: a write made while a trace context is
        # installed on THIS thread (obs/propagate.py) carries the request's
        # ids — explicit trace fields passed by the caller win (the serve
        # dispatcher stamps a request's context from another thread)
        ctx = propagate.current()
        if ctx is not None and "trace_id" not in fields:
            row.update(ctx.fields())
        row.update({k: _jsonable(v) for k, v in fields.items()})
        # the fault hook sits OUTSIDE the lock: an injected fault that
        # journals its own `fault` event re-enters write(), and the lock is
        # not reentrant (the injector skips journaling for this one point,
        # but the ordering keeps the invariant structural, not behavioral)
        try:
            from deep_vision_tpu.resilience import faults

            faults.fire("journal.flush")
            with self._lock:
                if self._f is not None:
                    self._f.write(json.dumps(row) + "\n")
                    self._f.flush()
        except OSError as e:
            # telemetry must degrade, never kill the training it observes:
            # a failed journal write drops the line, counts it, and the
            # first drop is loud on stderr
            self.dropped_lines += 1
            if self.dropped_lines == 1:
                print(f"journal: WRITE FAILED ({type(e).__name__}: {e}); "
                      "dropping lines (journal_dropped_lines_total counts "
                      "them)", file=sys.stderr)
            try:
                from deep_vision_tpu.obs.registry import get_registry

                get_registry().counter(
                    "journal_dropped_lines_total",
                    "journal lines lost to I/O errors").inc()
            except Exception:
                pass
        # taps observe the row even when the file write failed or this host
        # is a non-writer: the flight recorder's postmortem buffers must
        # stay current precisely when the journal volume is the thing dying
        for tap in self._taps:
            try:
                tap(row)
            except Exception:
                pass

    def manifest(self, config: Optional[dict] = None, **extra) -> None:
        """The run's identity card: everything needed to interpret (or
        machine-diff) the numbers that follow."""
        info = {
            "kind": self.kind,
            "argv": list(sys.argv),
            "python": platform.python_version(),
            "hostname": platform.node(),
            "pid": os.getpid(),
        }
        try:
            import jax

            info.update(
                jax_version=jax.__version__,
                backend=jax.default_backend(),
                device_kind=jax.devices()[0].device_kind,
                device_count=jax.device_count(),
                local_device_count=jax.local_device_count(),
                process_index=jax.process_index(),
                process_count=jax.process_count(),
            )
        except Exception as e:
            info["jax"] = f"unavailable: {e!r}"
        if config is not None:
            info["config"] = config
        info.update(extra)
        self._manifest_row = {k: _jsonable(v) for k, v in info.items()}
        self.write("run_manifest", **info)

    def manifest_row(self) -> Optional[dict]:
        """The captured manifest (None before manifest() runs) — the
        telemetry /statusz page serves it without re-reading the file."""
        return self._manifest_row

    def step(self, step: int, **fields) -> None:
        self.write("step", step=int(step), **fields)

    def bench(self, name: str, result: dict, **extra) -> None:
        self.write("bench", name=name, result=result, **extra)


def read_journal(path: str) -> List[dict]:
    """Parse a journal JSONL; tolerates a torn final line (crash mid-write)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                events.append({"event": "_torn_line", "raw": line[:200]})
    return events
