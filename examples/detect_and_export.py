"""Detection inference + model export (the demo_mscoco.ipynb analog).

The reference's YOLO demo notebook (YOLO/tensorflow/demo_mscoco.ipynb) runs
image -> model -> decode -> NMS -> boxes; its CycleGAN converter
(CycleGAN/tensorflow/convert.py) exports to TFLite. Both flows here, against
the library API: the jitted YoloPredictor, then StableHLO export with a
numeric round-trip check.

    python examples/detect_and_export.py [--out /tmp/yolo.stablehlo]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor a JAX_PLATFORMS override even when a site hook imported jax before
# the env var could take effect at backend init (e.g. JAX_PLATFORMS=cpu to
# run this example without an accelerator)
import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


import argparse

import jax
import jax.numpy as jnp
import numpy as np

from deep_vision_tpu.inference import make_yolo_detector
from deep_vision_tpu.models import get_model
from deep_vision_tpu.tools.export import export_model, load_exported


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="/tmp/yolov3.stablehlo")
    p.add_argument("--image-size", type=int, default=128)
    args = p.parse_args()

    model = get_model("yolov3", num_classes=4)
    img = np.random.RandomState(0).rand(
        1, args.image_size, args.image_size, 3).astype(np.float32)
    x = jnp.asarray(img)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)

    # image batch -> decoded, class-aware-NMS'd boxes, all jitted. The
    # detector donates its image argument (inference.py), and the export
    # round-trip below still needs x — hand the detector its own copy
    detect = make_yolo_detector(model, score_threshold=0.1)
    det = detect(variables, jnp.asarray(img))
    n = int(det["num"][0])
    print(f"detections: {n} boxes "
          f"(scores {np.asarray(det['scores'][0, :max(n, 1)]).round(3)})")

    # portable StableHLO artifact + numeric round-trip
    exported = export_model(model, variables, x)
    with open(args.out, "wb") as f:
        f.write(exported.serialize())
    restored = load_exported(args.out)
    ref = model.apply(variables, x, train=False)
    got = restored.call(x)
    err = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(got, ref))
    print(f"export round-trip: {args.out}  max err {err:.2e}")


if __name__ == "__main__":
    main()
