"""Cache smoke: the cold path must not pay the compiler twice.

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/cache_smoke.py \
        [--workdir artifacts/cache_smoke]

The CI teeth behind core/excache.py + serve/quantize.py (`make
cache-smoke`, a `make verify` prerequisite). Three REAL child processes
share one executable-cache directory — fresh processes, because an
in-process "second warmup" would ride jax's jit cache and prove
nothing:

  A. populate     a cold-cache Engine.warmup() compiles every
                  (model, bucket) pair and STORES each one: the child's
                  warmup stats show backend_compiles == pairs, and its
                  journal carries one `excache_store` (and one
                  `excache_miss`) per pair.
  B. zero-compile a FRESH process over the populated cache warms with
                  ZERO backend compiles: recompile-counter delta == 0,
                  every pair an `excache_hit`, bit-identical outputs
                  (the child re-runs a seeded probe batch and prints the
                  output hash; A and B must match).
  C. skew         the parent rewrites ONE entry's manifest fingerprint
                  to a different jax version: the child journals exactly
                  one typed `excache_invalid{reason: version_skew}`,
                  recompiles exactly that pair (backend_compiles == 1),
                  cache-hits the rest, and re-stores the refreshed entry
                  — a stale executable is never loaded.
  D. int8         serve/quantize.py end-to-end in the parent: clean
                  weights calibrate, pass the accuracy-delta gate
                  (typed `quant_calibrated accepted=true`), and the int8
                  engine serves the same seeded traffic as the f32 one
                  with the SLO report printed BEFORE and AFTER; then a
                  POISONED case — weights with a cancelling-outlier
                  channel, calibrated on the constant-image stream that
                  exposes it — must be REFUSED (`accepted=false` +
                  QuantizationRejected), because an int8 engine outside
                  its gate must never serve.
  E. artifacts    all journals pass `check_journal --strict` (excache_*
                  + quant_calibrated schemas), obs_report renders the
                  cold-path section, locksmith reports zero violations
                  in every process.

Exit status 0 = every contract held; 1 = something broke.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
from typing import List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from tools.smoke_util import read_jsonl  # noqa: E402

IMG = (8, 8, 1)
BUCKETS = (1, 2, 4)
#: unique computations per child = len(MODELS) * len(BUCKETS)
PAIRS = 2 * len(BUCKETS)


class Failures:
    def __init__(self):
        self.errors: List[str] = []

    def check(self, ok: bool, what: str) -> bool:
        print(("  ok  " if ok else "  FAIL") + f"  {what}")
        if not ok:
            self.errors.append(what)
        return ok


def build_models():
    """Two deterministic toy models (a dense scorer and a small conv
    net): identical weights in every child process, so runs A/B/C lower
    to identical stablehlo and the cache keys line up."""
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.RandomState(42)
    dense_w = {"w": (rng.randn(int(np.prod(IMG)), 8) * 0.1)
               .astype(np.float32)}
    conv_vars = {
        "conv": {"kernel": (rng.randn(3, 3, 1, 8) * 0.2).astype(np.float32)},
        "dense": {"kernel": (rng.randn(8, 4) * 0.3).astype(np.float32)},
    }

    def dense_fn(variables, images):
        flat = images.reshape(images.shape[0], -1)
        return {"scores": jnp.tanh(flat @ variables["w"])}

    def conv_fn(variables, images):
        import jax

        y = jax.lax.conv_general_dilated(
            images, variables["conv"]["kernel"], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = jnp.maximum(y, 0.0).mean(axis=(1, 2))
        return {"scores": y @ variables["dense"]["kernel"]}

    return {"dense": (dense_fn, dense_w), "conv": (conv_fn, conv_vars)}


# -- child: one warmup over the shared cache dir ------------------------------

def child_main(argv: List[str]) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--cache", required=True)
    p.add_argument("--journal", required=True)
    args = p.parse_args(argv)
    import hashlib

    import numpy as np

    from deep_vision_tpu.core.excache import ExecutableCache
    from deep_vision_tpu.obs import RunJournal, locksmith
    from deep_vision_tpu.serve import Engine

    journal = RunJournal(args.journal, kind="serve")
    journal.manifest(config={"name": "cache_smoke_child", "task": "serving"})
    locksmith.arm(journal=journal)
    excache = ExecutableCache(args.cache, journal=journal)
    engine = Engine(journal=journal, excache=excache)
    for name, (fn, variables) in build_models().items():
        engine.register(name, fn, variables, IMG, buckets=BUCKETS)
    stats = engine.warmup()
    # seeded probe batch through every model: the parent compares the
    # output hash across runs — a cached executable must be
    # bit-identical to a freshly compiled one
    probe = np.random.RandomState(7).rand(2, *IMG).astype(np.float32)
    h = hashlib.sha256()
    for name in sorted(engine.models):
        h.update(np.asarray(engine.run(name, probe)["scores"]).tobytes())
    lock_report = locksmith.report()
    locksmith.disarm()
    journal.close()
    print(json.dumps({
        "pairs": stats["pairs"],
        "backend_compiles": stats["backend_compiles"],
        "cache_hits": stats["cache_hits"],
        "output_sha": h.hexdigest(),
        "lock_violations": len(lock_report["violations"]),
    }), flush=True)
    return 0


# -- parent --------------------------------------------------------------------

def run_child(work: str, cache_dir: str, tag: str) -> Optional[dict]:
    j_path = os.path.join(work, f"journal_{tag}.jsonl")
    env = dict(os.environ, PYTHONPATH=ROOT, JAX_PLATFORMS="cpu")
    env.pop("DVT_FAULT_SPEC", None)
    env.pop("DVT_FAULT_SEED", None)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--cache", cache_dir, "--journal", j_path],
        cwd=ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, timeout=600)
    if proc.returncode != 0:
        print(f"  child {tag} FAILED rc={proc.returncode}\n{proc.stderr[-2000:]}")
        return None
    line = proc.stdout.strip().splitlines()[-1]
    return json.loads(line)


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--child":
        return child_main(argv[1:])

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default="artifacts/cache_smoke")
    args = p.parse_args(argv)

    work = os.path.abspath(args.workdir)
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work)
    cache_dir = os.path.join(work, "excache")
    f = Failures()

    # -- phase A: cold cache populates ----------------------------------
    print(f"phase A: cold-cache warmup compiles + stores every pair")
    a = run_child(work, cache_dir, "a")
    if a is None:
        return 1
    f.check(a["pairs"] == PAIRS and a["backend_compiles"] == PAIRS
            and a["cache_hits"] == 0,
            f"run A compiled all {PAIRS} pairs "
            f"({a['backend_compiles']} compiles, {a['cache_hits']} hits)")
    ev_a = read_jsonl(os.path.join(work, "journal_a.jsonl"))
    stores = [e for e in ev_a if e.get("event") == "excache_store"]
    misses = [e for e in ev_a if e.get("event") == "excache_miss"]
    f.check(len(stores) == PAIRS and len(misses) == PAIRS,
            f"journal A: one excache_store + one excache_miss per pair "
            f"({len(stores)} stores, {len(misses)} misses)")
    f.check(a["lock_violations"] == 0, "run A: locksmith clean")

    # -- phase B: fresh process, zero compiles --------------------------
    print("phase B: FRESH process over the populated cache: zero "
          "backend compiles")
    b = run_child(work, cache_dir, "b")
    if b is None:
        return 1
    f.check(b["backend_compiles"] == 0,
            f"recompile-counter delta == 0 across warmup "
            f"({b['backend_compiles']})")
    f.check(b["cache_hits"] == PAIRS,
            f"every pair loaded from cache ({b['cache_hits']}/{PAIRS})")
    ev_b = read_jsonl(os.path.join(work, "journal_b.jsonl"))
    hits = [e for e in ev_b if e.get("event") == "excache_hit"]
    f.check(len(hits) == PAIRS and not any(
        e.get("event") in ("excache_store", "excache_miss",
                           "excache_invalid") for e in ev_b),
            f"journal B: all excache_hit, nothing stored or refused "
            f"({len(hits)} hits)")
    f.check(a["output_sha"] == b["output_sha"],
            "cached executables compute BIT-IDENTICAL outputs "
            f"({b['output_sha'][:16]}...)")
    f.check(b["lock_violations"] == 0, "run B: locksmith clean")

    # -- phase C: version-skewed entry refused + recompiled -------------
    print("phase C: a version-skewed entry journals excache_invalid and "
          "falls through to the compiler")
    manifests = sorted(fn for fn in os.listdir(cache_dir)
                       if fn.endswith(".json"))
    f.check(len(manifests) == PAIRS, f"cache holds {PAIRS} manifests")
    victim = os.path.join(cache_dir, manifests[0])
    doc = json.load(open(victim))
    doc["fingerprint"]["jax"] = "0.0.0-cache-smoke-skew"
    with open(victim, "w") as fh:
        fh.write(json.dumps(doc))
    c = run_child(work, cache_dir, "c")
    if c is None:
        return 1
    f.check(c["backend_compiles"] == 1 and c["cache_hits"] == PAIRS - 1,
            f"exactly the skewed pair recompiled "
            f"({c['backend_compiles']} compiles, {c['cache_hits']} hits)")
    ev_c = read_jsonl(os.path.join(work, "journal_c.jsonl"))
    invalid = [e for e in ev_c if e.get("event") == "excache_invalid"]
    f.check(len(invalid) == 1
            and invalid[0].get("reason") == "version_skew",
            f"typed excache_invalid{{version_skew}} journaled ({invalid})")
    f.check(sum(1 for e in ev_c if e.get("event") == "excache_store") == 1,
            "the refreshed entry was re-stored for the next cold start")
    f.check(a["output_sha"] == c["output_sha"],
            "outputs still bit-identical after the skew fall-through")

    # -- phase D: int8 calibrate -> gate -> serve, and the refusal ------
    print("phase D: int8 gate accepts clean weights (SLO before/after) "
          "and refuses poisoned ones")
    import numpy as np

    from deep_vision_tpu.obs import RunJournal, locksmith
    from deep_vision_tpu.obs.registry import Registry
    from deep_vision_tpu.serve import Engine, Server
    from deep_vision_tpu.serve.quantize import (
        QuantizationRejected,
        calibrate_and_quantize,
    )

    j_path = os.path.join(work, "journal_int8.jsonl")
    journal = RunJournal(j_path, kind="serve")
    journal.manifest(config={"name": "cache_smoke_int8", "task": "serving"})
    locksmith.arm(journal=journal)
    models = build_models()
    dense_fn, dense_w = models["dense"]
    rng = np.random.RandomState(5)
    calib = [rng.rand(4, *IMG).astype(np.float32) for _ in range(4)]
    qm = calibrate_and_quantize("dense", dense_fn, dense_w, calib,
                                tolerance=0.02, journal=journal)
    f.check(qm.delta <= 0.02,
            f"clean weights pass the gate ({qm.metric} delta "
            f"{qm.delta:.2g}, {qm.report['compression']}x compression)")

    def serve_traffic(engine_name, fn, variables) -> "Server":
        registry = Registry()
        eng = Engine(journal=journal, registry=registry)
        eng.register("dense", fn, variables, IMG, buckets=BUCKETS)
        eng.warmup()
        server = Server(eng, journal=journal, registry=registry,
                        max_wait_ms=5.0, tags={"engine": engine_name})
        server.start()
        t_rng = np.random.RandomState(11)  # same seeded traffic for both
        for _ in range(16):
            out = server.submit(
                "dense", t_rng.rand(*IMG).astype(np.float32)
            ).result(timeout=120)
            assert out["scores"].shape == (8,), out["scores"].shape
        server.close()
        return server

    f32_server = serve_traffic("f32", dense_fn, dense_w)
    int8_server = serve_traffic("int8", qm.fn, qm.variables)
    print("  SLO before (f32):")
    print("    " + f32_server.slo.render().replace("\n", "\n    "))
    print("  SLO after (int8):")
    print("    " + int8_server.slo.render().replace("\n", "\n    "))
    f.check(f32_server.counts()["completed"] == 16
            and int8_server.counts()["completed"] == 16,
            "both engines served the full seeded traffic")

    # the poisoned case: a cancelling-outlier channel that only the
    # constant-image calibration stream exposes — quantization zeroes
    # the small weights carrying the real signal, the gate must fire
    poisoned_w = {"w": dense_w["w"].copy()}
    poisoned_w["w"][0, :], poisoned_w["w"][1, :] = 500.0, -500.0
    poison_calib = [np.full((4, *IMG), v, np.float32)
                    for v in (0.2, 0.5, 0.8, 0.3)]
    refused = False
    try:
        calibrate_and_quantize("dense", dense_fn, poisoned_w, poison_calib,
                               tolerance=0.005, journal=journal)
    except QuantizationRejected:
        refused = True
    f.check(refused, "poisoned weights REFUSED by the accuracy-delta gate")
    lock_report = locksmith.report()
    locksmith.disarm()
    journal.close()
    f.check(not lock_report["violations"], "int8 phase: locksmith clean")
    ev_q = read_jsonl(j_path)
    quants = [e for e in ev_q if e.get("event") == "quant_calibrated"]
    f.check(len(quants) == 2 and quants[0].get("accepted") is True
            and quants[1].get("accepted") is False,
            f"both calibration verdicts journaled (accepted="
            f"{[e.get('accepted') for e in quants]})")

    # -- phase E: artifacts validate ------------------------------------
    print("phase E: strict journals + cold-path report section")
    env = dict(os.environ, PYTHONPATH=ROOT)
    all_journals = [os.path.join(work, f"journal_{t}.jsonl")
                    for t in ("a", "b", "c", "int8")]
    rc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "check_journal.py")]
        + all_journals + ["--strict"],
        cwd=ROOT, env=env).returncode
    f.check(rc == 0, "check_journal --strict accepts all four journals "
                     "(excache_* + quant_calibrated schemas)")
    rep = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "obs_report.py"),
         os.path.join(work, "journal_c.jsonl")],
        cwd=ROOT, env=env, stdout=subprocess.PIPE, text=True)
    f.check(rep.returncode == 0 and "executable cache" in rep.stdout
            and "version_skew" in rep.stdout,
            "obs_report renders the executable-cache row with the "
            "refusal reason")
    rep2 = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "obs_report.py"),
         j_path],
        cwd=ROOT, env=env, stdout=subprocess.PIPE, text=True)
    f.check(rep2.returncode == 0 and "int8 dense" in rep2.stdout
            and "REFUSED" in rep2.stdout,
            "obs_report renders both int8 calibration verdicts")

    if f.errors:
        print(f"\ncache-smoke: {len(f.errors)} contract(s) BROKEN "
              f"(artifacts in {work})")
        return 1
    print(f"\ncache-smoke: the cold path never pays the compiler twice "
          f"(artifacts in {work})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
