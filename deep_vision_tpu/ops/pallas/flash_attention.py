"""Fused blockwise (flash) attention as a Pallas TPU kernel.

Why a kernel: naive attention materializes the (T, T) score matrix in HBM —
at T=16k that is 1GB per head in fp32, and the op is HBM-bandwidth-bound.
The fused kernel streams K/V blocks through VMEM, keeps the online-softmax
running (max, sumexp, accumulator) state in VMEM scratch across grid steps,
and never writes scores to HBM: O(T) memory, MXU-bound.

This is the single-chip sibling of `parallel/ring_attention.py` (same online
softmax); ring attention distributes the sequence across chips, this kernel
fuses the per-chip block loop. The reference framework has no attention op
anywhere (SURVEY.md §5) — this is net-new capability for long-context
workloads.

Backward pass: `jax.custom_vjp` with dense recompute (exact, O(T^2) memory
in the bwd only). Long-sequence *training* should shard with ring attention;
the fused kernel targets inference and fwd-dominant paths.

Grid layout: (batch*heads, q_blocks, k_blocks); TPU executes the grid
sequentially (last dim fastest), so VMEM scratch carries the accumulator
across the k dimension — init at k==0, finalize into the output block at
the last visible k block.

Measured on one v5e chip (B4 T4096 H8 D64, causal, fp32 io): 7.7 ms vs
14.1 ms for XLA's fused dense attention — 1.8x; defaults (block_q=512,
block_k=1024) come from that sweep.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  scale: float, causal: bool, block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # with causality, blocks strictly above the diagonal contribute nothing
    visible = jnp.logical_or(
        jnp.logical_not(causal), ki * block_k <= qi * block_q + block_q - 1
    )

    @pl.when(visible)
    def _attend():
        q = q_ref[0].astype(jnp.float32)  # (bq, d)
        k = k_ref[0].astype(jnp.float32)  # (bk, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (bq, bk)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, NEG_INF)

        m_prev = m_scr[:, :1]  # (bq, 1)
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # (bq, bk); rows w/o keys: exp(NEG_INF)≈0
        alpha = jnp.exp(m_prev - m_new)  # (bq, 1)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    # finalize on the last k step (beyond-diagonal steps were masked no-ops)
    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        o_ref[0] = (acc_scr[:] / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool):
    b, t, h, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, t)
    block_k = min(block_k, tk)
    assert t % block_q == 0 and tk % block_k == 0, (
        f"seq lens ({t}, {tk}) must divide blocks ({block_q}, {block_k})"
    )
    # (B, T, H, D) -> (B*H, T, D): each grid row owns one (batch, head) pair
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        grid=(b * h, t // block_q, tk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sumexp
            pltpu.VMEM((block_q, d), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _dense_reference(q, k, v, causal, scale):
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t, s_ = s.shape[-2], s.shape[-1]
        mask = jnp.arange(t)[:, None] >= jnp.arange(s_)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _dense_reference(q, k, v, causal, scale),
                     q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q, k, v, *, causal: bool = False, scale: Optional[float] = None,
    block_q: int = 512, block_k: int = 1024,
    interpret: Optional[bool] = None,
):
    """Fused attention. q: (B, Tq, H, D); k, v: (B, Tk, H, D).

    `interpret=None` auto-selects: compiled on TPU, interpreter elsewhere
    (the CPU test path; `conftest.py` meshes run it interpreted).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, causal, float(scale), int(block_q), int(block_k),
                  bool(interpret))
