"""Metrics: in-jit scalar computation + host-side series logging.

The reference logged (a) dict-of-lists persisted inside checkpoints
(ResNet/pytorch/train.py:260-285), (b) TensorBoard scalars at batch/epoch
cadence (YOLO/tensorflow/train.py:159-179), (c) stdout lines with timestamps,
and (d) examples/sec per epoch (YOLO/tensorflow/train.py:217-223) — its only
perf instrumentation.

Here: metric values are computed inside the jitted step (scalar means over the
global batch; under pjit a batch mean is already a global mean, replacing
`strategy.reduce(SUM)` at YOLO/tensorflow/train.py:134-151), and a MetricLogger
accumulates host-side series + writes TensorBoard events + prints stdout lines
with ISO timestamps, plus a built-in step timer / examples-per-sec meter.
"""
from __future__ import annotations

import collections
import datetime
import time
from typing import Dict, Optional

import jax.numpy as jnp


def topk_accuracy(logits, labels, ks=(1, 5), weights=None):
    """Top-k accuracy fractions. Mirrors accuracy() at ResNet/pytorch/train.py:524-538.

    labels: int class ids (B,). `weights` (B,) masks out padded rows (the
    final partial batch). Returns dict {f'top{k}': scalar}.
    """
    maxk = max(ks)
    # top-k prediction ids: (B, maxk)
    topk = jnp.argsort(-logits, axis=-1)[:, :maxk]
    correct = topk == labels[:, None]
    if weights is None:
        weights = jnp.ones(labels.shape, logits.dtype)
    denom = jnp.maximum(jnp.sum(weights), 1e-9)
    return {
        f"top{k}": jnp.sum(jnp.any(correct[:, :k], axis=-1) * weights) / denom
        for k in ks
    }


class _Meter:
    def __init__(self):
        self.total = 0.0
        self.count = 0

    def update(self, v, n=1):
        self.total += float(v) * n
        self.count += n

    @property
    def avg(self):
        return self.total / max(self.count, 1)


def _metric_slug(name: str) -> str:
    """Prometheus-safe metric name ('mAP@.5' -> 'mAP__5')."""
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


class MetricLogger:
    """Host-side metric series, stdout logging and examples/sec meter.

    With `registry`/`journal` (obs/ subsystem), every step's metrics also
    land as gauges and every epoch summary as a journal `epoch` event —
    one log call fans out to stdout, TensorBoard, Prometheus, and JSONL.
    """

    def __init__(self, tb_writer=None, print_every: int = 10, name: str = "train",
                 registry=None, journal=None):
        self.history: Dict[str, list] = collections.defaultdict(list)
        self.tb = tb_writer
        self.print_every = print_every
        self.name = name
        self.registry = registry
        self.journal = journal
        self._epoch_meters: Dict[str, _Meter] = {}
        self._epoch_start = time.time()
        self._epoch_examples = 0
        self._last_step_time: Optional[float] = None

    # -- epoch lifecycle ---------------------------------------------------
    def start_epoch(self):
        self._epoch_meters = collections.defaultdict(_Meter)
        self._epoch_start = time.time()
        self._epoch_examples = 0
        self._last_step_time = None

    def log_step(self, step: int, metrics: dict, batch_size: int = 0,
                 epoch: Optional[int] = None, lr: Optional[float] = None,
                 data_wait_ms: Optional[float] = None,
                 examples_per_sec: Optional[float] = None):
        metrics = {k: float(v) for k, v in metrics.items()}
        for k, v in metrics.items():
            self._epoch_meters[k].update(v, max(batch_size, 1))
        self._epoch_examples += batch_size
        # instantaneous rate when the caller has no StepClock: wall time
        # since the previous log_step closes the reference's only perf
        # metric (YOLO/tensorflow/train.py:217-223) at step granularity
        now = time.time()
        if examples_per_sec is None and batch_size and \
                self._last_step_time is not None:
            dt = max(now - self._last_step_time, 1e-9)
            examples_per_sec = batch_size / dt
        self._last_step_time = now
        if self.tb is not None:
            for k, v in metrics.items():
                self.tb.scalar(f"{self.name}/batch_{k}", v, step)
            if examples_per_sec is not None:
                self.tb.scalar(f"{self.name}/examples_per_sec",
                               examples_per_sec, step)
            if data_wait_ms is not None:
                self.tb.scalar(f"{self.name}/data_wait_ms", data_wait_ms, step)
        if self.registry is not None:
            for k, v in metrics.items():
                self.registry.gauge(
                    f"{self.name}_{_metric_slug(k)}").set(v)
            if lr is not None and lr == lr:  # skip NaN
                self.registry.gauge(f"{self.name}_learning_rate").set(lr)
        if self.print_every and step % self.print_every == 0:
            ts = datetime.datetime.now().isoformat(timespec="seconds")
            parts = " ".join(f"{k}={v:.4f}" for k, v in metrics.items())
            lr_s = f" lr={lr:.2e}" if lr is not None else ""
            ep_s = f"epoch {epoch} " if epoch is not None else ""
            perf_s = ""
            if examples_per_sec is not None:
                perf_s += f" ex/s={examples_per_sec:.1f}"
            if data_wait_ms is not None:
                perf_s += f" data_wait_ms={data_wait_ms:.1f}"
            print(f"[{ts}] {self.name} {ep_s}step {step}: {parts}{lr_s}{perf_s}",
                  flush=True)

    def end_epoch(self, epoch: int, extra: Optional[dict] = None) -> dict:
        elapsed = max(time.time() - self._epoch_start, 1e-9)
        summary = {k: m.avg for k, m in self._epoch_meters.items()}
        if extra:
            summary.update({k: float(v) for k, v in extra.items()})
        if self._epoch_examples:
            summary["examples_per_sec"] = self._epoch_examples / elapsed
        summary["epoch_time_s"] = elapsed
        for k, v in summary.items():
            self.history[k].append((epoch, v))
            if self.tb is not None:
                self.tb.scalar(f"{self.name}/epoch_{k}", v, epoch)
            if self.registry is not None:
                self.registry.gauge(
                    f"{self.name}_epoch_{_metric_slug(k)}").set(v)
        if self.journal is not None:
            self.journal.write("epoch", name=self.name, epoch=epoch,
                               summary=summary)
        ts = datetime.datetime.now().isoformat(timespec="seconds")
        parts = " ".join(f"{k}={v:.4f}" for k, v in summary.items())
        print(f"[{ts}] {self.name} epoch {epoch} done: {parts}", flush=True)
        return summary

    # -- persistence (goes into the checkpoint sidecar) --------------------
    def state_dict(self) -> dict:
        return {"history": {k: v for k, v in self.history.items()}}

    def load_state_dict(self, d: dict):
        self.history = collections.defaultdict(list)
        for k, v in d.get("history", {}).items():
            self.history[k] = [tuple(x) for x in v]
