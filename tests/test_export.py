"""StableHLO export round-trip: the TFLite-conversion analog
(CycleGAN/tensorflow/convert.py:1-15) must reproduce model.apply outputs."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # jit-heavy: excluded from the fast tier (`-m "not slow"`)


def test_roundtrip_classifier(tmp_path):
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.tools.export import (
        export_model,
        load_exported,
        save_exported,
    )

    model = get_model("lenet5", num_classes=10)
    x = jnp.asarray(np.random.RandomState(0).rand(4, 32, 32, 1), jnp.float32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    exported = export_model(model, variables, x)
    path = str(tmp_path / "lenet5.stablehlo")
    save_exported(exported, path)
    assert os.path.getsize(path) > 0

    back = load_exported(path)
    got = np.asarray(back.call(x))
    want = np.asarray(model.apply(variables, x, train=False))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_roundtrip_multi_output_detector(tmp_path):
    """YoloV3 returns a 3-tuple; the artifact must preserve the structure."""
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.tools.export import (
        export_model,
        load_exported,
        save_exported,
    )

    model = get_model("yolov3", num_classes=4)
    x = jnp.zeros((1, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    exported = export_model(model, variables, x)
    path = str(tmp_path / "yolo.stablehlo")
    save_exported(exported, path)
    back = load_exported(path)
    got = back.call(x)
    want = model.apply(variables, x, train=False)
    assert len(got) == 3
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-5, atol=1e-5)


def test_export_config_cli(tmp_path, capsys):
    from deep_vision_tpu.tools.export import main

    out = str(tmp_path / "dcgan_g.stablehlo")
    rc = main(["-m", "dcgan_mnist", "-o", out, "--batch", "2"])
    assert rc == 0
    assert os.path.getsize(out) > 0
    assert "exported dcgan_mnist" in capsys.readouterr().out


def test_export_restores_checkpoint(tmp_path):
    """Exported artifact must carry the *trained* weights, not the init."""
    from deep_vision_tpu.core.checkpoint import CheckpointManager
    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.tools.export import export_config, load_exported
    from deep_vision_tpu.train.optimizers import build_optimizer

    model = get_model("lenet5", num_classes=10)
    sample = jnp.zeros((2, 32, 32, 1), jnp.float32)
    state = create_train_state(model, build_optimizer("sgd", 0.1), sample)
    # make the params distinguishable from a PRNGKey(0) re-init
    state = state.replace(
        params=jax.tree_util.tree_map(lambda p: p + 1.0, state.params)
    )
    ck = str(tmp_path / "ck")
    mgr = CheckpointManager(ck)
    mgr.save(0, state)
    mgr.wait()

    out = str(tmp_path / "lenet5.stablehlo")
    export_config("lenet5", out, ckpt_dir=ck, batch=2)
    back = load_exported(out)
    x = jnp.asarray(np.random.RandomState(1).rand(2, 32, 32, 1), jnp.float32)
    variables = {"params": state.params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
    want = np.asarray(model.apply(variables, x, train=False))
    np.testing.assert_allclose(np.asarray(back.call(x)), want,
                               rtol=1e-5, atol=1e-5)


def test_roundtrip_vit(tmp_path):
    """StableHLO export of the attention family (flash path folds to dense
    at this T; export always runs eval mode so no aux tuple)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.tools.export import export_model, load_exported

    model = get_model("vmoe_s16", num_classes=5)
    x = jnp.asarray(np.random.RandomState(0).rand(2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    exported = export_model(model, variables, x)
    path = str(tmp_path / "vmoe.stablehlo")
    with open(path, "wb") as f:
        f.write(exported.serialize())
    restored = load_exported(path)
    np.testing.assert_allclose(
        np.asarray(restored.call(x)),
        np.asarray(model.apply(variables, x, train=False)),
        rtol=1e-5, atol=1e-5,
    )
