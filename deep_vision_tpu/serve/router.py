"""Multi-model Router/Server: queues in front, AOT engine behind.

One `Server` owns one device's serving plane: a `BatchingQueue` and a
dispatcher thread per registered model, all execution funneled through
one device lock (multi-model routing over ONE device — models share the
chip, batches serialize). The request path is:

    submit(model, image)                      # any thread
      -> faults.fire("data.read")             # the request-decode boundary:
                                              #   an injected/real I/O error
                                              #   fails THIS request's future,
                                              #   never the server
      -> BatchingQueue coalesces (max-wait / max-batch)
      -> bucket_for + pad_batch               # round up to a warmed shape
      -> Engine.run (compiled executable, donated input buffer)
      -> device_get, split rows, resolve futures

Everything rides the substrate from day one: typed `serve_request` /
`serve_batch` / `serve_drain` journal events, `serve/*` trace spans,
SLO metrics (serve/slo.py), health-policy wiring (non-finite outputs
journal a `health` event; policy `abort` fails the batch's requests
instead of shipping NaNs), and a SIGTERM drain that flushes every
accepted request and dumps a `preempt` flight bundle. A clean `close()`
drains without the bundle — a healthy shutdown leaves no postmortem.
"""
from __future__ import annotations

import signal
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional

import jax
import numpy as np

from deep_vision_tpu.obs import locksmith, propagate
from deep_vision_tpu.obs.trace import span
from deep_vision_tpu.serve.buckets import bucket_for, pad_batch, split_rows
from deep_vision_tpu.serve.engine import Engine, ServeError
from deep_vision_tpu.serve.queue import (
    BatchingQueue,
    DeadlineExceeded,
    QueueClosed,
    Request,
)
from deep_vision_tpu.serve.slo import SLOTracker

DRAIN_REASONS = ("close", "sigterm")
HEALTH_POLICIES = ("warn", "abort")


class ServerClosed(QueueClosed):
    """submit() on a draining/stopped server."""


class Server:
    """Production serving loop over a warmed Engine.

    Wire-up (what tools/serve_smoke.py does):

        server = Server(engine, journal=journal, max_wait_ms=5.0)
        server.start()                       # engine must be warmed
        fut = server.submit("yolo", image)   # -> Future of an output dict
        ...
        server.install_sigterm()             # main thread only
        server.wait_for_stop()               # returns True on SIGTERM
        server.drain("sigterm")              # flush + preempt flight bundle
    """

    def __init__(self, engine: Engine, journal=None, registry=None,
                 max_wait_ms: float = 5.0, drain_timeout_s: float = 30.0,
                 slo_ms: Optional[float] = None,
                 health_policy: str = "warn", health=None,
                 tags: Optional[dict] = None, telemetry=None):
        if health_policy not in HEALTH_POLICIES:
            raise ValueError(
                f"health_policy {health_policy!r} not in {HEALTH_POLICIES}")
        self.engine = engine
        self.journal = journal
        # extra fields stamped onto every serve_* / health journal event
        # this server writes (a ReplicaPool passes {"replica": "r0"} so a
        # shared fleet journal stays attributable per replica); keys must
        # not shadow the events' own schema fields
        self.tags = dict(tags or {})
        self.slo = SLOTracker(registry=registry, slo_ms=slo_ms)
        self.max_wait_ms = float(max_wait_ms)
        self.drain_timeout_s = float(drain_timeout_s)
        self.health_policy = health_policy
        self.health = health  # optional obs.HealthMonitor: beat() per batch
        self._queues: Dict[str, BatchingQueue] = {}
        self._threads: List[threading.Thread] = []
        # locksmith-named locks: armed smokes check their runtime order
        # (submit -> counts, never the reverse) and hold-time outliers
        self._device_lock = locksmith.lock("serve.device")  # one device,
        self._count_lock = locksmith.lock("serve.counts")  # serialized exec
        # serializes submit's accept-then-enqueue against drain's latch:
        # drain must never observe an accepted request that is not yet in
        # a queue (it would count as pending and taint the drain verdict)
        self._submit_lock = locksmith.lock("serve.submit")
        self.accepted = 0
        self.completed = 0
        self.errors = 0
        self.cancelled = 0  # client gave up while queued/dispatched
        self._started = False
        self._drained: Optional[dict] = None
        self._drain_done = threading.Event()
        self._stop = threading.Event()
        self._prev_sigterm = None
        # live plane (obs/telemetry.py): registration is idempotent by
        # name, so a respawned replica takes over its predecessor's slot
        # and the /healthz verdict tracks the CURRENT server's drain state
        self.telemetry = telemetry
        if telemetry is not None:
            name = f"serve:{self.tags.get('replica', 'server')}"
            telemetry.add_health(name, self.healthz)
            telemetry.add_status(name, self.telemetry_status)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Server":
        if not self.engine.warmed:
            raise ServeError("start() before engine.warmup(): the server "
                             "must never compile at request time")
        if self._started:
            return self
        for name in self.engine.models:
            entry = self.engine.entry(name)
            q = BatchingQueue(
                max_batch=max(entry.buckets),
                max_wait_ms=self.max_wait_ms,
                on_depth=lambda d, _m=name: self.slo.queue_depth(_m, d))
            self._queues[name] = q
            t = threading.Thread(target=self._dispatch_loop,
                                 args=(name, q), name=f"serve-{name}",
                                 daemon=True)
            self._threads.append(t)
            t.start()
        self._started = True
        return self

    # -- request ingestion ---------------------------------------------------

    def submit(self, model: str, image,
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one image for `model`; returns a Future resolving to
        the model's per-request output dict (padded rows already gone).

        Failures are REQUEST-scoped by design: a bad shape, an unknown
        model, or an I/O error at the decode boundary (the `data.read`
        fault-injection point) resolves this future with the exception
        and the server keeps serving everyone else.

        `deadline_ms` (optional) is the client's remaining budget from
        NOW: a request still queued when it expires is shed at dispatch
        (`DeadlineExceeded` on the future) instead of executed — the
        front door's deadline header lands here.
        """
        if not self._started:
            raise ServeError("submit() before start(): no dispatchers are "
                             "running to answer it")
        req = Request(model, image)
        if deadline_ms is not None and deadline_ms > 0:
            req.deadline_ts = req.t_submit + float(deadline_ms) / 1e3
        # request ingress mints the trace context: a caller that already
        # carries one (a traced client thread) makes this hop its child,
        # anyone else roots a fresh trace — either way every serve_request
        # event is stitchable by trace_id across processes
        parent = propagate.current()
        req.ctx = parent.child() if parent is not None else \
            propagate.new_trace()
        # decode OUTSIDE the submit lock: the dtype cast/copy, shape check,
        # and fault boundary are per-request work that must not serialize
        # ingestion across client threads — only the accept+enqueue below
        # needs atomicity against drain's latch
        decode_err: Optional[Exception] = None
        try:
            entry = self.engine.entry(model)
            # the request-decode boundary: exactly where a production
            # server reads/decodes the payload off the wire — injected
            # data.read faults (resilience/faults.py) land here and
            # degrade one request, not the process
            from deep_vision_tpu.resilience import faults

            faults.fire("data.read")
            arr = np.asarray(req.image, dtype=entry.dtype)
            if tuple(arr.shape) != entry.input_shape:
                raise ServeError(
                    f"request shape {tuple(arr.shape)} != {model!r} "
                    f"input {entry.input_shape} (spatial shapes are "
                    "static; resize on the client or register another "
                    "model)")
            req.image = arr
        except (ServeError, OSError, ValueError, TypeError) as e:
            decode_err = e
        with self._submit_lock:
            if self._drained is not None or self._stop.is_set():
                raise ServerClosed("server is draining/stopped")
            # accepted counts every request the server took responsibility
            # for — including ones that fail at the decode boundary:
            # drain's accepted == completed + errors + cancelled invariant
            # needs both
            with self._count_lock:
                self.accepted += 1
            if decode_err is None:
                try:
                    self._queues[model].submit(req)
                except QueueClosed:
                    with self._count_lock:
                        self.accepted -= 1  # never enqueued, nobody owes it
                    raise ServerClosed("server is draining/stopped")
            else:
                # account the failure while still holding the lock: drain
                # latching between accepted+=1 and errors+=1 would see an
                # unbalanced ledger and misreport timeout
                self._fail_request(req, decode_err)
        return req.future

    def healthz(self):
        """Telemetry health source: ready iff started and not
        draining/stopped — the 503 a router flips to on drain is what
        tells an upstream balancer to stop routing here."""
        draining = self._drained is not None or self._stop.is_set()
        ok = self._started and not draining
        return ok, {"started": self._started, "draining": draining,
                    **{k: str(v) for k, v in self.tags.items()}}

    def telemetry_status(self) -> dict:
        """Telemetry status source: the request ledger + per-model SLO
        view for /statusz. Host-side reads only."""
        out = dict(self.counts())
        out["models"] = sorted(self.engine.models)
        out["draining"] = self._drained is not None or self._stop.is_set()
        try:
            out["slo"] = self.slo.report()
        except Exception:
            pass
        if self.tags:
            out["tags"] = dict(self.tags)
        return out

    def queue_depth(self, model: str) -> int:
        """Current queue depth for `model` — the admission controller's
        input when a Transport fronts a bare Server."""
        q = self._queues.get(model)
        return q.depth if q is not None else 0

    def counts(self) -> dict:
        """One consistent snapshot of the request ledger (the drain
        invariant's four buckets) — a ReplicaPool folds these into its
        fleet totals when it retires a dead or drained replica."""
        with self._count_lock:
            return {"accepted": self.accepted, "completed": self.completed,
                    "errors": self.errors, "cancelled": self.cancelled}

    def _account(self, req: Request, outcome: str, latency_ms: float,
                 error: Optional[str] = None) -> None:
        """Count one request toward exactly one of completed / errors /
        cancelled (latched per request: the drain invariant
        accepted == completed + errors + cancelled must survive races
        between resolution, batch failure, and client cancellation)."""
        if req.accounted:
            return
        req.accounted = True
        with self._count_lock:
            if outcome == "ok":
                self.completed += 1
            elif outcome == "cancelled":
                self.cancelled += 1
            else:
                self.errors += 1
        self.slo.request_done(req.model, latency_ms, outcome)
        if self.journal is not None:
            extra = {"error": error[:200]} if error else {}
            # the request's OWN context, stamped explicitly: _account runs
            # on the dispatcher thread, whose ambient thread-local slot
            # belongs to no request in particular
            if req.ctx is not None:
                extra.update(req.ctx.fields())
            self.journal.write("serve_request", model=req.model,
                               latency_ms=round(latency_ms, 3),
                               outcome=outcome, **self.tags, **extra)

    def _fail_request(self, req: Request, exc: Exception) -> None:
        latency_ms = (time.perf_counter() - req.t_submit) * 1e3
        # a cancelled Future rejects set_exception; the client already
        # walked away — account it as cancelled, not as a server error
        if not req.future.set_running_or_notify_cancel():
            self._account(req, "cancelled", latency_ms)
            return
        req.future.set_exception(exc)
        self._account(req, "error", latency_ms,
                      error=f"{type(exc).__name__}: {exc}")

    # -- dispatch ------------------------------------------------------------

    def _dispatch_loop(self, model: str, q: BatchingQueue) -> None:
        # the serving hot loop: lint-clean by construction — no jit, no
        # lower/compile anywhere below (DV004's serve-aware check flags
        # exactly that), only warmed-executable lookups
        while True:
            batch = q.next_batch()
            if batch is None:
                return
            try:
                self._run_batch(model, batch)
            except Exception as e:  # a poisoned batch fails its requests,
                for req in batch:  # never the dispatcher
                    if req.future.cancelled():
                        self._account(
                            req, "cancelled",
                            (time.perf_counter() - req.t_submit) * 1e3)
                    elif not req.future.done():
                        self._fail_request(req, e)

    def _run_batch(self, model: str, batch: List[Request]) -> None:
        entry = self.engine.entry(model)
        t_pickup = time.perf_counter()
        # deadline enforcement AT DISPATCH: a request whose budget ran
        # out while it sat in the queue is shed here, not executed —
        # its answer has no reader, and executing it would tax every
        # co-batched request that still has time left
        expired = [r for r in batch
                   if r.deadline_ts is not None and t_pickup > r.deadline_ts]
        if expired:
            for req in expired:
                late_ms = (t_pickup - req.deadline_ts) * 1e3
                self._fail_request(req, DeadlineExceeded(
                    f"deadline passed {late_ms:.1f} ms before dispatch "
                    f"of {model!r}"))
            batch = [r for r in batch if r.deadline_ts is None
                     or t_pickup <= r.deadline_ts]
            if not batch:
                return
        bucket = bucket_for(len(batch), entry.buckets)
        t_dispatch = time.perf_counter()
        queue_wait_ms = (t_dispatch
                         - min(r.t_submit for r in batch)) * 1e3
        with span("serve/batch", model=model, bucket=bucket,
                  size=len(batch)):
            images = pad_batch([r.image for r in batch], bucket,
                               dtype=entry.dtype)
            with self._device_lock:
                out = self.engine.run(model, images)
                host = jax.device_get(out)  # fences: exec_ms is end-to-end
        exec_ms = (time.perf_counter() - t_dispatch) * 1e3
        bad = self._nonfinite_fields(host, len(batch))
        rows = self._split(host, len(batch))
        t_done = time.perf_counter()
        for req, row in zip(batch, rows):
            latency_ms = (t_done - req.t_submit) * 1e3
            if bad and self.health_policy == "abort":
                # the health policy's serving semantics: never ship NaNs —
                # the affected requests fail, the server keeps answering
                self._fail_request(req, ServeError(
                    f"non-finite output fields {bad} (health_policy=abort)"))
                continue
            if not req.future.set_running_or_notify_cancel():
                # client gave up while the request was queued: the row
                # has no recipient, but the books must still balance
                self._account(req, "cancelled", latency_ms)
                continue
            req.future.set_result(row)
            self._account(req, "ok", latency_ms)
        self.slo.batch_done(model, bucket, len(batch), queue_wait_ms,
                            exec_ms)
        if self.journal is not None:
            self.journal.write(
                "serve_batch", model=model, bucket=int(bucket),
                size=len(batch),
                occupancy_pct=round(100.0 * len(batch) / bucket, 1),
                padding_waste_pct=round(
                    100.0 * (bucket - len(batch)) / bucket, 1),
                queue_wait_ms=round(queue_wait_ms, 3),
                exec_ms=round(exec_ms, 3), **self.tags)
        if bad:
            self._emit_nonfinite(model, bad, len(batch))
        if self.health is not None:
            self.health.beat()  # the serve loop is the watchdog heartbeat

    def _split(self, host, n: int) -> List:
        """Batched host output -> one row per real request. Dicts (the
        detector contract) go through buckets.split_rows; any other
        pytree (e.g. the pose estimator's bare keypoint array) is
        row-indexed leaf-wise."""
        if isinstance(host, dict):
            return split_rows(host, n)
        return [jax.tree_util.tree_map(lambda a: a[i], host)
                for i in range(n)]

    def _nonfinite_fields(self, host, n: int) -> List[str]:
        items = (host.items() if isinstance(host, dict)
                 else enumerate(jax.tree_util.tree_leaves(host)))
        bad = []
        for k, v in items:
            a = np.asarray(v)
            if np.issubdtype(a.dtype, np.floating) and \
                    not np.isfinite(a[:n]).all():
                bad.append(str(k))
        return sorted(bad)

    def _emit_nonfinite(self, model: str, fields: List[str],
                        size: int) -> None:
        self.slo.registry.counter(
            "serve_nonfinite_batches_total",
            "batches with non-finite output fields",
            labels={"model": model}).inc()
        if self.journal is not None:
            # same typed health event the training monitor emits, so one
            # check_journal schema and one obs_report health table cover
            # both planes
            self.journal.write("health", kind="non_finite",
                               policy=self.health_policy, monitor="serve",
                               fields=fields, action=self.health_policy,
                               model=model, batch_size=size, **self.tags)

    # -- drain / shutdown ----------------------------------------------------

    def drain(self, reason: str = "close") -> dict:
        """Flush every accepted request, then stop. Idempotent (the first
        reason wins). `sigterm` additionally dumps a `preempt` flight
        bundle — a clean `close` leaves no postmortem artifacts.
        """
        if reason not in DRAIN_REASONS:
            raise ValueError(f"drain reason {reason!r} not in {DRAIN_REASONS}")
        # the submit lock guarantees no request is accepted-but-unqueued
        # when the latch lands: past this point every accepted request is
        # either in a queue (the dispatchers will flush it) or resolved
        with self._submit_lock, self._count_lock:
            already = self._drained is not None
            if not already:
                # full-keyed placeholder: a concurrent caller that times
                # out waiting below still sees a well-formed summary
                self._drained = {
                    "reason": reason, "outcome": "timeout",
                    "accepted": self.accepted, "completed": self.completed,
                    "errors": self.errors, "cancelled": self.cancelled,
                    "pending": max(0, self.accepted - self.completed
                                   - self.errors - self.cancelled),
                }
        if already:
            # a second drain (close racing a SIGTERM drain) waits for the
            # first one's verdict instead of returning a half-done record
            self._drain_done.wait(timeout=self.drain_timeout_s)
            return self._drained
        try:
            deadline = time.perf_counter() + self.drain_timeout_s
            with span("serve/drain", reason=reason):
                for q in self._queues.values():
                    q.close()  # stop accepting; flush-immediately mode
                for t in self._threads:
                    t.join(timeout=max(0.0,
                                       deadline - time.perf_counter()))
                with self._count_lock:
                    # one consistent snapshot: the journaled summary must
                    # balance even if a straggler is mid-account elsewhere
                    counts = {"accepted": self.accepted,
                              "completed": self.completed,
                              "errors": self.errors,
                              "cancelled": self.cancelled}
                pending = (counts["accepted"] - counts["completed"]
                           - counts["errors"] - counts["cancelled"])
                outcome = ("flushed" if pending == 0
                           and not any(t.is_alive() for t in self._threads)
                           else "timeout")
                summary = {"reason": reason, "outcome": outcome,
                           **counts, "pending": max(0, pending)}
                if self.journal is not None:
                    self.journal.write("serve_drain", **self.tags, **summary)
                if reason == "sigterm":
                    # the preemption postmortem: same bundle + reason the
                    # trainer's PreemptionGuard dumps, so one flight-dir
                    # convention covers both planes
                    from deep_vision_tpu.obs import flight

                    summary["flight_bundle"] = \
                        flight.emergency_dump("preempt")
            self._drained = summary
            return summary
        finally:
            self._stop.set()
            self._drain_done.set()

    def close(self) -> dict:
        return self.drain("close")

    # -- SIGTERM wiring ------------------------------------------------------

    def install_sigterm(self) -> None:
        """Arm SIGTERM -> stop flag (main thread only, like
        parallel/multihost.PreemptionGuard). The handler only sets the
        flag; the serving owner loop observes it (`wait_for_stop`) and
        runs the drain OUTSIDE signal context, where joining threads and
        journaling are safe."""
        self._prev_sigterm = signal.signal(signal.SIGTERM, self._on_sigterm)

    def uninstall_sigterm(self) -> None:
        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None

    def _on_sigterm(self, signum, frame) -> None:
        self._stop.set()

    @property
    def stop_requested(self) -> bool:
        return self._stop.is_set()

    def wait_for_stop(self, timeout: Optional[float] = None) -> bool:
        """Block until SIGTERM (or drain/close) flips the stop flag."""
        return self._stop.wait(timeout)
