"""One-off ablation harness for the bench train step (not part of the API).

Times variants of the ResNet-50 bench step on the real chip to locate the
remaining gap to the 2610 img/s/chip target: batch scaling, forward-only,
grad-without-update, bf16 master params.
"""
from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def make_step(batch_size, *, mode="full", param_dtype=jnp.float32):
    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.losses.classification import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.parallel.mesh import create_mesh, data_sharding, replicated
    from deep_vision_tpu.train.optimizers import build_optimizer

    mesh = create_mesh()
    model = get_model("resnet50", num_classes=1000, dtype=jnp.bfloat16, stem="s2d")
    tx = build_optimizer("sgd", learning_rate=0.1, momentum=0.9, weight_decay=1e-4)
    sample = jnp.ones((8, 112, 112, 12), jnp.float32)
    state = create_train_state(model, tx, sample)
    if param_dtype != jnp.float32:
        state = state.replace(
            params=jax.tree_util.tree_map(lambda p: p.astype(param_dtype), state.params)
        )
    state = jax.device_put(state, replicated(mesh))
    rng = np.random.RandomState(0)
    batch = {
        "image": rng.rand(batch_size, 112, 112, 12).astype(np.float32).astype(jnp.bfloat16),
        "label": rng.randint(0, 1000, size=(batch_size,)).astype(np.int32),
    }
    batch = {k: jax.device_put(v, data_sharding(mesh, v.ndim)) for k, v in batch.items()}

    def loss_fn(params, state, batch):
        variables = {"params": params, "batch_stats": state.batch_stats}
        outputs, new_model_state = state.apply_fn(
            variables, batch["image"], train=True,
            rngs={"dropout": jax.random.fold_in(state.rng, state.step)},
            mutable=["batch_stats"],
        )
        loss, _ = classification_loss_fn(outputs, batch)
        return loss, new_model_state["batch_stats"]

    if mode == "fwd":
        def step(state, batch):
            loss, _ = loss_fn(state.params, state, batch)
            return state, loss
    elif mode == "grad":
        def step(state, batch):
            (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, state, batch)
            # fold grads into loss so nothing is dead code
            return state, loss + jax.tree_util.tree_reduce(
                lambda a, g: a + jnp.sum(g) * 0.0, grads, 0.0)
    else:
        def step(state, batch):
            (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, state, batch)
            return state.apply_gradients(grads).replace(batch_stats=new_bs), loss

    if mode == "scan20":
        def scan_step(state, batch):
            def body(s, _):
                s2, loss = step(s, batch)
                return s2, loss

            state, losses = jax.lax.scan(body, state, None, length=20)
            return state, losses[-1]

        return jax.jit(scan_step, donate_argnums=0), state, batch

    return jax.jit(step, donate_argnums=0), state, batch


def time_variant(name, batch_size, **kw):
    inner = 20 if kw.get("mode") == "scan20" else 1  # steps per dispatch
    calls = 1 if inner > 1 else 15
    step, state, batch = make_step(batch_size, **kw)
    t0 = time.perf_counter()
    for _ in range(5 if inner == 1 else 1):
        state, loss = step(state, batch)
    float(loss)
    warm = time.perf_counter() - t0
    dts = []
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(calls):
            state, loss = step(state, batch)
        float(loss)
        dts.append((time.perf_counter() - t0) / (calls * inner))
    ms = min(dts) * 1e3
    print(f"{name}: {ms:.1f} ms/step  {batch_size / min(dts):.0f} img/s  "
          f"(warmup {warm:.0f}s)", flush=True)


if __name__ == "__main__":
    known = {"full256", "full512", "fwd256", "grad256", "bf16_512", "scan20"}
    which = sys.argv[1:] or ["full256", "full512", "fwd256", "grad256", "bf16_512"]
    unknown = set(which) - known
    if unknown:
        raise SystemExit(f"unknown variants {sorted(unknown)}; have {sorted(known)}")
    if "scan20" in which:
        time_variant("scan20 b256", 256, mode="scan20")
    if "full256" in which:
        time_variant("full  b256", 256)
    if "full512" in which:
        time_variant("full  b512", 512)
    if "fwd256" in which:
        time_variant("fwd   b256", 256, mode="fwd")
    if "grad256" in which:
        time_variant("grad  b256", 256, mode="grad")
    if "bf16_512" in which:
        time_variant("bf16p b512", 512, param_dtype=jnp.bfloat16)
