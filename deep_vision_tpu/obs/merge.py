"""Merge per-host journals into one timeline; detect cross-host stragglers.

A multi-host run writes one journal per process (`<path>.pN`, see
obs/journal.py) because host 7's last seconds must survive host 7. This
module is the read side: stitch the per-host files back into ONE
chronological timeline (every event annotated with its `host`), and
while doing so run the cheapest cross-host diagnosis there is — for
every optimizer step reported by two or more hosts, compare their step
times. SPMD lockstep means a step is as slow as its slowest host; a
persistent max−median gap IS the straggler signal (a fragmenting host
NIC, a throttled VM, a dying local SSD feeding one input pipeline), and
it is invisible in any single host's journal because the collective
stalls everyone equally.

Detected stragglers become typed `straggler` events in the merged
timeline (step, gap_ms, median_ms, max_ms, the offending host) and bump
`obs_straggler_total`. The merged file is itself a schema-valid
journal, rendered by `tools/obs_report.py --merged`. Under
`tools/check_journal.py --strict` it behaves like any journal: a merge
of clean runs passes, while a merge whose LAST terminal event is a
host's `crash` marker (or that has none after a SIGKILL) is flagged —
correctly, since strict mode exists to certify clean completions, and
a postmortem merge is evidence of the opposite.

CLI: `tools/obs_merge.py`. In-run: `parallel/multihost.aggregate_obs`
runs this on the primary host after an end-of-run barrier (shared
filesystem — the standard Cloud TPU pod setup where every host mounts
the same GCS/NFS run directory).

Partial journals are expected input, not failure: a host SIGKILLed
mid-run leaves a torn final line (tolerated line-wise) or no readable
file at all (recorded as `unreadable_sources` in the merge header) —
the merge is precisely the postmortem that must still assemble from
whatever the survivors wrote.
"""
from __future__ import annotations

import json
import re
from typing import Dict, List, Optional, Sequence, Tuple

from deep_vision_tpu.obs.journal import read_journal

#: run_id stamped on events the merge itself synthesizes
MERGE_RUN_ID = "obs-merge"


def _median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


def host_index(path: str, events: List[dict], fallback: int) -> int:
    """A journal's host id: the manifest's `process_index` when present,
    else the `.pN` path suffix, else the caller's positional fallback."""
    for e in events:
        if e.get("event") == "run_manifest" and "process_index" in e:
            try:
                return int(e["process_index"])
            except (TypeError, ValueError):
                break
    m = re.search(r"\.p(\d+)$", path)
    if m:
        return int(m.group(1))
    return fallback


def detect_stragglers(
    host_steps: Dict[int, Dict[int, dict]],
    gap_ms: float = 25.0,
    rel: float = 0.5,
) -> List[dict]:
    """Straggler events from per-host per-step records.

    `host_steps`: host -> step index -> step event. A step flags when at
    least two hosts reported it and the max−median step-time gap exceeds
    BOTH the absolute floor (`gap_ms` — sub-floor jitter is noise at any
    scale) and `rel` x median (so a 30ms gap on a 10ms step flags while
    the same 30ms on a 5s step does not).
    """
    out: List[dict] = []
    all_steps = sorted({s for steps in host_steps.values() for s in steps})
    for step in all_steps:
        reports = [
            (h, float(ev["step_time_ms"]), ev)
            for h, steps in sorted(host_steps.items())
            if (ev := steps.get(step)) is not None
            and ev.get("step_time_ms") is not None
        ]
        if len(reports) < 2:
            continue
        times = [t for _, t, _ in reports]
        med = _median(times)
        mx = max(times)
        gap = mx - med
        if gap <= gap_ms or gap <= rel * med:
            continue
        slow_host, _, slow_ev = max(reports, key=lambda r: r[1])
        out.append({
            "event": "straggler",
            "ts": slow_ev.get("ts"),
            "run_id": MERGE_RUN_ID,
            "step": int(step),
            "gap_ms": round(gap, 3),
            "median_ms": round(med, 3),
            "max_ms": round(mx, 3),
            "host": int(slow_host),
            "hosts": len(reports),
        })
    return out


def merge_events(
    per_host: Dict[int, List[dict]],
    gap_ms: float = 25.0,
    rel: float = 0.5,
) -> Tuple[List[dict], List[dict]]:
    """(merged timeline, straggler events). Every source event gains a
    `host` field; stragglers are interleaved at their step's timestamp
    and counted in `obs_straggler_total`."""
    merged: List[dict] = []
    host_steps: Dict[int, Dict[int, dict]] = {}
    for host, events in per_host.items():
        steps = host_steps.setdefault(host, {})
        for e in events:
            row = dict(e)
            row.setdefault("host", int(host))
            merged.append(row)
            if e.get("event") == "step" and e.get("step") is not None:
                steps[int(e["step"])] = e
    stragglers = detect_stragglers(host_steps, gap_ms=gap_ms, rel=rel)
    if stragglers:
        try:
            from deep_vision_tpu.obs.registry import get_registry

            get_registry().counter(
                "obs_straggler_total",
                "cross-host step-skew detections (obs_merge)",
            ).inc(len(stragglers))
        except Exception:
            pass
    merged.extend(stragglers)
    # stable sort: events sharing a ts keep source order (ts is the
    # journal's own clock, already rounded to ms)
    merged.sort(key=lambda e: (e.get("ts") is None, e.get("ts") or 0.0))
    return merged, stragglers


def trace_timelines(events: Sequence[dict]) -> List[dict]:
    """Group a (merged) timeline's events by `trace_id` into per-request
    causal timelines — the cross-PROCESS complement to the cross-HOST
    straggler pass. Each timeline is one request's hops in time order:

        {"trace_id": ..., "hops": [event, ...], "spans": n,
         "duration_ms": last.ts - first.ts, "processes": [run_id, ...]}

    Events without a trace_id (steps, checkpoints, ...) are untraced
    background and simply don't participate. Ordering within a timeline
    is causal first, clock second: each hop's effective ts is clamped to
    max(own ts, parent's effective ts) — journal timestamps are rounded
    to 1 ms, so a child hop can be stamped in an EARLIER millisecond
    bucket than its parent (a server journals its reply before the
    client journals the receipt, and the rounding boundary can fall
    between the two writes). The parent-link depth then breaks exact
    ties deterministically (root spans first), so a parent always
    renders before its children regardless of which side of a rounding
    boundary their wall clocks landed on.
    """
    by_trace: Dict[str, List[dict]] = {}
    for e in events:
        tid = e.get("trace_id")
        if isinstance(tid, str) and tid:
            by_trace.setdefault(tid, []).append(e)
    timelines: List[dict] = []
    for tid, hops in by_trace.items():
        parents = {e.get("span_id") for e in hops}
        by_span = {h.get("span_id"): h for h in hops}

        def depth(e, _parents=parents, _by_span=by_span):
            # root spans (parent absent or unknown) sort first at a tie
            p = e.get("parent_span_id")
            d = 0
            seen = set()
            while p in _parents and p not in seen:
                seen.add(p)
                d += 1
                p = _by_span.get(p, {}).get("parent_span_id")
            return d

        # the causal clamp: walking in depth order guarantees a hop's
        # parent has its effective ts settled first (a cycle in the
        # links caps depth via the seen-set, and the parent lookup then
        # simply falls back to the hop's own ts)
        eff: Dict[int, float] = {}
        for e in sorted(hops, key=depth):
            ts = e.get("ts") or 0.0
            parent = by_span.get(e.get("parent_span_id"))
            if parent is not None and id(parent) in eff:
                ts = max(ts, eff[id(parent)])
            eff[id(e)] = ts

        hops.sort(key=lambda e: (eff[id(e)], depth(e)))
        tss = [e["ts"] for e in hops if e.get("ts") is not None]
        timelines.append({
            "trace_id": tid,
            "hops": hops,
            "spans": len({e.get("span_id") for e in hops}),
            "duration_ms": round((max(tss) - min(tss)) * 1e3, 3)
            if len(tss) > 1 else 0.0,
            "processes": sorted({e.get("run_id") for e in hops
                                 if e.get("run_id")}),
        })
    timelines.sort(key=lambda t: (t["hops"][0].get("ts") or 0.0,
                                  t["trace_id"]))
    return timelines


def merge_journal_files(
    paths: Sequence[str],
    out_path: Optional[str] = None,
    gap_ms: float = 25.0,
    rel: float = 0.5,
) -> dict:
    """Merge journal files into `out_path` (JSONL); returns a summary.

    The merged file opens with a `note` event recording the sources, so
    a reader (and `obs_report --merged`) can tell a merged timeline from
    a single-host journal.
    """
    per_host: Dict[int, List[dict]] = {}
    unreadable: List[str] = []
    for i, path in enumerate(paths):
        try:
            events = [e for e in read_journal(path)
                      if e.get("event") != "_torn_line"]
        except OSError:
            # a host that died mid-run may leave a missing/unreadable
            # journal (SIGKILL before the first flush, a vanished local
            # volume): the merge is exactly the postmortem that must
            # still assemble — record the gap, keep the survivors
            unreadable.append(path)
            continue
        host = host_index(path, events, fallback=i)
        per_host.setdefault(host, []).extend(events)
    merged, stragglers = merge_events(per_host, gap_ms=gap_ms, rel=rel)
    ts0 = min((e["ts"] for e in merged if e.get("ts") is not None),
              default=0.0)
    header = {
        "event": "note", "ts": ts0, "run_id": MERGE_RUN_ID,
        "note": "obs_merge", "hosts": sorted(per_host),
        "sources": list(paths), "stragglers": len(stragglers),
    }
    if unreadable:
        header["unreadable_sources"] = unreadable
    summary = {
        "hosts": sorted(per_host),
        "events": len(merged),
        "stragglers": stragglers,
        "unreadable": unreadable,
        "out": out_path,
    }
    if out_path is not None:
        with open(out_path, "w") as f:
            f.write(json.dumps(header) + "\n")
            for e in merged:
                f.write(json.dumps(e) + "\n")
    return summary
