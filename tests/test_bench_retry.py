"""bench.py resilience: transient runtime failures must not kill the run.

Round 2 shipped with NO recorded perf number because one transient tunnel
error escaped bench.py's step loop (BENCH_r02.json: rc=1, parsed null).
These tests drive `_timed_windows` / `main` with an injected flaky step and
assert the retry-rebuild-replay path works and the JSON line is ALWAYS
emitted.
"""
import json
import sys
import types

import numpy as np
import pytest

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
import bench  # noqa: E402


class _FlakyStep:
    """Raises on the Nth call, healthy otherwise."""

    def __init__(self, fail_on_call=None):
        self.calls = 0
        self.fail_on_call = fail_on_call

    def __call__(self, state, batch):
        self.calls += 1
        if self.calls == self.fail_on_call:
            raise RuntimeError("INTERNAL: remote_compile: body closed")
        return state, np.float32(0.5)

    def lower(self, *a, **kw):  # cost-analysis path: pretend unsupported
        raise NotImplementedError


def _fake_build_factory(fail_plan):
    """fail_plan: list of fail_on_call values, one per build_bench call."""
    builds = []

    def fake_build(batch_per_chip, multistep):
        step = _FlakyStep(
            fail_plan[len(builds)] if len(builds) < len(fail_plan) else None
        )
        builds.append(step)
        batch = {"image": np.zeros((batch_per_chip, 4))}
        fake_dev = types.SimpleNamespace(device_kind="TPU v5 lite")
        return step, None, batch, batch_per_chip, 1, [fake_dev]

    return fake_build, builds


def test_transient_failure_mid_window_rebuilds_and_completes(monkeypatch):
    # build #1's step dies mid-window-1 (warmup + window 0 ok); build #2 is
    # healthy — all WINDOWS must still complete
    fake_build, builds = _fake_build_factory(
        [bench.WARMUP_STEPS + bench.TIMED_STEPS + 5, None]
    )
    monkeypatch.setattr(bench, "build_bench", fake_build)
    monkeypatch.setattr(bench, "_recover_backend", lambda attempt: None)
    (dts, step, state, batch, bs, n_chips, devs, errors) = (
        bench._timed_windows(8, 1)
    )
    assert len(dts) == bench.WINDOWS
    assert len(builds) == 2
    assert len(errors) == 1 and "remote_compile" in errors[0]
    # r3 advisor: pre-failure windows must NOT feed the median — every
    # window replays on the rebuilt (healthy) step
    assert builds[1].calls == bench.WARMUP_STEPS + (
        bench.WINDOWS * bench.TIMED_STEPS
    )


def test_retry_exhaustion_keeps_completed_windows(monkeypatch, capsys):
    """Budget exhaustion after some windows completed must still report the
    measured number (from the completed windows), not crash on a sentinel."""
    # build #1: warmup (WARMUP_STEPS calls) + window 0 (TIMED_STEPS calls)
    # ok, window 1 dies mid-way; every rebuild dies too -> exhaustion with
    # 1 good window
    calls = {"n": 0}

    def build_once_then_die(batch_per_chip, multistep):
        calls["n"] += 1
        if calls["n"] > 1:
            raise RuntimeError("tunnel still down")
        step = _FlakyStep(
            fail_on_call=bench.WARMUP_STEPS + bench.TIMED_STEPS + 5
        )
        batch = {"image": np.zeros((batch_per_chip, 4))}
        fake_dev = types.SimpleNamespace(device_kind="TPU v5 lite")
        return step, None, batch, batch_per_chip, 1, [fake_dev]

    monkeypatch.setattr(bench, "build_bench", build_once_then_die)
    monkeypatch.setattr(bench, "_recover_backend", lambda attempt: None)
    monkeypatch.setattr(bench, "_device_step_ms", lambda *a, **kw: None)
    monkeypatch.setattr(bench, "MAX_RETRIES", 2)
    args = types.SimpleNamespace(batch=8, multistep=1)
    bench.main(args)
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] > 0  # window 0's measurement survived
    assert payload["windows_completed"] == 1
    assert payload["errors"]


def test_main_emits_json_even_when_everything_fails(monkeypatch, capsys):
    def always_broken(batch_per_chip, multistep):
        raise RuntimeError("tunnel down")

    monkeypatch.setattr(bench, "build_bench", always_broken)
    monkeypatch.setattr(bench, "_recover_backend", lambda attempt: None)
    monkeypatch.setattr(bench, "MAX_RETRIES", 2)
    args = types.SimpleNamespace(batch=8, multistep=1)
    bench.main(args)
    out = capsys.readouterr().out.strip().splitlines()
    payload = json.loads(out[-1])  # the JSON line is ALWAYS the last line
    assert payload["metric"] == "resnet50_train_images_per_sec_per_chip"
    assert payload["value"] == 0.0
    assert payload["errors"]


def test_main_happy_path_reports_wall_rate_and_mfu(monkeypatch, capsys):
    fake_build, _ = _fake_build_factory([None])
    monkeypatch.setattr(bench, "build_bench", fake_build)
    monkeypatch.setattr(bench, "_device_step_ms", lambda *a, **kw: None)
    args = types.SimpleNamespace(batch=8, multistep=1)
    bench.main(args)
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] > 0
    assert payload["unit"] == "images/sec/chip"
    # wall semantics restored (ADVICE r2): vs_baseline is wall / target
    assert payload["vs_baseline"] == round(
        payload["value"] / bench.TARGET_PER_CHIP, 3
    )
    # analytic fallback path: flops reported even without cost analysis
    assert payload["flops_source"] == "analytic"
    assert payload["mfu_wall_pct"] > 0
