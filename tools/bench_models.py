"""Reproducible per-model benchmark artifacts (YOLOv3 step, flash attention).

README's Performance table cites two numbers beyond the ResNet-50 headline:
the YOLOv3-416 train step (the reference's ONLY published perf figure is a
YOLO epoch time — BASELINE.md) and the Pallas flash-attention kernel vs XLA
dense attention. This harness re-measures both on the local chip and writes
one JSON artifact so the claims stay numbers, not sentences:

    PYTHONPATH=. python tools/bench_models.py [--out artifacts/models_bench.json]

Methodology matches bench.py: median of timed windows, timing closed by a
device->host scalar fetch, one process (wall drift across sessions is +-4%
on this rig, artifacts record the session's interleaved values).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _median_ms(call, steps=100, windows=3):
    """Median wall ms per `call()`. `call` must return a DEVICE SCALAR:
    timing is closed by a float() fetch — on this rig's relay backend,
    block_until_ready() can return before execution completes, silently
    measuring enqueue time (a 70 ms step once "measured" 3 ms that way).

    steps=100 per window: the window-closing fetch costs a constant
    ~118 ms per synchronization for the ResNet train step
    (artifacts/dispatch_r04.json), which predicts short windows inflate
    per-call numbers by up to 118/steps ms. The r3 artifacts used
    steps=10; the regenerated artifact quantifies how much of that
    prediction this (smaller-output) call pattern actually paid."""
    for _ in range(3):
        out = call()
    float(out)
    dts = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = call()
        float(out)
        dts.append((time.perf_counter() - t0) / steps)
    return float(np.median(dts)) * 1e3


def bench_yolo(batch: int = 16, size: int = 416, classes: int = 80) -> dict:
    """Full YOLOv3 train step: fwd + 3-scale loss + bwd + SGD update."""
    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.losses.yolo import yolo_train_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train.optimizers import build_optimizer

    model = get_model("yolov3", num_classes=classes, dtype=jnp.bfloat16)
    tx = build_optimizer("sgd", 1e-3, momentum=0.9)
    state = create_train_state(
        model, tx, jnp.ones((2, size, size, 3), jnp.float32)
    )
    rng = np.random.RandomState(0)
    keep = rng.rand(batch, 100, 1) > 0.9  # ~10 real boxes per image
    boxes = np.tile([[0.2, 0.2, 0.6, 0.6]], (batch, 100, 1)) * keep
    batch_d = {
        "image": jnp.asarray(rng.rand(batch, size, size, 3), jnp.bfloat16),
        "boxes": jnp.asarray(boxes, jnp.float32),
        "classes": jnp.asarray(
            rng.randint(0, classes, size=(batch, 100)), jnp.int32
        ),
    }
    grid_sizes = (size // 32, size // 16, size // 8)

    def train_step(state, batch):
        def loss_fn(params):
            out, nms = state.apply_fn(
                {"params": params, "batch_stats": state.batch_stats},
                batch["image"], train=True,
                rngs={"dropout": jax.random.fold_in(state.rng, state.step)},
                mutable=["batch_stats"],
            )
            loss, _ = yolo_train_loss_fn(
                out, batch, grid_sizes=grid_sizes, num_classes=classes
            )
            return loss, nms["batch_stats"]

        (loss, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        return state.apply_gradients(grads).replace(batch_stats=bs), loss

    step = jax.jit(train_step, donate_argnums=0)

    box = {"state": state}  # donation: thread the live state through calls

    def call():
        box["state"], loss = step(box["state"], batch_d)
        return loss

    ms = _median_ms(call)
    return {
        "what": f"yolov3-{size} train step (fwd + 3-scale loss + bwd + sgd), "
                f"bf16, batch {batch}, {classes} classes, 100 padded boxes",
        "wall_ms_per_step": round(ms, 1),
        "images_per_sec": round(batch / ms * 1e3, 1),
        "reference_baseline": "~180 img/s on 8x V100 "
                              "(YOLO/tensorflow/README.md:7, BASELINE.md)",
    }


def bench_flash(b=4, t=4096, h=8, d=64) -> dict:
    """Pallas flash attention fwd+bwd vs XLA dense attention, causal bf16."""
    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.ops.pallas.flash_attention import (
        _dense_reference,
        flash_attention,
    )

    rng = np.random.RandomState(0)
    q, k, v = (
        jnp.asarray(rng.randn(b, t, h, d) * 0.2, jnp.bfloat16)
        for _ in range(3)
    )

    def _scalarized(attn):
        # grads still fully computed; reduced to one scalar so _median_ms
        # can close timing with a float() fetch
        @jax.jit
        def fwd_bwd(q, k, v):
            grads = jax.grad(
                lambda q, k, v: jnp.sum(attn(q, k, v).astype(jnp.float32)),
                argnums=(0, 1, 2),
            )(q, k, v)
            return sum(jnp.sum(g.astype(jnp.float32)) for g in grads)

        return fwd_bwd

    flash_fn = _scalarized(
        lambda q, k, v: flash_attention(q, k, v, causal=True)
    )
    dense_fn = _scalarized(
        lambda q, k, v: _dense_reference(q, k, v, True, d ** -0.5)
    )
    flash_ms = _median_ms(lambda: flash_fn(q, k, v))
    dense_ms = _median_ms(lambda: dense_fn(q, k, v))
    return {
        "what": f"attention fwd+bwd, causal bf16, B{b} T{t} H{h} D{d}",
        "pallas_flash_ms": round(flash_ms, 1),
        "xla_dense_ms": round(dense_ms, 1),
        "speedup": round(dense_ms / flash_ms, 2),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="artifacts/models_bench.json")
    p.add_argument("--journal", default=None,
                   help="bench-journal JSONL (default: <out>.journal.jsonl); "
                        "same schema as train_cli --journal, so BENCH_* "
                        "artifacts machine-diff across PRs via "
                        "tools/obs_report.py")
    p.add_argument("--skip-yolo", action="store_true")
    p.add_argument("--skip-flash", action="store_true")
    args = p.parse_args(argv)

    import jax

    from deep_vision_tpu.obs import RunJournal

    journal_path = args.journal or (
        os.path.splitext(args.out)[0] + ".journal.jsonl"
    )
    result = {"device_kind": jax.devices()[0].device_kind}
    with RunJournal(journal_path, kind="bench") as journal:
        journal.manifest(config={"tool": "bench_models", "out": args.out})
        if not args.skip_yolo:
            result["yolov3"] = bench_yolo()
            print("yolo:", json.dumps(result["yolov3"]))
            journal.bench("yolov3", result["yolov3"])
            # per-chip batch optimum moved for ResNet-50 (batch_scaling_r04);
            # check YOLO's curve one octave up too
            result["yolov3_b32"] = bench_yolo(batch=32)
            print("yolo b32:", json.dumps(result["yolov3_b32"]))
            journal.bench("yolov3_b32", result["yolov3_b32"])
        if not args.skip_flash:
            result["flash_attention"] = bench_flash()
            print("flash:", json.dumps(result["flash_attention"]))
            journal.bench("flash_attention", result["flash_attention"])
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(f"-> {args.out} (journal: {journal_path})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
