"""Host-side numpy image transforms (the reference's hand-written set).

Parity targets — the deliberately hand-written transform classes at
ResNet/pytorch/data_load.py:72-296 (Rescale, RandomHorizontalFlip, RandomCrop,
CenterCrop, ToTensor, Normalize, ColorJitter), the TF "ResNet preprocessing"
(ResNet/tensorflow/data_load.py:158-193: aspect resize, central crop, mean
subtraction), and the bbox-preserving detection augments at
YOLO/tensorflow/preprocess.py:37-119.

All transforms are `__call__(sample: dict, rng) -> dict` over
{'image': HWC uint8/float numpy, 'label'/'boxes'/...}. They run on host CPU
workers; the device boundary is `parallel.mesh.shard_batch`. Layout stays HWC
(NHWC batches) — the TPU-native layout; the reference's CHW ToTensor
(data_load.py:176-194) has no analog here by design.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

try:  # cv2 for fast resize; PIL fallback
    import cv2

    _HAS_CV2 = True
except Exception:  # pragma: no cover
    from PIL import Image

    _HAS_CV2 = False

# ImageNet channel stats (Normalize at ResNet/pytorch/train.py:327-329 uses
# torchvision's 0-1 stats; the TF path uses 0-255 means data_load.py:35-38)
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)
# TF "ResNet preprocessing" 0-255 RGB means (ResNet/tensorflow/data_load.py:35-38)
TF_IMAGENET_MEAN = np.array([123.68, 116.78, 103.94], np.float32)


def _resize(image: np.ndarray, h: int, w: int) -> np.ndarray:
    if _HAS_CV2:
        out = cv2.resize(image, (w, h), interpolation=cv2.INTER_LINEAR)
        if out.ndim == 2:  # cv2 drops the channel dim for single-channel
            out = out[:, :, None]
        return out
    pil = Image.fromarray(image.squeeze().astype(np.uint8))
    out = np.asarray(pil.resize((w, h), Image.BILINEAR))
    if out.ndim == 2:
        out = out[:, :, None]
    return out


class Rescale:
    """Aspect-preserving resize: shorter side -> `size`
    (ResNet/pytorch/data_load.py:72-101; _aspect_preserving_resize at
    ResNet/tensorflow/data_load.py:123-137)."""

    def __init__(self, size: int):
        self.size = size

    def __call__(self, sample: dict, rng: np.random.Generator) -> dict:
        image = sample["image"]
        h, w = image.shape[:2]
        if h < w:
            nh, nw = self.size, max(1, round(w * self.size / h))
        else:
            nh, nw = max(1, round(h * self.size / w)), self.size
        sample["image"] = _resize(image, nh, nw)
        return sample


class Resize:
    """Fixed-size (square) resize — YOLO 416 input (preprocess.py:24-27)."""

    def __init__(self, height: int, width: Optional[int] = None):
        self.h, self.w = height, width or height

    def __call__(self, sample: dict, rng) -> dict:
        image = sample["image"]
        sample["image"] = _resize(image, self.h, self.w)
        # normalized box coords are resize-invariant; nothing else to fix
        return sample


class RandomCrop:
    """Random fixed-size crop (ResNet/pytorch/data_load.py:116-143)."""

    def __init__(self, size: int):
        self.size = size

    def __call__(self, sample: dict, rng: np.random.Generator) -> dict:
        image = sample["image"]
        h, w = image.shape[:2]
        top = int(rng.integers(0, h - self.size + 1))
        left = int(rng.integers(0, w - self.size + 1))
        sample["image"] = image[top:top + self.size, left:left + self.size]
        return sample


class CenterCrop:
    """Center crop (ResNet/pytorch/data_load.py:146-173; _central_crop at
    ResNet/tensorflow/data_load.py:46-63)."""

    def __init__(self, size: int):
        self.size = size

    def __call__(self, sample: dict, rng) -> dict:
        image = sample["image"]
        h, w = image.shape[:2]
        top = (h - self.size) // 2
        left = (w - self.size) // 2
        sample["image"] = image[top:top + self.size, left:left + self.size]
        return sample


# MPII joint order (0=r-ankle .. 9=head-top, 10=r-wrist .. 15=l-wrist):
# pairs whose identities exchange under a horizontal flip. The reference
# wrote a keypoint flip but disabled it with the comment "doesn't work with
# human pose estimation because it's orientation sensitive"
# (Hourglass/tensorflow/preprocess.py:31-40) — because it forgot exactly
# this swap: mirroring moves the LEFT ankle to where the RIGHT ankle's
# heatmap channel expects it. Swapping channel identities fixes that.
MPII_FLIP_PAIRS = ((0, 5), (1, 4), (2, 3), (10, 15), (11, 14), (12, 13))


class RandomHorizontalFlip:
    """p=0.5 flip (ResNet/pytorch/data_load.py:104-113). Flips normalized
    [x1,y1,x2,y2] 'boxes' too (random_flip_image_and_label,
    YOLO/tensorflow/preprocess.py:37-50).

    `keypoint_swap_pairs` (e.g. MPII_FLIP_PAIRS) additionally exchanges
    left/right joint identities — required for pose: without it a flip
    teaches every lateral channel the wrong side (the bug that made the
    reference disable its flip, preprocess.py:31-33)."""

    def __init__(self, p: float = 0.5,
                 keypoint_swap_pairs: Optional[Sequence] = None):
        self.p = p
        self.swap_pairs = keypoint_swap_pairs

    def __call__(self, sample: dict, rng: np.random.Generator) -> dict:
        if rng.random() >= self.p:
            return sample
        sample["image"] = sample["image"][:, ::-1]
        if "boxes" in sample and len(sample["boxes"]):
            b = np.array(sample["boxes"], np.float32)
            valid = b.any(axis=-1)  # all-zero rows are padding; leave them
            x1 = 1.0 - b[:, 2]
            x2 = 1.0 - b[:, 0]
            b[valid, 0], b[valid, 2] = x1[valid], x2[valid]
            sample["boxes"] = b
        if "keypoints" in sample and len(sample["keypoints"]):
            k = np.array(sample["keypoints"], np.float32)
            k[:, 0] = 1.0 - k[:, 0]
            if self.swap_pairs is not None:
                perm = np.arange(len(k))
                for a, b_ in self.swap_pairs:
                    perm[a], perm[b_] = b_, a
                k = k[perm]
                if "visibility" in sample:
                    sample["visibility"] = np.asarray(
                        sample["visibility"], np.float32
                    )[perm]
            sample["keypoints"] = k
        return sample


class CropRoi:
    """Keypoint-driven person crop for pose training
    (crop_roi, Hourglass/tensorflow/preprocess.py:43-88).

    The visible-keypoint extent, padded by `margin x body height`, is cut
    out before the square resize — so the person fills the frame instead of
    being a small figure in a wide shot. Body height comes from the MPII
    person 'scale' annotation (scale x 200 px, the MPII convention) when
    the sample carries it, else from the visible keypoint extent itself.

    `margin` may be a float (eval: the reference's fixed 0.2) or a (lo, hi)
    range sampled per image (train: the reference's U(0.1, 0.3) — its scale
    augmentation). Keypoints are remapped to crop-relative normalized
    coordinates, invisible (-1) joints ride along and land outside [0, 1],
    where the heatmap scatter already drops them (data/labels.py).
    """

    def __init__(self, margin=0.2):
        self.margin = margin

    def __call__(self, sample: dict, rng: np.random.Generator) -> dict:
        image = sample["image"]
        h, w = image.shape[:2]
        kp = np.asarray(sample["keypoints"], np.float32)  # (J, 2) normalized
        vis = np.asarray(
            sample.get("visibility", np.ones((len(kp),))), np.float32
        )
        kx, ky = kp[:, 0] * w, kp[:, 1] * h
        visible = vis > 0
        if not visible.any():
            return sample  # nothing to anchor the crop on
        if isinstance(self.margin, (tuple, list)):
            margin = float(rng.uniform(self.margin[0], self.margin[1]))
        else:
            margin = float(self.margin)
        xmin, xmax = kx[visible].min(), kx[visible].max()
        ymin, ymax = ky[visible].min(), ky[visible].max()
        if sample.get("scale", 0) and float(sample["scale"]) > 0:
            body_h = float(sample["scale"]) * 200.0  # MPII scale convention
        else:  # scale 0.0 = unknown (older preprocessed jsons)
            body_h = max(ymax - ymin, 1.0)
        pad = margin * body_h
        # clamp the top-left INSIDE the image: keypoints may sit outside the
        # frame (unclamped annotations), and an x1 >= w would make the
        # x2 = x1+1 fixup produce an empty slice that kills Resize downstream
        x1 = min(max(int(xmin - pad), 0), w - 1)
        y1 = min(max(int(ymin - pad), 0), h - 1)
        x2 = min(int(xmax + pad), w)
        y2 = min(int(ymax + pad), h)
        x2, y2 = max(x2, x1 + 1), max(y2, y1 + 1)
        sample["image"] = image[y1:y2, x1:x2]
        nh, nw = y2 - y1, x2 - x1
        out = kp.copy()
        out[:, 0] = (kx - x1) / nw
        out[:, 1] = (ky - y1) / nh
        # a visible joint cropped out (tight margin) must not scatter a
        # wrong-position gaussian: the [0,1] range check downstream drops it
        sample["keypoints"] = out
        return sample


class RandomCropWithBoxes:
    """Bbox-preserving random crop: the crop window always contains every box
    (random_crop_image_and_label, YOLO/tensorflow/preprocess.py:79-119).

    Boxes are normalized [x1,y1,x2,y2]; rows of zeros are padding and ignored.
    """

    def __call__(self, sample: dict, rng: np.random.Generator) -> dict:
        image = sample["image"]
        boxes = np.array(sample.get("boxes", ()), np.float32)
        h, w = image.shape[:2]
        valid = boxes.any(axis=-1) if len(boxes) else np.zeros((0,), bool)
        if valid.any():
            vb = boxes[valid]
            min_x1, min_y1 = vb[:, 0].min(), vb[:, 1].min()
            max_x2, max_y2 = vb[:, 2].max(), vb[:, 3].max()
        else:
            min_x1 = min_y1 = 1.0
            max_x2 = max_y2 = 0.0
        # sample crop edges outside the union of boxes
        left = rng.uniform(0.0, min(min_x1, 1.0))
        top = rng.uniform(0.0, min(min_y1, 1.0))
        right = rng.uniform(max(max_x2, 0.0), 1.0)
        bottom = rng.uniform(max(max_y2, 0.0), 1.0)
        x1p, y1p = int(left * w), int(top * h)
        x2p, y2p = max(int(right * w), x1p + 1), max(int(bottom * h), y1p + 1)
        sample["image"] = image[y1p:y2p, x1p:x2p]
        if len(boxes):
            nw, nh = (x2p - x1p) / w, (y2p - y1p) / h
            out = boxes.copy()
            out[valid, 0] = (boxes[valid, 0] - x1p / w) / nw
            out[valid, 2] = (boxes[valid, 2] - x1p / w) / nw
            out[valid, 1] = (boxes[valid, 1] - y1p / h) / nh
            out[valid, 3] = (boxes[valid, 3] - y1p / h) / nh
            sample["boxes"] = np.clip(out, 0.0, 1.0)
        return sample


class ColorJitter:
    """Brightness/contrast/saturation/hue jitter
    (ResNet/pytorch/data_load.py:213-296, PIL-based there; vectorized here)."""

    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0, hue=0.0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    @staticmethod
    def _factor(rng, amount):
        return float(rng.uniform(max(0.0, 1.0 - amount), 1.0 + amount))

    _LUMA = np.array([0.299, 0.587, 0.114], np.float32)

    def __call__(self, sample: dict, rng: np.random.Generator) -> dict:
        was_uint8 = sample["image"].dtype == np.uint8
        img = sample["image"].astype(np.float32)
        if was_uint8 or img.max() > 1.5:  # uint8 range
            scale = 255.0
        else:
            scale = 1.0
        rgb = img.shape[-1] == 3
        # brightness (img *= fb), contrast ((img - m) fc + m with m the mean
        # luma), and saturation ((img - gray) fs + gray) are each affine in
        # (img, gray, 1) and luma is linear, so their composition folds into
        # ONE pass out = A*img + B*gray0 + C — the host pipeline is CPU-bound
        # (SURVEY §7 hard part #1) and the naive chain costs 3x the memory
        # traffic. Factor draws stay in the b, c, s order for seed parity
        # with the sequential implementation.
        fb = self._factor(rng, self.brightness) if self.brightness else 1.0
        fc = self._factor(rng, self.contrast) if self.contrast else 1.0
        fs = (
            self._factor(rng, self.saturation)
            if self.saturation and rgb
            else 1.0
        )
        if fc != 1.0 or fs != 1.0:
            gray0 = img[..., :3] @ self._LUMA if rgb else img[..., 0]
            m = fb * float(gray0.mean()) if fc != 1.0 else 0.0
            a = fb * fc * fs
            b_coef = (1.0 - fs) * fb * fc
            c = (1.0 - fc) * m
            img = a * img + (b_coef * gray0 + c)[..., None]
        elif fb != 1.0:
            img = fb * img
        if self.hue and rgb:
            # hue rotation in YIQ space (cheap, differentiable-free host op)
            theta = float(rng.uniform(-self.hue, self.hue)) * 2 * np.pi
            u, w_ = np.cos(theta), np.sin(theta)
            t = np.array(
                [
                    [0.299 + 0.701 * u + 0.168 * w_, 0.587 - 0.587 * u + 0.330 * w_, 0.114 - 0.114 * u - 0.497 * w_],
                    [0.299 - 0.299 * u - 0.328 * w_, 0.587 + 0.413 * u + 0.035 * w_, 0.114 - 0.114 * u + 0.292 * w_],
                    [0.299 - 0.300 * u + 1.250 * w_, 0.587 - 0.588 * u - 1.050 * w_, 0.114 + 0.886 * u - 0.203 * w_],
                ],
                np.float32,
            )
            img = img @ t.T
        img = np.clip(img, 0.0, scale)
        # preserve dtype so a later ToFloat still rescales 0-255 -> 0-1
        sample["image"] = img.astype(np.uint8) if was_uint8 else img
        return sample


class ToFloat:
    """uint8 [0,255] -> float32 [0,1]; grayscale stays single-channel
    unless `expand_gray_to_rgb` (ToTensor's 3-channel expand,
    ResNet/pytorch/data_load.py:176-194 — layout conversion dropped: NHWC).
    `scale=False` keeps the 0-255 range (the TF mean-subtraction chain
    normalizes on that scale, ResNet/tensorflow/data_load.py:158-193)."""

    def __init__(self, expand_gray_to_rgb: bool = False, scale: bool = True):
        self.expand = expand_gray_to_rgb
        self.scale = scale

    def __call__(self, sample: dict, rng) -> dict:
        img = sample["image"]
        if img.dtype == np.uint8 and self.scale:
            img = img.astype(np.float32) / 255.0
        else:
            img = img.astype(np.float32)
        if img.ndim == 2:
            img = img[:, :, None]
        if self.expand and img.shape[-1] == 1:
            img = np.repeat(img, 3, axis=-1)
        sample["image"] = img
        return sample


class Normalize:
    """(x - mean) / std per channel (ResNet/pytorch/data_load.py:197-210)."""

    def __init__(self, mean=IMAGENET_MEAN, std=IMAGENET_STD):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, sample: dict, rng) -> dict:
        sample["image"] = (sample["image"] - self.mean) / self.std
        return sample


class ToFloatNormalize:
    """Fused ToFloat + Normalize: uint8 [0,255] -> (x/255 - mean) / std in
    ONE pass (x * 1/(255 std) - mean/std). The sequential pair costs two
    full-image float passes; the host pipeline is CPU-bound (SURVEY §7 hard
    part #1), so the fusion matters at ImageNet rates. Semantics match
    `ToFloat(expand_gray_to_rgb=e)` followed by `Normalize(mean, std)`.
    """

    def __init__(self, mean=IMAGENET_MEAN, std=IMAGENET_STD,
                 expand_gray_to_rgb: bool = False):
        std = np.asarray(std, np.float32)
        mean = np.asarray(mean, np.float32)
        self._scale_u8 = (1.0 / (255.0 * std)).astype(np.float32)
        self._scale_f = (1.0 / std).astype(np.float32)
        self._shift = (mean / std).astype(np.float32)
        self.expand = expand_gray_to_rgb

    def __call__(self, sample: dict, rng) -> dict:
        img = sample["image"]
        if img.ndim == 2:
            img = img[:, :, None]
        if self.expand and img.shape[-1] == 1:
            img = np.repeat(img, 3, axis=-1)
        scale = self._scale_u8 if img.dtype == np.uint8 else self._scale_f
        sample["image"] = img * scale - self._shift
        return sample


class MeanSubtract:
    """The TF "ResNet preprocessing" normalization variant: subtract per-
    channel means from a 0-255 image, no scaling (_mean_image_subtraction at
    ResNet/tensorflow/data_load.py:66-92; channel means 123.68/116.78/103.94
    at :35-38). Use instead of ToFloat+Normalize to reproduce the reference's
    TF training chain exactly."""

    def __init__(self, mean=None):
        self.mean = np.asarray(
            TF_IMAGENET_MEAN if mean is None else mean, np.float32
        )

    def __call__(self, sample: dict, rng) -> dict:
        img = sample["image"].astype(np.float32)
        if img.ndim == 2:
            img = img[:, :, None]
        if img.shape[-1] != self.mean.shape[0]:
            raise ValueError(
                f"image has {img.shape[-1]} channels, "
                f"mean has {self.mean.shape[0]}"
            )
        sample["image"] = img - self.mean
        return sample


class PadBoxes:
    """Pad/truncate 'boxes' (+aligned 'classes') to a fixed count — ragged ->
    static shapes for jit (the reference's TensorArray loops become masked
    scatters; max 100 boxes matches yolov3.py:452-454)."""

    def __init__(self, max_boxes: int = 100):
        self.max_boxes = max_boxes

    def __call__(self, sample: dict, rng) -> dict:
        boxes = np.array(sample.get("boxes", ()), np.float32).reshape(-1, 4)
        classes = np.array(sample.get("classes", ()), np.int32).reshape(-1)
        n = min(len(boxes), self.max_boxes)
        out_b = np.zeros((self.max_boxes, 4), np.float32)
        out_c = np.zeros((self.max_boxes,), np.int32)
        out_b[:n] = boxes[:n]
        out_c[:n] = classes[:n] if len(classes) else 0
        sample["boxes"] = out_b
        sample["classes"] = out_c
        return sample


def space_to_depth(image: np.ndarray, block: int = 2) -> np.ndarray:
    """(H, W, C) -> (H/b, W/b, b*b*C), channel order (dy, dx, c).

    The host half of the MLPerf-ResNet stem trick (models/resnet.py
    SpaceToDepthStem): laying the image out this way on the host turns the
    MXU-hostile 7x7/s2 3-channel stem conv into an efficient 4x4 conv.
    """
    h, w, c = image.shape
    assert h % block == 0 and w % block == 0, (h, w, block)
    out = image.reshape(h // block, block, w // block, block, c)
    return out.transpose(0, 2, 1, 3, 4).reshape(h // block, w // block,
                                                block * block * c)


class SpaceToDepth:
    """Pipeline transform: rewrite sample['image'] with `space_to_depth`."""

    def __init__(self, block: int = 2):
        self.block = block

    def __call__(self, sample: dict, rng) -> dict:
        sample["image"] = space_to_depth(np.asarray(sample["image"]), self.block)
        return sample
