"""Anomaly-triggered `jax.profiler` capture with cooldown and budget.

`--profile` used to mean "hope the interesting thing happens between
steps 10 and 20": the window was hard-coded, and a second start while a
trace was in flight would double-start the profiler. Production TPU
stacks (xprof-style on-demand capture) treat the anomaly itself as the
trigger: when the step time regresses, THAT window is the one worth the
~2x profiling overhead. This module is both modes behind one owner:

- **Static window** (`--profile-dir` + `--profile-window START:STOP`):
  capture exactly [START, STOP), configurable instead of 10:20, and
  tolerant of resuming past START (capture begins at the first step
  inside the window).
- **Auto policy** (`--autoprof`): rolling z-score on `step_time_ms` and
  `data_wait_ms`, recompile bursts between telemetry samples, and HBM
  high-water jumps each ARM a one-shot N-step capture that starts at
  the next step boundary. A cooldown and a per-run capture budget keep
  a sustained regression from profiling the whole run to death.

One capture at a time, process-wide: `jax.profiler` owns global state,
so a module-level latch guards re-entry no matter how many profilers or
trainers exist — a second trigger while a trace is in flight journals
`outcome=skipped_inflight` instead of crashing the profiler.

Every decision is a typed `profile_capture` journal event (reason +
outcome + step), so the journal answers "why does this run have three
trace dirs" without guessing: `started` / `captured` / `closed_early`
(a run that ended mid-capture — Trainer.close stops the trace instead
of leaking it) / `skipped_cooldown` / `skipped_budget` /
`skipped_inflight` / `failed`.
"""
from __future__ import annotations

import os
import threading
from collections import deque
from typing import Callable, Optional, Tuple

from deep_vision_tpu.obs.registry import Registry, get_registry

REASONS = ("static_window", "step_time_z", "data_wait_z",
           "recompile_burst", "hbm_jump", "manual")
OUTCOMES = ("started", "captured", "closed_early", "skipped_cooldown",
            "skipped_budget", "skipped_inflight", "failed")

# jax.profiler is process-global: exactly one trace may be in flight no
# matter how many AutoProfiler instances exist (trainer + a tool, tests)
_capture_lock = threading.Lock()
_capture_active = False


def _release_capture() -> None:
    global _capture_active
    with _capture_lock:
        _capture_active = False


class AutoProfiler:
    """Owner of profiler captures for one run.

    Wiring (what Trainer does):

        prof.on_step_start(step)        # before dispatch, every step
        ... run the step ...
        prof.observe_step(step, rec.fields())   # after commit
        ...
        prof.close()                    # stops an in-flight capture

    `fence` (set by the trainer) is called before `stop_trace` so the
    device pipeline drains into the trace instead of being cut off
    mid-flight.
    """

    def __init__(
        self,
        profile_dir: str,
        journal=None,
        registry: Optional[Registry] = None,
        window: Optional[Tuple[int, int]] = None,  # static [start, stop)
        auto: bool = False,
        window_steps: int = 8,       # auto-capture length
        cooldown_steps: int = 200,
        max_captures: int = 2,       # auto-capture budget per run
        z_threshold: float = 5.0,
        history: int = 64,
        min_history: int = 16,
        recompile_burst: int = 3,
        hbm_jump_frac: float = 0.25,
    ):
        if window is not None:
            start, stop = int(window[0]), int(window[1])
            if not 0 <= start < stop:
                raise ValueError(
                    f"profile window must be 0 <= start < stop, got "
                    f"{start}:{stop}")
            window = (start, stop)
        self.profile_dir = profile_dir
        self.journal = journal
        self.registry = registry or get_registry()
        self.window = window
        self.auto = bool(auto)
        self.window_steps = max(1, int(window_steps))
        self.cooldown_steps = max(0, int(cooldown_steps))
        self.max_captures = max(0, int(max_captures))
        self.z_threshold = float(z_threshold)
        self.min_history = max(2, int(min_history))
        self.recompile_burst = max(1, int(recompile_burst))
        self.hbm_jump_frac = float(hbm_jump_frac)
        #: trainer-set: drains the device pipeline before stop_trace
        self.fence: Optional[Callable[[], None]] = None

        self._step_times: deque = deque(maxlen=int(history))
        self._data_waits: deque = deque(maxlen=int(history))
        self._last_recompiles: Optional[int] = None
        self._hbm_high_water: Optional[int] = None

        self._steps = 0                 # last step index seen
        self._static_pending = window is not None
        self._armed: Optional[Tuple[str, dict]] = None
        self._capturing = False
        self._capture_reason = ""
        self._capture_dir = ""
        self._capture_start = 0
        self._stop_at = 0
        self._captures = 0              # auto captures started (budget)
        self._cooldown_until = 0
        self._skip_latched = False      # one skipped_cooldown per cooldown
        self._budget_latched = False    # one skipped_budget per run
        self._seq = 0
        self._closed = False

        r = self.registry
        self._c_captures = r.counter("autoprof_captures_total",
                                     "profiler captures started")
        self._c_triggers = r.counter("autoprof_triggers_total",
                                     "anomaly triggers observed (incl. "
                                     "skipped ones)")

    # -- step boundary hooks ------------------------------------------------

    @property
    def capturing(self) -> bool:
        return self._capturing

    @property
    def needs_step_index(self) -> bool:
        """True while on_step_start needs the REAL optimizer step (a
        pending static window must anchor to it, e.g. after a resume).
        Otherwise the internal counter — recalibrated by every
        observe_step — suffices, and callers can skip the blocking
        device fetch the real index costs (see Trainer._profiler_hook)."""
        return self._static_pending

    def on_step_start(self, step: Optional[int] = None) -> None:
        """Called before each step's dispatch: starts a due capture, stops
        a finished one. `step` defaults to an internal counter for loops
        that would pay a device sync to know it."""
        if self._closed:
            return
        # counterless callers advance the internal counter here; callers
        # that DO pass (or later observe) the real optimizer step
        # recalibrate it, so the two styles can mix within one run
        step = self._steps + 1 if step is None else int(step)
        self._steps = step
        if self._capturing:
            if step >= self._stop_at:
                self._stop(step, "captured")
            return
        if (self._static_pending and self.window is not None
                and self.window[0] <= step < self.window[1]):
            # pending until a start SUCCEEDS: a failed start (unwritable
            # dir) or one skipped while another capture holds the latch
            # retries at the next step still inside the window, instead of
            # silently dropping the user's explicit capture request
            if self._start(step, "static_window", stop_at=self.window[1]):
                self._static_pending = False
            return
        if self._static_pending and self.window is not None \
                and step >= self.window[1]:
            self._static_pending = False  # window over: stop re-anchoring
        if self._armed is not None:
            reason, detail = self._armed
            self._armed = None
            self._start(step, reason, stop_at=step + self.window_steps,
                        **detail)

    def observe_step(self, step: int, fields: dict) -> None:
        """Feed one committed step record (StepClock `rec.fields()`);
        evaluates the anomaly triggers and arms a capture when one fires
        outside cooldown and under budget."""
        if self._closed:
            return
        self._steps = int(step)
        if self._capturing or not self.auto:
            # captured steps run ~2x slow under the profiler: keeping them
            # out of the baseline windows stops one capture from making
            # every following step look fast
            return
        st = _num(fields.get("step_time_ms"))
        dw = _num(fields.get("data_wait_ms"))
        trigger: Optional[Tuple[str, dict]] = None

        z = _zscore(self._step_times, st, self.min_history)
        if z is not None and z > self.z_threshold:
            trigger = ("step_time_z",
                       {"z": round(z, 2), "value_ms": round(st, 3)})
        else:
            zw = _zscore(self._data_waits, dw, self.min_history)
            if zw is not None and zw > self.z_threshold:
                trigger = ("data_wait_z",
                           {"z": round(zw, 2), "value_ms": round(dw, 3)})

        rc = fields.get("recompiles")
        if rc is not None:
            if (trigger is None and self._last_recompiles is not None
                    and rc - self._last_recompiles >= self.recompile_burst):
                trigger = ("recompile_burst",
                           {"new_compiles": int(rc - self._last_recompiles)})
            self._last_recompiles = int(rc)

        hbm = fields.get("hbm_peak_bytes", fields.get("hbm_bytes"))
        if hbm is not None:
            hw = self._hbm_high_water
            if (trigger is None and hw is not None and hw > 0
                    and hbm > hw * (1.0 + self.hbm_jump_frac)):
                trigger = ("hbm_jump", {"peak_bytes": int(hbm),
                                        "prev_high_water": int(hw)})
            self._hbm_high_water = max(int(hbm), hw or 0)

        # spiking values stay OUT of the baselines (the health monitor's
        # trick): admitting them would inflate the std until the very
        # regressions being hunted stop registering
        if trigger is None or trigger[0] != "step_time_z":
            if st is not None:
                self._step_times.append(st)
        if trigger is None or trigger[0] != "data_wait_z":
            if dw is not None:
                self._data_waits.append(dw)
        if trigger is not None:
            self._request(step, trigger[0], trigger[1])

    # -- capture control ----------------------------------------------------

    def _request(self, step: int, reason: str, detail: dict) -> None:
        self._c_triggers.inc()
        if self._captures >= self.max_captures:
            if not self._budget_latched:
                self._budget_latched = True
                self._journal(reason, "skipped_budget", step=step,
                              budget=self.max_captures, **detail)
            return
        if step < self._cooldown_until:
            if not self._skip_latched:
                self._skip_latched = True
                self._journal(reason, "skipped_cooldown", step=step,
                              cooldown_until=self._cooldown_until, **detail)
            return
        if self._armed is None:
            self._armed = (reason, detail)

    def _start(self, step: int, reason: str, stop_at: int,
               **detail) -> bool:
        global _capture_active
        with _capture_lock:
            if _capture_active:
                self._journal(reason, "skipped_inflight", step=step,
                              **detail)
                return False
            _capture_active = True
        self._seq += 1
        d = os.path.join(self.profile_dir, f"cap-{self._seq:03d}-{reason}")
        try:
            import jax

            os.makedirs(d, exist_ok=True)
            jax.profiler.start_trace(d)
        except Exception as e:
            _release_capture()
            self._journal(reason, "failed", step=step,
                          error=f"{type(e).__name__}: {e}", **detail)
            return False
        self._capturing = True
        self._capture_reason = reason
        self._capture_dir = d
        self._capture_start = step
        self._stop_at = int(stop_at)
        if reason != "static_window":
            self._captures += 1  # explicit windows don't spend the budget
        self._c_captures.inc()
        self._journal(reason, "started", step=step, dir=d,
                      stop_at=self._stop_at, **detail)
        return True

    def _stop(self, step: Optional[int], outcome: str) -> None:
        try:
            if self.fence is not None:
                self.fence()
        except Exception:
            pass
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
        finally:
            _release_capture()
        self._capturing = False
        end = self._steps if step is None else int(step)
        if self._capture_reason != "static_window":
            # like the budget, the cooldown is spent only by TRIGGERED
            # captures: an explicitly requested static window must not
            # blind the anomaly policy for cooldown_steps after it ends
            self._cooldown_until = end + self.cooldown_steps
            self._skip_latched = False
        self._journal(self._capture_reason, outcome, step=end,
                      dir=self._capture_dir,
                      captured_steps=max(0, end - self._capture_start))

    def interrupt(self) -> None:
        """Stop an in-flight capture without disabling the profiler (the
        epoch-driver teardown path); idempotent."""
        if self._capturing:
            self._stop(None, "closed_early")

    def close(self) -> None:
        """Terminal: stop any in-flight capture and refuse further work.
        Safe to call twice (Trainer.close is idempotent)."""
        self.interrupt()
        self._closed = True

    # -- journal ------------------------------------------------------------

    def _journal(self, reason: str, outcome: str, **fields) -> None:
        if self.journal is not None:
            try:
                self.journal.write("profile_capture", reason=reason,
                                   outcome=outcome, **fields)
            except Exception:
                pass


def _num(v) -> Optional[float]:
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def _zscore(window: deque, value: Optional[float],
            min_history: int) -> Optional[float]:
    if value is None or len(window) < min_history:
        return None
    mean = sum(window) / len(window)
    var = sum((x - mean) ** 2 for x in window) / len(window)
    std = var ** 0.5
    return (value - mean) / max(std, 1e-9)
