"""Perf smoke: the CPU-provable contracts behind the step-time attack.

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/perf_smoke.py \
        [--workdir artifacts/perf_smoke]

The CI teeth behind the perf layer (`make perf-smoke`, a `make verify`
prerequisite) the way obs-smoke gates obs/ and chaos-smoke gates
resilience/. The on-TPU acceptance for this arc is a bench delta
(vs_baseline >= 1.0 wall, mfu_device_pct >= 40); these are the proxies
that must hold on ANY backend before that bench is even worth running:

  1. fused kernels   ops/pallas/bn_act.py (scale-bias+ReLU+residual) and
                     ops/pallas/nms.py run under interpret=True and must
                     match their pure-lax references — values AND grads
                     for bn_act, exact index/score agreement for NMS
                     through the full class-aware non_maximum_suppression.
  2. multistep       a Trainer(multistep=4) superstep over 4 stacked
                     batches must land within float-ulp of 4 single-step
                     dispatches (same params, same per-microstep losses),
                     with step counters advanced identically.
  3. dispatch math   a journal-wired multistep=4 run must show 4x fewer
                     step events than optimizer steps (one dispatch per K
                     microsteps), each stamped multistep=4, and ZERO
                     backend recompiles after the first superstep across
                     the whole window (tail single-steps excluded: they
                     own one compile of their own executable).
  4. device prefetch a DevicePrefetcher at depth 2 feeding a slower
                     consumer must never starve (starvation counter 0);
                     a depth-1 buffer against a slow producer must.
  5. schema          the journal (multistep step fields + a bench event
                     carrying the new wall/device-ms fields) passes
                     `check_journal --strict` — extended fields are
                     forward-compatible, not schema violations.

Exit status 0 = every contract held; 1 = something broke.
"""
from __future__ import annotations

import argparse
import os
import shutil
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


class Failures:
    def __init__(self):
        self.rows = []

    def check(self, ok: bool, what: str):
        print(("PASS " if ok else "FAIL ") + what, flush=True)
        if not ok:
            self.rows.append(what)


def phase1_fused_kernels(f: Failures):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.ops.nms import non_maximum_suppression
    from deep_vision_tpu.ops.pallas.bn_act import (
        fused_scale_bias_act,
        reference_scale_bias_act,
    )

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 8, 8, 128).astype(np.float32))
    res = jnp.asarray(rng.randn(2, 8, 8, 128).astype(np.float32))
    a = jnp.asarray(rng.rand(128).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(128).astype(np.float32))
    got = fused_scale_bias_act(x, a, b, residual=res, act="relu",
                               interpret=True)
    want = reference_scale_bias_act(x, a, b, residual=res, act="relu")
    f.check(np.allclose(np.asarray(got), np.asarray(want), atol=1e-6),
            "bn_act: fused fwd matches lax reference")

    def loss_f(fn):
        return lambda *args: jnp.sum(
            fn(args[0], args[1], args[2], residual=args[3], act="relu") ** 2)

    g1 = jax.grad(loss_f(fused_scale_bias_act), argnums=(0, 1, 2, 3))(
        x, a, b, res)
    g2 = jax.grad(loss_f(reference_scale_bias_act), argnums=(0, 1, 2, 3))(
        x, a, b, res)
    ok = all(np.allclose(np.asarray(u), np.asarray(v), atol=2e-5)
             for u, v in zip(g1, g2))
    f.check(ok, "bn_act: custom-vjp grads match lax reference (x, scale, "
                "bias, residual)")

    xy = rng.rand(2, 300, 2).astype(np.float32) * 0.8
    wh = rng.rand(2, 300, 2).astype(np.float32) * 0.2 + 0.02
    boxes = jnp.asarray(np.concatenate([xy, xy + wh], -1))
    scores = jnp.asarray(rng.rand(2, 300).astype(np.float32))
    classes = jnp.asarray(rng.randint(0, 7, size=(2, 300)).astype(np.int32))
    kw = dict(max_detections=32, iou_threshold=0.5, score_threshold=0.3)
    lax_out = non_maximum_suppression(boxes, scores, classes, impl="lax",
                                      **kw)
    pal_out = non_maximum_suppression(boxes, scores, classes, impl="pallas",
                                      **kw)
    ok = all(np.array_equal(np.asarray(u), np.asarray(v))
             for u, v in zip(lax_out, pal_out))
    f.check(ok, "nms: pallas kernel selections EXACTLY match the lax loop "
                "(boxes/scores/classes/valid)")


def _make_trainer(multistep: int, journal=None, registry=None):
    import jax.numpy as jnp

    from deep_vision_tpu.losses import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train import Trainer, build_optimizer

    model = get_model("lenet5", num_classes=4)
    tx = build_optimizer("sgd", 0.05, momentum=0.9)
    return Trainer(model, tx, classification_loss_fn,
                   sample_input=jnp.zeros((8, 32, 32, 1)),
                   multistep=multistep, journal=journal, registry=registry)


def _batches(n, bs=32, seed=0):
    import numpy as np

    rng = np.random.RandomState(seed)
    return [{"image": rng.rand(bs, 32, 32, 1).astype(np.float32),
             "label": rng.randint(0, 4, size=bs)} for _ in range(n)]


def phase2_multistep_equivalence(f: Failures):
    import jax
    import numpy as np

    batches = _batches(4)
    t1 = _make_trainer(1)
    t4 = _make_trainer(4)
    singles = [t1.train_step(b) for b in batches]
    stacked = t4.train_superstep(batches)
    p1 = jax.device_get(t1.state.params)
    p4 = jax.device_get(t4.state.params)
    diffs = jax.tree_util.tree_leaves(jax.tree_util.tree_map(
        lambda u, v: float(np.abs(u - v).max()), p1, p4))
    f.check(max(diffs) <= 1e-6,
            f"multistep: params after 1 superstep == 4 single steps "
            f"(max leaf diff {max(diffs):.2e} <= 1e-6)")
    losses_ok = all(
        abs(float(singles[i]["loss"]) - float(stacked[i]["loss"])) <= 1e-5
        for i in range(4))
    f.check(losses_ok, "multistep: per-microstep losses recovered from the "
                       "scan stack match the single-step series")
    f.check(int(t1.state.step) == int(t4.state.step) == 4,
            "multistep: step counter advanced by K in one dispatch")


def phase3_dispatch_and_recompiles(f: Failures, workdir: str):
    import json
    import subprocess

    from deep_vision_tpu.obs.journal import RunJournal
    from deep_vision_tpu.obs.registry import Registry
    from deep_vision_tpu.obs.stepclock import recompile_count

    jpath = os.path.join(workdir, "perf_smoke.jsonl")
    with RunJournal(jpath, kind="train") as journal:
        journal.manifest(config={"tool": "perf_smoke", "multistep": 4})
        t = _make_trainer(4, journal=journal, registry=Registry())
        batches = _batches(16, seed=1)
        # epoch 1 owns the one allowed compile (superstep executable);
        # epoch 2 re-runs the same shapes and must be compile-free
        t.fit(lambda: iter(batches), epochs=1, handle_preemption=False)
        before = recompile_count()
        t.fit(lambda: iter(batches), epochs=2, start_epoch=1,
              handle_preemption=False)
        delta = recompile_count() - before
        f.check(delta == 0,
                f"multistep: ZERO recompiles across the second multistep "
                f"window (saw {delta})")
        f.check(int(t.state.step) == 32,
                "multistep: 32 optimizer steps from 8 dispatches")
        # bench event with the NEW fields (wall/device per-step ms,
        # dispatch arithmetic) — the schema must accept them
        journal.bench("resnet50_train", {
            "metric": "resnet50_train_images_per_sec_per_chip",
            "value": 0.0, "vs_baseline": 0.0, "multistep": 4,
            "wall_ms_per_step": 1.0, "device_ms_per_step": 0.9,
            "dispatches_per_window": 150, "steps_per_dispatch": 4,
        })
    rows = [json.loads(line) for line in open(jpath)]
    steps = [r for r in rows if r["event"] == "step"]
    f.check(len(steps) == 8 and all(r.get("multistep") == 4 for r in steps),
            "journal: one step event per dispatch, each stamped multistep=4 "
            f"(saw {len(steps)} events for 32 steps — 4x fewer dispatches)")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "check_journal.py"),
         jpath, "--strict"], capture_output=True, text=True)
    f.check(proc.returncode == 0,
            "journal: check_journal --strict accepts the multistep step "
            f"fields and extended bench event ({proc.stdout.strip()!r})")


def phase4_device_prefetch(f: Failures):
    import time

    from deep_vision_tpu.data.device_prefetch import (
        DevicePrefetcher,
        PlacedBatch,
    )
    from deep_vision_tpu.obs.registry import Registry

    reg = Registry()

    def place(b):
        return PlacedBatch(b, 1, 1)

    # fast producer, slow consumer, depth 2: never starves
    pf = DevicePrefetcher(place_one=place, depth=2, name="smoke", registry=reg)
    for _ in pf(iter(range(20))):
        time.sleep(0.002)
    starved = reg.counter("device_prefetch_starved_total",
                          labels={"loader": "smoke"}).value
    f.check(starved == 0,
            f"device prefetch: depth-2 buffer never starves a slower "
            f"consumer (starved={starved})")

    def slow_src():
        for i in range(10):
            time.sleep(0.01)
            yield i

    pf2 = DevicePrefetcher(place_one=place, depth=1, name="smoke2",
                           registry=reg)
    list(pf2(slow_src()))
    starved2 = reg.counter("device_prefetch_starved_total",
                           labels={"loader": "smoke2"}).value
    f.check(starved2 > 0,
            f"device prefetch: a slow producer IS visible as starvation "
            f"(starved={starved2}) — the gauge is live, not decorative")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default="artifacts/perf_smoke")
    args = p.parse_args(argv)
    shutil.rmtree(args.workdir, ignore_errors=True)
    os.makedirs(args.workdir, exist_ok=True)

    f = Failures()
    print("== phase 1: fused-kernel parity (interpret mode) ==", flush=True)
    phase1_fused_kernels(f)
    print("== phase 2: scan-multistep equivalence ==", flush=True)
    phase2_multistep_equivalence(f)
    print("== phase 3: dispatch amortization + zero recompiles ==",
          flush=True)
    phase3_dispatch_and_recompiles(f, args.workdir)
    print("== phase 4: device-prefetch overlap ==", flush=True)
    phase4_device_prefetch(f)

    if f.rows:
        print(f"\nperf-smoke: {len(f.rows)} contract(s) FAILED:")
        for r in f.rows:
            print("  - " + r)
        return 1
    print("\nperf-smoke: all contracts held")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
