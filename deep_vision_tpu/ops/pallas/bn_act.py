"""Fused per-channel scale-bias + activation (+ residual add) Pallas kernel.

The ResNet/VGG hot path applies BatchNorm, adds the skip tensor, and takes a
ReLU — three elementwise passes XLA usually fuses into the conv epilogue,
but the profiled flagship step still shows separate normalize/add/relu
fusions around the residual joins (the bf16 activation crosses HBM once per
pass). This kernel does the whole tail in ONE pass through VMEM:

    y = act(x * scale + bias [+ residual])

with `scale`/`bias` per channel (the folded BN apply: scale = gamma *
rsqrt(var + eps), bias = beta - mean * scale). The big tensor is read once
and written once; compute happens in f32 inside the kernel regardless of the
io dtype, so bf16 activations lose no precision to the folding.

Three implementations, one contract:
  - the Pallas TPU kernel (compiled on TPU, `interpret=True` elsewhere so
    CPU tier-1 tests exercise the real kernel code);
  - `reference_scale_bias_act`, the pure-lax twin used for parity tests and
    as the fallback when the channel layout can't tile (C not a power-of-two
    multiple/divisor of the 128-lane width);
  - the unfused module path in nn/layers.py, which stays byte-identical to
    the pre-kernel code when fusion is disabled.

Differentiable via custom_vjp: the forward is the Pallas kernel, the
backward is a handful of lax reductions (dx = g*mask*scale is elementwise;
dscale/dbias are per-channel sums XLA reduces well — the win is the fwd
pass, which runs once more in recompute-free form because y is saved).

Enable/disable: `fusion_enabled()` — on by default on TPU backends, off
elsewhere; `DVT_PALLAS_FUSED=1/0` forces either way (the config flag the
bench A/B and a suspicious-numerics triage reach for).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deep_vision_tpu.core import backend as dvt_backend
from deep_vision_tpu.core import knobs

_LANES = 128
_BLOCK_ROWS = 256  # rows of the (R, C) view per grid step


def fusion_enabled() -> bool:
    """Should the fused Pallas path run? Pallas-compiled backends: yes
    unless DVT_PALLAS_FUSED=0; elsewhere: only if DVT_PALLAS_FUSED=1
    (tests force it; the default CPU path keeps the exact pre-kernel
    arithmetic so goldens never drift)."""
    forced = knobs.get_flag("DVT_PALLAS_FUSED")
    if forced is not None:
        return forced
    return dvt_backend.get_backend().pallas_compiled


def reference_scale_bias_act(x, scale, bias, residual=None,
                             act: Optional[str] = "relu"):
    """Pure-lax reference: same folded arithmetic as the kernel (f32
    compute, io dtype out). The parity target AND the non-tileable-layout
    fallback."""
    y = x.astype(jnp.float32) * scale.astype(jnp.float32) + bias.astype(
        jnp.float32)
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    elif act is not None:
        raise ValueError(f"unsupported act {act!r}")
    return y.astype(x.dtype)


def _kernel(x_ref, a_ref, b_ref, o_ref, *, act: Optional[str],
            has_residual: bool, r_ref=None):
    x = x_ref[...].astype(jnp.float32)
    y = x * a_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    if has_residual:
        y = y + r_ref[...].astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    o_ref[...] = y.astype(o_ref.dtype)


def _kernel_res(x_ref, r_ref, a_ref, b_ref, o_ref, *, act):
    _kernel(x_ref, a_ref, b_ref, o_ref, act=act, has_residual=True,
            r_ref=r_ref)


def _lane_layout(c: int):
    """(lane_c, repeat): reshape the flat (R*C,) stream to rows of
    `lane_c = lcm-ish` channels so per-channel params are constant per lane.

    C a multiple of 128 -> rows of C; C a divisor of 128 -> rows of 128
    covering 128//C samples each (params tiled across the lanes). Returns
    None when neither holds — caller falls back to the lax reference.
    """
    if c % _LANES == 0:
        return c, 1
    if _LANES % c == 0:
        return _LANES, _LANES // c
    return None


def _pallas_apply(x, scale, bias, residual, act: str | None,
                  interpret: bool):
    """Run the kernel on the (R, lane_c) row view; assumes _lane_layout
    accepted C and total elements divide lane_c."""
    c = x.shape[-1]
    lane_c, repeat = _lane_layout(c)
    total = x.size
    rows = total // lane_c
    x2 = x.reshape(rows, lane_c)
    a2 = jnp.tile(scale.astype(jnp.float32), repeat).reshape(1, lane_c)
    b2 = jnp.tile(bias.astype(jnp.float32), repeat).reshape(1, lane_c)
    block_r = min(_BLOCK_ROWS, rows)
    grid = (pl.cdiv(rows, block_r),)
    row_spec = pl.BlockSpec((block_r, lane_c), lambda i: (i, 0))
    par_spec = pl.BlockSpec((1, lane_c), lambda i: (0, 0))
    if residual is not None:
        out = pl.pallas_call(
            functools.partial(_kernel_res, act=act),
            out_shape=jax.ShapeDtypeStruct((rows, lane_c), x.dtype),
            grid=grid,
            in_specs=[row_spec, row_spec, par_spec, par_spec],
            out_specs=row_spec,
            interpret=interpret,
        )(x2, residual.reshape(rows, lane_c), a2, b2)
    else:
        out = pl.pallas_call(
            functools.partial(_kernel, act=act, has_residual=False),
            out_shape=jax.ShapeDtypeStruct((rows, lane_c), x.dtype),
            grid=grid,
            in_specs=[row_spec, par_spec, par_spec],
            out_specs=row_spec,
            interpret=interpret,
        )(x2, a2, b2)
    return out.reshape(x.shape)


# -- differentiable wrappers (one per arity so `residual=None` never ships a
# zeros tensor through HBM just to satisfy a uniform signature) -------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused3(x, scale, bias, act, interpret):
    return _pallas_apply(x, scale, bias, None, act, interpret)


def _fused3_fwd(x, scale, bias, act, interpret):
    y = _pallas_apply(x, scale, bias, None, act, interpret)
    return y, (x, scale, bias, y)


def _bwd_common(x, scale, y, g, act):
    gf = g.astype(jnp.float32)
    if act == "relu":
        gf = jnp.where(y > 0, gf, 0.0)
    axes = tuple(range(x.ndim - 1))
    dx = (gf * scale.astype(jnp.float32)).astype(x.dtype)
    dscale = jnp.sum(gf * x.astype(jnp.float32), axis=axes)
    dbias = jnp.sum(gf, axis=axes)
    return gf, dx, dscale.astype(scale.dtype), dbias


def _fused3_bwd(act, interpret, res, g):
    x, scale, bias, y = res
    _, dx, dscale, dbias = _bwd_common(x, scale, y, g, act)
    return dx, dscale, dbias.astype(bias.dtype)


_fused3.defvjp(_fused3_fwd, _fused3_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _fused4(x, scale, bias, residual, act, interpret):
    return _pallas_apply(x, scale, bias, residual, act, interpret)


def _fused4_fwd(x, scale, bias, residual, act, interpret):
    y = _pallas_apply(x, scale, bias, residual, act, interpret)
    return y, (x, scale, bias, y)


def _fused4_bwd(act, interpret, res, g):
    x, scale, bias, y = res
    gf, dx, dscale, dbias = _bwd_common(x, scale, y, g, act)
    return dx, dscale, dbias.astype(bias.dtype), gf.astype(x.dtype)


_fused4.defvjp(_fused4_fwd, _fused4_bwd)


def fused_scale_bias_act(x, scale, bias, residual=None,
                         act: Optional[str] = "relu",
                         interpret: Optional[bool] = None):
    """y = act(x * scale + bias [+ residual]), one fused pass.

    x: (..., C); scale/bias: (C,) — the folded BN apply; residual: same
    shape as x or None. act: 'relu' or None. Differentiable in x, scale,
    bias, residual. Layouts whose C neither divides nor is divided by the
    128-lane width fall back to the lax reference (same math, same vjp
    structure via jax autodiff).
    """
    if act not in ("relu", None):
        raise ValueError(f"unsupported act {act!r}")
    if interpret is None:
        interpret = dvt_backend.pallas_interpret()
    c = x.shape[-1]
    if scale.shape != (c,) or bias.shape != (c,):
        raise ValueError(
            f"scale/bias must be ({c},), got {scale.shape}/{bias.shape}")
    lane = _lane_layout(c)
    if lane is None or x.size % lane[0] != 0:
        return reference_scale_bias_act(x, scale, bias, residual, act)
    if residual is not None:
        if residual.shape != x.shape:
            raise ValueError(
                f"residual shape {residual.shape} != x shape {x.shape}")
        return _fused4(x, scale, bias, residual, act, bool(interpret))
    return _fused3(x, scale, bias, act, bool(interpret))


def fused_bn_act(x, mean, var, gamma, beta, *, epsilon: float = 1e-5,
                 residual=None, act: Optional[str] = "relu",
                 interpret: Optional[bool] = None):
    """BN-apply + act (+ residual) from raw statistics: folds (mean, var,
    gamma, beta) to per-channel (scale, bias) — two (C,)-sized ops — then
    runs the fused kernel over the big tensor."""
    inv = gamma.astype(jnp.float32) * jax.lax.rsqrt(
        var.astype(jnp.float32) + epsilon)
    b = beta.astype(jnp.float32) - mean.astype(jnp.float32) * inv
    return fused_scale_bias_act(x, inv, b, residual=residual, act=act,
                                interpret=interpret)
