"""Inference CLI (the demo-notebook/inference.py analog) across tasks."""
import os

import cv2
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # jit-heavy: excluded from the fast tier (`-m "not slow"`)


@pytest.fixture()
def jpg(tmp_path):
    img = (np.random.RandomState(0).rand(300, 400, 3) * 255).astype(np.uint8)
    path = str(tmp_path / "img.jpg")
    cv2.imwrite(path, img)
    return path


def test_infer_classification(jpg, capsys):
    from deep_vision_tpu.tools.infer import main

    rc = main(["-m", "lenet5", jpg])
    assert rc == 0
    out = capsys.readouterr().out
    assert "class" in out and jpg in out


def test_infer_classification_s2d_stem(jpg, capsys):
    """resnet50's config uses stem='s2d'; infer must feed (112,112,12)."""
    from deep_vision_tpu.tools.infer import main

    rc = main(["-m", "resnet50", jpg])
    assert rc == 0
    assert "class" in capsys.readouterr().out


def test_infer_vit(jpg, capsys):
    """The attention family rides the same classification infer path."""
    from deep_vision_tpu.tools.infer import main

    rc = main(["-m", "vit_s16", jpg])
    assert rc == 0
    assert "class" in capsys.readouterr().out


def test_infer_detection_writes_sidecar(jpg, tmp_path, capsys):
    from deep_vision_tpu.tools.infer import main

    rc = main(["-m", "yolov3_voc", "-o", str(tmp_path / "out"),
               "--score-threshold", "0.05", jpg])
    assert rc == 0
    assert "detections" in capsys.readouterr().out
    assert os.path.exists(tmp_path / "out" / "img_boxes.txt")
    # rendered overlay (demo_mscoco.ipynb parity): a real decodable JPEG
    drawn = cv2.imread(str(tmp_path / "out" / "img_detected.jpg"))
    assert drawn is not None and drawn.shape[2] == 3


def test_infer_pose(jpg, capsys):
    from deep_vision_tpu.tools.infer import main

    rc = main(["-m", "hourglass_mpii", jpg])
    assert rc == 0
    assert "joint 0:" in capsys.readouterr().out
    # skeleton overlay written next to the input
    drawn = cv2.imread(jpg.replace(".jpg", "_pose.jpg"))
    assert drawn is not None and drawn.shape[2] == 3


def test_infer_cyclegan_saves_image(jpg, tmp_path, capsys):
    from deep_vision_tpu.tools.infer import main

    rc = main(["-m", "cyclegan", "-o", str(tmp_path / "gen"), jpg])
    assert rc == 0
    dst = tmp_path / "gen" / "img_generated.jpg"
    assert dst.exists()
    out = cv2.imread(str(dst))
    assert out is not None and out.shape[-1] == 3


def test_infer_restores_trained_checkpoint(jpg, tmp_path, capsys):
    """The -c path must load trained weights, not re-init."""
    from deep_vision_tpu.train_cli import main as train_main
    from deep_vision_tpu.tools.infer import main

    ck = str(tmp_path / "ck")
    rc = train_main(["-m", "lenet5", "--fake-data", "--epochs", "1",
                     "--batch-size", "8", "--fake-batches", "1",
                     "--ckpt-dir", ck])
    assert rc == 0
    rc = main(["-m", "lenet5", "-c", ck, jpg])
    assert rc == 0
    assert "class" in capsys.readouterr().out
