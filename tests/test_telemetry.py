"""Live telemetry plane (obs/telemetry.py) + cross-process trace context
(obs/propagate.py): endpoint contracts, health flips, respawn survival,
propagation round-trips, and the journal schema drift guards."""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deep_vision_tpu.obs import RunJournal, locksmith, propagate, read_journal
from deep_vision_tpu.obs.registry import Registry
from deep_vision_tpu.obs.telemetry import (
    DISCOVERY_PREFIX,
    TELEMETRY_OUTCOMES,
    TelemetryServer,
    read_discovery,
    validate_prometheus,
)


def get(address, path, timeout=5.0):
    """(status, content_type, body_text); HTTP errors return their code."""
    try:
        with urllib.request.urlopen(f"http://{address}{path}",
                                    timeout=timeout) as resp:
            return (resp.status, resp.headers.get("Content-Type", ""),
                    resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type", ""), \
            e.read().decode("utf-8")


@pytest.fixture
def tele(tmp_path):
    reg = Registry()
    j = RunJournal(str(tmp_path / "run.jsonl"), kind="train")
    t = TelemetryServer(port=0, role="test", registry=reg, journal=j,
                        discovery_dir=str(tmp_path))
    t.registry_ref = reg  # test convenience
    t.journal_ref = j
    t.start()
    yield t
    t.close()
    if not j._closed:
        j.close()


# -- propagate: W3C-shaped trace context --------------------------------------

class TestPropagate:
    def test_traceparent_round_trip(self):
        ctx = propagate.new_trace()
        tp = ctx.to_traceparent()
        assert tp.startswith("00-")
        back = propagate.from_traceparent(tp)
        assert back is not None
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id
        # bytes form (the data-service frame carries bytes)
        assert propagate.from_traceparent(tp.encode()) == back

    def test_child_links_parent(self):
        root = propagate.new_trace()
        child = root.child()
        assert child.trace_id == root.trace_id
        assert child.parent_span_id == root.span_id
        assert child.span_id != root.span_id
        assert "parent_span_id" in child.fields()
        assert "parent_span_id" not in root.fields()

    @pytest.mark.parametrize("garbage", [
        "", "nonsense", b"", b"\x00\xff",
        "00-zz-zz-01",                                    # non-hex
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01",        # zero trace id
        "00-" + "1" * 32 + "-" + "0" * 16 + "-01",        # zero span id
        "ff-" + "1" * 32 + "-" + "2" * 16 + "-01",        # forbidden version
        "00-" + "1" * 31 + "-" + "2" * 16 + "-01",        # short trace id
        "00-" + "A" * 32 + "-" + "2" * 16 + "-01",        # uppercase hex
        None, 7,
    ])
    def test_garbage_parses_to_none(self, garbage):
        assert propagate.from_traceparent(garbage) is None

    def test_thread_local_use_nests_and_restores(self):
        assert propagate.current() is None
        a, b = propagate.new_trace(), propagate.new_trace()
        with propagate.use(a):
            assert propagate.current() is a
            with propagate.use(b):
                assert propagate.current() is b
                with propagate.use(None):  # masking
                    assert propagate.current() is None
                assert propagate.current() is b
            assert propagate.current() is a
        assert propagate.current() is None

    def test_context_is_thread_local(self):
        seen = []
        ctx = propagate.new_trace()
        with propagate.use(ctx):
            t = threading.Thread(
                target=lambda: seen.append(propagate.current()))
            t.start()
            t.join()
        assert seen == [None]  # other threads see nothing


# -- the endpoints ------------------------------------------------------------

class TestEndpoints:
    def test_metrics_prometheus(self, tele):
        tele.registry_ref.counter("thing_total", "things").inc(3)
        tele.registry_ref.histogram("lat_ms", "latency").observe(5.0)
        code, ctype, body = get(tele.address, "/metrics")
        assert code == 200 and ctype.startswith("text/plain")
        assert "thing_total 3" in body
        assert validate_prometheus(body) == []

    def test_varz_json_snapshot(self, tele):
        tele.registry_ref.gauge("depth", "queue depth").set(4)
        code, ctype, body = get(tele.address, "/varz")
        assert code == 200 and ctype.startswith("application/json")
        assert json.loads(body)["depth"] == 4

    def test_healthz_aggregates_sources(self, tele):
        code, _, body = get(tele.address, "/healthz")
        assert code == 200  # vacuous truth: no sources, nothing failing
        tele.add_health("good", lambda: (True, {"x": 1}))
        code, _, body = get(tele.address, "/healthz")
        assert code == 200 and json.loads(body)["checks"]["good"]["ok"]
        tele.add_health("bad", lambda: (False, {"why": "down"}))
        code, _, body = get(tele.address, "/healthz")
        row = json.loads(body)
        assert code == 503 and row["ok"] is False
        assert row["checks"]["bad"]["why"] == "down"
        assert row["checks"]["good"]["ok"] is True  # still reported

    def test_raising_source_fails_closed_not_500(self, tele):
        def boom():
            raise RuntimeError("probe exploded")

        tele.add_health("boom", boom)
        code, _, body = get(tele.address, "/healthz")
        assert code == 503  # an unevaluable probe is not a passing probe
        assert "probe exploded" in json.loads(body)["checks"]["boom"]["error"]
        # statusz still renders around a broken status source
        tele.add_status("boom", boom)
        code, _, body = get(tele.address, "/statusz")
        assert code == 200
        assert "probe exploded" in json.loads(body)["status"]["boom"]["error"]

    def test_statusz_json_and_html(self, tele):
        tele.journal_ref.manifest(config={"name": "t5", "task": "clf"})
        tele.add_status("train", lambda: {"step": 12, "epoch": 1})
        code, _, body = get(tele.address, "/statusz")
        row = json.loads(body)
        assert code == 200
        assert row["role"] == "test"
        assert row["status"]["train"]["step"] == 12
        assert row["manifest"]["config"]["name"] == "t5"
        code, ctype, html = get(tele.address, "/statusz?format=html")
        assert code == 200 and ctype.startswith("text/html")
        assert "HEALTHY" in html and "statusz" in html

    def test_unknown_route_404(self, tele):
        code, _, _ = get(tele.address, "/nope")
        assert code == 404
        code, _, body = get(tele.address, "/")
        assert code == 200 and "/metrics" in body

    def test_registration_idempotent_by_name(self, tele):
        tele.add_status("s", lambda: {"v": 1})
        tele.add_status("s", lambda: {"v": 2})  # replace, not duplicate
        _, _, body = get(tele.address, "/statusz")
        assert json.loads(body)["status"]["s"]["v"] == 2
        tele.remove("s")
        _, _, body = get(tele.address, "/statusz")
        assert "s" not in json.loads(body)["status"]


class TestLifecycle:
    def test_discovery_and_journal_events(self, tele, tmp_path):
        recs = read_discovery(str(tmp_path))
        assert len(recs) == 1
        rec = recs[0]
        assert rec["port"] == tele.port and rec["role"] == "test"
        assert rec["discovery_file"].startswith(DISCOVERY_PREFIX)
        tele.close()
        assert read_discovery(str(tmp_path)) == []
        tele.close()  # idempotent
        tele.journal_ref.close()
        ev = [e for e in read_journal(tele.journal_ref.path)
              if e.get("event") == "telemetry_server"]
        assert [e["outcome"] for e in ev] == ["started", "stopped"]
        assert all(e["port"] == rec["port"] for e in ev)

    def test_garbled_discovery_file_skipped(self, tmp_path):
        (tmp_path / f"{DISCOVERY_PREFIX}train-1.json").write_text("{tor")
        (tmp_path / f"{DISCOVERY_PREFIX}train-2.json").write_text(
            json.dumps({"host": "127.0.0.1", "port": 1234, "pid": 2}))
        recs = read_discovery(str(tmp_path))
        assert len(recs) == 1 and recs[0]["port"] == 1234

    def test_bind_conflict_journals_failed_and_raises(self, tmp_path):
        j = RunJournal(str(tmp_path / "r.jsonl"), kind="train")
        a = TelemetryServer(port=0, journal=j).start()
        b = TelemetryServer(port=a.port, journal=j)
        with pytest.raises(OSError):
            b.start()
        a.close()
        j.close()
        ev = [e for e in read_journal(j.path)
              if e.get("event") == "telemetry_server"]
        assert [e["outcome"] for e in ev] == ["started", "failed", "stopped"]


# -- health flips: abort -> 503, fresh run -> 200 -----------------------------

class TestHealthFlip:
    def test_healthz_flips_on_abort_and_back_on_fresh_monitor(
            self, tele, tmp_path):
        from deep_vision_tpu.obs.health import (
            HealthMonitor,
            TrainingHealthError,
        )

        mon = HealthMonitor(policy="abort", journal=tele.journal_ref,
                            registry=tele.registry_ref)
        tele.add_health("train", mon.healthz)
        code, _, _ = get(tele.address, "/healthz")
        assert code == 200
        with pytest.raises(TrainingHealthError):
            mon.check_step(7, loss=float("nan"))
        code, _, body = get(tele.address, "/healthz")
        row = json.loads(body)
        assert code == 503
        assert row["checks"]["train"]["aborted"] is True
        assert "abort_reason" in row["checks"]["train"]
        mon.stop()
        # a fresh run's monitor re-registers UNDER THE SAME NAME — that
        # is the recovery story, not clearing the dead monitor's latch
        fresh = HealthMonitor(policy="abort", journal=tele.journal_ref,
                              registry=Registry())
        tele.add_health("train", fresh.healthz)
        code, _, _ = get(tele.address, "/healthz")
        assert code == 200
        fresh.stop()


# -- concurrent scrapes under a jitted loop, locksmith armed ------------------

class TestConcurrentScrapes:
    def test_scrapes_during_jit_loop_zero_violations(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from deep_vision_tpu.obs.stepclock import recompile_count

        reg = Registry()
        j = RunJournal(str(tmp_path / "r.jsonl"), kind="train")
        san = locksmith.arm(journal=j)
        try:
            tele = TelemetryServer(port=0, role="train", registry=reg,
                                   journal=j, discovery_dir=str(tmp_path))
            tele.start()
            step_box = [0]
            tele.add_health("loop", lambda: (True, {}))
            tele.add_status("loop", lambda: {"step": step_box[0]})
            step_t = reg.histogram("step_time_ms", "steps")
            loss_g = reg.gauge("loss", "loss")

            @jax.jit
            def step(x):
                return (x * 1.0001 + 0.1).sum()

            stop = threading.Event()
            failures = []

            def scrape():
                while not stop.is_set():
                    for path in ("/metrics", "/healthz", "/statusz",
                                 "/varz"):
                        code, _, body = get(tele.address, path)
                        if code not in (200, 503):
                            failures.append((path, code))
                    time.sleep(0.002)

            scrapers = [threading.Thread(target=scrape, daemon=True)
                        for _ in range(3)]
            for t in scrapers:
                t.start()
            x = jnp.arange(64, dtype=jnp.float32)
            step(x)  # compile ONCE before the baseline
            c0 = recompile_count()
            for i in range(60):
                t0 = time.perf_counter()
                val = float(step(x))
                step_t.observe((time.perf_counter() - t0) * 1e3)
                loss_g.set(val)
                step_box[0] = i
            stop.set()
            for t in scrapers:
                t.join(timeout=10)
            assert not failures, failures[:3]
            # scraping is read-only: ZERO recompiles triggered by it
            assert recompile_count() == c0
            _, _, body = get(tele.address, "/metrics")
            assert validate_prometheus(body) == []
            tele.close()
            report = locksmith.report()
            assert report["violations"] == []
        finally:
            locksmith.disarm()
            if not j._closed:
                j.close()


# -- replica respawn keeps the endpoint alive ---------------------------------

class TestServeRespawn:
    def test_endpoint_survives_replica_respawn(self, tmp_path):
        from tests.test_serve_pool import (
            build_engine_factory,
            images,
            wait_all_serving,
        )

        from deep_vision_tpu.resilience import faults
        from deep_vision_tpu.serve import ReplicaPool, ServeError

        reg = Registry()
        j = RunJournal(str(tmp_path / "fleet.jsonl"), kind="serve")
        tele = TelemetryServer(port=0, role="serve", registry=reg,
                               journal=j, discovery_dir=str(tmp_path))
        tele.start()
        pool = ReplicaPool(build_engine_factory(reg, journal=j),
                           replicas=2, journal=j, registry=reg,
                           max_wait_ms=3.0, telemetry=tele)
        pool.start()
        try:
            code, _, body = get(tele.address, "/healthz")
            assert code == 200
            checks = json.loads(body)["checks"]
            assert "fleet" in checks
            assert any(k.startswith("serve:") for k in checks)
            faults.install_spec("serve.replica:io_error@1", seed=0,
                                journal=j, export_env=False)
            futs = [pool.submit("toy", im) for im in images(6)]
            for f in futs:
                try:
                    f.result(timeout=30)
                except ServeError:
                    pass
            faults.install(None)
            assert wait_all_serving(pool)
            # the respawned replica re-registered its sources BY NAME:
            # the endpoint answers 200 and statusz shows full strength
            code, _, body = get(tele.address, "/healthz")
            assert code == 200, body
            _, _, body = get(tele.address, "/statusz")
            fleet = json.loads(body)["status"]["fleet"]
            assert all(r["state"] == "serving"
                       for r in fleet["replicas"].values())
        finally:
            faults.install(None)
            pool.drain("close")
            tele.close()
            if not j._closed:
                j.close()


# -- propagation across the data-service boundary -----------------------------

class TestDataServicePropagation:
    def test_codec_round_trips_traceparent(self):
        from deep_vision_tpu.data.example_codec import decode_example
        from deep_vision_tpu.data.service import _control

        ctx = propagate.new_trace().child()
        frame = _control("get", traceparent=ctx.to_traceparent())
        feats = decode_example(frame)
        back = propagate.from_traceparent(
            feats.get("traceparent", [b""])[0])
        assert back is not None
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id

    def test_live_get_journals_one_trace_across_processes(self, tmp_path):
        from tests.test_data_service import _smoke_schema, _write_shards

        from deep_vision_tpu.data.datasets import RecordDataset
        from deep_vision_tpu.data.service import (
            DataService,
            DataServiceClient,
        )
        from deep_vision_tpu.obs.merge import trace_timelines

        pattern = _write_shards(tmp_path)
        sj = RunJournal(str(tmp_path / "server.jsonl"), kind="data_service")
        cj = RunJournal(str(tmp_path / "client.jsonl"), kind="train")
        ds = RecordDataset(pattern, _smoke_schema, shuffle_shards=True,
                           seed=3)
        svc = DataService(ds, batch_size=8, num_workers=1,
                          shuffle_buffer=16, seed=7, queue_depth=8,
                          journal=sj).start()
        try:
            client = DataServiceClient(svc.address, name="t", journal=cj)
            # steady state: NO trace context installed -> no per-request
            # data_service events (training streams must pay nothing)
            assert client.get() is not None
            # ingress installs a root context -> both sides journal
            root = propagate.new_trace()
            with propagate.use(root):
                assert client.get() is not None
            client.close()
        finally:
            svc.close()
        sj.close()
        cj.close()
        # op="get" marks the per-request hop events; the client's close()
        # summary event (no op) is the pre-existing aggregate
        client_ev = [e for e in read_journal(cj.path)
                     if e.get("event") == "data_service"
                     and e.get("role") == "client" and e.get("op") == "get"]
        server_ev = [e for e in read_journal(sj.path)
                     if e.get("event") == "data_service"
                     and e.get("role") == "server" and e.get("op") == "get"]
        assert len(client_ev) == 1, client_ev  # the traced get, only
        assert len(server_ev) == 1, server_ev
        c, s = client_ev[0], server_ev[0]
        # one trace; the causal chain is root -> client hop -> server hop
        assert c["trace_id"] == root.trace_id == s["trace_id"]
        assert c["parent_span_id"] == root.span_id
        assert s["parent_span_id"] == c["span_id"]
        # merged, the hops stitch into ONE cross-process timeline
        merged = read_journal(cj.path) + read_journal(sj.path)
        tls = trace_timelines(merged)
        assert len(tls) == 1
        tl = tls[0]
        assert tl["trace_id"] == root.trace_id
        assert len(tl["processes"]) == 2
        assert [h["role"] for h in tl["hops"]] == ["client", "server"]

    def test_serve_submit_stamps_request_events(self, tmp_path):
        from tests.test_serve_pool import build_engine_factory, images

        from deep_vision_tpu.serve import Server

        reg = Registry()
        j = RunJournal(str(tmp_path / "serve.jsonl"), kind="serve")
        eng = build_engine_factory(reg, journal=j)("r0")
        eng.warmup()
        srv = Server(eng, journal=j, registry=reg, max_wait_ms=2.0)
        srv.start()
        try:
            root = propagate.new_trace()
            with propagate.use(root):
                assert srv.submit(
                    "toy", images(1)[0]).result(timeout=30) is not None
            # no installed context: a fresh root is minted per request
            assert srv.submit(
                "toy", images(1)[0]).result(timeout=30) is not None
        finally:
            srv.drain("close")
            j.close()
        reqs = [e for e in read_journal(j.path)
                if e.get("event") == "serve_request"]
        assert len(reqs) == 2
        traced = [e for e in reqs if e.get("trace_id") == root.trace_id]
        assert len(traced) == 1
        assert traced[0]["parent_span_id"] == root.span_id
        # the untraced request still carries ITS OWN fresh trace
        other = next(e for e in reqs if e is not traced[0])
        assert propagate.valid_trace_id(other.get("trace_id"))
        assert other["trace_id"] != root.trace_id


# -- timeline hop ordering is causal, not clock-trusting ----------------------

class TestTraceTimelineOrdering:
    """Regression for the ~25% flake in the cross-process propagation
    test: journal ts is rounded to 1 ms, and the server journals its
    reply BEFORE the client journals the receipt — so the child hop can
    land in an earlier millisecond bucket than its parent. The causal
    clamp in trace_timelines must put the parent first anyway."""

    TID = "ab" * 16

    def _hop(self, role, ts, span, parent):
        return {"event": "data_service", "role": role, "op": "get",
                "ts": ts, "run_id": f"run-{role}", "trace_id": self.TID,
                "span_id": span, "parent_span_id": parent}

    def test_tied_ts_breaks_by_parent_link_depth(self):
        from deep_vision_tpu.obs.merge import trace_timelines

        client = self._hop("client", 100.000, "c" * 16, "0" * 15 + "1")
        server = self._hop("server", 100.000, "d" * 16, "c" * 16)
        # server listed first: input order must not decide the tie
        tls = trace_timelines([server, client])
        assert len(tls) == 1
        assert [h["role"] for h in tls[0]["hops"]] == ["client", "server"]

    def test_child_in_earlier_ms_bucket_still_sorts_after_parent(self):
        from deep_vision_tpu.obs.merge import trace_timelines

        # the flake's exact shape: server's write raced one rounding
        # boundary ahead, stamping the CHILD 1 ms before its parent
        client = self._hop("client", 100.001, "c" * 16, "0" * 15 + "1")
        server = self._hop("server", 100.000, "d" * 16, "c" * 16)
        tls = trace_timelines([server, client])
        assert len(tls) == 1
        tl = tls[0]
        assert [h["role"] for h in tl["hops"]] == ["client", "server"]
        # duration still reads from the raw stamps (clamping orders, it
        # does not rewrite the stored timestamps)
        assert tl["duration_ms"] == 1.0

    def test_grandchild_chain_clamps_transitively(self):
        from deep_vision_tpu.obs.merge import trace_timelines

        root = self._hop("client", 100.005, "a" * 16, None)
        mid = self._hop("server", 100.003, "b" * 16, "a" * 16)
        leaf = self._hop("worker", 100.004, "e" * 16, "b" * 16)
        tls = trace_timelines([leaf, mid, root])
        assert [h["span_id"] for h in tls[0]["hops"]] == \
            ["a" * 16, "b" * 16, "e" * 16]


# -- journal schema + drift guards --------------------------------------------

class TestSchema:
    def _check(self, tmp_path, row):
        from tools.check_journal import check_journal

        path = str(tmp_path / "j.jsonl")
        base = {"ts": time.time(), "run_id": "r1"}
        rows = [
            {"event": "run_manifest", "kind": "train", "argv": [], **base},
            {**base, **row},
            {"event": "exit", "status": "clean_exit", **base},
        ]
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        return check_journal(path, strict=True)

    def test_valid_telemetry_server_passes(self, tmp_path):
        assert self._check(tmp_path, {
            "event": "telemetry_server", "host": "127.0.0.1",
            "port": 9090, "outcome": "started", "role": "train",
            "pid": 1}) == []

    def test_bad_outcome_and_port_rejected(self, tmp_path):
        errs = self._check(tmp_path, {
            "event": "telemetry_server", "host": "h", "port": "9090",
            "outcome": "exploded"})
        assert any("outcome" in e for e in errs)
        assert any("port" in e for e in errs)

    def test_trace_fields_validated_everywhere(self, tmp_path):
        good = propagate.new_trace().child()
        assert self._check(tmp_path, {
            "event": "serve_request", "model": "m", "latency_ms": 1.0,
            "outcome": "ok", **good.fields()}) == []
        errs = self._check(tmp_path, {
            "event": "serve_request", "model": "m", "latency_ms": 1.0,
            "outcome": "ok", "trace_id": "SHORT", "span_id": "x"})
        assert any("trace_id" in e for e in errs)
        assert any("span_id" in e for e in errs)
        errs = self._check(tmp_path, {
            "event": "data_service", "role": "client", "batches": 1,
            **dict(good.fields(), parent_span_id="nope")})
        assert any("parent_span_id" in e for e in errs)

    def test_outcome_enums_do_not_drift(self):
        # (event REGISTRATION is DV204's job now — lint fails any
        # journal.write with no check_journal schema; this test keeps
        # only the enum-VALUE sync DV204 cannot see)
        from tools.check_journal import TELEMETRY_SERVER_OUTCOMES

        assert set(TELEMETRY_OUTCOMES) == TELEMETRY_SERVER_OUTCOMES

    def test_emitter_matches_schema(self, tele, tmp_path):
        """The real emitter's events pass the strict checker — the
        PR-13-style drift guard between obs/telemetry.py and
        tools/check_journal.py."""
        from tools.check_journal import check_journal

        tele.journal_ref.manifest(config={"name": "t", "task": "clf"})
        tele.close()
        tele.journal_ref.close()
        assert check_journal(tele.journal_ref.path, strict=True) == []
