"""Golden loss-trajectory regressions: fixed seed, N steps, exact-ish curves.

The reference's QA for training math is committed log files users diff
against ("compare with other's losses", YOLO/tensorflow/README.md:18;
ResNet/pytorch/logs/*.log). This is that idea made executable: for each task
family, run a deterministic few-step training on fixture data and assert the
loss trajectory matches recorded values. Shape tests can't catch a silently
wrong loss weight or a broken gradient path; these do.

Regenerate after an *intentional* math change:
    JAX_PLATFORMS=cpu python tests/test_golden.py regen
(goldens are CPU-f32; the suite runs on the CPU mesh, so they are stable)
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # jit-heavy: excluded from the fast tier (`-m "not slow"`)

# First-step losses recorded on the 8-device virtual CPU mesh (jax 0.9.0,
# f32). XLA-CPU convolution reductions are thread-order nondeterministic
# (~5e-3 relative), and SGD chaos amplifies that over steps, so the golden is
# the FIRST loss (pure forward+loss math — a wrong loss weight or broken term
# moves it far beyond the 2e-2 gate) plus a per-family descent predicate on
# the rest of the curve (a dead gradient path fails it regardless of jitter).
# Reference full curves at recording time, for humans diffing a failure:
#   dcgan     [0.702221, 0.690243, 0.688571, 0.683367, 0.681751]   (g_loss)
#   hourglass [1.163254, 4.041249, 3.133657, 1.586254, 0.519971]
#   resnet50  [2.301217, 0.693428, 0.046284, 0.263074, 0.000116]
#   yolov3    [109.012268, 404.102478, 801.318359, 164.799316, 125.669052]
GOLDEN_FIRST = {
    "vmoe_s16": 2.029176,
    "dcgan": 0.702221,
    "hourglass": 1.163254,
    "resnet50": 2.301217,
    "yolov3": 109.012268,
}
DESCENT = {
    # fixture is memorizable: near-zero by step 5
    "resnet50": lambda got: got[-1] < 0.01,
    # spikes as RMSprop warms up, then descends well off the peak
    "hourglass": lambda got: got[-1] < 0.5 * max(got),
    "dcgan": lambda got: got[-1] < got[0],
    # spikes while obj/class terms rebalance, then collapses off the peak
    "yolov3": lambda got: got[-1] < 0.25 * max(got),
    # AdamW warmup spike (6.6 by step 2), then descends below both the
    # peak and the first loss; 20 steps (reference curve ends ~1.37)
    "vmoe_s16": lambda got: got[-1] < 0.5 * max(got) and got[-1] < got[0],
}
STEPS = 5
FIRST_RTOL = 2e-2


def _classification_losses():
    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.losses.classification import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train.optimizers import build_optimizer

    model = get_model("resnet50", num_classes=8)
    tx = build_optimizer("sgd", 0.1, momentum=0.9, weight_decay=1e-4)
    state = create_train_state(model, tx, jnp.ones((2, 64, 64, 3)),
                               jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch = {"image": jnp.asarray(rng.rand(8, 64, 64, 3), jnp.float32),
             "label": jnp.asarray(rng.randint(0, 8, 8), jnp.int32)}

    def step(state, batch):
        def loss_fn(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            out, nms = state.apply_fn(
                variables, batch["image"], train=True,
                rngs={"dropout": jax.random.PRNGKey(1)},
                mutable=["batch_stats"])
            loss, _ = classification_loss_fn(out, batch)
            return loss, nms["batch_stats"]

        (loss, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        return state.apply_gradients(grads).replace(batch_stats=bs), loss

    step = jax.jit(step)
    losses = []
    for _ in range(STEPS):
        state, loss = step(state, batch)
        losses.append(float(loss))
    return losses


def _yolo_losses():
    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.losses.yolo import yolo_train_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train.optimizers import build_optimizer

    model = get_model("yolov3", num_classes=4)
    tx = build_optimizer("adam", 1e-3)
    state = create_train_state(model, tx, jnp.ones((2, 64, 64, 3)),
                               jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    boxes = np.zeros((4, 10, 4), np.float32)
    classes = np.zeros((4, 10), np.int32)
    for b in range(4):
        boxes[b, 0] = [0.3, 0.3, 0.6, 0.7]
        classes[b, 0] = b % 4
    batch = {"image": jnp.asarray(rng.rand(4, 64, 64, 3), jnp.float32),
             "boxes": jnp.asarray(boxes), "classes": jnp.asarray(classes)}

    def step(state, batch):
        def loss_fn(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            out, nms = state.apply_fn(
                variables, batch["image"], train=True,
                rngs={"dropout": jax.random.PRNGKey(1)},
                mutable=["batch_stats"])
            loss, _ = yolo_train_loss_fn(
                out, batch, grid_sizes=(2, 4, 8), num_classes=4)
            return loss, nms["batch_stats"]

        (loss, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        return state.apply_gradients(grads).replace(batch_stats=bs), loss

    step = jax.jit(step)
    losses = []
    for _ in range(STEPS):
        state, loss = step(state, batch)
        losses.append(float(loss))
    return losses


def _hourglass_losses():
    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.losses.heatmap import hourglass_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train.optimizers import build_optimizer

    model = get_model("hourglass", num_stack=1, num_heatmap=4)
    tx = build_optimizer("rmsprop", 2.5e-3)
    state = create_train_state(model, tx, jnp.ones((2, 64, 64, 3)),
                               jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    hm = np.zeros((4, 16, 16, 4), np.float32)
    hm[:, 8, 8, :] = 1.0
    batch = {"image": jnp.asarray(rng.rand(4, 64, 64, 3), jnp.float32),
             "heatmap": jnp.asarray(hm)}

    def step(state, batch):
        def loss_fn(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            out, nms = state.apply_fn(
                variables, batch["image"], train=True,
                rngs={"dropout": jax.random.PRNGKey(1)},
                mutable=["batch_stats"])
            loss, _ = hourglass_loss_fn(out, batch)
            return loss, nms["batch_stats"]

        (loss, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        return state.apply_gradients(grads).replace(batch_stats=bs), loss

    step = jax.jit(step)
    losses = []
    for _ in range(STEPS):
        state, loss = step(state, batch)
        losses.append(float(loss))
    return losses


def _dcgan_losses():
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train.gan import DcganTrainer
    from deep_vision_tpu.train.optimizers import build_optimizer

    trainer = DcganTrainer(
        get_model("dcgan_generator"), get_model("dcgan_discriminator"),
        build_optimizer("adam", 1e-4, b1=0.5),
        build_optimizer("adam", 1e-4, b1=0.5),
        rng=jax.random.PRNGKey(0),
    )
    rng = np.random.RandomState(0)
    real = jnp.asarray(rng.rand(8, 28, 28, 1) * 2 - 1, jnp.float32)
    losses = []
    for _ in range(STEPS):
        metrics = trainer.train_step(real)
        losses.append(float(metrics["g_loss"]))
    return losses


def _vmoe_losses():
    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.losses.classification import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train.optimizers import build_optimizer

    model = get_model("vmoe_s16", num_classes=8)
    tx = build_optimizer("adamw", 1e-3, weight_decay=1e-4)
    state = create_train_state(model, tx, jnp.ones((2, 64, 64, 3)),
                               jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch = {"image": jnp.asarray(rng.rand(8, 64, 64, 3), jnp.float32),
             "label": jnp.asarray(rng.randint(0, 8, 8), jnp.int32)}

    def step(state, batch):
        def loss_fn(params):
            out = state.apply_fn(
                {"params": params}, batch["image"], train=True,
                rngs={"dropout": jax.random.PRNGKey(1)})
            loss, _ = classification_loss_fn(out, batch)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads), loss

    step = jax.jit(step)
    losses = []
    for _ in range(20):  # the AdamW spike resolves later than STEPS
        state, loss = step(state, batch)
        losses.append(float(loss))
    return losses


_RUNNERS = {
    "resnet50": _classification_losses,
    "vmoe_s16": _vmoe_losses,
    "yolov3": _yolo_losses,
    "hourglass": _hourglass_losses,
    "dcgan": _dcgan_losses,
}


@pytest.mark.parametrize("name", sorted(_RUNNERS))
def test_golden_trajectory(name):
    got = _RUNNERS[name]()
    np.testing.assert_allclose(got[0], GOLDEN_FIRST[name], rtol=FIRST_RTOL,
                               err_msg=f"{name} first-step loss: {got}")
    assert DESCENT[name](got), f"{name} did not descend as recorded: {got}"


if __name__ == "__main__":
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "regen":  # prints full curves
        for name, fn in sorted(_RUNNERS.items()):
            print(f'    "{name}": {[round(v, 6) for v in fn()]},')
