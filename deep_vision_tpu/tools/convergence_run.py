"""Short real-hardware convergence run; records the loss curve as an artifact.

The reference commits multi-MB training logs as convergence evidence
(ResNet/pytorch/logs/resnet50-yanjiali-010919.log; "compare with other's
losses", YOLO/tensorflow/README.md:18). This is the executable equivalent
sized for CI-on-a-chip: N optimizer steps of the flagship ResNet-50 recipe
(bf16, s2d stem, SGD+momentum exactly as configs/resnet50) on a fixed
memorizable fixture, asserting the loss collapses, and writing the full curve
+ environment to artifacts/ for humans to diff between rounds.

    python -m deep_vision_tpu.tools.convergence_run [--steps 200] [--batch 64]

`--holdout` switches the fixture to a PROCEDURAL dataset with a train/val
split: class identity is a visual structure (oriented sinusoidal grating x
spatial frequency, under per-sample phase/position/noise jitter), so a model
can only score on the held-out split by learning the structure — memorizing
the train set scores chance on val. The artifact then also records val
top-1/top-5 against chance (the `validate`/`accuracy` evidence shape of
ResNet/pytorch/train.py:488-538, sized for one chip).
"""
from __future__ import annotations

import argparse
import json
import os
import time
from typing import Optional


def procedural_gratings(n: int, classes: int = 16, size: int = 112,
                        seed: int = 0, noise: float = 0.15,
                        amp_range=(0.35, 0.5)):
    """(images, labels): class = (orientation, spatial frequency) pair.

    Per-sample random phase, center offset, amplitude and pixel noise make
    every image unique; the class-defining structure (angle x frequency) is
    all that separates classes. `classes` factors as n_orientations x
    n_frequencies with n_orientations = 4 for classes <= 16, else 8 —
    16 classes = 4 angles x 4 freqs (the r1-r3 task); 32 = 8 x 4. For
    class counts that don't divide evenly, n_frequencies rounds UP so every
    label maps to a frequency inside the 4-13 cycles grid (the last
    frequency row is then partially used). `noise`/`amp_range` set the
    difficulty: r3's task saturated at val top-1 = 1.0, so the r4 evidence
    runs raise noise until accuracy lands strictly between chance and 1.0
    (VERDICT r3 task 5).
    """
    import math

    import numpy as np

    n_orient = 4 if classes <= 16 else 8
    n_freq = max(1, math.ceil(classes / n_orient))
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, size=n)
    ys, xs = np.mgrid[0:size, 0:size].astype(np.float32) / size
    images = np.empty((n, size, size, 3), np.float32)
    for i, c in enumerate(labels):
        theta = (c % n_orient) * np.pi / n_orient
        freq = 4.0 + (9.0 / max(1, n_freq - 1)) * (c // n_orient)
        phase = rng.uniform(0, 2 * np.pi)
        dx, dy = rng.uniform(-0.2, 0.2, size=2)
        amp = rng.uniform(*amp_range)
        wave = np.sin(
            2 * np.pi * freq * ((xs - dx) * np.cos(theta)
                                + (ys - dy) * np.sin(theta)) + phase
        )
        img = 0.5 + amp * wave[..., None]
        img = img + rng.randn(size, size, 3).astype(np.float32) * noise
        images[i] = np.clip(img, 0.0, 1.0)
    return images, labels.astype(np.int32)


def _build_recipe(model_name: str, classes: int, sgd_lr: float,
                  adamw_lr: float, warmup: int = 0):
    """(state, recipe string, prep fn): the shared model/optimizer setup.

    `prep` maps host float images (N, 112, 112, 3) to the model's input
    layout (the s2d stem's host half for resnet50, identity otherwise).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.data.transforms import space_to_depth
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train.optimizers import build_optimizer

    if model_name == "resnet50":
        model = get_model("resnet50", num_classes=classes, dtype=jnp.bfloat16,
                          stem="s2d")
        tx = build_optimizer("sgd", sgd_lr, momentum=0.9, weight_decay=1e-4)
        sample = jnp.ones((8, 56, 56, 12), jnp.float32)
        recipe = f"resnet50 (bf16, s2d stem, SGD {sgd_lr}/0.9/1e-4)"
        prep = lambda a: np.stack([space_to_depth(i) for i in a])
    else:  # the attention family: AdamW recipe on raw 112px inputs
        import optax

        model = get_model(model_name, num_classes=classes, dtype=jnp.bfloat16)
        lr = (optax.linear_schedule(0.0, adamw_lr, warmup) if warmup
              else adamw_lr)
        tx = build_optimizer("adamw", lr, weight_decay=1e-4)
        sample = jnp.ones((8, 112, 112, 3), jnp.float32)
        recipe = (f"{model_name} (bf16, AdamW {adamw_lr}/1e-4"
                  + (f", warmup {warmup}" if warmup else "") + ")")
        prep = lambda a: a
    state = create_train_state(model, tx, sample, jax.random.PRNGKey(0))
    return state, recipe, prep


def _train_step(state, batch, aux_weight: float = 0.01):
    """One classification train step (shared by run / run_holdout).

    Returns (new_state, metrics): metrics always carries 'loss' and, for
    MoE models, the router telemetry ('router_entropy',
    'expert_load_max', 'moe_aux' — see models/vit.py) used to diagnose
    the round-3 V-MoE cold-start stall.
    """
    import jax

    from deep_vision_tpu.losses.classification import classification_loss_fn

    def loss_fn(params):
        variables = {"params": params}
        # NB mutable=False, not []: flax returns (y, vars) for ANY list
        mutable = False
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
            mutable = ["batch_stats"]
        out = state.apply_fn(
            variables, batch["image"], train=True,
            rngs={"dropout": jax.random.fold_in(state.rng, state.step)},
            mutable=mutable)
        out, nms = out if mutable else (out, {})
        loss, metrics = classification_loss_fn(
            out, batch, penalty_weight=aux_weight)
        return loss, (nms.get("batch_stats", {}), metrics)

    (loss, (bs, metrics)), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(state.params)
    new_state = state.apply_gradients(grads)
    if state.batch_stats:
        new_state = new_state.replace(batch_stats=bs)
    metrics = {k: v for k, v in metrics.items()
               if k not in ("top1", "top5")}
    metrics["loss"] = loss
    return new_state, metrics


def _write_artifact(out_path: str, result: dict) -> None:
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)


def run(steps: int = 200, batch: int = 64, classes: int = 64,
        model_name: str = "resnet50", out_path: Optional[str] = None,
        warmup: int = 0, aux_weight: float = 0.01) -> dict:
    out_path = out_path or f"artifacts/{model_name}_tpu_convergence.json"
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    # fixed fixture: `batch` images / `classes` labels, memorizable in O(100)
    # steps — real-data ImageNet is not present in this environment, so the
    # evidence is "the full recipe optimizes on hardware", not accuracy parity
    rng = np.random.RandomState(0)
    imgs = rng.rand(batch, 112, 112, 3).astype(np.float32)
    state, recipe, prep = _build_recipe(model_name, classes,
                                        sgd_lr=0.05, adamw_lr=1e-3,
                                        warmup=warmup)
    batch_d = {
        "image": jnp.asarray(prep(imgs), jnp.bfloat16),
        "label": jnp.asarray(np.arange(batch) % classes, jnp.int32),
    }

    step = jax.jit(
        functools.partial(_train_step, aux_weight=aux_weight),
        donate_argnums=0,
    )
    curves = {}  # name -> [(step, value)]
    t0 = time.time()
    for i in range(steps):
        state, metrics = step(state, batch_d)
        if i % 10 == 0 or i == steps - 1:
            # one device->host fetch for ALL scalars: per-scalar float()
            # pays one ~118 ms relay sync EACH on this rig (bench.py)
            host = jax.device_get(metrics)
            for k, v in host.items():
                curves.setdefault(k, []).append((i, float(v)))
    wall = time.time() - t0

    losses = curves["loss"]
    dev = jax.devices()[0]
    result = {
        "model": recipe,
        "device": f"{dev.platform}:{dev.device_kind}",
        "steps": steps,
        "batch": batch,
        "classes": classes,
        "aux_weight": aux_weight,
        "warmup": warmup,
        "wall_seconds": round(wall, 1),
        "loss_curve": [[i, round(l, 4)] for i, l in losses],
        "first_loss": round(losses[0][1], 4),
        "final_loss": round(losses[-1][1], 4),
    }
    # router telemetry curves (MoE models): entropy in nats (ln E =
    # uniform), max expert load fraction (1/E = balanced)
    for k in ("router_entropy", "expert_load_max", "moe_aux"):
        if k in curves:
            result[f"{k}_curve"] = [[i, round(v, 4)] for i, v in curves[k]]
    _write_artifact(out_path, result)
    return result


def run_holdout(steps: int = 300, batch: int = 64, classes: int = 16,
                model_name: str = "resnet50", out_path: Optional[str] = None,
                n_train: int = 512, n_val: int = 256,
                noise: float = 0.15) -> dict:
    """Train on a procedural split, score the HELD-OUT split.

    Evidence of generalization, not memorization: val images are freshly
    sampled (different seed) from the same class-structure distribution.
    """
    out_path = out_path or f"artifacts/{model_name}_holdout.json"
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.core.metrics import topk_accuracy

    tr_x, tr_y = procedural_gratings(n_train, classes, seed=0, noise=noise)
    va_x, va_y = procedural_gratings(n_val, classes, seed=1, noise=noise)
    # lower LRs than run(): generalizing a split is harder than memorizing
    # one fixed batch
    state, recipe, prep = _build_recipe(model_name, classes,
                                        sgd_lr=0.02, adamw_lr=3e-4)
    tr_x, va_x = prep(tr_x), prep(va_x)

    def eval_logits(state, images):
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        out = state.apply_fn(variables, images, train=False)
        return out[0] if isinstance(out, tuple) else out

    # device-resident dataset, indexed inside jit: through this rig's relay
    # a per-step host->device image transfer costs more than the step itself
    def sampled_step(state, data_x, data_y, idx):
        return _train_step(state, {"image": jnp.take(data_x, idx, axis=0),
                                   "label": jnp.take(data_y, idx, axis=0)})

    step = jax.jit(sampled_step, donate_argnums=0)
    eval_fn = jax.jit(eval_logits)
    data_x = jnp.asarray(tr_x, jnp.bfloat16)
    data_y = jnp.asarray(tr_y)

    rng = np.random.RandomState(7)
    losses = []
    t0 = time.time()
    for i in range(steps):
        idx = jnp.asarray(rng.randint(0, n_train, size=batch))
        state, metrics = step(state, data_x, data_y, idx)
        if i % 10 == 0 or i == steps - 1:
            losses.append((i, float(metrics["loss"])))
    wall = time.time() - t0

    def split_top1(x, y):
        # eval batch clamped to the split size: --batch larger than n_val
        # must not produce zero batches (mean of [] = NaN); the sub-batch
        # tail is dropped, n reports rows actually scored
        eb = min(batch, len(x))
        accs, n = [], 0
        for s in range(0, len(x) - eb + 1, eb):
            logits = eval_fn(state, jnp.asarray(x[s:s + eb], jnp.bfloat16))
            accs.append(topk_accuracy(logits, jnp.asarray(y[s:s + eb])))
            n += eb
        return (float(np.mean([float(a["top1"]) for a in accs])),
                float(np.mean([float(a["top5"]) for a in accs])), n)

    val_top1, val_top5, n_scored = split_top1(va_x, va_y)
    train_top1, _, _ = split_top1(tr_x, tr_y)

    dev = jax.devices()[0]
    result = {
        "model": recipe,
        "dataset": "procedural gratings: class = orientation x frequency, "
                   "per-sample phase/offset/noise jitter; val resampled "
                   "with a different seed",
        "noise": noise,
        "device": f"{dev.platform}:{dev.device_kind}",
        "steps": steps,
        "batch": batch,
        "classes": classes,
        "n_train": n_train,
        "n_val": n_scored,
        "chance_top1": round(1.0 / classes, 4),
        "wall_seconds": round(wall, 1),
        "loss_curve": [[i, round(l, 4)] for i, l in losses],
        "first_loss": round(losses[0][1], 4),
        "final_loss": round(losses[-1][1], 4),
        "train_top1": round(train_top1, 4),
        "val_top1": round(val_top1, 4),
        "val_top5": round(val_top5, 4),
    }
    _write_artifact(out_path, result)
    return result


def procedural_shapes(n: int, size: int = 192, max_boxes: int = 3,
                      seed: int = 0, noise: float = 0.15):
    """Detection analog of procedural_gratings: class = shape kind.

    Each image carries 1..max_boxes non-degenerate shapes (0=disc, 1=square
    outline, 2=cross) with random size/position/brightness on a noisy
    background. Returns (images (N,S,S,3) f32, boxes (N,M,4) xyxy
    normalized 0-padded, classes (N,M) int32 -1-padded) — exactly the
    padded-GT layout losses/yolo.yolo_train_loss_fn consumes.
    """
    import numpy as np

    rng = np.random.RandomState(seed)
    images = rng.rand(n, size, size, 3).astype(np.float32) * noise
    boxes = np.zeros((n, max_boxes, 4), np.float32)
    classes = np.full((n, max_boxes), -1, np.int32)
    ys, xs = np.mgrid[0:size, 0:size]
    for i in range(n):
        k = rng.randint(1, max_boxes + 1)
        for j in range(k):
            r = rng.randint(size // 16, size // 6)  # half-extent in px
            cy = rng.randint(r + 1, size - r - 1)
            cx = rng.randint(r + 1, size - r - 1)
            cls = rng.randint(0, 3)
            amp = rng.uniform(0.55, 0.95)
            ch = rng.randint(0, 3)
            if cls == 0:  # filled disc
                mask = (ys - cy) ** 2 + (xs - cx) ** 2 <= r * r
            elif cls == 1:  # square outline
                inside = (abs(ys - cy) <= r) & (abs(xs - cx) <= r)
                inner = (abs(ys - cy) <= r - 3) & (abs(xs - cx) <= r - 3)
                mask = inside & ~inner
            else:  # cross
                mask = ((abs(ys - cy) <= 2) | (abs(xs - cx) <= 2)) & \
                       (abs(ys - cy) <= r) & (abs(xs - cx) <= r)
            images[i, ..., ch][mask] = amp
            boxes[i, j] = [(cx - r) / size, (cy - r) / size,
                           (cx + r) / size, (cy + r) / size]
            classes[i, j] = cls
    return images, boxes, classes


def run_holdout_detection(steps: int = 400, batch: int = 16,
                          size: int = 192, out_path: Optional[str] = None,
                          n_train: int = 256, n_val: int = 256,
                          lr: float = 1e-3,
                          render_dir: Optional[str] = None) -> dict:
    """Train YOLOv3 on procedural shapes ON-CHIP, score HELD-OUT mAP via
    the real decode -> NMS -> VOC-matching eval path (inference.py +
    core/detection_metrics.py) — the detection analog of run_holdout
    (VERDICT r3 task 5; evidence shape of `--eval-only` mAP).
    """
    out_path = out_path or "artifacts/yolov3_holdout.json"
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.core.detection_metrics import DetectionEvaluator
    from deep_vision_tpu.inference import make_yolo_detector
    from deep_vision_tpu.losses.yolo import yolo_train_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train.optimizers import build_optimizer
    from deep_vision_tpu.core.train_state import create_train_state

    tr_x, tr_b, tr_c = procedural_shapes(n_train, size, seed=0)
    va_x, va_b, va_c = procedural_shapes(n_val, size, seed=1)

    model = get_model("yolov3", num_classes=3)
    tx = build_optimizer("adam", lr, grad_clip_norm=10.0)
    sample = jnp.ones((2, size, size, 3), jnp.float32)
    state = create_train_state(model, tx, sample, jax.random.PRNGKey(0))
    loss_fn = functools.partial(
        yolo_train_loss_fn,
        grid_sizes=(size // 32, size // 16, size // 8), num_classes=3,
    )

    def train_step(state, data, idx):
        batch_d = {k: jnp.take(v, idx, axis=0) for k, v in data.items()}

        def lf(params):
            outputs, nms = state.apply_fn(
                {"params": params, "batch_stats": state.batch_stats},
                batch_d["image"], train=True, mutable=["batch_stats"],
                rngs={"dropout": jax.random.fold_in(state.rng, state.step)},
            )
            loss, metrics = loss_fn(outputs, batch_d)
            return loss, (nms["batch_stats"], metrics)

        (loss, (bs, metrics)), grads = jax.value_and_grad(
            lf, has_aux=True)(state.params)
        return (state.apply_gradients(grads).replace(batch_stats=bs),
                metrics)

    # device-resident dataset (per-step host->device transfers through the
    # relay dwarf the step itself; see round-3 memory)
    data = {
        "image": jnp.asarray(tr_x, jnp.float32),
        "boxes": jnp.asarray(tr_b),
        "classes": jnp.asarray(tr_c),
    }
    step = jax.jit(train_step, donate_argnums=0)
    rng = np.random.RandomState(7)
    losses = []
    t0 = time.time()
    for i in range(steps):
        idx = jnp.asarray(rng.randint(0, n_train, size=batch))
        state, metrics = step(state, data, idx)
        if i % 20 == 0 or i == steps - 1:
            losses.append((i, float(metrics["loss"])))
    wall = time.time() - t0

    # held-out eval through the REAL inference path (decode -> class-aware
    # NMS -> greedy VOC matching), the `--eval-only` machinery
    detect = make_yolo_detector(model, score_threshold=0.1)
    ev = DetectionEvaluator(num_classes=3)
    variables = state.variables
    first_det = None  # first batch's detections, reused by the render path
    for s in range(0, n_val, batch):
        imgs = jnp.asarray(va_x[s:s + batch], jnp.float32)
        det = detect(variables, imgs)
        if first_det is None:
            first_det = jax.device_get(det)
        for j in range(imgs.shape[0]):
            n = int(det["num"][j])
            gt = va_b[s + j][va_c[s + j] >= 0]
            gc = va_c[s + j][va_c[s + j] >= 0]
            ev.add(np.asarray(det["boxes"][j][:n]),
                   np.asarray(det["scores"][j][:n]),
                   np.asarray(det["classes"][j][:n]), gt, gc)
    res = ev.compute(iou_threshold=0.5)

    if render_dir and first_det is not None:
        # rendered-overlay demo outputs (demo_mscoco.ipynb's role): the
        # first val images with the model's boxes drawn by the real
        # tools/infer.py overlay path. Reuses the eval loop's first-batch
        # detections (a fresh batch-4 call would recompile the whole graph
        # for the new shape — minutes on this rig). cv2 is optional
        # package-wide: a missing cv2 skips the overlays with a warning
        # instead of crashing after the training spend.
        try:
            from deep_vision_tpu.tools.infer import (
                _write_jpeg,
                draw_detections,
            )

            os.makedirs(render_dir, exist_ok=True)
            for j in range(min(4, batch)):
                n = int(first_det["num"][j])
                img = (np.clip(va_x[j], 0, 1) * 255).astype(np.uint8)
                over = draw_detections(
                    img, first_det["boxes"][j][:n],
                    first_det["scores"][j][:n],
                    first_det["classes"][j][:n],
                    class_names=("disc", "square", "cross"),
                )
                _write_jpeg(
                    os.path.join(render_dir, f"demo_detect_{j}.jpg"), over
                )
        except Exception as e:  # cv2 missing/broken: evidence > overlays
            print(f"render skipped ({type(e).__name__}: {e})")

    dev = jax.devices()[0]
    result = {
        "model": f"yolov3-{size} (adam {lr}, grad-clip 10)",
        "dataset": "procedural shapes: disc / square outline / cross, "
                   "1-3 per image, random size/position/channel; val "
                   "resampled with a different seed",
        "device": f"{dev.platform}:{dev.device_kind}",
        "steps": steps, "batch": batch, "n_train": n_train, "n_val": n_val,
        "wall_seconds": round(wall, 1),
        "loss_curve": [[i, round(l, 4)] for i, l in losses],
        "val_map50": round(float(res["mAP"]), 4),
        "val_ap_per_class": {str(k): round(float(v), 4)
                             for k, v in res.get("ap_per_class", {}).items()},
        # per-class GT support: makes round-to-round AP deltas attributable
        # (a 1-point swing over 20 boxes is noise; over 300 it isn't)
        "val_gt_per_class": {
            str(k): int((va_c[va_c >= 0] == k).sum()) for k in range(3)
        },
    }
    _write_artifact(out_path, result)
    return result


def procedural_figures(n: int, size: int = 128, seed: int = 0,
                       noise: float = 0.2):
    """Pose analog: a 5-keypoint stick figure (head, 2 hands, 2 feet).

    Figures vary in center, scale, limb angles and brightness over a noisy
    background; the head is a disc whose diameter is the PCKh norm. Returns
    (images (N,S,S,3) f32, kpts (N,5,2) normalized xy, head_sizes (N,)
    normalized).
    """
    import numpy as np

    rng = np.random.RandomState(seed)
    images = rng.rand(n, size, size, 3).astype(np.float32) * noise
    kpts = np.zeros((n, 5, 2), np.float32)
    heads = np.zeros((n,), np.float32)
    ys, xs = np.mgrid[0:size, 0:size]
    for i in range(n):
        s = rng.uniform(0.22, 0.32) * size          # torso length px
        cx = rng.uniform(0.35, 0.65) * size
        cy = rng.uniform(0.35, 0.6) * size
        amp = rng.uniform(0.6, 0.95)
        hr = s * 0.28                               # head radius
        head = (cx + rng.uniform(-4, 4), cy - s * 0.55)
        pts = [head]
        for base in (-0.45, 0.45):                  # hands
            a = base * np.pi + rng.uniform(-0.5, 0.5)
            pts.append((cx + np.sin(a) * s * 0.9,
                        cy - s * 0.1 + np.cos(a) * s * 0.35))
        for base in (-0.2, 0.2):                    # feet
            a = base * np.pi + rng.uniform(-0.25, 0.25)
            pts.append((cx + np.sin(a) * s * 0.8,
                        cy + s * 0.55 + abs(np.cos(a)) * s * 0.45))
        # draw: head disc + limbs as thick lines from the torso center
        mask = (ys - head[1]) ** 2 + (xs - head[0]) ** 2 <= hr * hr
        ch = rng.randint(0, 3)
        images[i, ..., ch][mask] = amp
        for px, py in pts[1:]:
            t = np.linspace(0, 1, 64)[:, None]
            lx = cx + (px - cx) * t
            ly = cy + (py - cy) * t
            for lxx, lyy in zip(lx[:, 0], ly[:, 0]):
                d2 = (ys - lyy) ** 2 + (xs - lxx) ** 2
                images[i, ..., ch][d2 <= 4.0] = amp
        kpts[i] = np.asarray(pts, np.float32) / size
        heads[i] = 2 * hr / size
    np.clip(images, 0.0, 1.0, out=images)
    return images, kpts, heads


def run_holdout_pose(steps: int = 300, batch: int = 16, size: int = 128,
                     out_path: Optional[str] = None, n_train: int = 256,
                     n_val: int = 256, lr: float = 2.5e-4,
                     render_dir: Optional[str] = None) -> dict:
    """Train a 2-stack hourglass on procedural figures ON-CHIP, score
    HELD-OUT PCKh@0.5 via the real heatmap-peak decode
    (inference.heatmaps_to_keypoints + detection_metrics.pckh) — the pose
    analog of run_holdout (VERDICT r3 task 5).
    """
    out_path = out_path or "artifacts/hourglass_holdout.json"
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.core.detection_metrics import pckh
    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.inference import heatmaps_to_keypoints
    from deep_vision_tpu.losses.heatmap import hourglass_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.ops.heatmaps import gaussian_heatmaps
    from deep_vision_tpu.train.optimizers import build_optimizer

    tr_x, tr_k, tr_h = procedural_figures(n_train, size, seed=0)
    va_x, va_k, va_h = procedural_figures(n_val, size, seed=1)

    model = get_model("hourglass", num_stack=2, num_heatmap=5)
    tx = build_optimizer("adam", lr)
    sample = jnp.ones((2, size, size, 3), jnp.float32)
    state = create_train_state(model, tx, sample, jax.random.PRNGKey(0))
    hm_size = size // 4  # stem downsamples /4 (models/hourglass.py)

    # GT heatmaps at output resolution, once, device-resident
    def to_heatmaps(kpts):
        pts = jnp.asarray(kpts) * hm_size
        return jax.vmap(
            lambda p: gaussian_heatmaps(p, hm_size, hm_size, sigma=1.5)
        )(pts)

    data = {
        "image": jnp.asarray(tr_x, jnp.float32),
        "heatmap": jnp.asarray(to_heatmaps(tr_k), jnp.float32),
    }

    def train_step(state, data, idx):
        batch_d = {k: jnp.take(v, idx, axis=0) for k, v in data.items()}

        def lf(params):
            outputs = state.apply_fn(
                {"params": params, "batch_stats": state.batch_stats},
                batch_d["image"], train=True, mutable=["batch_stats"],
            )
            outputs, nms = outputs
            loss, metrics = hourglass_loss_fn(outputs, batch_d)
            return loss, (nms["batch_stats"], metrics)

        (loss, (bs, metrics)), grads = jax.value_and_grad(
            lf, has_aux=True)(state.params)
        return (state.apply_gradients(grads).replace(batch_stats=bs),
                metrics)

    step = jax.jit(train_step, donate_argnums=0)
    rng = np.random.RandomState(7)
    losses = []
    t0 = time.time()
    for i in range(steps):
        idx = jnp.asarray(rng.randint(0, n_train, size=batch))
        state, metrics = step(state, data, idx)
        if i % 20 == 0 or i == steps - 1:
            losses.append((i, float(metrics["loss"])))
    wall = time.time() - t0

    # held-out PCKh through the real decode path
    @jax.jit
    def predict(state, images):
        outputs = state.apply_fn(state.variables, images, train=False)
        return heatmaps_to_keypoints(outputs[-1])

    preds = []
    for s in range(0, n_val, batch):
        kp = predict(state, jnp.asarray(va_x[s:s + batch], jnp.float32))
        preds.append(np.asarray(kp))
    full_preds = np.concatenate(preds)  # (N, J, 3): x, y, score
    preds = full_preds[..., :2]
    vis = np.ones(va_k.shape[:2], bool)
    res = pckh(preds, va_k, vis, va_h, alpha=0.5)

    if render_dir:
        # rendered pose overlays (demo_hourglass_pose.ipynb's role); the
        # 5-keypoint figure uses a star skeleton (all joints to the head).
        # Reuses the eval predictions (scores included) — a fresh batch-4
        # call would recompile the graph; missing cv2 skips overlays with
        # a warning instead of crashing after the training spend.
        try:
            from deep_vision_tpu.tools.infer import _write_jpeg, draw_pose

            os.makedirs(render_dir, exist_ok=True)
            for j in range(4):
                img = (np.clip(va_x[j], 0, 1) * 255).astype(np.uint8)
                over = draw_pose(img, full_preds[j], score_threshold=0.05,
                                 skeleton=((0, 1), (0, 2), (0, 3), (0, 4)))
                _write_jpeg(os.path.join(render_dir, f"demo_pose_{j}.jpg"),
                            over)
        except Exception as e:
            print(f"render skipped ({type(e).__name__}: {e})")

    dev = jax.devices()[0]
    result = {
        "model": f"hourglass-2stack-{size} (adam {lr})",
        "dataset": "procedural 5-keypoint stick figures (head disc + "
                   "hands/feet), random scale/pose/channel; val resampled "
                   "with a different seed",
        "device": f"{dev.platform}:{dev.device_kind}",
        "steps": steps, "batch": batch, "n_train": n_train, "n_val": n_val,
        "wall_seconds": round(wall, 1),
        "loss_curve": [[i, round(l, 5)] for i, l in losses],
        "val_pckh50": round(float(res["PCKh@0.5"]), 4),
        "val_pck_per_joint": [round(float(v), 4)
                              for v in res.get("per_joint", [])],
        # support per joint (all joints visible on every procedural figure):
        # the denominator behind each per-joint number above
        "val_scored_per_joint": int(vis.sum(axis=0)[0]),
    }
    _write_artifact(out_path, result)
    return result


def procedural_glyphs(n: int, size: int = 28, seed: int = 0):
    """DCGAN fixture: MNIST-shaped (N, S, S, 1) glyph images in tanh range.

    Each image carries one bright glyph (disc, square outline, or cross)
    with random center/half-extent on a dark background — structured enough
    that a generator that learned the distribution emits visible glyph
    blobs, while one that collapsed or diverged emits flat/noise fields
    (the committed-sample-grid evidence role of DCGAN/tensorflow/main.py's
    per-epoch sample images).
    """
    import numpy as np

    rng = np.random.RandomState(seed)
    images = np.full((n, size, size, 1), -0.9, np.float32)
    ys, xs = np.mgrid[0:size, 0:size]
    for i in range(n):
        r = rng.randint(size // 6, size // 3)
        cy = rng.randint(r + 1, size - r - 1)
        cx = rng.randint(r + 1, size - r - 1)
        kind = rng.randint(0, 3)
        if kind == 0:
            mask = (ys - cy) ** 2 + (xs - cx) ** 2 <= r * r
        elif kind == 1:
            inside = (abs(ys - cy) <= r) & (abs(xs - cx) <= r)
            inner = (abs(ys - cy) <= r - 2) & (abs(xs - cx) <= r - 2)
            mask = inside & ~inner
        else:
            mask = ((abs(ys - cy) <= 1) | (abs(xs - cx) <= 1)) & \
                   (abs(ys - cy) <= r) & (abs(xs - cx) <= r)
        images[i, ..., 0][mask] = rng.uniform(0.6, 0.95)
        images[i] += rng.randn(size, size, 1).astype(np.float32) * 0.03
    return np.clip(images, -1.0, 1.0)


def procedural_oriented(n: int, size: int = 64, horizontal: bool = True,
                        seed: int = 0):
    """CycleGAN domain fixture: sinusoidal gratings, domain = orientation.

    Domain A (horizontal=True) varies along y, domain B along x, with random
    frequency/phase/color balance per image, tanh range (N, S, S, 3). The
    translation task A<->B is a pure structure change — a learned generator
    visibly rotates the stripes, an unlearned one does not — giving the
    qualitative-output evidence shape of CycleGAN/tensorflow/README.md's
    published sample pairs on a procedural domain.
    """
    import numpy as np

    rng = np.random.RandomState(seed)
    images = np.empty((n, size, size, 3), np.float32)
    coords = np.arange(size, dtype=np.float32) / size
    for i in range(n):
        freq = rng.uniform(2.0, 5.0)
        phase = rng.uniform(0, 2 * np.pi)
        wave = np.sin(2 * np.pi * freq * coords + phase)
        field = wave[:, None] if horizontal else wave[None, :]
        field = np.broadcast_to(field, (size, size))
        tint = rng.uniform(0.6, 1.0, size=3).astype(np.float32)
        images[i] = field[..., None] * tint * 0.8
        images[i] += rng.randn(size, size, 3).astype(np.float32) * 0.05
    return np.clip(images, -1.0, 1.0)


def _image_grid(images, cols: int = 8):
    """Tanh-range (N, H, W, C) -> one RGB uint8 grid image."""
    import numpy as np

    images = np.asarray(images, np.float32)
    n, h, w, c = images.shape
    if c == 1:
        images = np.repeat(images, 3, axis=-1)
    rows = (n + cols - 1) // cols
    pad = rows * cols - n
    if pad:
        images = np.concatenate(
            [images, np.full((pad, h, w, 3), -1.0, np.float32)]
        )
    grid = (images.reshape(rows, cols, h, w, 3)
            .transpose(0, 2, 1, 3, 4)
            .reshape(rows * h, cols * w, 3))
    return ((np.clip(grid, -1, 1) + 1) * 127.5).astype("uint8")


def run_gan_dcgan(steps: int = 600, batch: int = 64,
                  out_path: Optional[str] = None,
                  render_dir: Optional[str] = None) -> dict:
    """Train DCGAN on the glyph fixture ON-CHIP; record G/D loss curves and
    write real-vs-generated sample grids (the reference's GAN evidence is
    qualitative output, DCGAN/tensorflow/main.py:74-87)."""
    import jax
    import numpy as np

    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train.gan import DcganTrainer
    from deep_vision_tpu.train.optimizers import build_optimizer

    out_path = out_path or "artifacts/dcgan_convergence.json"
    t0 = time.time()
    data = procedural_glyphs(16 * batch, seed=0)
    # host numpy slices: the trainers' train_step shard_batches its input
    # themselves (a staged device array would be pulled BACK to host by
    # np.asarray first — strictly worse); at 28x28x1 the per-step upload is
    # ~0.2 MB and rides the async dispatch
    batches = [data[i * batch:(i + 1) * batch] for i in range(16)]
    trainer = DcganTrainer(
        get_model("dcgan_generator", latent_dim=64),
        get_model("dcgan_discriminator"),
        build_optimizer("adam", 2e-4, b1=0.5),
        build_optimizer("adam", 2e-4, b1=0.5),
        latent_dim=64,
    )
    curves = {"g_loss": [], "d_loss": []}
    for i in range(steps):
        m = trainer.train_step(batches[i % len(batches)])
        if i % 10 == 0 or i == steps - 1:
            host = jax.device_get(m)  # one fetch for all scalars
            curves["g_loss"].append((i, round(float(host["g_loss"]), 4)))
            curves["d_loss"].append((i, round(float(host["d_loss"]), 4)))
    wall = time.time() - t0
    samples = np.asarray(trainer.generate(64, seed=7), np.float32)
    sample_std = float(samples.reshape(64, -1).std(axis=1).mean())
    # mean |pairwise difference| between a few samples: ~0 under mode
    # collapse even when each image has internal structure
    diversity = float(np.abs(samples[:8, None] - samples[None, :8]).mean())
    if render_dir:
        from deep_vision_tpu.tools.infer import _write_jpeg

        os.makedirs(render_dir, exist_ok=True)
        _write_jpeg(os.path.join(render_dir, "demo_gan_dcgan_real.jpg"),
                    _image_grid(data[:64]))
        _write_jpeg(os.path.join(render_dir, "demo_gan_dcgan_samples.jpg"),
                    _image_grid(samples))
    dev = jax.devices()[0]
    final_g = curves["g_loss"][-1][1]
    final_d = curves["d_loss"][-1][1]
    result = {
        "what": "DCGAN on procedural glyph fixture: G/D loss curves + "
                "sample statistics; sample grids in examples/output",
        "model": "dcgan (latent 64, adam 2e-4 b1=0.5 both nets)",
        "device": f"{dev.platform}:{dev.device_kind}",
        "steps": steps, "batch": batch,
        "final_g_loss": final_g, "final_d_loss": final_d,
        "sample_std": round(sample_std, 4),
        "sample_diversity": round(diversity, 4),
        "curves": curves,
        "wall_seconds": round(wall, 1),
    }
    _write_artifact(out_path, result)
    return result


def run_gan_cyclegan(steps: int = 400, batch: int = 8, size: int = 64,
                     out_path: Optional[str] = None,
                     render_dir: Optional[str] = None) -> dict:
    """Train CycleGAN between the two oriented-grating domains ON-CHIP;
    record the loss curves and write A / A->B / B sample strips (the
    qualitative-pair evidence of CycleGAN/tensorflow/README.md:55-77)."""
    import jax
    import numpy as np

    from deep_vision_tpu.models.cyclegan import (
        CycleGanGenerator,
        PatchGanDiscriminator,
    )
    from deep_vision_tpu.train.gan import CycleGanTrainer
    from deep_vision_tpu.train.optimizers import build_optimizer

    out_path = out_path or "artifacts/cyclegan_convergence.json"
    t0 = time.time()
    n_batches = 8
    a = procedural_oriented(n_batches * batch, size, horizontal=True, seed=0)
    b = procedural_oriented(n_batches * batch, size, horizontal=False, seed=1)
    # host numpy slices: train_step shard_batches internally (see the dcgan
    # runner's staging note)
    a_batches = [a[i * batch:(i + 1) * batch] for i in range(n_batches)]
    b_batches = [b[i * batch:(i + 1) * batch] for i in range(n_batches)]
    mk_g = lambda: CycleGanGenerator(n_blocks=3, base=16)
    mk_d = lambda: PatchGanDiscriminator(base=16)
    trainer = CycleGanTrainer(
        mk_g(), mk_g(), mk_d(), mk_d(),
        g_tx_fn=lambda: build_optimizer("adam", 2e-4, b1=0.5),
        d_tx_fn=lambda: build_optimizer("adam", 2e-4, b1=0.5),
        image_shape=(size, size, 3),
    )
    curves = {"g_loss": [], "g_cycle": [], "d_loss": []}
    for i in range(steps):
        m = trainer.train_step(a_batches[i % n_batches],
                               b_batches[i % n_batches])
        if i % 10 == 0 or i == steps - 1:
            host = jax.device_get(m)
            for k in curves:
                curves[k].append((i, round(float(host[k]), 4)))
    wall = time.time() - t0
    val_a = procedural_oriented(8, size, horizontal=True, seed=99)
    fake_b = np.asarray(trainer.translate(val_a), np.float32)
    # orientation energy: row-to-row variation dominates horizontal
    # stripes, column-to-column vertical ones; translation must move energy
    # toward the target domain's axis
    def _axis_ratio(x):  # >1 = vertical-ish structure
        dy = np.abs(np.diff(x, axis=1)).mean()
        dx = np.abs(np.diff(x, axis=2)).mean()
        return float(dx / max(dy, 1e-6))

    ratio_in, ratio_out = _axis_ratio(val_a), _axis_ratio(fake_b)
    if render_dir:
        from deep_vision_tpu.tools.infer import _write_jpeg

        os.makedirs(render_dir, exist_ok=True)
        strip = np.concatenate([
            _image_grid(val_a[:4], cols=1),
            _image_grid(fake_b[:4], cols=1),
            _image_grid(b[:4], cols=1),
        ], axis=1)  # columns: A | A->B | real B reference
        _write_jpeg(os.path.join(render_dir, "demo_gan_cyclegan_a2b.jpg"),
                    strip)
    dev = jax.devices()[0]
    result = {
        "what": "CycleGAN between oriented-grating domains: loss curves + "
                "orientation-energy shift of A->B; sample strip in "
                "examples/output (columns: A, A->B, real-B reference)",
        "model": f"cyclegan (3 res-blocks, base 16, {size}px, "
                 "adam 2e-4 b1=0.5, ImagePool 50)",
        "device": f"{dev.platform}:{dev.device_kind}",
        "steps": steps, "batch": batch,
        "first_g_cycle": curves["g_cycle"][0][1],
        "final_g_cycle": curves["g_cycle"][-1][1],
        "final_g_loss": curves["g_loss"][-1][1],
        "final_d_loss": curves["d_loss"][-1][1],
        "orientation_ratio_input": round(ratio_in, 3),
        "orientation_ratio_translated": round(ratio_out, 3),
        "curves": curves,
        "wall_seconds": round(wall, 1),
    }
    _write_artifact(out_path, result)
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=None,
                   help="default 200 (memorization) / 300 (--holdout "
                        "classification, pose) / 400 (--holdout yolov3)")
    p.add_argument("--batch", type=int, default=None,
                   help="default 64 (classification) / 16 (detection, pose)")
    p.add_argument("--model", default="resnet50",
                   help="resnet50 | vit_s16 | vmoe_s16 | yolov3 (--holdout "
                        "only) | hourglass (--holdout only) | dcgan | "
                        "cyclegan")
    p.add_argument("--holdout", action="store_true",
                   help="procedural train/val split; report held-out top-1")
    p.add_argument("--warmup", type=int, default=0,
                   help="linear LR warmup steps (attention family only)")
    p.add_argument("--aux-weight", type=float, default=0.01,
                   help="MoE load-balance penalty weight")
    p.add_argument("--noise", type=float, default=0.15,
                   help="grating pixel-noise sigma (holdout difficulty)")
    p.add_argument("--render-dir", default=None,
                   help="write demo overlay JPEGs here (detection/pose "
                        "holdouts)")
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)
    if args.model in ("yolov3", "hourglass") and not args.holdout:
        p.error(f"--model {args.model} is a --holdout-only runner "
                "(detection mAP / pose PCKh evidence); add --holdout")
    if args.model == "dcgan":
        out = args.out or "artifacts/dcgan_convergence.json"
        r = run_gan_dcgan(args.steps or 600, args.batch or 64, out_path=out,
                          render_dir=args.render_dir)
        print(f"device={r['device']} g={r['final_g_loss']} "
              f"d={r['final_d_loss']} sample_std={r['sample_std']} "
              f"diversity={r['sample_diversity']} "
              f"wall={r['wall_seconds']}s -> {out}")
        # trained = equilibrium (neither net won outright) + structured,
        # non-collapsed samples
        ok = (0.05 < r["final_d_loss"] < 2.5 and r["sample_std"] > 0.15
              and r["sample_diversity"] > 0.1)
        print("TRAINED" if ok else "DID NOT TRAIN")
        return 0 if ok else 1
    if args.model == "cyclegan":
        out = args.out or "artifacts/cyclegan_convergence.json"
        r = run_gan_cyclegan(args.steps or 400, args.batch or 8,
                             out_path=out, render_dir=args.render_dir)
        print(f"device={r['device']} cycle {r['first_g_cycle']} -> "
              f"{r['final_g_cycle']} orientation "
              f"{r['orientation_ratio_input']} -> "
              f"{r['orientation_ratio_translated']} "
              f"wall={r['wall_seconds']}s -> {out}")
        # trained = cycle consistency learned + stripes actually rotated
        ok = (r["final_g_cycle"] < 0.5 * r["first_g_cycle"]
              and r["orientation_ratio_translated"]
              > 2 * r["orientation_ratio_input"])
        print("TRAINED" if ok else "DID NOT TRAIN")
        return 0 if ok else 1
    if args.holdout and args.model == "yolov3":
        out = args.out or "artifacts/yolov3_holdout.json"
        r = run_holdout_detection(args.steps or 400, args.batch or 16,
                                  out_path=out, render_dir=args.render_dir)
        print(f"device={r['device']} val_mAP50={r['val_map50']} "
              f"per-class={r['val_ap_per_class']} "
              f"wall={r['wall_seconds']}s -> {out}")
        ok = r["val_map50"] >= 0.25
        print("GENERALIZED" if ok else "DID NOT GENERALIZE")
        return 0 if ok else 1
    if args.holdout and args.model == "hourglass":
        out = args.out or "artifacts/hourglass_holdout.json"
        r = run_holdout_pose(args.steps or 300, args.batch or 16,
                             out_path=out, render_dir=args.render_dir)
        print(f"device={r['device']} val_PCKh@0.5={r['val_pckh50']} "
              f"wall={r['wall_seconds']}s -> {out}")
        ok = r["val_pckh50"] >= 0.25
        print("GENERALIZED" if ok else "DID NOT GENERALIZE")
        return 0 if ok else 1
    if args.holdout:
        out = args.out or f"artifacts/{args.model}_holdout.json"
        r = run_holdout(args.steps or 300, args.batch or 64,
                        model_name=args.model, out_path=out,
                        noise=args.noise)
        chance = r["chance_top1"]
        print(f"device={r['device']} final_loss={r['final_loss']} "
              f"train_top1={r['train_top1']} val_top1={r['val_top1']} "
              f"(chance {chance}) wall={r['wall_seconds']}s -> {out}")
        ok = r["val_top1"] >= 4 * chance
        print("GENERALIZED" if ok else "DID NOT GENERALIZE")
        return 0 if ok else 1
    out = args.out or f"artifacts/{args.model}_tpu_convergence.json"
    r = run(args.steps or 200, args.batch or 64, model_name=args.model,
            out_path=out, warmup=args.warmup, aux_weight=args.aux_weight)
    print(f"device={r['device']} first={r['first_loss']} "
          f"final={r['final_loss']} wall={r['wall_seconds']}s -> {out}")
    ok = r["final_loss"] < 0.5 * r["first_loss"]
    print("CONVERGED" if ok else "DID NOT CONVERGE")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
