"""Flash attention kernel: interpret-mode CPU tests against dense golden."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.ops.pallas.flash_attention import (
    _dense_reference,
    flash_attention,
)

pytestmark = pytest.mark.slow  # jit-heavy: excluded from the fast tier (`-m "not slow"`)


def _qkv(b=2, t=64, h=2, d=32, seed=0, tk=None):
    rng = np.random.RandomState(seed)
    tk = tk or t
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, tk, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, tk, h, d).astype(np.float32))
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    got = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    want = _dense_reference(q, k, v, causal, q.shape[-1] ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_flash_cross_attention_shapes():
    q, k, v = _qkv(t=32, tk=64)
    got = flash_attention(q, k, v, block_q=16, block_k=16)
    want = _dense_reference(q, k, v, False, q.shape[-1] ** -0.5)
    assert got.shape == (2, 32, 2, 32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_flash_single_block():
    q, k, v = _qkv(t=16)
    got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    want = _dense_reference(q, k, v, True, q.shape[-1] ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_flash_extreme_scores_stable():
    q, k, v = _qkv(seed=3)
    q = q * 120.0  # rows with true max << 0 must survive online softmax
    got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    want = _dense_reference(q, k, v, True, q.shape[-1] ** -0.5)
    assert np.isfinite(np.asarray(got)).all()
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=1e-4)


def test_flash_grads_match_dense():
    q, k, v = _qkv(b=1, t=32, h=1, d=16)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=16, block_k=16) ** 2)

    def f_dense(q, k, v):
        return jnp.sum(_dense_reference(q, k, v, True, q.shape[-1] ** -0.5) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=1e-4)


def test_flash_bf16_io():
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(t=32))
    got = flash_attention(q, k, v, block_q=16, block_k=16)
    assert got.dtype == jnp.bfloat16
    want = _dense_reference(q, k, v, False, q.shape[-1] ** -0.5)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2,
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bwd_kernel_matches_dense(causal):
    """The Pallas backward (dq + dkv kernels) vs autodiff of dense attention,
    including non-square blocks and multi-block grids."""
    rng = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rng.randn(2, 64, 2, 8), jnp.float32) for _ in range(3))
    g = jnp.asarray(rng.randn(2, 64, 2, 8), jnp.float32)

    def f_flash(q, k, v):
        return jnp.vdot(flash_attention(q, k, v, causal=causal,
                                        block_q=16, block_k=32), g)

    def f_dense(q, k, v):
        return jnp.vdot(_dense_reference(q, k, v, causal, q.shape[-1] ** -0.5), g)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


def test_flash_bwd_cross_attention():
    """Tq != Tk exercises the independent q/k grid extents in both kernels."""
    rng = np.random.RandomState(4)
    q = jnp.asarray(rng.randn(1, 32, 2, 8), jnp.float32)
    k = jnp.asarray(rng.randn(1, 64, 2, 8), jnp.float32)
    v = jnp.asarray(rng.randn(1, 64, 2, 8), jnp.float32)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=16, block_k=16) ** 2)

    def f_dense(q, k, v):
        return jnp.sum(_dense_reference(q, k, v, False, q.shape[-1] ** -0.5) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_bwd_bf16():
    rng = np.random.RandomState(5)
    q, k, v = (jnp.asarray(rng.randn(1, 32, 1, 8), jnp.bfloat16)
               for _ in range(3))
    grads = jax.grad(
        lambda q, k, v: float(0) + jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
            .astype(jnp.float32)),
        argnums=(0, 1, 2))(q, k, v)
    dense = jax.grad(
        lambda q, k, v: jnp.sum(
            _dense_reference(q, k, v, True, q.shape[-1] ** -0.5)
            .astype(jnp.float32)),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(grads, dense):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=0.1, atol=0.1)


def test_flash_with_lse_grads_include_lse_cotangent():
    """A loss that uses BOTH outputs must differentiate exactly (the ring
    merge depends on lse; its cotangent shifts the delta term)."""
    from deep_vision_tpu.ops.pallas.flash_attention import (
        flash_attention_with_lse,
    )

    rng = np.random.RandomState(7)
    q, k, v = (jnp.asarray(rng.randn(1, 32, 2, 8), jnp.float32)
               for _ in range(3))
    scale = 8 ** -0.5

    def f_flash(q, k, v):
        out, lse = flash_attention_with_lse(q, k, v, block_q=16, block_k=16)
        return jnp.sum(out ** 2) + jnp.sum(lse[:, :, 0] ** 2)

    def f_dense(q, k, v):
        s = jnp.einsum("bthd,bshd->bhts", q, k) * scale
        lse = jax.scipy.special.logsumexp(s, axis=-1)  # (B,H,T)
        out = jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(s, -1), v)
        return jnp.sum(out ** 2) + jnp.sum(
            lse.transpose(0, 2, 1).reshape(1, 32, 2).reshape(-1) ** 2
        )

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4, err_msg=name)
