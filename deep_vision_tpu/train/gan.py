"""GAN trainers: DCGAN (twin-update) and CycleGAN (2G + 2D + image pool).

Parity targets: the twin-GradientTape `train_step` at DCGAN/tensorflow/main.py:55-71
(one noise batch drives both G and D updates) and the CycleGAN loop at
CycleGAN/tensorflow/train.py:150-265: `train_generator` (one tape over both
generators: adversarial + cycle + identity), host-side `ImagePool.query`
between the G and D steps (utils.py:32-61 — eager-only in the reference;
here it is host-side numpy state BETWEEN two jitted SPMD steps, which is the
TPU-native factoring of the same replay buffer), then `train_discriminator`.

Each sub-network is its own TrainState, so optimizers/schedules stay
independent (Adam beta1=0.5 etc., train.py:130-131).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deep_vision_tpu.core.train_state import TrainState, create_train_state
from deep_vision_tpu.obs.stepclock import StepClock
from deep_vision_tpu.obs.trace import span
from deep_vision_tpu.losses.gan import (
    bce_discriminator_loss,
    bce_generator_loss,
    cycle_consistency_loss,
    identity_loss,
    lsgan_discriminator_loss,
    lsgan_generator_loss,
)
from deep_vision_tpu.parallel.mesh import create_mesh, replicated, shard_batch


class ImagePool:
    """Replay buffer of generated images (CycleGAN/tensorflow/utils.py:32-61).

    Host-side by construction: lives between the jitted G and D steps.
    """

    def __init__(self, size: int = 50, seed: int = 0):
        self.size = size
        self.images: list[np.ndarray] = []
        self.rng = np.random.RandomState(seed)

    def query(self, batch: np.ndarray) -> np.ndarray:
        if self.size == 0:
            return batch
        out = []
        for img in np.asarray(batch):
            # copy: a row view would pin the whole batch array in the pool
            if len(self.images) < self.size:
                self.images.append(img.copy())
                out.append(img)
            elif self.rng.rand() < 0.5:
                idx = self.rng.randint(self.size)
                out.append(self.images[idx])
                self.images[idx] = img.copy()
            else:
                out.append(img)
        return np.stack(out)


from deep_vision_tpu.core.checkpoint import state_arrays as _state_arrays


def _load_state_arrays(state: TrainState, arrays: dict) -> TrainState:
    return state.replace(**arrays)


def _apply(state: TrainState, x, rng, train=True):
    variables = {"params": state.params}
    mutable = False
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
        mutable = ["batch_stats"]
    out = state.apply_fn(
        variables, x, train=train, rngs={"dropout": rng}, mutable=mutable
    )
    if mutable:
        return out[0], out[1].get("batch_stats", {})
    return out, {}


class DcganTrainer:
    """Alternating (actually simultaneous, like the reference) G/D updates."""

    def __init__(self, generator, discriminator, g_tx, d_tx,
                 latent_dim: int = 100, image_shape=(28, 28, 1),
                 mesh=None, rng: Optional[jax.Array] = None,
                 journal=None, registry=None,
                 telemetry_sample_every: int = 32, health=None,
                 autoprof=None):
        self.mesh = mesh if mesh is not None else create_mesh()
        self.latent_dim = latent_dim
        # anomaly-triggered profiling (obs/autoprof.py): the GAN loop has
        # no optimizer-step fetch, so captures key on the clock's counter;
        # the fence drains async dispatch into the trace before stop_trace
        # (otherwise the tail of the anomalous steps is cut off mid-flight)
        self.autoprof = autoprof
        if autoprof is not None:
            autoprof.fence = lambda: jax.block_until_ready(
                (self.g_state, self.d_state))
        # health: the GAN loop keeps metrics on device until epoch end, so
        # the per-step hook is heartbeat-only; the epoch summary check
        # (check_summary) runs from the train_cli loop
        self.health = health
        # per-step journal events carry timing only: the GAN loop keeps
        # metrics as device arrays until epoch end, and the clock's sampled
        # fence is the only sync (obs/stepclock.py)
        self.clock = StepClock(registry=registry, journal=journal,
                               name="gan",
                               sample_every=telemetry_sample_every)
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        g_rng, d_rng = jax.random.split(rng)
        g_state = create_train_state(
            generator, g_tx, jnp.zeros((2, latent_dim)), g_rng
        )
        d_state = create_train_state(
            discriminator, d_tx, jnp.zeros((2, *image_shape)), d_rng
        )
        self.g_state = jax.device_put(g_state, replicated(self.mesh))
        self.d_state = jax.device_put(d_state, replicated(self.mesh))
        self._step = jax.jit(self._step_impl, donate_argnums=(0, 1))

    def _step_impl(self, g_state: TrainState, d_state: TrainState, real):
        rng = jax.random.fold_in(g_state.rng, g_state.step)
        # one subkey per network application (DV002): the discriminator runs
        # three times here (G's adversarial pass, D on real, D on fake) and
        # its dropout masks must be independent draws, not one mask reused
        z_rng, g_rng, dg_rng, dr_rng, df_rng = jax.random.split(rng, 5)
        noise = jax.random.normal(z_rng, (real.shape[0], self.latent_dim))

        def g_loss_fn(g_params):
            fake, g_bs = _apply(g_state.replace(params=g_params), noise, g_rng)
            fake_logits, _ = _apply(d_state, fake, dg_rng)
            return bce_generator_loss(fake_logits), (g_bs, fake)

        def d_loss_fn(d_params, fake):
            ds = d_state.replace(params=d_params)
            real_logits, d_bs = _apply(ds, real, dr_rng)
            fake_logits, _ = _apply(ds, fake, df_rng)
            return bce_discriminator_loss(real_logits, fake_logits), d_bs

        (g_loss, (g_bs, fake)), g_grads = jax.value_and_grad(
            g_loss_fn, has_aux=True
        )(g_state.params)
        (d_loss, d_bs), d_grads = jax.value_and_grad(d_loss_fn, has_aux=True)(
            d_state.params, jax.lax.stop_gradient(fake)
        )
        g_state = g_state.apply_gradients(g_grads)
        d_state = d_state.apply_gradients(d_grads)
        if g_bs:
            g_state = g_state.replace(batch_stats=g_bs)
        if d_bs:
            d_state = d_state.replace(batch_stats=d_bs)
        return g_state, d_state, {"g_loss": g_loss, "d_loss": d_loss}

    def train_step(self, real_images) -> dict:
        if self.autoprof is not None:
            self.autoprof.on_step_start()
        with span("gan/step"):
            with self.clock.step(batch_size=np.shape(real_images)[0]) as rec:
                real = shard_batch(self.mesh, np.asarray(real_images))
                self.g_state, self.d_state, metrics = self._step(
                    self.g_state, self.d_state, real
                )
                rec.fence_on(metrics)
        if self.autoprof is not None:
            self.autoprof.observe_step(self.clock.steps_seen, rec.fields())
        if self.health is not None:
            self.health.beat()
        return metrics

    def generate(self, n: int, seed: int = 0):
        noise = jax.random.normal(jax.random.PRNGKey(seed), (n, self.latent_dim))
        out, _ = _apply(self.g_state, noise, jax.random.PRNGKey(0), train=False)
        return out

    # checkpoint/resume: the tf.train.Checkpoint G/D/optimizers capture +
    # restore-or-initialize pattern (DCGAN/tensorflow/main.py:34-40)
    def save(self, ckpt, epoch: int, completed_epoch: int | None = None) -> bool:
        """Checkpoint under the GLOBAL optimizer step (unique, monotonic):
        epoch-keyed steps collide when a preemption save and the re-run
        epoch's boundary save land on the same epoch number, and orbax
        silently declines the second. `completed_epoch` (default: epoch) is
        what restore() resumes after — the preemption path passes epoch-1
        so the interrupted epoch re-runs. Returns whether orbax saved."""
        with span("checkpoint/save", epoch=epoch,
                  step=int(self.g_state.step)):
            return bool(ckpt.save_tree(
                int(self.g_state.step),
                {"g": _state_arrays(self.g_state),
                 "d": _state_arrays(self.d_state)},
                host_state={"epoch": epoch if completed_epoch is None
                            else completed_epoch},
            ))

    def restore(self, ckpt) -> int:
        """Restore-or-initialize; returns the next epoch to run (0 if fresh)."""
        template = {
            "g": _state_arrays(self.g_state), "d": _state_arrays(self.d_state)
        }
        with span("checkpoint/restore"):
            restored, host = ckpt.restore_tree(template)
        if restored is None:
            return 0
        self.g_state = _load_state_arrays(self.g_state, restored["g"])
        self.d_state = _load_state_arrays(self.d_state, restored["d"])
        if host is None or "epoch" not in host:
            # sidecar lost (crash between tree save and JSON write): the
            # step index is an optimizer step, not an epoch — re-run from
            # epoch 0 with the restored weights rather than guess
            print("GAN restore: no epoch sidecar; weights restored, "
                  "restarting epoch count at 0")
            return 0
        return int(host["epoch"]) + 1


class CycleGanTrainer:
    """A<->B translation: G_ab, G_ba, D_a, D_b + two image pools."""

    def __init__(self, gen_ab, gen_ba, disc_a, disc_b, g_tx_fn: Callable,
                 d_tx_fn: Callable, image_shape=(256, 256, 3), mesh=None,
                 pool_size: int = 50, rng: Optional[jax.Array] = None,
                 journal=None, registry=None,
                 telemetry_sample_every: int = 32, health=None,
                 autoprof=None):
        self.mesh = mesh if mesh is not None else create_mesh()
        self.health = health
        self.autoprof = autoprof
        if autoprof is not None:
            # drain all four sub-network states into the trace on stop
            autoprof.fence = lambda: jax.block_until_ready(
                (self.gab, self.gba, self.da, self.db))
        self.clock = StepClock(registry=registry, journal=journal,
                               name="gan",
                               sample_every=telemetry_sample_every)
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        rngs = jax.random.split(rng, 4)
        sample = jnp.zeros((2, *image_shape))
        put = lambda s: jax.device_put(s, replicated(self.mesh))
        self.gab = put(create_train_state(gen_ab, g_tx_fn(), sample, rngs[0]))
        self.gba = put(create_train_state(gen_ba, g_tx_fn(), sample, rngs[1]))
        self.da = put(create_train_state(disc_a, d_tx_fn(), sample, rngs[2]))
        self.db = put(create_train_state(disc_b, d_tx_fn(), sample, rngs[3]))
        self.pool_a = ImagePool(pool_size, seed=1)
        self.pool_b = ImagePool(pool_size, seed=2)
        self._g_step = jax.jit(self._g_step_impl, donate_argnums=(0, 1))
        self._d_step = jax.jit(self._d_step_impl, donate_argnums=(0, 1))

    # checkpoint/resume: G_ab/G_ba/D_a/D_b + epoch, saved every N epochs
    # (CycleGAN/tensorflow/train.py:133-148, 329-333)
    def save(self, ckpt, epoch: int, completed_epoch: int | None = None) -> bool:
        with span("checkpoint/save", epoch=epoch, step=int(self.gab.step)):
            return bool(ckpt.save_tree(
                int(self.gab.step),
                {"gab": _state_arrays(self.gab),
                 "gba": _state_arrays(self.gba),
                 "da": _state_arrays(self.da), "db": _state_arrays(self.db)},
                host_state={"epoch": epoch if completed_epoch is None
                            else completed_epoch},
            ))

    def restore(self, ckpt) -> int:
        template = {
            "gab": _state_arrays(self.gab), "gba": _state_arrays(self.gba),
            "da": _state_arrays(self.da), "db": _state_arrays(self.db),
        }
        with span("checkpoint/restore"):
            restored, host = ckpt.restore_tree(template)
        if restored is None:
            return 0
        self.gab = _load_state_arrays(self.gab, restored["gab"])
        self.gba = _load_state_arrays(self.gba, restored["gba"])
        self.da = _load_state_arrays(self.da, restored["da"])
        self.db = _load_state_arrays(self.db, restored["db"])
        if host is None or "epoch" not in host:
            print("GAN restore: no epoch sidecar; weights restored, "
                  "restarting epoch count at 0")
            return 0
        return int(host["epoch"]) + 1

    # generator step: one grad over BOTH generators (train.py:150-205)
    def _g_step_impl(self, gab: TrainState, gba: TrainState, da, db, real_a, real_b):
        # eight network applications -> eight subkeys (DV002): subscripts of
        # one split, so each dropout draw is independent
        r = jax.random.split(jax.random.fold_in(gab.rng, gab.step), 8)

        def loss_fn(params):
            gab_p, gba_p = params
            fake_b, gab_bs = _apply(gab.replace(params=gab_p), real_a, r[0])
            fake_a, gba_bs = _apply(gba.replace(params=gba_p), real_b, r[1])
            cycled_a, _ = _apply(gba.replace(params=gba_p), fake_b, r[2])
            cycled_b, _ = _apply(gab.replace(params=gab_p), fake_a, r[3])
            same_a, _ = _apply(gba.replace(params=gba_p), real_a, r[4])
            same_b, _ = _apply(gab.replace(params=gab_p), real_b, r[5])
            logits_fake_b, _ = _apply(db, fake_b, r[6])
            logits_fake_a, _ = _apply(da, fake_a, r[7])
            adv = lsgan_generator_loss(logits_fake_b) + lsgan_generator_loss(
                logits_fake_a
            )
            cyc = cycle_consistency_loss(real_a, cycled_a) + cycle_consistency_loss(
                real_b, cycled_b
            )
            ident = identity_loss(real_a, same_a) + identity_loss(real_b, same_b)
            total = adv + cyc + ident
            aux = {
                "adv": adv, "cycle": cyc, "identity": ident,
                "fake_a": fake_a, "fake_b": fake_b,
                "gab_bs": gab_bs, "gba_bs": gba_bs,
            }
            return total, aux

        (g_loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            (gab.params, gba.params)
        )
        gab = gab.apply_gradients(grads[0])
        gba = gba.apply_gradients(grads[1])
        if aux["gab_bs"]:
            gab = gab.replace(batch_stats=aux["gab_bs"])
        if aux["gba_bs"]:
            gba = gba.replace(batch_stats=aux["gba_bs"])
        metrics = {"g_loss": g_loss, "g_adv": aux["adv"], "g_cycle": aux["cycle"],
                   "g_identity": aux["identity"]}
        return gab, gba, metrics, jax.lax.stop_gradient(aux["fake_a"]), \
            jax.lax.stop_gradient(aux["fake_b"])

    def _d_step_impl(self, da: TrainState, db: TrainState, real_a, real_b,
                     fake_a, fake_b):
        # four discriminator applications -> four subkeys (DV002)
        r = jax.random.split(jax.random.fold_in(da.rng, da.step), 4)

        def loss_fn(params):
            da_p, db_p = params
            ra, da_bs = _apply(da.replace(params=da_p), real_a, r[0])
            fa, _ = _apply(da.replace(params=da_p), fake_a, r[1])
            rb, db_bs = _apply(db.replace(params=db_p), real_b, r[2])
            fb, _ = _apply(db.replace(params=db_p), fake_b, r[3])
            loss = lsgan_discriminator_loss(ra, fa) + lsgan_discriminator_loss(rb, fb)
            return loss, (da_bs, db_bs)

        (d_loss, (da_bs, db_bs)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )((da.params, db.params))
        da = da.apply_gradients(grads[0])
        db = db.apply_gradients(grads[1])
        if da_bs:
            da = da.replace(batch_stats=da_bs)
        if db_bs:
            db = db.replace(batch_stats=db_bs)
        return da, db, {"d_loss": d_loss}

    def train_step(self, real_a, real_b) -> dict:
        if self.autoprof is not None:
            self.autoprof.on_step_start()
        with span("gan/step"):
            with self.clock.step(batch_size=np.shape(real_a)[0]) as rec:
                real_a = shard_batch(self.mesh, np.asarray(real_a))
                real_b = shard_batch(self.mesh, np.asarray(real_b))
                with span("gan/g_step"):
                    self.gab, self.gba, g_metrics, fake_a, fake_b = \
                        self._g_step(
                            self.gab, self.gba, self.da, self.db,
                            real_a, real_b
                        )
                # host boundary: replay-buffer query between the two
                # jitted steps (the np.asarray fetch is the sync point,
                # which is why it gets its own span)
                with span("gan/pool"):
                    fake_a = shard_batch(
                        self.mesh, self.pool_a.query(np.asarray(fake_a)))
                    fake_b = shard_batch(
                        self.mesh, self.pool_b.query(np.asarray(fake_b)))
                with span("gan/d_step"):
                    self.da, self.db, d_metrics = self._d_step(
                        self.da, self.db, real_a, real_b, fake_a, fake_b
                    )
                metrics = {**g_metrics, **d_metrics}
                rec.fence_on(metrics)
        if self.autoprof is not None:
            self.autoprof.observe_step(self.clock.steps_seen, rec.fields())
        if self.health is not None:
            self.health.beat()
        return metrics

    def translate(self, images_a):
        out, _ = _apply(self.gab, jnp.asarray(images_a), jax.random.PRNGKey(0),
                        train=False)
        return out
