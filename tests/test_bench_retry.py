"""bench.py resilience: transient runtime failures must not kill the run.

Round 2 shipped with NO recorded perf number because one transient tunnel
error escaped bench.py's step loop (BENCH_r02.json: rc=1, parsed null).
These tests drive `_timed_windows` / `main` with an injected flaky step and
assert the retry-rebuild-replay path works and the JSON line is ALWAYS
emitted.
"""
import json
import sys
import types

import numpy as np
import pytest

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
import bench  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_bench_process_state(monkeypatch):
    """The emit-once latch and watchdog deadline are process-lifetime state
    in the real CLI; each test is its own 'process'."""
    monkeypatch.setattr(bench, "_EMITTED", False)
    monkeypatch.setattr(bench, "_DEADLINE", None)
    monkeypatch.setattr(bench, "_WINDOWS_DONE", 0)
    # unit tests drive injected steps, not a real backend: the probe must
    # not spend wall time compiling a trivial op per test
    monkeypatch.setattr(bench, "_backend_alive", lambda *a, **k: (True, None))


def _instant_retries(monkeypatch):
    """Zero-delay retry schedule: the BackendSupervisor bench builds via
    _retry_policy() must not sleep real backoff in unit tests (budget
    still honors a monkeypatched MAX_RETRIES at call time)."""
    monkeypatch.setattr(bench, "_retry_policy", lambda: bench.RetryPolicy(
        name="bench.window", max_attempts=bench.MAX_RETRIES + 1,
        base_delay_s=0.0, jitter=0.0, retry_on=Exception))


class _FlakyStep:
    """Raises on the Nth call, healthy otherwise."""

    def __init__(self, fail_on_call=None):
        self.calls = 0
        self.fail_on_call = fail_on_call

    def __call__(self, state, batch):
        self.calls += 1
        if self.calls == self.fail_on_call:
            raise RuntimeError("INTERNAL: remote_compile: body closed")
        return state, np.float32(0.5)

    def lower(self, *a, **kw):  # cost-analysis path: pretend unsupported
        raise NotImplementedError


def _fake_build_factory(fail_plan):
    """fail_plan: list of fail_on_call values, one per build_bench call."""
    builds = []

    def fake_build(batch_per_chip, multistep):
        step = _FlakyStep(
            fail_plan[len(builds)] if len(builds) < len(fail_plan) else None
        )
        builds.append(step)
        batch = {"image": np.zeros((batch_per_chip, 4))}
        fake_dev = types.SimpleNamespace(device_kind="TPU v5 lite")
        return step, None, batch, batch_per_chip, 1, [fake_dev]

    return fake_build, builds


def test_transient_failure_mid_window_rebuilds_and_completes(monkeypatch):
    # build #1's step dies mid-window-1 (warmup + window 0 ok); build #2 is
    # healthy — all WINDOWS must still complete
    fake_build, builds = _fake_build_factory(
        [bench.WARMUP_STEPS + bench.TIMED_STEPS + 5, None]
    )
    monkeypatch.setattr(bench, "build_bench", fake_build)
    _instant_retries(monkeypatch)
    (dts, step, state, batch, bs, n_chips, devs, errors) = (
        bench._timed_windows(8, 1)
    )
    assert len(dts) == bench.WINDOWS
    assert len(builds) == 2
    assert len(errors) == 1 and "remote_compile" in errors[0]
    # r3 advisor: pre-failure windows must NOT feed the median — every
    # window replays on the rebuilt (healthy) step
    assert builds[1].calls == bench.WARMUP_STEPS + (
        bench.WINDOWS * bench.TIMED_STEPS
    )


def test_retry_exhaustion_keeps_completed_windows(monkeypatch, capsys):
    """Budget exhaustion after some windows completed must still report the
    measured number (from the completed windows), not crash on a sentinel."""
    # build #1: warmup (WARMUP_STEPS calls) + window 0 (TIMED_STEPS calls)
    # ok, window 1 dies mid-way; every rebuild dies too -> exhaustion with
    # 1 good window
    calls = {"n": 0}

    def build_once_then_die(batch_per_chip, multistep):
        calls["n"] += 1
        if calls["n"] > 1:
            raise RuntimeError("tunnel still down")
        step = _FlakyStep(
            fail_on_call=bench.WARMUP_STEPS + bench.TIMED_STEPS + 5
        )
        batch = {"image": np.zeros((batch_per_chip, 4))}
        fake_dev = types.SimpleNamespace(device_kind="TPU v5 lite")
        return step, None, batch, batch_per_chip, 1, [fake_dev]

    monkeypatch.setattr(bench, "build_bench", build_once_then_die)
    _instant_retries(monkeypatch)
    monkeypatch.setattr(bench, "_device_step_ms", lambda *a, **kw: None)
    monkeypatch.setattr(bench, "MAX_RETRIES", 2)
    args = types.SimpleNamespace(batch=8, multistep=1)
    bench.main(args)
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] > 0  # window 0's measurement survived
    assert payload["windows_completed"] == 1
    assert payload["errors"]


def test_main_emits_json_even_when_everything_fails(monkeypatch, capsys):
    def always_broken(batch_per_chip, multistep):
        raise RuntimeError("tunnel down")

    monkeypatch.setattr(bench, "build_bench", always_broken)
    _instant_retries(monkeypatch)
    monkeypatch.setattr(bench, "MAX_RETRIES", 2)
    args = types.SimpleNamespace(batch=8, multistep=1)
    bench.main(args)
    out = capsys.readouterr().out.strip().splitlines()
    payload = json.loads(out[-1])  # the JSON line is ALWAYS the last line
    assert payload["metric"] == "resnet50_train_images_per_sec_per_chip"
    assert payload["value"] == 0.0
    assert payload["errors"]


# the autouse fixture stubs _backend_alive for the retry tests; keep a
# handle on the real implementation so it can be tested itself
_REAL_BACKEND_ALIVE = bench._backend_alive


def test_backend_alive_detects_block_error_and_health():
    import time

    # a dead relay BLOCKS (r4 failure mode): join timeout must catch it
    ok, err = _REAL_BACKEND_ALIVE(0.2, probe=lambda: time.sleep(60))
    assert not ok and "blocked" in err
    # an erroring backend raises: caught and reported
    ok, err = _REAL_BACKEND_ALIVE(5.0, probe=lambda: 1 / 0)
    assert not ok and "ZeroDivisionError" in err
    ok, err = _REAL_BACKEND_ALIVE(5.0, probe=lambda: 1.0)
    assert ok and err is None


def test_main_emits_degraded_json_when_backend_dead(monkeypatch, capsys):
    """Dead-tunnel gate: no backend work attempted, JSON still emitted."""
    monkeypatch.setattr(
        bench, "_backend_alive",
        lambda *a, **k: (False, "backend liveness probe still blocked"),
    )

    def must_not_run(*a, **k):
        raise AssertionError("build_bench must not run against a dead backend")

    monkeypatch.setattr(bench, "build_bench", must_not_run)
    args = types.SimpleNamespace(batch=128, multistep=1)
    bench.main(args)
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] == 0.0
    assert "blocked" in payload["errors"][0]


def test_emit_is_once_per_process(capsys):
    assert bench._emit({"metric": "m", "value": 1})
    assert not bench._emit({"metric": "m", "value": 2})
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1 and json.loads(lines[0])["value"] == 1


def test_timed_windows_stops_when_budget_nearly_exhausted(monkeypatch):
    """Past-deadline loop entry must break out (with the measured windows
    intact), not burn the remaining budget on doomed rebuild attempts."""
    import time

    fake_build, builds = _fake_build_factory([None])
    monkeypatch.setattr(bench, "build_bench", fake_build)
    monkeypatch.setattr(bench, "_DEADLINE", time.monotonic() - 1.0)
    dts, *_, errors = bench._timed_windows(8, 1)
    assert dts == [] and builds == []
    assert any("budget" in e for e in errors)


def test_cli_degraded_paths_exit_zero_within_budget():
    """End-to-end rehearsal of the r4 outage: a blocked (not erroring)
    backend must yield rc=0 + one parseable JSON line, first via the
    liveness gate, then via the watchdog."""
    import os
    import subprocess
    import time

    repo = os.path.dirname(os.path.abspath(bench.__file__))

    # (a) dead-from-the-start tunnel: the liveness gate reports, fast
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "bench.py", "--batch", "8"],
        cwd=repo,
        env={**os.environ, "BENCH_SIMULATE_DEAD": "1",
             "BENCH_INIT_BUDGET_S": "1", "BENCH_BUDGET_S": "600"},
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["value"] == 0.0
    assert "liveness" in " ".join(payload["errors"]), payload
    assert time.time() - t0 < 60

    # (b) backend alive but the run wedges mid-build: the watchdog
    # force-emits and hard-exits 0 even though the main thread never returns
    script = (
        "import time, types, bench\n"
        "bench._backend_alive = lambda *a, **k: (True, None)\n"
        "def wedge(*a, **k):\n"
        "    bench._log('compile')\n"
        "    time.sleep(3600)\n"
        "bench.build_bench = wedge\n"
        "args = types.SimpleNamespace(batch=8, multistep=1)\n"
        "result = bench.train_result_stub(args)\n"
        "bench._start_watchdog(result)\n"
        "bench.main(args, result)\n"
        "raise SystemExit('unreachable: watchdog must have exited')\n"
    )
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-c", script],
        cwd=repo,
        env={**os.environ, "BENCH_BUDGET_S": "4"},
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["value"] == 0.0
    assert "budget exhausted" in " ".join(payload["errors"]), payload
    assert "last stage: compile" in " ".join(payload["errors"]), payload
    assert time.time() - t0 < 60


def test_main_happy_path_reports_wall_rate_and_mfu(monkeypatch, capsys):
    fake_build, _ = _fake_build_factory([None])
    monkeypatch.setattr(bench, "build_bench", fake_build)
    monkeypatch.setattr(bench, "_device_step_ms", lambda *a, **kw: None)
    args = types.SimpleNamespace(batch=8, multistep=1)
    bench.main(args)
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] > 0
    assert payload["unit"] == "images/sec/chip"
    # wall semantics restored (ADVICE r2): vs_baseline is wall / target
    assert payload["vs_baseline"] == round(
        payload["value"] / bench.TARGET_PER_CHIP, 3
    )
    # analytic fallback path: flops reported even without cost analysis
    assert payload["flops_source"] == "analytic"
    assert payload["mfu_wall_pct"] > 0
