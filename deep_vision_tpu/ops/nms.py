"""Fixed-shape greedy NMS, jit-able on TPU.

Replaces the hand-rolled dynamic-shape while-loop NMS at
YOLO/tensorflow/postprocess.py:38-96 (tf.map_fn + boolean_mask per class) with
a static-shape algorithm: select max_detections boxes iteratively with
`lax.fori_loop`, suppressing by IoU mask — no dynamic shapes anywhere, so it
compiles once and runs on-device. Multi-label (per-class scores thresholded
independently, postprocess.py:58-63) with class offsets so one pass handles
all classes.

Two interchangeable selection backends behind `impl=`:
  - 'lax'    — the vmapped `_nms_single` fori_loop below (the reference);
  - 'pallas' — ops/pallas/nms.py, the same greedy loop pinned in VMEM
    (one grid step per image, no HBM round-trip per selection; runs the
    identical kernel under `interpret=True` off-TPU).
Default ('auto'): pallas on TPU, lax elsewhere; `DVT_NMS_IMPL=lax|pallas`
forces either (the disable flag for a suspicious-decode triage).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deep_vision_tpu.core import backend as dvt_backend
from deep_vision_tpu.core import knobs
from deep_vision_tpu.ops.boxes import broadcast_iou


def _resolve_impl(impl: Optional[str]) -> str:
    if impl in ("lax", "pallas"):
        return impl
    if impl not in (None, "auto"):
        raise ValueError(f"unknown NMS impl {impl!r} (lax|pallas|auto)")
    # the disable flag exists for triage — a typo ('LAX', trailing
    # space) silently running the suspect kernel defeats it, so the
    # choice knob raises on anything but lax|pallas
    env = knobs.get_choice("DVT_NMS_IMPL")
    if env:
        return env
    return dvt_backend.default_nms_impl()


def _nms_single(boxes, scores, max_detections: int, iou_threshold: float,
                score_threshold: float):
    """boxes (N,4) xyxy, scores (N,) -> (max_det,) scores, (max_det,) idx.

    Memory is O(N) per iteration: the IoU row of the selected box is computed
    on the fly (max_det * N total work) instead of materializing the NxN
    matrix, which at YOLO-scale N=10647 would be ~450MB/image.
    """
    n = boxes.shape[0]
    scores = jnp.where(scores >= score_threshold, scores, -1.0)

    def body(i, carry):
        live_scores, sel_idx, sel_score = carry
        best = jnp.argmax(live_scores)
        best_score = live_scores[best]
        keep = best_score > 0.0
        sel_idx = sel_idx.at[i].set(jnp.where(keep, best, -1))
        sel_score = sel_score.at[i].set(jnp.where(keep, best_score, 0.0))
        # suppress: the chosen box and anything overlapping it (one IoU row)
        iou_row = broadcast_iou(boxes[best][None, :], boxes)[0]  # (N,)
        suppress = (iou_row >= iou_threshold) | (jnp.arange(n) == best)
        live_scores = jnp.where(keep & suppress, -1.0, live_scores)
        return live_scores, sel_idx, sel_score

    sel_idx = jnp.full((max_detections,), -1, jnp.int32)
    sel_score = jnp.zeros((max_detections,), scores.dtype)
    _, sel_idx, sel_score = jax.lax.fori_loop(
        0, max_detections, body, (scores, sel_idx, sel_score)
    )
    return sel_score, sel_idx


def non_maximum_suppression(
    boxes,
    scores,
    classes=None,
    max_detections: int = 100,
    iou_threshold: float = 0.5,
    score_threshold: float = 0.5,
    impl: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Batched class-aware NMS.

    boxes: (B, N, 4) xyxy in [0,1]; scores: (B, N); classes: (B, N) int or None.
    Returns (boxes (B,D,4), scores (B,D), classes (B,D), valid (B,) count),
    D = max_detections. Padded entries have score 0 and class -1.
    impl: 'lax' | 'pallas' | None/'auto' (see module docstring).
    """
    if classes is None:
        classes = jnp.zeros(scores.shape, jnp.int32)

    # class offset trick: translate boxes per class so cross-class IoU is 0
    offsets = classes.astype(boxes.dtype)[..., None] * 2.0
    shifted = boxes + offsets

    if _resolve_impl(impl) == "pallas":
        from deep_vision_tpu.ops.pallas.nms import pallas_nms

        sel_s, sel_i = pallas_nms(
            shifted, scores, max_detections, iou_threshold, score_threshold
        )
        sel_s = sel_s.astype(scores.dtype)
        safe = jnp.maximum(sel_i, 0)
        picked = sel_i >= 0  # (B, D)
        out_classes = jnp.where(
            picked, jnp.take_along_axis(classes, safe, axis=1), -1)
        out_boxes = jnp.where(
            picked[..., None],
            jnp.take_along_axis(boxes, safe[..., None], axis=1), 0.0)
        out_scores = sel_s
    else:
        def per_image(b, s, c, raw_b):
            sel_s, sel_i = _nms_single(
                b, s, max_detections, iou_threshold, score_threshold
            )
            sel_c = jnp.where(sel_i >= 0, c[jnp.maximum(sel_i, 0)], -1)
            out_b = jnp.where((sel_i >= 0)[:, None], raw_b[jnp.maximum(sel_i, 0)], 0.0)
            return out_b, sel_s, sel_c

        out_boxes, out_scores, out_classes = jax.vmap(per_image)(
            shifted, scores, classes, boxes
        )
    valid = jnp.sum((out_classes >= 0).astype(jnp.int32), axis=-1)
    return out_boxes, out_scores, out_classes, valid
