"""jaxlint (deep_vision_tpu/lint): per-rule fixtures, suppressions,
baseline mechanics, CLI, and the self-lint gate.

Every rule gets at least one positive and one negative fixture — the
acceptance contract is that introducing any DV001-DV005 violation
fails `make lint` while the shipped tree stays clean.
"""
from __future__ import annotations

import json
import os
import textwrap
from pathlib import Path

import pytest

from deep_vision_tpu.lint import (
    Finding,
    lint_source,
    load_baseline,
    save_baseline,
    split_baselined,
)
from deep_vision_tpu.lint.engine import iter_python_files
from deep_vision_tpu.lint.__main__ import main

REPO_ROOT = Path(__file__).resolve().parents[1]


def run(src: str, **kw):
    kept, _ = lint_source(textwrap.dedent(src), "fixture.py", **kw)
    return kept


def codes(src: str, **kw):
    return [f.code for f in run(src, **kw)]


# -- DV001 host-sync-in-jit ---------------------------------------------------

def test_dv001_mixed_static_dynamic_cast_flagged():
    # shape metadata appearing in the expression must not excuse a traced
    # leaf: float(x.mean() * x.shape[0]) is still a per-step sync
    found = run("""
        import jax

        @jax.jit
        def step(x):
            a = float(x.mean() * x.shape[0])   # traced leaf: sync
            b = float(x.shape[0] / x.size)     # all-metadata: fine
            c = int(len(x) * 2)                # len is static: fine
            return a + b + c
    """, select=["DV001"])
    assert [(f.code, f.line) for f in found] == [("DV001", 6)]

def test_dv001_item_and_print_in_jit():
    found = run("""
        import jax

        @jax.jit
        def step(state, batch):
            loss = state.sum()
            print(loss)
            return loss.item()
    """, select=["DV001"])
    assert [f.code for f in found] == ["DV001", "DV001"]
    assert "jax.debug.print" in found[0].message
    assert found[1].symbol == "step"


def test_dv001_static_print_is_a_trace_time_log():
    # print("literal") inside jit runs once at trace time and prints
    # nothing traced — only printing a traced value is the hazard
    found = run("""
        import jax

        @jax.jit
        def step(x):
            print("compiling step")
            return x * 2
    """, select=["DV001"])
    assert found == []


def test_dv001_inside_associative_scan_callback():
    # the callback handed to lax.associative_scan is traced like any
    # other jit consumer (regression: the consumer table had a typo)
    found = run("""
        import jax

        def combine(a, b):
            return a.item() + b

        def scan(xs):
            return jax.lax.associative_scan(combine, xs)
    """, select=["DV001"])
    assert [f.symbol for f in found] == ["combine"]


def test_dv001_float_cast_flagged_shape_cast_not():
    src = """
        import jax

        @jax.jit
        def step(state, x):
            n = int(x.shape[0])      # static: fine
            lim = float("inf")       # literal: fine
            return float(x) + n + lim
    """
    found = run(src, select=["DV001"])
    assert [f.code for f in found] == ["DV001"]
    assert "float()" in found[0].message


def test_dv001_np_asarray_and_block_until_ready():
    assert codes("""
        import jax, numpy as np

        @jax.jit
        def step(state):
            host = np.asarray(state)
            jax.block_until_ready(state)
            return host
    """, select=["DV001"]) == ["DV001", "DV001"]


def test_dv001_np_array_constant_table_not_flagged():
    # np.array over literals is a trace-time constant, not a host pull;
    # np.asarray of the traced argument on the next line must still flag
    found = run("""
        import jax, numpy as np

        @jax.jit
        def step(x):
            table = np.array([1.0, 2.0, 4.0])
            return np.asarray(x) * table.sum()
    """, select=["DV001"])
    assert [(f.code, f.line) for f in found] == [("DV001", 7)]


def test_dv001_host_code_not_flagged():
    # the same calls OUTSIDE any jit context are the normal host idiom
    assert codes("""
        import jax, numpy as np

        def fetch(fn, x):
            out = jax.block_until_ready(fn(x))
            print(out)
            return float(np.asarray(out).sum())
    """) == []


def test_dv001_resolves_method_reference_jit():
    # the Trainer pattern: jax.jit(self._step_impl) marks the method traced
    found = run("""
        import jax

        class T:
            def __init__(self):
                self._fwd = jax.jit(self._fwd_impl)

            def _fwd_impl(self, state):
                return state.params.item()
    """)
    assert [f.code for f in found] == ["DV001"]
    assert found[0].symbol == "T._fwd_impl"


def test_dv001_resolves_partial_wrapped_jit():
    assert codes("""
        import functools
        import jax

        def decode(variables, images):
            return images.item()

        fn = functools.partial(decode, scale=2)
        decoder = jax.jit(fn)
    """) == ["DV001"]


# -- DV002 prng-key-reuse -----------------------------------------------------

def test_dv002_sampler_reuse_flagged():
    found = run("""
        import jax

        def f(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.uniform(key, (2,))
            return a + b
    """)
    assert [f.code for f in found] == ["DV002"]
    assert "'key'" in found[0].message


def test_dv002_split_keys_not_flagged():
    assert codes("""
        import jax

        def f(key):
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1, (2,))
            b = jax.random.uniform(k2, (2,))
            return a + b
    """) == []


def test_dv002_double_split_flagged():
    # splitting the same base twice yields identical subkeys
    assert codes("""
        import jax

        def f(key):
            k1, k2 = jax.random.split(key)
            k3, k4 = jax.random.split(key)
            return k1, k2, k3, k4
    """) == ["DV002"]


def test_dv002_fold_in_distinct_data_not_flagged():
    # the canonical per-index idiom: two independent streams minted from
    # one parent via fold_in with distinct data is NOT key reuse
    assert codes("""
        import jax

        def f(key):
            a = jax.random.fold_in(key, 0)
            b = jax.random.fold_in(key, 1)
            return jax.random.normal(a, (2,)) + jax.random.normal(b, (2,))
    """) == []


def test_dv002_fold_in_identical_data_flagged():
    found = run("""
        import jax

        def f(key):
            a = jax.random.fold_in(key, 1)
            b = jax.random.fold_in(key, 1)
            return a, b
    """)
    assert [f.code for f in found] == ["DV002"]
    assert "identical" in found[0].message


def test_dv002_split_default_num_collides_with_explicit_two():
    # split(key) and split(key, 2) yield the same subkeys
    assert codes("""
        import jax

        def f(key):
            k1, k2 = jax.random.split(key)
            k3, k4 = jax.random.split(key, 2)
            return k1, k2, k3, k4
    """) == ["DV002"]


def test_dv002_identical_derive_in_exclusive_arms_not_flagged():
    assert codes("""
        import jax

        def f(cond, key):
            if cond:
                k = jax.random.fold_in(key, 1)
            else:
                k = jax.random.fold_in(key, 1)
            return jax.random.normal(k, (2,))
    """) == []


def test_dv002_reuse_through_generic_call():
    # the GAN-trainer bug shape: one derived key feeding two model applies
    assert codes("""
        import jax

        def g(model, x, base):
            rng = jax.random.fold_in(base, 1)
            y = model.apply(x, rngs={"dropout": rng})
            z = model.apply(x, rngs={"dropout": rng})
            return y + z
    """) == ["DV002"]


def test_dv002_key_from_outside_loop_flagged():
    found = run("""
        import jax

        def f(key, xs):
            out = []
            for x in xs:
                out.append(jax.random.normal(key, (2,)))
            return out
    """)
    assert [f.code for f in found] == ["DV002"]
    assert "loop" in found[0].message


def test_dv002_fold_in_per_iteration_is_the_fix():
    # deriving a fresh subkey per iteration is the recommended idiom and
    # must NOT be flagged, including the deriver's own in-loop consumption
    assert codes("""
        import jax

        def f(key, xs):
            out = []
            for i, x in enumerate(xs):
                k = jax.random.fold_in(key, i)
                out.append(jax.random.normal(k, (2,)))
            return out
    """) == []


def test_dv002_subscripted_split_not_flagged():
    # r[0]..r[3] are distinct subkeys of one split
    assert codes("""
        import jax

        def f(model, x, base):
            r = jax.random.split(base, 4)
            a = model.apply(x, rngs={"dropout": r[0]})
            b = model.apply(x, rngs={"dropout": r[1]})
            return a + b
    """) == []


def test_dv002_key_arg_of_state_builder_not_treated_as_key():
    # `state = build(..., PRNGKey(0))` consumes a key, it does not mint one:
    # later generic uses of `state` must not count as key reuse
    assert codes("""
        import jax

        def f(model, tx, batch):
            state = build(model, tx, jax.random.PRNGKey(0))
            state = update(state, batch)
            state = update(state, batch)
            return state
    """) == []


def test_dv002_rebinding_fold_in_idiom_not_flagged():
    # `key = fold_in(key, i)` rebinding: the RHS consumes the OLD binding,
    # the sampler consumes the NEW one — no reuse either way
    assert codes("""
        import jax

        def f(key, xs):
            out = []
            for i, x in enumerate(xs):
                key = jax.random.fold_in(key, i)
                out.append(jax.random.normal(key, (2,)))
            return out
    """) == []
    assert codes("""
        import jax

        def g(key):
            key = jax.random.fold_in(key, 1)
            return jax.random.normal(key, (2,))
    """) == []


def test_dv002_recognizes_from_jax_import_random():
    # the `from jax import random` alias form must count as a sampler
    assert codes("""
        import jax
        from jax import random

        def f(key):
            a = random.normal(key, (2,))
            b = random.uniform(key, (2,))
            return a + b
    """) == ["DV002"]


def test_dv002_exclusive_branches_not_flagged():
    # only one arm ever executes: one consume each is correct code
    assert codes("""
        import jax

        def f(cond, rng):
            if cond:
                return jax.random.normal(rng, (2,))
            else:
                return jax.random.uniform(rng, (2,))
    """) == []
    # early-return arm: code after the if is the other arm in effect
    assert codes("""
        import jax

        def f(cond, rng):
            if cond:
                return jax.random.normal(rng, (2,))
            return jax.random.uniform(rng, (2,))
    """) == []
    # elif chain where every taken arm returns
    assert codes("""
        import jax

        def f(mode, rng):
            if mode == 0:
                return jax.random.normal(rng, (2,))
            elif mode == 1:
                return jax.random.uniform(rng, (2,))
            return jax.random.bernoulli(rng)
    """) == []


def test_dv002_reuse_across_coexecuting_branch_flagged():
    # a non-terminal if body falls through: its consume and the one after
    # the if CAN both run, so this is a real reuse
    found = run("""
        import jax

        def f(cond, rng):
            x = 0
            if cond:
                x = jax.random.normal(rng, (2,))
            return x + jax.random.uniform(rng, (2,))
    """)
    assert [(f.code, f.line) for f in found] == [("DV002", 8)]
    # two consumes inside the SAME arm are still a reuse
    assert codes("""
        import jax

        def f(cond, rng):
            if cond:
                a = jax.random.normal(rng, (2,))
                b = jax.random.uniform(rng, (2,))
                return a + b
            return jax.random.normal(rng, (2,))
    """) == ["DV002"]


# -- DV003 missing-donation ---------------------------------------------------

def test_dv003_undonated_train_step_flagged():
    found = run("""
        import jax

        def train_step(state, batch):
            return state

        step = jax.jit(train_step)
    """)
    assert [f.code for f in found] == ["DV003"]
    assert "donate_argnums" in found[0].message


def test_dv003_donated_train_step_ok():
    assert codes("""
        import jax

        def train_step(state, batch):
            return state

        step = jax.jit(train_step, donate_argnums=0)
    """) == []


def test_dv003_eval_step_exempt():
    # eval steps REUSE the state across batches; donation would be a bug
    assert codes("""
        import jax

        def eval_step(state, batch):
            return state

        e = jax.jit(eval_step)
    """) == []


def test_dv003_partial_wrapped_step_flagged():
    assert codes("""
        import functools
        import jax

        def train_step(state, batch, aux_weight):
            return state

        step = jax.jit(functools.partial(train_step, aux_weight=0.1))
    """) == ["DV003"]


def test_dv003_decorator_forms():
    assert codes("""
        import jax

        @jax.jit
        def update_params(params, grads):
            return params
    """) == ["DV003"]
    assert codes("""
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=0)
        def update_params(params, grads):
            return params
    """) == []


def test_dv003_non_state_step_not_flagged():
    # a "step" over plain arrays has nothing worth donating
    assert codes("""
        import jax

        def ray_step(x, dt):
            return x + dt

        s = jax.jit(ray_step)
    """) == []


def test_dv002_parent_key_consumed_after_split():
    # the JAX PRNG guide's canonical bug: split, then sample from the
    # parent — the parent stream is correlated with its subkeys
    found = run("""
        import jax

        def f(key, shape):
            k1, k2 = jax.random.split(key)
            a = jax.random.uniform(k1, shape)
            b = jax.random.normal(key, shape)
            return a + b
    """, select=["DV002"])
    assert [f.code for f in found] == ["DV002"]
    assert "after being split" in found[0].message


def test_dv002_rebound_parent_after_split_ok():
    # `key, sub = split(key)` discards the old parent: consuming the NEW
    # binding is clean, and repeated fold_in with distinct data is the
    # sanctioned idiom, not a consumption of the parent
    assert codes("""
        import jax

        def f(key, shape):
            key, sub = jax.random.split(key)
            a = jax.random.uniform(sub, shape)
            b = jax.random.normal(key, shape)
            k0 = jax.random.fold_in(b_key := jax.random.PRNGKey(0), 0)
            k1 = jax.random.fold_in(b_key, 1)
            return a + b
    """, select=["DV002"]) == []


# -- DV004 jit-in-loop --------------------------------------------------------

def test_dv004_jit_in_loop_flagged():
    found = run("""
        import jax

        def sweep(xs):
            outs = []
            for x in xs:
                f = jax.jit(lambda v: v + x)
                outs.append(f(x))
            return outs
    """)
    assert [f.code for f in found] == ["DV004"]
    assert "recompile" in found[0].message


def test_dv004_decorated_def_in_loop_flagged():
    assert codes("""
        import jax

        def sweep(xs):
            for x in xs:
                @jax.jit
                def f(v):
                    return v + 1
                f(x)
    """) == ["DV004"]


def test_dv004_non_jax_jit_method_in_loop_ok():
    # .jit() on something that isn't jax (a compiler wrapper, self.jit)
    # is not jax.jit; only jax-rooted calls recompile per iteration
    assert codes("""
        import jax

        def sweep(model, xs):
            outs = []
            for x in xs:
                outs.append(model.jit(x))
            return outs
    """) == []


def test_dv004_module_level_and_calls_in_loop_ok():
    # calling an already-jitted function in a loop is the POINT of jit
    assert codes("""
        import jax

        f = jax.jit(lambda v: v + 1)

        def sweep(xs):
            return [f(x) for x in xs]

        def sweep2(xs):
            out = []
            for x in xs:
                out.append(f(x))
            return out
    """) == []


def test_dv004_def_in_loop_with_deferred_jit_ok():
    # the jit call runs when make() is invoked, not per loop iteration
    assert codes("""
        import jax

        def build(xs):
            makers = []
            for x in xs:
                def make(body):
                    return jax.jit(body)
                makers.append(make)
            return makers
    """) == []


def test_dv004_aot_compile_in_dispatch_loop_flagged():
    # the serve-aware check: .lower().compile() in a request/dispatch
    # loop is compilation at serve time — users wait on XLA
    found = run("""
        import jax

        def dispatch_loop(fn, variables, queue):
            while True:
                batch = queue.get()
                exe = jax.jit(fn).lower(variables, batch).compile()
                exe(variables, batch)
    """)
    assert "DV004" in [f.code for f in found]
    assert any("warmup" in f.message for f in found)


def test_dv004_aot_compile_in_warmup_loop_ok():
    # warmup is THE sanctioned compile loop: one jit per model, one
    # lower/compile per bucket (serve/engine.py's shape)
    assert codes("""
        import jax

        def warmup(fn, variables, buckets, shape):
            compiled = {}
            jitted = jax.jit(fn, donate_argnums=1)
            for b in buckets:
                spec = jax.ShapeDtypeStruct((b,) + shape, "float32")
                compiled[b] = jitted.lower(variables, spec).compile()
            return compiled
    """) == []


def test_dv004_warmup_exemption_is_name_anchored():
    # 'warm' buried mid-name is not a warmup path: the exemption must
    # not weaken the gate for a function that merely contains the word
    assert codes("""
        import jax

        def swarm_dispatch(fn, xs):
            out = []
            for x in xs:
                out.append(jax.jit(fn)(x))
            return out
    """) == ["DV004"]


def test_dv004_non_lower_compile_in_loop_ok():
    # re.compile (and any non-AOT .compile) in a loop is not jax's
    # problem; calling an already-compiled executable is the point
    assert codes("""
        import re

        def scan_all(patterns, lines, exe, batches):
            out = []
            for p in patterns:
                out.append(re.compile(p))
            for b in batches:
                out.append(exe(b))
            return out
    """) == []


# -- DV005 impure-jit ---------------------------------------------------------

def test_dv005_self_write_time_and_np_random():
    found = run("""
        import time
        import jax
        import numpy as np

        class T:
            def __init__(self):
                self._go = jax.jit(self._go_impl)

            def _go_impl(self, state):
                self.count = 1
                t0 = time.perf_counter()
                noise = np.random.rand(3)
                return state
    """, select=["DV005"])
    assert [f.code for f in found] == ["DV005", "DV005", "DV005"]
    msgs = " ".join(f.message for f in found)
    assert "self.count" in msgs and "time.perf_counter" in msgs \
        and "np.random" in msgs


def test_dv005_jax_random_and_host_methods_ok():
    assert codes("""
        import time
        import jax
        import numpy as np

        @jax.jit
        def step(state, key):
            return state + jax.random.normal(key, (2,))

        class Host:
            def tick(self):
                self.t = time.time()       # host code: fine
                return np.random.rand(3)
    """, select=["DV005"]) == []


def test_dv005_nonlocal_write_flagged():
    assert codes("""
        import jax

        def make():
            n = 0

            @jax.jit
            def step(state):
                nonlocal n
                n = n + 1
                return state

            return step
    """, select=["DV005"]) == ["DV005"]


def test_dv005_from_jax_import_random_not_impure():
    # `from jax import random; random.normal(...)` IS jax.random
    assert codes("""
        import jax
        from jax import random

        @jax.jit
        def scale(x, key):
            return x + random.normal(key, (2,))
    """, select=["DV005"]) == []


def test_builtin_map_does_not_mark_callable_traced():
    # bare `map`/`checkpoint` are Python, not jax.lax: the callable's body
    # must not be treated as jit context (dotted jax.lax.map still counts)
    assert codes("""
        def parse(line):
            print(line)
            return float(line)

        def load(f):
            return list(map(parse, f))
    """, select=["DV001"]) == []
    assert codes("""
        import jax

        def body(x):
            return x.item()

        def run(xs):
            return jax.lax.map(body, xs)
    """, select=["DV001"]) == ["DV001"]


# -- DV006 untraced-python-branch --------------------------------------------

def test_dv006_branch_on_traced_arg_warns():
    found = run("""
        import jax

        @jax.jit
        def step(state, x):
            if x > 0:
                return state
            return -state
    """, select=["DV006"])
    assert [f.code for f in found] == ["DV006"]
    assert found[0].severity == "warning"
    assert "lax.cond" in found[0].message


def test_dv006_while_on_traced_arg_warns():
    assert codes("""
        import jax

        @jax.jit
        def iterate(x):
            while x > 0:
                x = x - 1
            return x
    """) == ["DV006"]


def test_dv006_static_tests_not_flagged():
    # shape arithmetic, pytree structure, None-checks, and keyword-only
    # config flags are all static under trace
    assert codes("""
        import jax

        @jax.jit
        def step(state, x, mask=None, *, causal=False):
            if x.shape[0] > 2:
                x = x[:2]
            if state.batch_stats:
                x = x + 1
            if mask is None:
                x = x * 2
            if causal:
                x = x * 3
            return x
    """, select=["DV006"]) == []


# -- DV007 trace-time-constant ------------------------------------------------

def test_dv007_from_import_time_in_jit():
    # DV005 catches `time.time()`; the bare alias form escapes its
    # attribute matching — DV007 closes the hole
    found = run("""
        import jax
        from time import time, perf_counter

        @jax.jit
        def step(x):
            t0 = perf_counter()
            return x * time() + t0
    """, select=["DV007"])
    assert [f.code for f in found] == ["DV007", "DV007"]
    assert "trace time" in found[0].message


def test_dv007_from_import_random_in_jit():
    assert [f.code for f in run("""
        import jax
        from random import randint

        @jax.jit
        def step(x):
            return x + randint(0, 9)
    """, select=["DV007"])] == ["DV007"]


def test_dv007_rng_object_method_in_jit():
    # np.random.default_rng() itself is DV005 territory; the *object's*
    # method calls are only visible to DV007's assignment tracking
    found = run("""
        import jax
        import numpy as np

        rng = np.random.default_rng(0)

        @jax.jit
        def step(x):
            return x + rng.normal()
    """, select=["DV007"])
    assert [f.code for f in found] == ["DV007"]
    assert "rng.normal" in found[0].message


def test_dv007_jax_random_alias_not_flagged():
    # `from jax import random` is the sanctioned sampler, not stdlib
    # impurity — the alias map must exclude it
    assert run("""
        import jax
        from jax import random

        @jax.jit
        def step(x, key):
            return x + random.normal(key)
    """, select=["DV007"]) == []


def test_dv007_host_use_outside_jit_not_flagged():
    assert run("""
        import numpy as np
        from time import perf_counter

        rng = np.random.default_rng(0)

        def host_loop(x):
            t0 = perf_counter()
            return x + rng.normal() + t0
    """, select=["DV007"]) == []


def test_dv007_datetime_now_in_jit():
    assert [f.code for f in run("""
        import jax
        import datetime

        @jax.jit
        def step(x):
            return x * datetime.datetime.now().microsecond
    """, select=["DV007"])] == ["DV007"]


def test_dv007_datetime_constructor_is_pure():
    # only .now()/.today() is impure; the class constructor is a literal
    # (regression: the alias map used to register the class name as a
    # bare-call trap and flag `datetime(1970, 1, 1)`)
    found = run("""
        import jax
        from datetime import datetime

        EPOCH = None

        @jax.jit
        def step(x):
            epoch = datetime(1970, 1, 1)
            return x + epoch.toordinal()
    """, select=["DV007"])
    assert found == []
    assert [f.code for f in run("""
        import jax
        from datetime import datetime

        @jax.jit
        def step(x):
            return x * datetime.now().microsecond
    """, select=["DV007"])] == ["DV007"]


# -- suppressions -------------------------------------------------------------

def test_inline_suppression_same_line():
    kept, dropped = lint_source(textwrap.dedent("""
        import jax

        @jax.jit
        def step(state):
            return state.item()  # jaxlint: disable=DV001 -- scalar debug path
    """), "fixture.py", select=["DV001"])
    assert kept == []
    assert [f.code for f in dropped] == ["DV001"]


def test_inline_suppression_preceding_line_and_all():
    kept, dropped = lint_source(textwrap.dedent("""
        import jax

        @jax.jit
        def step(state):
            # jaxlint: disable=all -- fixture
            return state.item()
    """), "fixture.py", select=["DV001"])
    assert kept == [] and len(dropped) == 1


def test_trailing_suppression_does_not_cover_next_line():
    # a trailing pragma acknowledges ITS line only; a fresh violation
    # added directly below must still fail the gate
    kept, dropped = lint_source(textwrap.dedent("""
        import jax

        @jax.jit
        def step(a, b):
            x = a.item()  # jaxlint: disable=DV001 -- acknowledged
            y = b.item()
            return x + y
    """), "fixture.py", select=["DV001"])
    assert [f.line for f in kept] == [7]
    assert [f.line for f in dropped] == [6]


def test_suppression_of_other_code_does_not_mask():
    kept, _ = lint_source(textwrap.dedent("""
        import jax

        @jax.jit
        def step(state):
            return state.item()  # jaxlint: disable=DV002 -- wrong code
    """), "fixture.py", select=["DV001", "DV002"])
    assert [f.code for f in kept] == ["DV001"]


def test_syntax_error_is_a_finding():
    kept, _ = lint_source("def broken(:\n", "fixture.py")
    assert [f.code for f in kept] == ["DV000"]
    assert kept[0].severity == "error"


# -- baseline -----------------------------------------------------------------

def _two_findings():
    return [
        Finding("DV001", "msg-a", "pkg/a.py", 3, 1, "error", "f"),
        Finding("DV003", "msg-b", "pkg/b.py", 9, 1, "error", "g"),
    ]


def test_baseline_roundtrip(tmp_path):
    path = str(tmp_path / "baseline.json")
    save_baseline(path, _two_findings())
    fresh, accepted = split_baselined(_two_findings(), load_baseline(path))
    assert fresh == [] and len(accepted) == 2


def test_baseline_is_line_drift_proof_but_counts_multiplicity(tmp_path):
    path = str(tmp_path / "baseline.json")
    save_baseline(path, _two_findings())
    moved = [Finding("DV001", "msg-a", "pkg/a.py", 300, 5, "error", "f")]
    fresh, accepted = split_baselined(moved, load_baseline(path))
    assert fresh == []  # same (code, path, symbol, message), new line: ok
    # a SECOND identical finding exceeds the baselined multiplicity
    fresh, _ = split_baselined(moved + moved, load_baseline(path))
    assert len(fresh) == 1


def test_missing_baseline_is_empty(tmp_path):
    assert load_baseline(str(tmp_path / "nope.json")) == {}


# -- CLI ----------------------------------------------------------------------

BAD_STEP = """\
import jax


def train_step(state, batch):
    return state


step = jax.jit(train_step)
"""


def _project(tmp_path, source=BAD_STEP):
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.jaxlint]
        paths = ["mod.py"]
        baseline = "baseline.json"
    """))
    (tmp_path / "mod.py").write_text(source)
    return str(tmp_path / "pyproject.toml")


def test_cli_exit_codes_and_baseline_flow(tmp_path, capsys):
    pp = _project(tmp_path)
    assert main(["--config", pp]) == 1  # new DV003
    assert main(["--config", pp, "--write-baseline"]) == 0
    assert (tmp_path / "baseline.json").exists()
    assert main(["--config", pp]) == 0  # baselined now
    # a NEW violation on top of the baseline still fails
    (tmp_path / "mod.py").write_text(
        BAD_STEP + "\n\nstep2 = jax.jit(train_step)\n")
    assert main(["--config", pp]) == 1
    capsys.readouterr()


def test_cli_select_and_no_baseline(tmp_path, capsys):
    pp = _project(tmp_path)
    assert main(["--config", pp, "--select", "DV001"]) == 0  # rule off
    assert main(["--config", pp, "--no-baseline"]) == 1
    capsys.readouterr()


def test_cli_json_format(tmp_path, capsys):
    pp = _project(tmp_path)
    rc = main(["--config", pp, "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["summary"]["errors"] == 1 and doc["summary"]["failed"]
    f = doc["findings"][0]
    assert f["code"] == "DV003" and f["path"] == "mod.py" and f["line"] == 8


def test_cli_warnings_do_not_fail_without_flag(tmp_path, capsys):
    pp = _project(tmp_path, source=textwrap.dedent("""
        import jax

        @jax.jit
        def scale(x):
            if x > 0:
                return x
            return -x
    """))
    assert main(["--config", pp]) == 0  # DV006 is warn-level
    assert main(["--config", pp, "--fail-on-warn"]) == 1
    capsys.readouterr()


def test_cli_config_fallback_parser_reads_pyproject(tmp_path):
    from deep_vision_tpu.lint.config import load_config

    pp = tmp_path / "pyproject.toml"
    pp.write_text(textwrap.dedent("""
        [tool.other]
        paths = ["nope"]

        [tool.jaxlint]
        paths = [
            "a",
            "b.py",
        ]
        baseline = "bl.json"
        disable = ["DV006"]
    """))
    cfg = load_config(str(pp))
    assert cfg["paths"] == ["a", "b.py"]
    assert cfg["baseline"] == "bl.json"
    assert cfg["disable"] == ["DV006"]
    assert cfg["root"] == str(tmp_path)


def test_cli_nonexistent_path_fails(tmp_path, capsys):
    # a typo'd paths entry must not silently lint zero files and pass
    pp = tmp_path / "pyproject.toml"
    pp.write_text('[tool.jaxlint]\npaths = ["no_such_dir"]\n'
                  'baseline = "b.json"\n')
    assert main(["--config", str(pp)]) == 1
    assert "does not exist" in capsys.readouterr().err


def test_cli_write_baseline_refuses_dv000(tmp_path, capsys):
    # baselining a config/parse error would permanently silence the guard:
    # a typo'd path or syntax-broken file must fail --write-baseline
    pp = tmp_path / "pyproject.toml"
    pp.write_text('[tool.jaxlint]\npaths = ["no_such_dir"]\n'
                  'baseline = "b.json"\n')
    assert main(["--config", str(pp), "--write-baseline"]) == 1
    assert "refusing" in capsys.readouterr().err
    assert not (tmp_path / "b.json").exists()
    pp.write_text('[tool.jaxlint]\npaths = ["bad.py"]\n'
                  'baseline = "b.json"\n')
    (tmp_path / "bad.py").write_text("def f(:\n")
    assert main(["--config", str(pp), "--write-baseline"]) == 1
    assert not (tmp_path / "b.json").exists()
    capsys.readouterr()


def test_cli_write_baseline_refuses_partial_rule_runs(tmp_path, capsys):
    # the baseline is the full-rule acceptance set: writing it from a
    # --select/--disable run would drop every other rule's entries
    pp = _project(tmp_path)
    assert main(["--config", pp, "--select", "DV002",
                 "--write-baseline"]) == 64
    assert "all rules enabled" in capsys.readouterr().err
    assert not (tmp_path / "baseline.json").exists()


def test_cli_config_disable_is_case_insensitive(tmp_path, capsys):
    # lowercase codes in [tool.jaxlint] disable must match the uppercase
    # rule registry, same as --disable on the CLI
    pp = _project(tmp_path)
    Path(pp).write_text(Path(pp).read_text() + 'disable = ["dv003"]\n')
    assert main(["--config", pp]) == 0  # the DV003 fixture is disabled
    capsys.readouterr()


def test_cli_unknown_select_code_is_usage_error(tmp_path, capsys):
    # a typo'd --select must not run zero rules and report "clean"
    pp = _project(tmp_path)
    assert main(["--config", pp, "--select", "DV0001"]) == 64
    assert "unknown rule code" in capsys.readouterr().err
    assert main(["--config", pp, "--disable", "DV999"]) == 64
    capsys.readouterr()


def test_exclude_is_a_root_relative_prefix(tmp_path):
    # `tools` must exclude tools/ but NOT pkg/tools/, and must also
    # drop an explicitly passed tools/file.py
    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "a.py").write_text("x = 1\n")
    (tmp_path / "pkg" / "tools").mkdir(parents=True)
    (tmp_path / "pkg" / "tools" / "b.py").write_text("x = 1\n")
    got = iter_python_files([str(tmp_path)], exclude=["tools"],
                            root=str(tmp_path))
    assert [os.path.relpath(p, tmp_path) for p in got] == [
        os.path.join("pkg", "tools", "b.py")]
    got = iter_python_files([str(tmp_path / "tools" / "a.py")],
                            exclude=["tools"], root=str(tmp_path))
    assert got == []


def test_cli_write_baseline_refuses_partial_paths(tmp_path, capsys):
    # writing from a path subset would drop every other file's accepted
    # entries from the baseline, same as a partial rule run
    pp = _project(tmp_path)
    assert main(["--config", pp, str(tmp_path / "mod.py"),
                 "--write-baseline"]) == 64
    assert "full" in capsys.readouterr().err
    assert not (tmp_path / "baseline.json").exists()


def test_cli_select_disable_conflict_is_usage_error(tmp_path, capsys):
    # selecting and disabling the same code would run zero rules and
    # report the repo clean — the gate must refuse instead
    pp = _project(tmp_path)
    assert main(["--config", pp, "--select", "DV001",
                 "--disable", "DV001"]) == 64
    assert "no rules enabled" in capsys.readouterr().err


def test_cli_unknown_config_disable_is_invalid(tmp_path, capsys):
    # a typo'd code in [tool.jaxlint] disable is a broken config file (2),
    # not a bad invocation (64)
    pp = _project(tmp_path)
    Path(pp).write_text(Path(pp).read_text() + 'disable = ["dv0003"]\n')
    assert main(["--config", pp]) == 2
    assert "unknown rule code" in capsys.readouterr().err


def test_cli_usage_error_exits_64(capsys):
    # bad invocation is 64, not argparse's default 2 (reserved for
    # invalid files, matching tools/check_journal.py)
    with pytest.raises(SystemExit) as exc:
        main(["--format", "yaml"])
    assert exc.value.code == 64
    capsys.readouterr()


def test_cli_corrupt_baseline_is_a_clean_error(tmp_path, capsys):
    pp = _project(tmp_path)
    (tmp_path / "baseline.json").write_text("{truncated")
    assert main(["--config", pp]) == 2
    assert "unreadable baseline" in capsys.readouterr().err
    (tmp_path / "baseline.json").write_text('{"version": 99, "findings": []}')
    assert main(["--config", pp]) == 2
    # a hand-edited row missing a required field is the same clean exit-2,
    # not a KeyError traceback
    (tmp_path / "baseline.json").write_text(
        '{"version": 1, "findings": [{"path": "mod.py", "message": "m"}]}')
    assert main(["--config", pp]) == 2
    assert "findings[0]" in capsys.readouterr().err


# -- the gate itself ----------------------------------------------------------

def test_repo_self_lint_clean(capsys):
    """The shipped tree lints clean under the checked-in baseline: every
    true positive was fixed, every deliberate exception carries an inline
    reason. This is `make lint`, as a test."""
    rc = main(["--config", str(REPO_ROOT / "pyproject.toml")])
    out = capsys.readouterr().out
    assert rc == 0, f"jaxlint found new violations:\n{out}"


def test_repo_gate_catches_injected_violation(tmp_path, capsys):
    """End-to-end teeth: the same config, plus one bad file, must fail."""
    bad = tmp_path / "bad_mod.py"
    bad.write_text(BAD_STEP)
    rc = main([str(bad), "--config", str(REPO_ROOT / "pyproject.toml")])
    capsys.readouterr()
    assert rc == 1
