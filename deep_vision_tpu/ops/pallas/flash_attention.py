"""Fused blockwise (flash) attention as Pallas TPU kernels, fwd + bwd.

Why a kernel: naive attention materializes the (T, T) score matrix in HBM —
at T=16k that is 1GB per head in fp32, and the op is HBM-bandwidth-bound.
The fused kernels stream K/V blocks through VMEM, keep the online-softmax
running (max, sumexp, accumulator) state in VMEM scratch across grid steps,
and never write scores to HBM: O(T) memory, MXU-bound.

This is the single-chip sibling of `parallel/ring_attention.py` (same online
softmax); ring attention distributes the sequence across chips, this kernel
fuses the per-chip block loop. The reference framework has no attention op
anywhere (SURVEY.md §5) — this is net-new capability for long-context
workloads.

Backward pass (FlashAttention-2 recipe): the forward additionally writes the
per-row logsumexp L = m + log(l); the backward recomputes score blocks from
(q, k, L) in VMEM — still O(T) HBM — in two kernels that match the TPU's
sequential grid:
  - dq kernel: grid (BH, q_blocks, k_blocks), dq accumulates in scratch
    across the inner k loop;
  - dkv kernel: grid (BH, k_blocks, q_blocks), dk/dv accumulate across the
    inner q loop.
Both use delta = rowsum(dO * O), computed outside (one fused XLA pass).

Grid layout note: TPU executes the grid sequentially (last dim fastest), so
VMEM scratch legally carries accumulators across the innermost dimension —
init at inner==0, write out at inner==last.

Measured on one v5e chip (B4 T4096 H8 D64, causal): fwd 7.7 ms vs 14.1 ms
for XLA's fused dense attention (1.8x, fp32 io); fwd+bwd 17.2 ms vs 41.0 ms
(2.4x, bf16 io), and fwd+bwd at T=16384 runs in 117 ms where dense would
materialize ~4GB of score gradients. Defaults (block_q=512, block_k=1024)
come from that sweep.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deep_vision_tpu.core import backend as dvt_backend
from deep_vision_tpu.core import knobs

NEG_INF = -1e30

# below this many tokens the dense einsum beats the flash kernel (and the
# kernel's 128-lane tiling would need padding anyway). The floor is a
# per-platform tuning knob — the crossover sits elsewhere on a v5e than
# on a v4 — so DVT_FLASH_MIN_TOKENS overrides it at trace time, the
# DVT_NMS_IMPL convention (a routing knob must never no-op on a typo).
# Lives with the kernel so BOTH consumers — the ViT backbone
# (models/vit.py) and ring attention's per-shard compute
# (parallel/ring_attention.py) — route through the same floor.
FLASH_MIN_TOKENS = 1024


def flash_min_tokens() -> int:
    """The routing floor, env-overridable; a mistyped value raises
    instead of silently running the default (knobs.get_int)."""
    env = knobs.get_int("DVT_FLASH_MIN_TOKENS", default=None)
    return FLASH_MIN_TOKENS if env is None else env


def _causal_mask(s, qi, ki, block_q, block_k):
    qpos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    kpos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return jnp.where(qpos >= kpos, s, NEG_INF)


def _block_visible(causal: bool, qi, ki, block_q: int, block_k: int):
    """False only for blocks strictly above the causal diagonal."""
    return jnp.logical_or(
        jnp.logical_not(causal), ki * block_k <= qi * block_q + block_q - 1
    )


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *rest,
                  scale: float, causal: bool, block_q: int, block_k: int,
                  need_lse: bool):
    if need_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # with causality, blocks strictly above the diagonal contribute nothing
    visible = _block_visible(causal, qi, ki, block_q, block_k)

    @pl.when(visible)
    def _attend():
        q = q_ref[0].astype(jnp.float32)  # (bq, d)
        k = k_ref[0].astype(jnp.float32)  # (bk, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (bq, bk)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)

        m_prev = m_scr[:, :1]  # (bq, 1)
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # (bq, bk); rows w/o keys: exp(NEG_INF)≈0
        alpha = jnp.exp(m_prev - m_new)  # (bq, 1)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    # finalize on the last k step (beyond-diagonal steps were masked no-ops)
    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-20)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        if need_lse:
            lse = m_scr[:, :1] + jnp.log(l)  # (bq, 1)
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref[0].shape)


def _flash_forward(q, k, v, *, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool, need_lse: bool = True):
    """Returns (out (B,T,H,D), lse (B*H, T, 128) f32 lane-broadcast).

    With need_lse=False (the inference-only primal) the lse output and its
    HBM write are elided entirely and None is returned for it."""
    b, t, h, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, t)
    block_k = min(block_k, tk)
    assert t % block_q == 0 and tk % block_k == 0, (
        f"seq lens ({t}, {tk}) must divide blocks ({block_q}, {block_k})"
    )
    # (B, T, H, D) -> (B*H, T, D): each grid row owns one (batch, head) pair
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, need_lse=need_lse,
    )
    out_shape = [jax.ShapeDtypeStruct((b * h, t, d), q.dtype)]
    out_specs = [pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0))]
    if need_lse:
        # lse broadcast across a 128-lane minor dim: Mosaic requires
        # (8, 128)-aligned blocks, so per-row residuals ride 128 lanes
        # (the layout the official TPU flash kernels use as well)
        out_shape.append(jax.ShapeDtypeStruct((b * h, t, 128), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, block_q, 128), lambda bh, qi, ki: (bh, qi, 0))
        )
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(b * h, t // block_q, tk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sumexp
            pltpu.VMEM((block_q, d), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
    )(qr, kr, vr)
    out, lse = res if need_lse else (res[0], None)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3), lse


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, scale: float, causal: bool, block_q: int,
               block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    visible = _block_visible(causal, qi, ki, block_q, block_k)

    @pl.when(visible)
    def _accum():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]      # (bq, 1)
        delta = delta_ref[0][:, :1]  # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse)  # (bq, bk); masked entries -> 0
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bq, bk)
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == nk - 1)
    def _write():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                causal: bool, block_q: int, block_k: int):
    ki = pl.program_id(1)  # note: k is the OUTER loop here
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    visible = _block_visible(causal, qi, ki, block_q, block_k)

    @pl.when(visible)
    def _accum():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse)  # (bq, bk)
        # dV += P^T dO
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale  # (bq, bk)
        # dK += dS^T Q
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    # the last q block is on/below the diagonal for every k block, so the
    # write step always executes
    @pl.when(qi == nq - 1)
    def _write():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, *, causal: bool, scale: float,
                    block_q: int, block_k: int, interpret: bool,
                    delta_shift=None):
    b, t, h, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, t)
    block_k = min(block_k, tk)
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    dor = g.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    outr = out.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    # delta = rowsum(dO * O): one fused elementwise+reduce pass in XLA,
    # broadcast across the 128-lane residual layout (see _flash_forward).
    # `delta_shift` (an lse cotangent, _flash_lse_bwd) subtracts in here.
    delta_row = jnp.sum(
        dor.astype(jnp.float32) * outr.astype(jnp.float32),
        axis=-1, keepdims=True,
    )
    if delta_shift is not None:
        delta_row = delta_row - delta_shift[..., None]
    delta = jnp.broadcast_to(delta_row, (b * h, t, 128))

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0))
    k_spec = pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0))
    row_spec = pl.BlockSpec((1, block_q, 128), lambda bh, qi, ki: (bh, qi, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        grid=(b * h, t // block_q, tk // block_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, lse, delta)

    # swapped grid: k blocks outer, q blocks inner
    q_spec2 = pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0))
    k_spec2 = pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0))
    row_spec2 = pl.BlockSpec((1, block_q, 128),
                             lambda bh, ki, qi: (bh, qi, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, d), v.dtype),
        ],
        grid=(b * h, tk // block_k, t // block_q),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=[k_spec2, k_spec2],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr, dor, lse, delta)

    unshape = lambda x, tt: x.reshape(b, h, tt, d).transpose(0, 2, 1, 3)
    return unshape(dq, t), unshape(dk, tk), unshape(dv, tk)


def _dense_reference(q, k, v, causal, scale):
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t, s_ = s.shape[-2], s.shape[-1]
        mask = jnp.arange(t)[:, None] >= jnp.arange(s_)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    # primal (inference) path: skip computing/writing the lse residual
    out, _ = _flash_forward(q, k, v, causal=causal, scale=scale,
                            block_q=block_q, block_k=block_k,
                            interpret=interpret, need_lse=False)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal=causal, scale=scale,
                              block_q=block_q, block_k=block_k,
                              interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal=causal, scale=scale,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret, need_lse=True)


def _flash_lse_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal=causal, scale=scale,
                              block_q=block_q, block_k=block_k,
                              interpret=interpret, need_lse=True)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(causal, scale, block_q, block_k, interpret, res, cts):
    """Backward when BOTH outputs carry cotangents (the ring-attention merge
    differentiates through lse).

    d lse / d s_j = p_j, so the lse cotangent enters the score gradient as
    ds += p * g_lse — algebraically a shift of the delta term:
    ds = p (dp - (delta - g_lse)) scale. The kernels take delta as an input,
    so the shift needs no kernel change.
    """
    q, k, v, out, lse = res
    g_out, g_lse = cts
    b, t, h, d = q.shape
    # cotangent of the 128-lane broadcast = sum over lanes
    g_lse_row = jnp.sum(g_lse.astype(jnp.float32), axis=-1)  # (BH, T)
    return _flash_backward(
        q, k, v, out, lse, g_out, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
        delta_shift=g_lse_row,
    )


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_with_lse(
    q, k, v, *, causal: bool = False, scale: Optional[float] = None,
    block_q: int = 512, block_k: int = 1024,
    interpret: Optional[bool] = None,
):
    """flash_attention that also returns the per-row logsumexp.

    lse comes back as (B*H, T, 128) f32 with the value broadcast across the
    lane dim (take `[:, :, 0]`). Differentiable in both outputs — the
    building block for blockwise merges (parallel/ring_attention.py).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = dvt_backend.pallas_interpret()
    return _flash_lse(q, k, v, causal, float(scale), int(block_q),
                      int(block_k), bool(interpret))


def flash_attention(
    q, k, v, *, causal: bool = False, scale: Optional[float] = None,
    block_q: int = 512, block_k: int = 1024,
    interpret: Optional[bool] = None,
):
    """Fused attention. q: (B, Tq, H, D); k, v: (B, Tk, H, D).

    Differentiable: the backward runs the Pallas dq / dkv kernels above
    (O(T) memory), so the op is safe for long-sequence *training*, not just
    inference.

    `interpret=None` auto-selects: compiled on TPU, interpreter elsewhere
    (the CPU test path; `conftest.py` meshes run it interpreted).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = dvt_backend.pallas_interpret()
    return _flash(q, k, v, causal, float(scale), int(block_q), int(block_k),
                  bool(interpret))
