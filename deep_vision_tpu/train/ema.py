"""Exponential moving average of model params (evaluation weights).

Net-new utility (the reference has nothing like it; modern vision recipes —
MoCo, EfficientNet, the CenterNet paper's test-time setup — evaluate an EMA
of the weights rather than the raw optimum). Device-resident and jitted: the
update is one fused multiply-add pass over the param tree, so enabling it
costs a single extra HBM sweep per step.

Usage (standalone):

    ema = EmaParams(state.params, decay=0.999)
    for batch in data:
        state, _ = train_step(state, batch)
        ema.update(state.params)
    eval_metrics = eval_fn(ema.params)

or via ``Trainer(..., ema_decay=0.999)`` which maintains it automatically
and evaluates with the averaged weights.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


# donate the incoming EMA tree (DV003): update() immediately rebinds
# self.params to the return value, so the old shadow buffer is dead the
# moment this is called — donation lets XLA update it in place instead of
# holding a second full-precision copy of the params in HBM
@functools.partial(jax.jit, donate_argnums=0)
def _ema_update(ema, params, decay):
    # debiasing handled by the warmup decay schedule below, not a division:
    # keeps the update a single fused pass with no extra state
    return jax.tree_util.tree_map(
        lambda e, p: e * decay + p.astype(e.dtype) * (1.0 - decay),
        ema, params,
    )


class EmaParams:
    """Shadow copy of a param pytree, EMA-updated in place on device."""

    def __init__(self, params, decay: float = 0.999, warmup: bool = True):
        self.decay = float(decay)
        self.warmup = warmup
        self._count = 0
        # copy=True: the caller's params are typically the train state that
        # jitted steps DONATE — an aliased buffer would be deleted by the
        # first step and poison the first update
        self.params = jax.tree_util.tree_map(
            lambda p: jnp.array(p, jnp.float32, copy=True), params
        )

    def update(self, params) -> None:
        self._count += 1
        d = self.decay
        if self.warmup:
            # tf.train.ExponentialMovingAverage zero-debias: ramp the decay
            # so early steps aren't dominated by the random init
            d = min(d, (1.0 + self._count) / (10.0 + self._count))
        self.params = _ema_update(self.params, params, d)

    # -- checkpoint side-car ------------------------------------------------
    def state_dict(self) -> dict:
        return {"count": self._count, "decay": self.decay,
                "warmup": self.warmup}

    def load_state_dict(self, d: dict) -> None:
        self._count = int(d.get("count", 0))
        self.decay = float(d.get("decay", self.decay))
        self.warmup = bool(d.get("warmup", self.warmup))
