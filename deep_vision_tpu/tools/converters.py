"""Dataset -> sharded record conversion with process-parallel shard writers.

Parity targets (field names byte-compatible, so shards interop both ways):
- VOC: XML parse + normalized-bbox Example (Datasets/VOC2007/tfrecords.py:
  38-95,124-155), train/val/test splits from ImageSets (:163-175).
- COCO: JSON -> per-image grouped annotations (Datasets/MSCOCO/tfrecords.py:
  135+), 64/8 shard convention (:13-14).
- MPII: joints x/y normalized + visibility (Datasets/MPII/
  tfrecords_mpii.py:54-84).
- ImageNet: synset label from folder/filename + label index Example
  (Datasets/ILSVRC2012/build_imagenet_tfrecord.py:184+, 1024/128 shards).
- CycleGAN: image-only Examples, one file per split
  (CycleGAN/tensorflow/tfrecords.py).

The reference fans out with Ray (`@ray.remote build_single_tfrecord`,
VOC2007/tfrecords.py:98-107) or threads (ImageNet). Here:
`multiprocessing.Pool` over shard chunks — same parallelism, stdlib only.
"""
from __future__ import annotations

import json
import os
import shutil
import xml.etree.ElementTree as ET
from multiprocessing import Pool
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from deep_vision_tpu.data.example_codec import encode_example
from deep_vision_tpu.data.records import RecordWriter

VOC_CLASSES = (
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)


def chunkify(items: Sequence, n_chunks: int) -> List[List]:
    """Split into n roughly-equal chunks (chunkify, VOC2007/tfrecords.py:20-28)."""
    if not items:
        return []
    n_chunks = max(1, min(n_chunks, len(items)))
    size = -(-len(items) // n_chunks)
    return [list(items[i:i + size]) for i in range(0, len(items), size)]


def _write_shard(args) -> int:
    chunk, path, make_example = args
    n = 0
    with RecordWriter(path) as w:
        for anno in chunk:
            ex = make_example(anno)
            if ex is not None:
                w.write(encode_example(ex))
                n += 1
    return n


def build_shards(
    annotations: Sequence,
    make_example: Callable[[dict], Optional[dict]],
    out_dir: str,
    prefix: str,
    num_shards: int,
    num_workers: Optional[int] = None,
) -> List[str]:
    """Fan annotation chunks out to worker processes, one shard file each.

    Shard naming mirrors the reference: `{prefix}_{i:04d}_of_{n:04d}.tfrecord`.
    """
    os.makedirs(out_dir, exist_ok=True)
    chunks = chunkify(annotations, num_shards)
    jobs = [
        (
            chunk,
            os.path.join(
                out_dir, f"{prefix}_{i:04d}_of_{len(chunks):04d}.tfrecord"
            ),
            make_example,
        )
        for i, chunk in enumerate(chunks)
    ]
    if num_workers is None:
        num_workers = min(len(jobs), os.cpu_count() or 1)
    if num_workers <= 1 or len(jobs) == 1:
        counts = [_write_shard(j) for j in jobs]
    else:
        with Pool(num_workers) as pool:
            counts = pool.map(_write_shard, jobs)
    print(f"wrote {sum(counts)} examples to {len(jobs)} shards in {out_dir}")
    return [j[1] for j in jobs]


# -- VOC ---------------------------------------------------------------------

def voc_annotations(voc_root: str, split: str = "train") -> List[dict]:
    """Parse VOCdevkit annotations for an ImageSets/Main split
    (VOC2007/tfrecords.py:124-175)."""
    split_file = os.path.join(voc_root, "ImageSets", "Main", f"{split}.txt")
    with open(split_file) as f:
        ids = [line.strip().split()[0] for line in f if line.strip()]
    annos = []
    for image_id in ids:
        xml_path = os.path.join(voc_root, "Annotations", f"{image_id}.xml")
        root = ET.parse(xml_path).getroot()
        size = root.find("size")
        anno = {
            "filename": f"{image_id}.jpg",
            "filepath": os.path.join(voc_root, "JPEGImages", f"{image_id}.jpg"),
            "width": int(size.find("width").text),
            "height": int(size.find("height").text),
            "depth": int(size.find("depth").text or 3),
            "bboxes": [],
        }
        for obj in root.iter("object"):
            name = obj.find("name").text
            box = obj.find("bndbox")
            anno["bboxes"].append(
                {
                    "class_id": VOC_CLASSES.index(name),
                    "class_text": name,
                    "xmin": float(box.find("xmin").text),
                    "ymin": float(box.find("ymin").text),
                    "xmax": float(box.find("xmax").text),
                    "ymax": float(box.find("ymax").text),
                }
            )
        annos.append(anno)
    return annos


def detection_example(anno: dict) -> Optional[dict]:
    """Normalized-bbox Example, exact field names of VOC2007/tfrecords.py:69-93."""
    with open(anno["filepath"], "rb") as f:
        content = f.read()
    w, h = anno["width"], anno["height"]
    xmins, ymins, xmaxs, ymaxs, ids, texts = [], [], [], [], [], []
    for b in anno["bboxes"]:
        xmin, ymin = b["xmin"] / w, b["ymin"] / h
        xmax, ymax = b["xmax"] / w, b["ymax"] / h
        if not all(0.0 <= v <= 1.0 for v in (xmin, ymin, xmax, ymax)):
            # reference hard-asserts (tfrecords.py:61-64); tolerate + clamp
            xmin, ymin = max(0.0, min(1.0, xmin)), max(0.0, min(1.0, ymin))
            xmax, ymax = max(0.0, min(1.0, xmax)), max(0.0, min(1.0, ymax))
        xmins.append(xmin)
        ymins.append(ymin)
        xmaxs.append(xmax)
        ymaxs.append(ymax)
        ids.append(int(b["class_id"]))
        texts.append(b["class_text"].encode())
    return {
        "image/height": [anno["height"]],
        "image/width": [anno["width"]],
        "image/depth": [anno.get("depth", 3)],
        "image/object/bbox/xmin": xmins,
        "image/object/bbox/ymin": ymins,
        "image/object/bbox/xmax": xmaxs,
        "image/object/bbox/ymax": ymaxs,
        "image/object/class/label": ids,
        "image/object/class/text": texts,
        "image/encoded": [content],
        "image/filename": [anno["filename"].encode()],
    }


# -- COCO --------------------------------------------------------------------

def coco_annotations(instances_json: str, images_dir: str) -> List[dict]:
    """COCO instances JSON -> per-image grouped annos
    (Datasets/MSCOCO/tfrecords.py:135+). Category ids are remapped to a dense
    0..C-1 range sorted by original id (COCO ids have holes)."""
    with open(instances_json) as f:
        coco = json.load(f)
    cat_ids = sorted(c["id"] for c in coco["categories"])
    cat_index = {cid: i for i, cid in enumerate(cat_ids)}
    cat_name = {c["id"]: c["name"] for c in coco["categories"]}
    by_image: Dict[int, List[dict]] = {}
    for a in coco.get("annotations", []):
        if a.get("iscrowd"):
            continue
        by_image.setdefault(a["image_id"], []).append(a)
    annos = []
    for img in coco["images"]:
        boxes = []
        for a in by_image.get(img["id"], ()):
            x, y, bw, bh = a["bbox"]  # COCO xywh absolute
            boxes.append(
                {
                    "class_id": cat_index[a["category_id"]],
                    "class_text": cat_name[a["category_id"]],
                    "xmin": x,
                    "ymin": y,
                    "xmax": x + bw,
                    "ymax": y + bh,
                }
            )
        annos.append(
            {
                "filename": img["file_name"],
                "filepath": os.path.join(images_dir, img["file_name"]),
                "width": img["width"],
                "height": img["height"],
                "depth": 3,
                "bboxes": boxes,
            }
        )
    return annos


# -- MPII --------------------------------------------------------------------

def mpii_annotations(json_path: str, images_dir: str) -> List[dict]:
    """Preprocessed MPII train/validation.json (the input format the
    reference consumes, Datasets/MPII/tfrecords_mpii.py)."""
    with open(json_path) as f:
        people = json.load(f)
    annos = []
    for p in people:
        annos.append(
            {
                "filename": p["image"],
                "filepath": os.path.join(images_dir, p["image"]),
                "joints": p["joints"],  # [[x, y] * 16] absolute
                "joints_vis": p["joints_vis"],
                # MPII person center/scale (scale x 200 px = body height),
                # consumed by the CropRoi transform; optional in older
                # preprocessed jsons
                "center": p.get("center"),
                "scale": p.get("scale"),
            }
        )
    return annos


def mpii_example(anno: dict) -> Optional[dict]:
    """Keypoint Example (tfrecords_mpii.py:65-84): normalized x/y + visibility."""
    from deep_vision_tpu.data.datasets import decode_image

    with open(anno["filepath"], "rb") as f:
        content = f.read()
    img = decode_image(content)
    h, w = img.shape[:2]
    xs = [float(j[0]) / w for j in anno["joints"]]
    ys = [float(j[1]) / h for j in anno["joints"]]
    vis = [int(v) for v in anno["joints_vis"]]
    ex = {
        "image/height": [h],
        "image/width": [w],
        "image/person/keypoints/x": xs,
        "image/person/keypoints/y": ys,
        "image/person/keypoints/visibility": vis,
        "image/encoded": [content],
        "image/filename": [anno["filename"].encode()],
    }
    # person scale (image/object/scale at Datasets/MPII/tfrecords_mpii.py):
    # drives the CropRoi body-height pad (scale x 200 px). center is written
    # for record-schema parity with the reference only — its crop_roi reads
    # but never uses it (preprocess.py:52-53), and neither does CropRoi.
    if anno.get("scale") is not None:
        ex["image/person/scale"] = [float(anno["scale"])]
    if anno.get("center") is not None:
        cx, cy = anno["center"]
        ex["image/person/center/x"] = [float(cx) / w]
        ex["image/person/center/y"] = [float(cy) / h]
    return ex


# -- ImageNet ----------------------------------------------------------------

def imagenet_annotations(root: str, synsets_path: str) -> List[dict]:
    """Flattened `nXXXXXXXX_*.JPEG` folder -> annotations with 1-based labels
    (0 reserved for background, build_imagenet_tfrecord.py convention)."""
    with open(synsets_path) as f:
        synsets = [line.strip().split()[0] for line in f if line.strip()]
    label_of = {s: i + 1 for i, s in enumerate(synsets)}
    annos = []
    for name in sorted(os.listdir(root)):
        if not name.lower().endswith((".jpeg", ".jpg", ".png")):
            continue
        synset = name.split("_")[0]
        annos.append(
            {
                "filename": name,
                "filepath": os.path.join(root, name),
                "synset": synset,
                "label": label_of[synset],
            }
        )
    return annos


def imagenet_example(anno: dict) -> Optional[dict]:
    """Colorspace/synset/label Example (build_imagenet_tfrecord.py:184+);
    non-JPEG/non-RGB inputs (PNG, CMYK jpegs) are re-encoded to RGB JPEG so
    the stamped format/colorspace metadata is truthful — the reference's
    PNG/CMYK fixups (:256-308)."""
    import io

    from PIL import Image

    with open(anno["filepath"], "rb") as f:
        content = f.read()
    img = Image.open(io.BytesIO(content))
    if img.format != "JPEG" or img.mode != "RGB":
        buf = io.BytesIO()
        img.convert("RGB").save(buf, format="JPEG", quality=95)
        content = buf.getvalue()
    return {
        "image/colorspace": [b"RGB"],
        "image/channels": [3],
        "image/class/label": [anno["label"]],
        "image/class/synset": [anno["synset"].encode()],
        "image/format": [b"JPEG"],
        "image/filename": [anno["filename"].encode()],
        "image/encoded": [content],
    }


# -- CycleGAN ----------------------------------------------------------------

def cyclegan_examples(images_dir: str) -> Iterable[dict]:
    """Image-only annos for one domain split (CycleGAN/tensorflow/tfrecords.py)."""
    return [
        {"filepath": os.path.join(images_dir, n), "filename": n}
        for n in sorted(os.listdir(images_dir))
        if n.lower().endswith((".jpg", ".jpeg", ".png"))
    ]


def image_only_example(anno: dict) -> Optional[dict]:
    with open(anno["filepath"], "rb") as f:
        content = f.read()
    return {
        "image/encoded": [content],
        "image/filename": [anno["filename"].encode()],
    }


def celeba_split(
    attr_file: str,
    images_dir: str,
    out_dir: str,
    attribute: str = "Male",
    copy: bool = True,
) -> Tuple[int, int]:
    """Split CelebA into trainA/trainB domain folders by a binary attribute.

    The CycleGAN data story's first step (CycleGAN/tensorflow/celeba.py:1-24,
    which hardcodes byte offsets into list_attr_celeba.txt for the gender
    column); here the attribute is looked up by name from the header so any
    of the 40 CelebA attributes works. +1 -> trainA, -1 -> trainB.

    Returns (n_trainA, n_trainB). Missing image files are skipped.
    """
    with open(attr_file) as fp:
        fp.readline()  # line 1: image count
        names = fp.readline().split()  # line 2: attribute names
        if attribute not in names:
            raise ValueError(f"attribute {attribute!r} not in {names}")
        col = names.index(attribute)
        rows = [line.split() for line in fp if line.strip()]

    dir_a = os.path.join(out_dir, "trainA")
    dir_b = os.path.join(out_dir, "trainB")
    os.makedirs(dir_a, exist_ok=True)
    os.makedirs(dir_b, exist_ok=True)
    counts = [0, 0]
    n_skipped = 0
    for row in rows:
        filename, flags = row[0], row[1:]
        value = int(flags[col])
        if value not in (-1, 1):
            raise ValueError(f"bad attribute value {value} for {filename}")
        src = os.path.join(images_dir, filename)
        if not os.path.exists(src):
            n_skipped += 1
            continue
        dst_dir = dir_a if value == 1 else dir_b
        if copy:
            shutil.copyfile(src, os.path.join(dst_dir, filename))
        counts[0 if value == 1 else 1] += 1
    if rows and not (counts[0] or counts[1]):
        raise FileNotFoundError(
            f"none of the {len(rows)} listed images exist under {images_dir!r}"
            " — wrong --images-dir?"
        )
    if n_skipped:
        print(f"celeba_split: skipped {n_skipped} rows with missing images")
    return counts[0], counts[1]
