# Cloud training image — the TPU-native analog of the reference's CUDA
# recipe (Hourglass/tensorflow/Dockerfile:1-19): same shape (deps -> env ->
# code -> train entrypoint), but built for a Cloud TPU VM, where the TPU
# runtime comes from the jax[tpu] wheel instead of an nvidia base image.
#
#   docker build -t deep-vision-tpu .
#   docker run --privileged --net=host deep-vision-tpu -m lenet5 --fake-data
#   docker run --privileged --net=host \
#       -e UPLOAD_TO=gs://my-bucket/runs deep-vision-tpu -m resnet50 \
#       --data-dir /data --upload-to gs://my-bucket/runs
#
# --privileged/--net=host: required for the container to reach the TPU
# driver and its gRPC runtime on a Cloud TPU VM.
FROM python:3.12-slim

ENV LC_ALL=C.UTF-8 \
    LANG=C.UTF-8 \
    PYTHONUNBUFFERED=TRUE \
    PYTHONDONTWRITEBYTECODE=TRUE

RUN pip install --no-cache-dir \
    "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    flax optax orbax-checkpoint numpy opencv-python-headless \
    google-crc32c google-cloud-storage

WORKDIR /app
COPY pyproject.toml train.py ./
COPY deep_vision_tpu ./deep_vision_tpu

ENTRYPOINT ["python3", "train.py"]
