"""The production data plane: iterator snapshots + the shared dataset service.

Covers data/snapshot.py (DataLoaderState save/restore determinism — the
byte-identical-stream contract behind `make data-smoke` and the chaos
deterministic-resume phase), the satellite epoch-derivation fix (a
resumed loader replays the same shard order instead of restarting its
private epoch counter at zero), the bad-record-budget carryover, and
data/service.py (frame codec, client/server round-trip, worker-death
supervision, client reconnect, per-host shard assignment).
"""
import hashlib
import json
import os
import socket
import struct
import threading

import numpy as np
import pytest


# -- fixtures: tiny record shards (module-level fns stay spawn-picklable) -----

def _smoke_schema(feats):
    raw = np.frombuffer(feats["image/raw"][0], np.uint8)
    side = int(np.sqrt(raw.size))  # 4x4 fixtures; 32x32 for real models
    return {
        "image": raw.reshape(side, side, 1),
        "label": np.int32(feats["image/class/label"][0]),
    }


def _to_float(sample, rng):
    return {"image": sample["image"].astype(np.float32) / 255.0,
            "label": sample["label"]}


def _write_shards(tmp_path, n_shards=3, per_shard=20, corrupt_at=(),
                  side=4):
    from deep_vision_tpu.data.example_codec import encode_example
    from deep_vision_tpu.data.records import write_records

    rng = np.random.RandomState(0)
    for s in range(n_shards):
        write_records(
            str(tmp_path / f"train-{s:03d}"),
            [encode_example({
                "image/raw": [rng.randint(0, 256, size=(side, side, 1),
                                          dtype=np.uint8).tobytes()],
                "image/class/label": [i % 10],
            }) for i in range(per_shard)],
        )
    for path, offset in corrupt_at:
        p = str(tmp_path / path)
        data = bytearray(open(p, "rb").read())
        data[offset] ^= 0xFF  # flip a data byte: CRC catches, budget skips
        open(p, "wb").write(bytes(data))
    return str(tmp_path / "train-*")


def _loader(pattern, budget=None, **kw):
    from deep_vision_tpu.data.datasets import RecordDataset
    from deep_vision_tpu.data.pipeline import DataLoader

    ds = RecordDataset(pattern, _smoke_schema, shuffle_shards=True, seed=3,
                       bad_record_budget=budget)
    args = dict(batch_size=8, transform=_to_float, shuffle=True,
                shuffle_buffer=16, num_workers=2, drop_remainder=True,
                seed=5, prefetch=2, name="t")
    args.update(kw)
    dl = DataLoader(ds, **args)
    if dl.snapshot_supported():
        dl.enable_snapshots()  # what Trainer does for its data_loader
    return dl


def _hashes(batches):
    out = []
    for b in batches:
        h = hashlib.sha1()
        for k in sorted(b):
            h.update(np.ascontiguousarray(b[k]).tobytes())
        out.append(h.hexdigest())
    return out


# -- snapshot: save/restore determinism ---------------------------------------

class TestSnapshot:
    def test_mid_epoch_restore_byte_identical(self, tmp_path):
        pattern = _write_shards(tmp_path)
        ref = _loader(pattern)
        epochs = [_hashes(ref) for _ in range(3)]

        b = _loader(pattern)
        assert _hashes(b) == epochs[0]
        it = iter(b)
        prefix = _hashes([next(it) for _ in range(3)])
        state = b.state_dict()
        assert state["epoch"] == 1 and state["batches"] == 3
        del it

        c = _loader(pattern)
        info = c.load_state_dict(state)
        assert info["epoch"] == 1 and info["batches"] == 3
        assert prefix + _hashes(c) == epochs[1]
        assert _hashes(c) == epochs[2]  # and the NEXT epoch stays aligned

    def test_boundary_restore_continues_next_epoch(self, tmp_path):
        pattern = _write_shards(tmp_path)
        ref = _loader(pattern)
        e0, e1 = _hashes(ref), _hashes(ref)

        a = _loader(pattern)
        assert _hashes(a) == e0
        state = a.state_dict()  # epoch boundary: resume = next epoch clean
        assert state["epoch"] == 1 and state["batches"] == 0
        c = _loader(pattern)
        c.load_state_dict(state)
        assert _hashes(c) == e1

    def test_mid_shard_cursor_reported(self, tmp_path):
        pattern = _write_shards(tmp_path)
        a = _loader(pattern, prefetch=0, shuffle=False, shuffle_buffer=0)
        it = iter(a)
        [next(it) for _ in range(3)]  # 24 samples: into shard 2 of 3x20
        state = a.state_dict()
        cur = state["cursor"]
        assert cur is not None and cur["shard"] in a.dataset.files
        assert cur["read"] >= 3 * 8  # the frontier covers what was consumed
        assert cur["record"] >= 0 and cur["shard_index"] >= 1
        del it
        # and the mid-shard position restores byte-identically
        ref = _loader(pattern, prefetch=0, shuffle=False, shuffle_buffer=0)
        full = _hashes(ref)
        c = _loader(pattern, prefetch=0, shuffle=False, shuffle_buffer=0)
        c.load_state_dict(state)
        assert _hashes(c) == full[3:]

    def test_epoch_rng_derived_not_process_local(self, tmp_path):
        """Satellite regression: a FRESH process (fresh loader) armed at
        epoch N must replay epoch N's shard order — the old code derived
        it from a private per-process iteration counter that silently
        restarted at 0 after a kill/resume."""
        pattern = _write_shards(tmp_path)
        ref = _loader(pattern)
        _, e1 = _hashes(ref), _hashes(ref)

        fresh = _loader(pattern)  # new process, counter at 0
        fresh.load_state_dict(
            {"version": 1, "epoch": 1, "batches": 0,
             "epoch_seed": fresh.seed + 1,
             "fingerprint": fresh._fingerprint()})
        assert _hashes(fresh) == e1

    def test_budget_spend_carryover(self, tmp_path):
        from deep_vision_tpu.data.records import BadRecordBudget

        pattern = _write_shards(tmp_path,
                                corrupt_at=[("train-000", 150),
                                            ("train-001", 300)])
        ref_budget = BadRecordBudget(max_count=50)
        ref = _loader(pattern, budget=ref_budget)
        e0, e1 = _hashes(ref), _hashes(ref)
        want = ref_budget.spend()
        assert want["bad"] > 0  # the corruption is actually exercised

        b_budget = BadRecordBudget(max_count=50)
        b = _loader(pattern, budget=b_budget)
        it = iter(b)
        prefix = _hashes([next(it) for _ in range(2)])
        state = b.state_dict()
        assert state["budget"]["bad"] >= 0
        del it

        c_budget = BadRecordBudget(max_count=50)
        c = _loader(pattern, budget=c_budget)
        c.load_state_dict(state)
        rest0, rest1 = _hashes(c), _hashes(c)
        assert prefix + rest0 == e0 and rest1 == e1
        # the resumed run's total spend equals the uninterrupted run's
        assert c_budget.spend() == want

    def test_fingerprint_mismatch_refuses(self, tmp_path):
        import deep_vision_tpu.data.snapshot as snap

        pattern = _write_shards(tmp_path)
        a = _loader(pattern)
        state = a.state_dict()
        other = tmp_path / "other"
        other.mkdir()
        pattern2 = _write_shards(other, n_shards=2)
        b = _loader(pattern2)
        with pytest.raises(snap.SnapshotMismatch):
            b.load_state_dict(state)

    def test_fingerprint_covers_loader_shape(self, tmp_path):
        """shuffle/shuffle_buffer/drop_remainder change the post-shuffle
        order `skip` counts in — a snapshot must refuse across them."""
        import deep_vision_tpu.data.snapshot as snap

        pattern = _write_shards(tmp_path)
        a = _loader(pattern)
        state = a.state_dict()
        for changed in (_loader(pattern, shuffle_buffer=64),
                        _loader(pattern, shuffle=False),
                        _loader(pattern, drop_remainder=False)):
            with pytest.raises(snap.SnapshotMismatch):
                changed.load_state_dict(state)

    def test_state_dict_refuses_unarmed_mid_epoch(self, tmp_path):
        """Iterating before enable_snapshots() must not fabricate a
        position — the loud-refusal half of the ring contract."""
        import deep_vision_tpu.data.snapshot as snap
        from deep_vision_tpu.data.datasets import RecordDataset
        from deep_vision_tpu.data.pipeline import DataLoader

        pattern = _write_shards(tmp_path)
        dl = DataLoader(RecordDataset(pattern, _smoke_schema, seed=3), 8,
                        shuffle=True, shuffle_buffer=16,
                        drop_remainder=True, seed=5)
        it = iter(dl)
        next(it)
        with pytest.raises(snap.SnapshotError):
            dl.state_dict()
        del it

    def test_num_procs_refuses(self, tmp_path):
        import deep_vision_tpu.data.snapshot as snap

        pattern = _write_shards(tmp_path)
        dl = _loader(pattern, num_procs=2)
        with pytest.raises(snap.SnapshotUnsupported):
            dl.state_dict()
        with pytest.raises(snap.SnapshotUnsupported):
            dl.load_state_dict({"epoch": 0, "batches": 0})

    def test_state_validates(self):
        import deep_vision_tpu.data.snapshot as snap

        with pytest.raises(snap.SnapshotMismatch):
            snap.validate_state({"epoch": 0, "batches": 0,
                                 "epoch_seed": 0, "fingerprint": "",
                                 "version": 99})
        with pytest.raises(snap.SnapshotMismatch):
            snap.validate_state({"epoch": -1, "batches": 0,
                                 "epoch_seed": 0, "fingerprint": ""})


# -- trainer integration: the sidecar carries the loader ----------------------

class TestTrainerIntegration:
    def _trainer(self, loader, ckpt_dir, journal=None):
        import jax.numpy as jnp

        from deep_vision_tpu.core import CheckpointManager
        from deep_vision_tpu.losses import classification_loss_fn
        from deep_vision_tpu.models import get_model
        from deep_vision_tpu.train import Trainer, build_optimizer

        return Trainer(
            get_model("lenet5", num_classes=10),
            build_optimizer("sgd", 0.05),
            classification_loss_fn,
            sample_input=jnp.zeros((8, 32, 32, 1)),
            checkpoint_manager=CheckpointManager(str(ckpt_dir),
                                                 journal=journal),
            journal=journal, data_loader=loader,
        )

    def test_checkpoint_carries_data_state_and_resume_journals(
            self, tmp_path):
        from deep_vision_tpu.obs import RunJournal

        pattern = _write_shards(tmp_path, side=32)
        jpath = str(tmp_path / "run.jsonl")
        journal = RunJournal(jpath)
        loader = _loader(pattern)
        tr = self._trainer(loader, tmp_path / "ckpt", journal)
        tr.fit(lambda: loader, None, epochs=1)
        tr.close()

        # a fresh "process": new loader, new trainer, resume
        loader2 = _loader(pattern)
        tr2 = self._trainer(loader2, tmp_path / "ckpt", journal)
        start = tr2.resume()
        assert start == 1
        # the loader was re-armed at the checkpointed position
        assert loader2._epoch == 1 and loader2._resume is not None
        tr2.close()
        journal.close()
        events = [json.loads(ln) for ln in open(jpath) if ln.strip()]
        resumes = [e for e in events if e["event"] == "data_resume"]
        assert len(resumes) == 1
        assert resumes[0]["verdict"] == "restored"
        assert resumes[0]["epoch"] == 1 and resumes[0]["batches"] == 0

    def test_resume_without_data_state_is_fresh(self, tmp_path):
        from deep_vision_tpu.obs import RunJournal

        pattern = _write_shards(tmp_path, side=32)
        jpath = str(tmp_path / "run.jsonl")
        journal = RunJournal(jpath)
        loader = _loader(pattern)
        # checkpoint written WITHOUT a data_loader attached (pre-PR12 run)
        tr = self._trainer(None, tmp_path / "ckpt", journal)
        tr.fit(lambda: loader, None, epochs=1)
        tr.close()
        loader2 = _loader(pattern)
        tr2 = self._trainer(loader2, tmp_path / "ckpt", journal)
        tr2.resume()
        tr2.close()
        journal.close()
        events = [json.loads(ln) for ln in open(jpath) if ln.strip()]
        resumes = [e for e in events if e["event"] == "data_resume"]
        assert len(resumes) == 1 and resumes[0]["verdict"] == "fresh"


# -- service: framing + codec -------------------------------------------------

class TestServiceCodec:
    def test_batch_roundtrip(self):
        from deep_vision_tpu.data.service import decode_batch, encode_batch

        batch = {"image": np.random.RandomState(0).rand(4, 8, 8, 3)
                 .astype(np.float32),
                 "label": np.arange(4, dtype=np.int32)}
        out = decode_batch(encode_batch(batch))
        assert set(out) == set(batch)
        for k in batch:
            assert out[k].dtype == batch[k].dtype
            assert np.array_equal(out[k], batch[k])

    def test_frame_roundtrip_and_corruption(self):
        from deep_vision_tpu.data.service import recv_frame, send_frame

        a, b = socket.socketpair()
        try:
            send_frame(a, b"hello world")
            assert recv_frame(b) == b"hello world"
            # corrupt payload: flip a byte behind a valid header
            payload = b"x" * 32
            header = struct.pack("<Q", len(payload))
            from deep_vision_tpu.data.records import _masked_crc

            a.sendall(header + struct.pack("<I", _masked_crc(header))
                      + b"y" + payload[1:]
                      + struct.pack("<I", _masked_crc(payload)))
            with pytest.raises(IOError):
                recv_frame(b)
        finally:
            a.close()
            b.close()

    def test_clean_eof_is_none(self):
        from deep_vision_tpu.data.service import recv_frame

        a, b = socket.socketpair()
        a.close()
        try:
            assert recv_frame(b) is None
        finally:
            b.close()


# -- service: per-host shard assignment ---------------------------------------

class TestShardForHost:
    def test_disjoint_and_covering(self):
        from deep_vision_tpu.data.service import shard_for_host

        files = [f"s{i:03d}" for i in range(17)]
        for num_hosts in (1, 2, 4, 5):
            parts = [shard_for_host(h, num_hosts, files)
                     for h in range(num_hosts)]
            flat = [f for p in parts for f in p]
            assert sorted(flat) == sorted(files)  # covering
            assert len(flat) == len(set(flat))    # disjoint

    def test_index_form_feeds_record_dataset(self, tmp_path):
        from deep_vision_tpu.data.datasets import RecordDataset
        from deep_vision_tpu.data.service import shard_for_host

        pattern = _write_shards(tmp_path, n_shards=4)
        full = RecordDataset(pattern, _smoke_schema)
        seen = []
        for h in range(2):
            si, ns = shard_for_host(h, 2)
            part = RecordDataset(pattern, _smoke_schema,
                                 shard_index=si, num_shards=ns)
            seen.extend(part.files)
        assert sorted(seen) == sorted(full.files)

    def test_rejects_bad_ids(self):
        from deep_vision_tpu.data.service import shard_for_host

        with pytest.raises(ValueError):
            shard_for_host(2, 2)
        with pytest.raises(ValueError):
            shard_for_host(0, 0)


# -- service: live client/server ----------------------------------------------

class TestServiceLive:
    def _service(self, pattern, journal=None, registry=None, **kw):
        from deep_vision_tpu.data.datasets import RecordDataset
        from deep_vision_tpu.data.service import DataService

        ds = RecordDataset(pattern, _smoke_schema, shuffle_shards=True,
                           seed=3)
        args = dict(batch_size=8, num_workers=1, shuffle_buffer=16,
                    seed=7, queue_depth=8, worker_poll_s=0.3,
                    journal=journal, registry=registry)
        args.update(kw)
        return DataService(ds, **args)

    def test_round_trip_two_clients_fixed_shapes(self, tmp_path):
        from deep_vision_tpu.data.service import DataServiceClient
        from deep_vision_tpu.obs.registry import Registry

        pattern = _write_shards(tmp_path)
        reg = Registry()
        svc = self._service(pattern, registry=reg).start()
        try:
            c1 = DataServiceClient(svc.address, name="c1", registry=reg)
            c2 = DataServiceClient(svc.address, name="c2", registry=reg)
            got1, got2 = [], []
            t = threading.Thread(
                target=lambda: got2.extend(c2.batches(3)), daemon=True)
            t.start()
            got1.extend(c1.batches(3))
            t.join(timeout=60)
            assert not t.is_alive()
            for b in got1 + got2:
                assert b["image"].shape == (8, 4, 4, 1)
                assert b["label"].shape == (8,)
            # one shared stream: the two consumers' batches are disjoint
            assert not (set(_hashes(got1)) & set(_hashes(got2)))
            c1.close()
            c2.close()
        finally:
            svc.close()

    def test_worker_death_absorbed_and_journaled(self, tmp_path):
        from deep_vision_tpu.data.service import DataServiceClient
        from deep_vision_tpu.obs import RunJournal
        from deep_vision_tpu.obs.registry import Registry
        from deep_vision_tpu.resilience import faults

        pattern = _write_shards(tmp_path)
        jpath = str(tmp_path / "svc.jsonl")
        journal = RunJournal(jpath)
        journal.manifest()
        os.environ[faults.ENV_SPEC] = "data.service:crash@4"
        os.environ[faults.ENV_SEED] = "0"
        try:
            svc = self._service(pattern, journal=journal,
                                registry=Registry()).start()
            c = DataServiceClient(svc.address, name="c", journal=journal,
                                  registry=Registry())
            got = list(c.batches(6))  # 48 samples: well past the crash
            assert len(got) == 6
            assert c.reconnects == 0  # absorbed server-side
            c.close()
            svc.close()
        finally:
            os.environ.pop(faults.ENV_SPEC, None)
            os.environ.pop(faults.ENV_SEED, None)
        journal.close()
        events = [json.loads(ln) for ln in open(jpath) if ln.strip()]
        lost = [e for e in events if e["event"] == "data_worker_lost"]
        rec = [e for e in events if e["event"] == "data_worker_recovered"]
        assert len(lost) >= 1 and len(rec) >= 1
        assert lost[0]["worker"] == rec[0]["worker"] == 0
        # strict schema validation accepts the whole journal
        sys_path_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        import subprocess
        import sys as _sys

        rc = subprocess.run(
            [_sys.executable,
             os.path.join(sys_path_root, "tools", "check_journal.py"),
             jpath, "--strict"],
            env=dict(os.environ, PYTHONPATH=sys_path_root)).returncode
        assert rc == 0

    def test_client_reconnects_on_frame_fault(self, tmp_path):
        from deep_vision_tpu.data.service import DataServiceClient
        from deep_vision_tpu.obs.registry import Registry
        from deep_vision_tpu.resilience import install_spec

        pattern = _write_shards(tmp_path)
        svc = self._service(pattern, registry=Registry()).start()
        try:
            c = DataServiceClient(svc.address, name="c",
                                  registry=Registry())
            assert c.get() is not None  # healthy first batch
            install_spec("data.service:io_error@2", export_env=False)
            try:
                got = [c.get() for _ in range(3)]
            finally:
                install_spec(None)
            assert len(got) == 3
            assert c.reconnects >= 1
            c.close()
        finally:
            svc.close()


# -- schemas ------------------------------------------------------------------

class TestJournalSchemas:
    def _check(self, rows):
        import subprocess
        import sys as _sys
        import tempfile

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                         delete=False) as f:
            base = {"ts": 0.0, "run_id": "t"}
            f.write(json.dumps({"event": "run_manifest", "kind": "train",
                                "argv": [], **base}) + "\n")
            for r in rows:
                f.write(json.dumps({**base, **r}) + "\n")
            f.write(json.dumps({"event": "exit", "status": "clean_exit",
                                **base}) + "\n")
            path = f.name
        try:
            return subprocess.run(
                [_sys.executable,
                 os.path.join(root, "tools", "check_journal.py"),
                 path, "--strict"],
                env=dict(os.environ, PYTHONPATH=root),
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL).returncode
        finally:
            os.unlink(path)

    def test_valid_data_plane_events_pass_strict(self):
        assert self._check([
            {"event": "data_resume", "verdict": "restored", "epoch": 2,
             "batches": 3, "shard": "train-0", "record": 17},
            {"event": "data_resume", "verdict": "fresh", "epoch": 0,
             "batches": 0},
            {"event": "data_worker_lost", "worker": 1, "attempt": 1,
             "error": "died"},
            {"event": "data_worker_recovered", "worker": 1, "attempt": 1},
            {"event": "data_service", "role": "server", "batches": 10},
            {"event": "data_service", "role": "client", "batches": 10,
             "reconnects": 1},
        ]) == 0

    def test_invalid_data_plane_events_fail_strict(self):
        assert self._check([{"event": "data_resume", "verdict": "maybe",
                             "epoch": 0, "batches": 0}]) != 0
        assert self._check([{"event": "data_resume", "verdict": "restored",
                             "epoch": "two", "batches": 0}]) != 0
        assert self._check([{"event": "data_worker_lost", "worker": "w0",
                             "attempt": 1}]) != 0
        assert self._check([{"event": "data_service", "role": "pump",
                             "batches": 1}]) != 0
        assert self._check([{"event": "data_service", "role": "server",
                             "batches": "many"}]) != 0

    def test_obs_report_renders_data_plane(self, tmp_path):
        import subprocess
        import sys as _sys

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        path = str(tmp_path / "j.jsonl")
        base = {"ts": 0.0, "run_id": "t"}
        with open(path, "w") as f:
            for r in [
                {"event": "run_manifest", "kind": "train", "argv": []},
                {"event": "data_service", "role": "server", "batches": 42,
                 "workers_lost": 1, "workers_recovered": 1},
                {"event": "data_service", "role": "client", "batches": 42,
                 "reconnects": 2},
                {"event": "data_resume", "verdict": "restored", "epoch": 1,
                 "batches": 4, "shard": "/x/train-0"},
                {"event": "exit", "status": "clean_exit"},
            ]:
                f.write(json.dumps({**base, **r}) + "\n")
        out = subprocess.run(
            [_sys.executable, os.path.join(root, "tools", "obs_report.py"),
             path],
            env=dict(os.environ, PYTHONPATH=root),
            stdout=subprocess.PIPE).stdout.decode()
        assert "data service [server]" in out
        assert "data service [client]" in out and "2 reconnect" in out
        assert "data resume" in out and "restored" in out
