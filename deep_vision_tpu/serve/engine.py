"""AOT inference engine: every (model, bucket) pair compiles at startup.

The serving steady state must never trace: `warmup()` walks the
registered models' bucket menus and runs
`jax.jit(fn, donate_argnums=1).lower(...).compile()` for each batch
shape, so the first user request hits an executable, not the compiler.
`run()` only ever looks up a pre-compiled executable by exact batch
size — an unwarmed shape raises instead of silently jitting, which is
the same contract jaxlint DV004 enforces statically on dispatch loops.

Donation: the IMAGES argument (argnum 1) is donated, not the variables —
detectors reuse `variables` across every request (donating state on an
eval path is a use-after-free, the DV003 exemption rationale), while a
request's input buffer is dead the moment the batch is dispatched, so
its HBM is reusable for the outputs. inference.py's per-call jits carry
the same donation. EXCEPTION: with an ExecutableCache attached, warmup
lowers WITHOUT donation — jax's executable serialize round trip drops
the donated-buffer bookkeeping, and a deserialized donating executable
aliases buffers the caller still owns (measured: a segfault on the
second call). One batch buffer of HBM is the price of every cached
executable being safe to reload.
"""
from __future__ import annotations

import time
import warnings
from typing import Dict, Sequence, Tuple

import jax
import numpy as np

from deep_vision_tpu.obs import perfwatch
from deep_vision_tpu.obs.trace import span
from deep_vision_tpu.serve.buckets import DEFAULT_BUCKETS, normalize_buckets


class ServeError(RuntimeError):
    """Serving contract violation (unwarmed bucket, unknown model, bad
    request shape)."""


class ModelEntry:
    """One registered model: the raw predict fn + its static serving menu."""

    __slots__ = ("name", "fn", "variables", "input_shape", "dtype", "buckets")

    def __init__(self, name: str, fn, variables, input_shape: Tuple[int, ...],
                 dtype, buckets: Tuple[int, ...]):
        self.name = name
        self.fn = fn  # (variables, images) -> dict of batched outputs
        self.variables = variables
        self.input_shape = tuple(int(d) for d in input_shape)
        self.dtype = dtype
        self.buckets = buckets


class Engine:
    """Multi-model AOT compile cache over one device.

    Wire-up (what serve/router.py and tools/serve_smoke.py do):

        eng = Engine(journal=journal)
        eng.register("yolo", yolo_predict_fn(model), variables,
                     input_shape=(416, 416, 3), buckets=(1, 2, 4, 8))
        stats = eng.warmup()       # compiles every (model, bucket) pair
        out = eng.run("yolo", images)   # images.shape[0] must be a bucket
    """

    def __init__(self, journal=None, registry=None, excache=None):
        self.journal = journal
        #: core.excache.ExecutableCache or None: with a cache attached,
        #: warmup() loads AOT-serialized executables instead of paying
        #: the compiler — a restarted server (or a replica respawned
        #: onto a fresh device) warms with ZERO backend compiles
        self.excache = excache
        self._entries: Dict[str, ModelEntry] = {}
        self._compiled: Dict[Tuple[str, int], object] = {}
        self._warmed = False
        if registry is None:
            from deep_vision_tpu.obs.registry import get_registry

            registry = get_registry()
        self._registry = registry
        self._g_warmed = registry.gauge(
            "serve_warmed_buckets", "(model, bucket) executables compiled")

    # -- registration --------------------------------------------------------

    def register(self, name: str, fn, variables,
                 input_shape: Sequence[int],
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 dtype=np.float32) -> ModelEntry:
        if self._warmed:
            raise ServeError(
                f"register({name!r}) after warmup: the bucket menu is "
                "closed once compiled (restart to change it)")
        if name in self._entries:
            raise ServeError(f"model {name!r} already registered")
        entry = ModelEntry(name, fn, variables, tuple(input_shape), dtype,
                           normalize_buckets(buckets))
        self._entries[name] = entry
        return entry

    @property
    def models(self) -> Tuple[str, ...]:
        return tuple(self._entries)

    def entry(self, name: str) -> ModelEntry:
        e = self._entries.get(name)
        if e is None:
            raise ServeError(
                f"unknown model {name!r}; registered: {sorted(self._entries)}")
        return e

    # -- warmup --------------------------------------------------------------

    def warmup(self) -> dict:
        """Compile (or cache-load) every (model, bucket) pair; returns
        the warmup report (pairs, per-pair compile ms + source,
        backend-compile counter delta, cache hits). With an attached
        ExecutableCache a fully warm cache means ZERO backend compiles —
        the restarted-server / fresh-device cold path costs a disk read.

        The ONE sanctioned compile loop in the serving path — jaxlint's
        serve-aware DV004 exempts warm* functions and flags the same
        .lower().compile() chain anywhere near a dispatch loop.
        """
        from deep_vision_tpu.obs.stepclock import recompile_count

        if not self._entries:
            raise ServeError("warmup() with no registered models")
        compiles_before = recompile_count()
        pairs = []
        for entry in self._entries.values():
            # the jit wrapper hoists out of the bucket loop: one traced
            # callable per model, one lowering+compile per bucket shape.
            # CACHE PATH LOWERS WITHOUT DONATION: jax's executable
            # serialize round trip drops the donated-buffer bookkeeping,
            # so a deserialized donating executable aliases buffers the
            # caller still owns — measured as a segfault on the second
            # call (use-after-free). The donated image buffer is one
            # batch of HBM; correctness of every cached executable wins.
            if self.excache is not None:
                jitted = jax.jit(entry.fn)
            else:
                jitted = jax.jit(entry.fn, donate_argnums=1)
            for bucket in entry.buckets:
                spec = jax.ShapeDtypeStruct(
                    (bucket,) + entry.input_shape, entry.dtype)
                t0 = time.perf_counter()
                with span("serve/warmup", model=entry.name, bucket=bucket), \
                        warnings.catch_warnings():
                    # CPU has no donation support and warns per lowering;
                    # the donation is real on TPU and free to declare here
                    warnings.filterwarnings(
                        "ignore", message="Some donated buffers")
                    lowered = jitted.lower(entry.variables, spec)
                    if self.excache is not None:
                        compiled, source = self.excache.get_or_compile(
                            lowered, name=f"{entry.name}/b{bucket}")
                    else:
                        compiled, source = lowered.compile(), "compiled"
                ms = (time.perf_counter() - t0) * 1e3
                self._compiled[(entry.name, bucket)] = compiled
                pairs.append({"model": entry.name, "bucket": bucket,
                              "compile_ms": round(ms, 1), "source": source})
                # perf attribution (obs/perfwatch): the warmup loop is the
                # one place the serving path holds a compiled executable,
                # so its XLA cost + collective inventory are journaled
                # here (typed perf_profile/perf_collective); extraction
                # failures cost fields, never the warmup
                perfwatch.profile_compiled(
                    f"serve/{entry.name}/b{bucket}", compiled,
                    journal=self.journal, registry=self._registry,
                    extra={"source": source})
        self._warmed = True
        self._g_warmed.set(len(self._compiled))
        stats = {
            "models": len(self._entries),
            "pairs": len(pairs),
            "backend_compiles": recompile_count() - compiles_before,
            "cache_hits": sum(1 for p in pairs if p["source"] == "cache"),
            "compile_ms_total": round(sum(p["compile_ms"] for p in pairs), 1),
            "detail": pairs,
        }
        if self.journal is not None:
            self.journal.write("note", note="serve_warmup", **{
                k: v for k, v in stats.items() if k != "detail"})
        return stats

    @property
    def warmed(self) -> bool:
        return self._warmed

    def warmed_buckets(self, name: str) -> Tuple[int, ...]:
        return tuple(b for (n, b) in self._compiled if n == name)

    # -- weight swap (serve/swap.py) -----------------------------------------

    @staticmethod
    def _check_like(name: str, old, new) -> None:
        """New variables must be executable-compatible with the old ones:
        the compiled executables were lowered against the OLD avals, and
        variables are a runtime argument, so same tree structure + same
        per-leaf shape/dtype means the swap needs no compiler at all."""
        old_s = jax.tree_util.tree_structure(old)
        new_s = jax.tree_util.tree_structure(new)
        if old_s != new_s:
            raise ServeError(
                f"swap variables for {name!r} have a different tree "
                f"structure than the serving ones ({new_s} != {old_s}); "
                "a structural change needs a re-warm, not a hot swap")
        for o, n in zip(jax.tree_util.tree_leaves(old),
                        jax.tree_util.tree_leaves(new)):
            if (tuple(getattr(o, "shape", ())) != tuple(getattr(n, "shape", ()))
                    or np.dtype(getattr(o, "dtype", np.float32))
                    != np.dtype(getattr(n, "dtype", np.float32))):
                raise ServeError(
                    f"swap variables for {name!r} change a leaf aval "
                    f"({getattr(n, 'shape', ())}/{getattr(n, 'dtype', '?')} "
                    f"vs {getattr(o, 'shape', ())}/"
                    f"{getattr(o, 'dtype', '?')}); shape/dtype changes "
                    "need a re-warm, not a hot swap")

    def set_variables(self, name: str, variables) -> None:
        """Hot-swap `name`'s weights into the warmed executables.

        Zero-downtime by construction: `run()` reads `entry.variables` at
        dispatch, the compiled (model, bucket) executables take variables
        as a runtime argument (argnum 0, never donated), and the avals are
        validated to match what warmup lowered against — so the swap is
        one attribute assignment, takes effect at the next batch, and can
        never touch the compiler (the serve/swap.py canary path asserts
        this with the backend-compile counter)."""
        entry = self.entry(name)
        self._check_like(name, entry.variables, variables)
        entry.variables = variables

    def clone_with_variables(self, variables_by_model) -> "Engine":
        """A shadow engine over the SAME compiled executables with new
        weights for the given models (swap canary: the shadow serves x%
        of traffic without compiling anything). Models not named keep the
        serving weights. The clone shares `_compiled` by reference —
        executables are weight-agnostic, so the shadow is warm at birth."""
        if not self._warmed:
            raise ServeError("clone_with_variables() before warmup(): "
                             "there are no executables to share yet")
        for name in variables_by_model:
            self.entry(name)  # unknown model raises the clear error
        clone = Engine.__new__(Engine)
        clone.journal = self.journal
        clone.excache = self.excache
        clone._compiled = self._compiled  # shared, read-only on this path
        clone._warmed = True
        clone._g_warmed = self._g_warmed
        clone._registry = self._registry
        clone._entries = {}
        for name, entry in self._entries.items():
            variables = variables_by_model.get(name, entry.variables)
            if name in variables_by_model:
                self._check_like(name, entry.variables, variables)
            clone._entries[name] = ModelEntry(
                name, entry.fn, variables, entry.input_shape, entry.dtype,
                entry.buckets)
        return clone

    # -- the request path ----------------------------------------------------

    def run(self, name: str, images):
        """Execute one padded batch; images.shape must be exactly
        (bucket, *input_shape) for a warmed bucket. Returns the device
        output pytree (the router fetches + splits it)."""
        compiled = self._compiled.get((name, int(images.shape[0])))
        if compiled is None:
            entry = self.entry(name)  # raises the clearer error first
            raise ServeError(
                f"model {name!r} has no warmed bucket {images.shape[0]} "
                f"(warmed: {sorted(self.warmed_buckets(name))}, menu: "
                f"{entry.buckets}); serving must never compile — fix the "
                "bucket menu and re-warm")
        return compiled(self.entry(name).variables, images)
