"""Resilience subsystem tests: retry policy, fault injection, bounded
bad-record degradation, checksummed/quarantining checkpoints, and the
crash-consistency e2e (SIGKILL mid-checkpoint-save, resume recovers).

Kept deterministic: every fault comes from resilience.faults (seeded) or
from bytes this file flips itself; retries run with injected sleep.
"""
import json
import os
import signal
import struct
import subprocess
import sys

import numpy as np
import pytest

from deep_vision_tpu.resilience import (
    FaultInjected,
    FaultInjector,
    FaultSpecError,
    RetryPolicy,
    faults,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    """No test may leak an installed injector (module-global) or the
    worker-inheritance env vars into its neighbors."""
    yield
    faults.install(None)
    os.environ.pop(faults.ENV_SPEC, None)
    os.environ.pop(faults.ENV_SEED, None)


class _Journal:
    """Collects journal rows; stands in for obs.RunJournal."""

    def __init__(self):
        self.rows = []

    def write(self, event, **fields):
        self.rows.append({"event": event, **fields})


# -- RetryPolicy -------------------------------------------------------------

class TestRetryPolicy:
    def _policy(self, **kw):
        kw.setdefault("jitter", 0)
        kw.setdefault("base_delay_s", 0.01)
        sleeps = []
        p = RetryPolicy(sleep=sleeps.append, **kw)
        return p, sleeps

    def test_recovers_after_transient_failures(self):
        p, sleeps = self._policy(max_attempts=5)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise IOError("transient")
            return "ok"

        assert p.call(flaky) == "ok"
        assert len(calls) == 3
        assert len(sleeps) == 2

    def test_gives_up_and_reraises_unchanged(self):
        p, _ = self._policy(max_attempts=3)
        boom = IOError("still down")

        def always():
            raise boom

        with pytest.raises(IOError) as ei:
            p.call(always)
        assert ei.value is boom

    def test_non_retryable_class_fails_fast(self):
        p, sleeps = self._policy(max_attempts=5)
        calls = []

        def bug():
            calls.append(1)
            raise ValueError("a real bug, not weather")

        with pytest.raises(ValueError):
            p.call(bug)
        assert len(calls) == 1 and sleeps == []

    def test_keyboard_interrupt_never_retried(self):
        p, _ = self._policy(max_attempts=5, retry_on=BaseException)
        with pytest.raises(KeyboardInterrupt):
            p.call(lambda: (_ for _ in ()).throw(KeyboardInterrupt()))

    def test_retry_if_predicate_extends_classification(self):
        p, _ = self._policy(
            max_attempts=3,
            retry_if=lambda e: "UNAVAILABLE" in str(e))
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 2:
                raise RuntimeError("UNAVAILABLE: tunnel fell over")
            return 7

        assert p.call(flaky) == 7

    def test_deadline_stops_before_sleeping_past_it(self):
        clock = [0.0]
        p = RetryPolicy(max_attempts=100, base_delay_s=10.0, jitter=0,
                        deadline_s=5.0, sleep=lambda d: None,
                        clock=lambda: clock[0])
        calls = []

        def always():
            calls.append(1)
            raise IOError("down")

        with pytest.raises(IOError):
            p.call(always)
        assert len(calls) == 1  # first delay (10s) would cross the 5s budget

    def test_backoff_schedule_exponential_and_capped(self):
        p, _ = self._policy(max_attempts=9, base_delay_s=1.0, multiplier=2.0,
                            max_delay_s=5.0)
        assert [p.delay(a) for a in (1, 2, 3, 4)] == [1.0, 2.0, 4.0, 5.0]

    def test_jitter_deterministic_per_seed(self):
        a = RetryPolicy(base_delay_s=1.0, jitter=0.5, seed=3)
        b = RetryPolicy(base_delay_s=1.0, jitter=0.5, seed=3)
        assert [a.delay(1) for _ in range(4)] == [b.delay(1) for _ in range(4)]

    def test_decorator_form(self):
        p, _ = self._policy(max_attempts=3)
        calls = []

        @p
        def flaky(x):
            calls.append(x)
            if len(calls) < 2:
                raise OSError("blip")
            return x * 2

        assert flaky(21) == 42
        assert flaky.retry_policy is p

    def test_attempts_loop_form(self):
        p, _ = self._policy(max_attempts=4)
        tries = []
        for attempt in p.attempts():
            with attempt:
                tries.append(1)
                if len(tries) < 3:
                    raise IOError("blip")
        assert len(tries) == 3

    def test_attempts_loop_reraises_on_budget(self):
        p, _ = self._policy(max_attempts=2)
        with pytest.raises(IOError):
            for attempt in p.attempts():
                with attempt:
                    raise IOError("down")

    def test_journal_events_typed(self):
        j = _Journal()
        p = RetryPolicy(name="t", max_attempts=3, base_delay_s=0, jitter=0,
                        journal=j, sleep=lambda d: None)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 2:
                raise IOError("blip")

        p.call(flaky)
        outcomes = [(r["event"], r["outcome"]) for r in j.rows]
        assert outcomes == [("retry", "retrying"), ("retry", "recovered")]
        assert j.rows[0]["name"] == "t" and j.rows[0]["attempt"] == 1

        j.rows.clear()
        with pytest.raises(ValueError):
            p.call(lambda: (_ for _ in ()).throw(ValueError("bug")))
        assert [(r["event"], r["outcome"]) for r in j.rows] == \
            [("retry", "gave_up")]


# -- FaultInjector -----------------------------------------------------------

class TestFaultInjector:
    def test_parse_rejects_unknown_point_kind_and_shape(self):
        for bad in ("nope.read:io_error", "data.read:frobnicate",
                    "data.read", "data.read:io_error@zero",
                    "data.read:io_error@-1"):
            with pytest.raises(FaultSpecError):
                FaultInjector.parse(bad)

    def test_nth_hit_fires_exactly_once(self):
        inj = FaultInjector.parse("data.read:io_error@3")
        faults.install(inj)
        hits = []
        for i in range(6):
            try:
                faults.fire("data.read")
                hits.append("ok")
            except FaultInjected:
                hits.append("boom")
        assert hits == ["ok", "ok", "boom", "ok", "ok", "ok"]

    def test_probability_sequence_reproducible_per_seed(self):
        def seq(seed):
            inj = FaultInjector.parse("data.read:io_error@0.3", seed=seed)
            out = []
            for _ in range(50):
                try:
                    inj.fire("data.read")
                    out.append(0)
                except FaultInjected:
                    out.append(1)
            return out

        assert seq(11) == seq(11)
        assert seq(11) != seq(12)
        assert sum(seq(11)) > 0

    def test_injected_error_is_an_ioerror(self):
        # handlers for the genuine article (retry/budget code catching
        # IOError/OSError) must treat injected faults identically
        assert issubclass(FaultInjected, IOError)

    def test_points_are_scoped(self):
        faults.install(FaultInjector.parse("ckpt.save:io_error@1"))
        faults.fire("data.read")  # different point: no fault
        with pytest.raises(FaultInjected):
            faults.fire("ckpt.save")

    def test_corrupt_transform_mangles_bytes(self):
        inj = FaultInjector.parse("ckpt.sidecar:corrupt@1")
        data = b"x" * 64
        mangled = inj.transform("ckpt.sidecar", data)
        assert mangled != data
        assert inj.transform("ckpt.sidecar", data) == data  # once only

    def test_disabled_hooks_are_noops(self):
        assert faults.installed() is None
        faults.fire("data.read")
        assert faults.transform("ckpt.sidecar", b"abc") == b"abc"

    def test_install_spec_exports_and_clears_env(self):
        faults.install_spec("data.read:io_error@2", seed=9)
        assert os.environ[faults.ENV_SPEC] == "data.read:io_error@2"
        assert os.environ[faults.ENV_SEED] == "9"
        faults.install_spec(None)
        assert faults.ENV_SPEC not in os.environ
        assert faults.installed() is None

    def test_fired_fault_journals_and_skips_journal_flush_point(self):
        j = _Journal()
        inj = FaultInjector.parse(
            "data.read:io_error@1;journal.flush:io_error@1", journal=j)
        faults.install(inj)
        with pytest.raises(FaultInjected):
            faults.fire("data.read")
        with pytest.raises(FaultInjected):
            faults.fire("journal.flush")
        points = [r["point"] for r in j.rows if r["event"] == "fault"]
        assert points == ["data.read"]  # journal.flush must not self-journal


# -- bad-record budget + tolerant reader -------------------------------------

class TestBadRecordBudget:
    def test_parse_count_vs_fraction(self):
        from deep_vision_tpu.data.records import BadRecordBudget

        assert BadRecordBudget.parse("5").max_count == 5
        assert BadRecordBudget.parse("0.25").max_fraction == 0.25
        with pytest.raises(ValueError):
            BadRecordBudget.parse("0")

    def test_count_budget_allows_n_then_aborts(self, tmp_path):
        from deep_vision_tpu.data.records import (
            BadRecordBudget,
            BadRecordBudgetExceeded,
        )

        b = BadRecordBudget(max_count=2,
                            dead_letter_path=str(tmp_path / "dl.jsonl"))
        b.record_bad("f", 0, "r1")
        b.record_bad("f", 10, "r2")
        with pytest.raises(BadRecordBudgetExceeded):
            b.record_bad("f", 20, "r3")
        rows = [json.loads(x) for x in
                (tmp_path / "dl.jsonl").read_text().splitlines()]
        assert [r["offset"] for r in rows] == [0, 10, 20]
        assert all(r["path"] == "f" and r["reason"] for r in rows)

    def test_fraction_budget_waits_for_min_seen(self):
        from deep_vision_tpu.data.records import (
            BadRecordBudget,
            BadRecordBudgetExceeded,
        )

        b = BadRecordBudget(max_fraction=0.1, min_seen=10)
        b.record_bad("f", 0, "early")   # 1/1 bad, but below min_seen
        b.record_ok(7)                  # seen = 8
        b.record_bad("f", 1, "second")  # seen = 9: still below min_seen
        with pytest.raises(BadRecordBudgetExceeded):
            b.record_bad("f", 2, "third")  # seen = 10: 3/10 > 0.1

    def test_journal_dropped_on_pickle(self):
        import pickle

        from deep_vision_tpu.data.records import BadRecordBudget

        b = BadRecordBudget(max_count=5, journal=_Journal())
        b2 = pickle.loads(pickle.dumps(b))
        assert b2.journal is None and b2.max_count == 5
        b2.record_bad("f", 0, "works without a journal")


def _write_shard(path, payloads):
    from deep_vision_tpu.data.records import write_records

    write_records(str(path), payloads)
    return str(path)


def _record_offsets(path):
    """[(offset, length)] per record, walking the clean framing."""
    out = []
    with open(path, "rb") as f:
        while True:
            off = f.tell()
            header = f.read(8)
            if not header:
                return out
            (length,) = struct.unpack("<Q", header)
            f.read(4)
            f.read(length)
            f.read(4)
            out.append((off, length))


class TestTolerantReader:
    def _flip(self, path, byte_at):
        with open(path, "r+b") as f:
            f.seek(byte_at)
            b = f.read(1)
            f.seek(byte_at)
            f.write(bytes([b[0] ^ 0xFF]))

    def test_clean_file_yields_offsets_and_payloads(self, tmp_path):
        from deep_vision_tpu.data.records import (
            BadRecordBudget,
            read_records_tolerant,
        )

        payloads = [b"aa", b"bbbb", b"cccccc"]
        p = _write_shard(tmp_path / "s", payloads)
        budget = BadRecordBudget(max_count=10)
        got = list(read_records_tolerant(p, budget))
        assert [d for _, d in got] == payloads
        assert [o for o, _ in got] == [o for o, _ in _record_offsets(p)]
        assert budget.bad == 0 and budget.ok == 3

    def test_data_corruption_skips_exactly_that_record(self, tmp_path):
        from deep_vision_tpu.data.records import (
            BadRecordBudget,
            read_records_tolerant,
        )

        payloads = [b"record-%d" % i for i in range(5)]
        p = _write_shard(tmp_path / "s", payloads)
        off, _ = _record_offsets(p)[2]
        self._flip(p, off + 12 + 3)  # a data byte of record 2
        budget = BadRecordBudget(max_count=10,
                                 dead_letter_path=str(tmp_path / "dl.jsonl"))
        got = [d for _, d in read_records_tolerant(p, budget)]
        assert got == [payloads[0], payloads[1], payloads[3], payloads[4]]
        row = json.loads((tmp_path / "dl.jsonl").read_text().splitlines()[0])
        assert row["offset"] == off and "corrupt record data" in row["reason"]

    def test_header_corruption_dead_letters_shard_remainder(self, tmp_path):
        from deep_vision_tpu.data.records import (
            BadRecordBudget,
            read_records_tolerant,
        )

        payloads = [b"record-%d" % i for i in range(5)]
        p = _write_shard(tmp_path / "s", payloads)
        off, _ = _record_offsets(p)[2]
        self._flip(p, off + 2)  # a length byte: framing is gone
        budget = BadRecordBudget(max_count=10)
        got = [d for _, d in read_records_tolerant(p, budget)]
        assert got == payloads[:2]  # remainder skipped as ONE budget event
        assert budget.bad == 1

    def test_truncated_tail_tolerated(self, tmp_path):
        from deep_vision_tpu.data.records import (
            BadRecordBudget,
            read_records_tolerant,
        )

        payloads = [b"one", b"two", b"three"]
        p = _write_shard(tmp_path / "s", payloads)
        size = os.path.getsize(p)
        with open(p, "r+b") as f:
            f.truncate(size - 5)
        budget = BadRecordBudget(max_count=10)
        got = [d for _, d in read_records_tolerant(p, budget)]
        assert got == payloads[:2]
        assert budget.bad == 1

    def test_strict_reader_still_raises(self, tmp_path):
        from deep_vision_tpu.data.records import read_records

        p = _write_shard(tmp_path / "s", [b"payload-zero", b"payload-one"])
        off, _ = _record_offsets(p)[1]
        self._flip(p, off + 12 + 2)
        with pytest.raises(IOError):
            list(read_records(p))

    def test_injected_read_fault_burns_budget_not_run(self, tmp_path):
        from deep_vision_tpu.data.records import (
            BadRecordBudget,
            read_records_tolerant,
        )

        payloads = [b"r%d" % i for i in range(6)]
        p = _write_shard(tmp_path / "s", payloads)
        faults.install(FaultInjector.parse("data.read:io_error@2"))
        budget = BadRecordBudget(max_count=10)
        got = [d for _, d in read_records_tolerant(p, budget)]
        assert len(got) == 5 and budget.bad == 1

    def test_record_dataset_budget_covers_decode_failures(self, tmp_path):
        from deep_vision_tpu.data.datasets import RecordDataset
        from deep_vision_tpu.data.example_codec import encode_example
        from deep_vision_tpu.data.records import BadRecordBudget

        good = encode_example({"label": [1]})
        p = tmp_path / "train-0"
        _write_shard(p, [good, b"not-an-example-proto", good])
        budget = BadRecordBudget(max_count=5)
        ds = RecordDataset(str(tmp_path / "train-*"),
                           schema=lambda f: {"label": f["label"][0]},
                           bad_record_budget=budget)
        assert [s["label"] for s in ds] == [1, 1]
        assert budget.bad == 1


# -- journal flush degradation ------------------------------------------------

class TestJournalDegradation:
    def test_flush_fault_drops_line_not_run(self, tmp_path):
        from deep_vision_tpu.obs.journal import RunJournal, read_journal

        faults.install(FaultInjector.parse("journal.flush:io_error@2"))
        j = RunJournal(str(tmp_path / "j.jsonl"), kind="test")
        j.write("note", note="first")
        j.write("note", note="second")  # injected flush failure: dropped
        j.write("note", note="third")
        j.close("clean_exit")
        faults.install(None)
        notes = [e["note"] for e in read_journal(str(tmp_path / "j.jsonl"))
                 if e["event"] == "note"]
        assert notes == ["first", "third"]
        assert j.dropped_lines == 1


# -- checkpoint hardening -----------------------------------------------------

def _tree(v):
    return {"w": np.full((4,), v, np.float32), "b": np.full((2,), -v,
                                                            np.float32)}


class TestCheckpointResilience:
    def _manager(self, tmp_path, journal=None, **kw):
        from deep_vision_tpu.core.checkpoint import CheckpointManager

        return CheckpointManager(str(tmp_path / "ckpt"), journal=journal,
                                 **kw)

    def test_sidecar_roundtrip_checksummed(self, tmp_path):
        cm = self._manager(tmp_path)
        cm._write_sidecar(3, {"epoch": 3, "lr": 0.1})
        doc = json.load(open(cm._sidecar_path(3)))
        assert doc["__sidecar_format__"] == 1 and "crc32c" in doc
        host, err = cm._read_sidecar(3)
        assert err is None and host == {"epoch": 3, "lr": 0.1}
        assert not [p for p in os.listdir(cm.directory) if ".tmp." in p]

    def test_sidecar_rot_detected_by_checksum(self, tmp_path):
        cm = self._manager(tmp_path)
        cm._write_sidecar(3, {"epoch": 3})
        path = cm._sidecar_path(3)
        data = bytearray(open(path, "rb").read())
        i = data.index(b'"epoch"') + 2  # flip a payload byte, keep JSON-ish
        data[i] ^= 0x01
        open(path, "wb").write(bytes(data))
        host, err = cm._read_sidecar(3)
        assert host is None and err is not None

    def test_legacy_plain_json_sidecar_accepted(self, tmp_path):
        cm = self._manager(tmp_path)
        with open(cm._sidecar_path(7), "w") as f:
            json.dump({"epoch": 7}, f)  # pre-checksum format
        host, err = cm._read_sidecar(7)
        assert err is None and host == {"epoch": 7}

    def test_half_written_sidecar_is_an_error_not_a_crash(self, tmp_path):
        cm = self._manager(tmp_path)
        with open(cm._sidecar_path(2), "w") as f:
            f.write('{"__sidecar_format__": 1, "crc32c": 12, "payl')  # torn
        host, err = cm._read_sidecar(2)
        assert host is None and "unreadable" in err

    def test_sidecar_write_retries_transient_io_error(self, tmp_path):
        from deep_vision_tpu.core.checkpoint import CheckpointManager

        j = _Journal()
        cm = CheckpointManager(
            str(tmp_path / "ckpt"), journal=j,
            retry=RetryPolicy(name="ckpt.sidecar", max_attempts=3,
                              journal=j, jitter=0, sleep=lambda d: None))
        faults.install(FaultInjector.parse("ckpt.sidecar:io_error@1"))
        cm._write_sidecar(1, {"epoch": 1})
        faults.install(None)
        assert cm._read_sidecar(1) == ({"epoch": 1}, None)
        outcomes = [r["outcome"] for r in j.rows if r["event"] == "retry"]
        assert outcomes == ["retrying", "recovered"]

    def test_corrupt_fault_caught_by_checksum(self, tmp_path):
        cm = self._manager(tmp_path)
        faults.install(FaultInjector.parse("ckpt.sidecar:corrupt@1"))
        cm._write_sidecar(1, {"epoch": 1})
        faults.install(None)
        host, err = cm._read_sidecar(1)
        assert host is None and err is not None

    @pytest.mark.slow
    def test_restore_tree_quarantines_corrupt_latest_and_falls_back(
            self, tmp_path):
        j = _Journal()
        cm = self._manager(tmp_path, journal=j)
        for step in (1, 2, 3):
            assert cm.save_tree(step, _tree(step), host_state={"step": step})
        cm._mgr.wait_until_finished()
        # rot the newest sidecar on disk
        with open(cm._sidecar_path(3), "r+b") as f:
            f.seek(os.path.getsize(cm._sidecar_path(3)) // 2)
            f.write(b"\x00\x00")
        tree, host = cm.restore_tree(_tree(0))
        assert host == {"step": 2}
        np.testing.assert_array_equal(tree["w"], _tree(2)["w"])
        q = [r for r in j.rows if r["event"] == "ckpt_quarantine"]
        assert len(q) == 1 and q[0]["step"] == 3
        qdir = os.path.join(cm.directory, "quarantine")
        assert os.path.isdir(qdir) and len(os.listdir(qdir)) >= 1
        # the quarantined step must stay forgotten for the NEXT restore too
        tree2, host2 = cm.restore_tree(_tree(0))
        assert host2 == {"step": 2}

    @pytest.mark.slow
    def test_missing_sidecar_with_siblings_quarantined(self, tmp_path):
        j = _Journal()
        cm = self._manager(tmp_path, journal=j)
        for step in (1, 2):
            cm.save_tree(step, _tree(step), host_state={"step": step})
        cm._mgr.wait_until_finished()
        os.remove(cm._sidecar_path(2))  # the died-before-sidecar signature
        tree, host = cm.restore_tree(_tree(0))
        assert host == {"step": 1}
        assert any(r["event"] == "ckpt_quarantine" and r["step"] == 2
                   for r in j.rows)

    @pytest.mark.slow
    def test_explicit_step_corrupt_raises_not_falls_back(self, tmp_path):
        from deep_vision_tpu.core.checkpoint import CheckpointCorruptError

        cm = self._manager(tmp_path)
        for step in (1, 2):
            cm.save_tree(step, _tree(step), host_state={"step": step})
        cm._mgr.wait_until_finished()
        with open(cm._sidecar_path(2), "r+b") as f:
            f.seek(10)
            f.write(b"\xff")
        with pytest.raises(CheckpointCorruptError):
            cm.restore_tree(_tree(0), step=2)

    @pytest.mark.slow
    def test_nothing_valid_left_returns_none(self, tmp_path):
        cm = self._manager(tmp_path)
        assert cm.restore_tree(_tree(0)) == (None, None)

    @pytest.mark.slow
    def test_sidecar_gc_follows_max_to_keep(self, tmp_path):
        cm = self._manager(tmp_path, max_to_keep=2)
        for step in (1, 2, 3, 4):
            cm.save_tree(step, _tree(step), host_state={"step": step})
        cm._mgr.wait_until_finished()
        cm.save_tree(5, _tree(5), host_state={"step": 5})
        cm._mgr.wait_until_finished()
        kept = set(cm._sidecar_steps())
        assert kept == set(cm._mgr.all_steps())


# -- crash consistency e2e ----------------------------------------------------

_SAVER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
from deep_vision_tpu.core.checkpoint import CheckpointManager

cm = CheckpointManager(sys.argv[1])
for step in (1, 2, 3):
    if step == 3:
        cm._mgr.wait_until_finished()  # 1 and 2 fully committed
    cm.save_tree(step, {"w": np.full((4,), float(step), np.float32)},
                 host_state={"step": step})
cm._mgr.wait_until_finished()
print("UNREACHABLE: the injected crash never fired")
"""


class TestCrashConsistencyE2E:
    @pytest.mark.slow
    def test_sigkill_mid_save_then_restore_recovers(self, tmp_path):
        """SIGKILL a saver inside the sidecar torn-write window; restore
        must land on the newest fully-committed step."""
        ckpt_dir = str(tmp_path / "ckpt")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env[faults.ENV_SPEC] = "ckpt.sidecar:crash_after_write@3"
        env["PYTHONPATH"] = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run([sys.executable, "-c", _SAVER, ckpt_dir],
                              env=env, capture_output=True, text=True,
                              timeout=300)
        assert proc.returncode == -signal.SIGKILL, proc.stdout + proc.stderr
        assert "UNREACHABLE" not in proc.stdout

        from deep_vision_tpu.core.checkpoint import CheckpointManager

        j = _Journal()
        cm = CheckpointManager(ckpt_dir, journal=j)
        tree, host = cm.restore_tree({"w": np.zeros((4,), np.float32)})
        assert host == {"step": 2}
        np.testing.assert_array_equal(tree["w"], np.full((4,), 2.0))

    @pytest.mark.slow
    def test_cli_run_sigkilled_mid_save_resumes(self, tmp_path):
        """The satellite e2e: a tiny CPU train run is SIGKILLed mid-
        checkpoint-save; `Trainer.resume()` recovers to the newest valid
        step and the rerun completes cleanly."""
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ckpt_dir = str(tmp_path / "ckpt")
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=root)
        env.pop(faults.ENV_SPEC, None)
        base = [sys.executable, os.path.join(root, "train.py"), "-m",
                "lenet5", "--fake-data", "--fake-batches", "2",
                "--epochs", "3", "--ckpt-dir", ckpt_dir]
        crashed = subprocess.run(
            base + ["--fault-spec", "ckpt.sidecar:crash_after_write@3",
                    "--journal", str(tmp_path / "j1.jsonl")],
            env=env, cwd=root, capture_output=True, text=True, timeout=560)
        assert crashed.returncode == -signal.SIGKILL, (
            crashed.stdout + crashed.stderr)

        resumed = subprocess.run(
            base + ["-c", ckpt_dir, "--journal", str(tmp_path / "j2.jsonl")],
            env=env, cwd=root, capture_output=True, text=True, timeout=560)
        assert resumed.returncode == 0, resumed.stdout + resumed.stderr
        # 2 fake batches/epoch; epoch 3's save was torn, so the newest
        # valid step is end-of-epoch-2 = 4
        assert "resumed from step 4" in resumed.stdout
        from tools.check_journal import check_journal

        assert check_journal(str(tmp_path / "j2.jsonl"), strict=True) == []


# -- dead data-worker resubmission -------------------------------------------

class _KillableDataset:
    """Round-robin-splittable dataset; worker `kill_wid`'s process SIGKILLs
    itself at local index `kill_at`. One-shot mode drops a sentinel file
    first so the replacement worker survives; `always` kills every
    incarnation (the restart-budget case)."""

    def __init__(self, n, sentinel, kill_wid=0, kill_at=3, always=False):
        self.items = list(range(n))
        self.sentinel = sentinel
        self.kill_wid = kill_wid
        self.kill_at = kill_at
        self.always = always
        self.wid = None

    def split(self, i, n):
        out = _KillableDataset.__new__(_KillableDataset)
        out.__dict__.update(self.__dict__)
        out.items = self.items[i::n]
        out.wid = i
        return out

    def __iter__(self):
        for j, v in enumerate(self.items):
            if (self.wid == self.kill_wid and j == self.kill_at
                    and (self.always or not os.path.exists(self.sentinel))):
                if not self.always:
                    open(self.sentinel, "w").close()
                os.kill(os.getpid(), signal.SIGKILL)
            yield {"x": np.array([v])}


class TestDeadWorkerResubmission:
    @pytest.mark.slow
    def test_dead_worker_restarted_no_loss_no_duplicates(self, tmp_path):
        from deep_vision_tpu.data import DataLoader

        ds = _KillableDataset(16, str(tmp_path / "sentinel"))
        dl = DataLoader(ds, batch_size=4, num_procs=2, worker_poll_s=0.5)
        got = sorted(int(v) for batch in dl for v in batch["x"].ravel())
        assert got == list(range(16))

    @pytest.mark.slow
    def test_restart_budget_spent_raises(self, tmp_path):
        from deep_vision_tpu.data import DataLoader

        ds = _KillableDataset(16, str(tmp_path / "sentinel"), always=True)
        dl = DataLoader(ds, batch_size=4, num_procs=2, worker_poll_s=0.5,
                        worker_restarts=1)
        with pytest.raises(RuntimeError, match="restart budget"):
            for _ in dl:
                pass


# -- check_journal schema coverage -------------------------------------------

class TestCheckJournalResilienceEvents:
    def _journal(self, tmp_path, rows):
        path = tmp_path / "j.jsonl"
        base = {"ts": 1.0, "run_id": "r1"}
        with open(path, "w") as f:
            f.write(json.dumps({"event": "run_manifest", "kind": "t",
                                "argv": [], **base}) + "\n")
            for r in rows:
                f.write(json.dumps({**base, **r}) + "\n")
            f.write(json.dumps({"event": "exit", "status": "clean_exit",
                                **base}) + "\n")
        return str(path)

    def test_strict_accepts_all_resilience_events(self, tmp_path):
        from tools.check_journal import check_journal

        path = self._journal(tmp_path, [
            {"event": "retry", "name": "ckpt.sidecar", "attempt": 1,
             "error": "IOError: blip", "outcome": "retrying",
             "delay_s": 0.05},
            {"event": "fault", "point": "data.read", "kind": "io_error"},
            {"event": "data_skip", "path": "train-0", "offset": 128,
             "reason": "corrupt record data"},
            {"event": "ckpt_quarantine", "step": 3,
             "reason": "sidecar checksum mismatch", "moved_to": []},
        ])
        assert check_journal(path, strict=True) == []

    def test_strict_rejects_missing_fields_and_bad_outcome(self, tmp_path):
        from tools.check_journal import check_journal

        path = self._journal(tmp_path, [
            {"event": "retry", "name": "x", "attempt": 1,
             "error": "e", "outcome": "exploded"},
            {"event": "data_skip", "path": "train-0", "reason": "r"},
            {"event": "ckpt_quarantine", "reason": "r"},
        ])
        errs = check_journal(path, strict=True)
        assert len(errs) == 3
        assert any("outcome" in e for e in errs)
        assert any("offset" in e for e in errs)
        assert any("step" in e for e in errs)
