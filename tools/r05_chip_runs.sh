#!/bin/bash
# Round-5 chip-work queue: run ONE AT A TIME when the tunnel is back.
# (Two concurrent clients can wedge the tunnel permanently — see the
# ONE-CLIENT-AT-A-TIME note in the perf memory; poll log files only.)
#
#   nohup bash tools/r05_chip_runs.sh > /tmp/r05_chip.log 2>&1 &
#
# Order: cheapest/most-valuable first, so a mid-queue outage still leaves
# the headline evidence captured.
set -x
cd "$(dirname "$0")/.."

# 0. liveness
timeout 120 python -c "import jax; print(float(jax.numpy.ones(()).sum()))" || exit 1

# 1. headline bench preview (the driver runs its own at round end; this is
#    the builder-side capture + sanity that the outage-proofing didn't slow
#    the healthy path)
timeout 1800 python bench.py > artifacts/bench_preview_r05.json.tmp 2>/tmp/bench_r05.err \
  && tail -1 artifacts/bench_preview_r05.json.tmp > artifacts/bench_preview_r05.json \
  && rm artifacts/bench_preview_r05.json.tmp

# 2. roofline measured half (DMA totals + device step)
timeout 1800 python -m deep_vision_tpu.tools.roofline --out artifacts/roofline_r05.json

# 3. fine batch sweep around the knee (argv: out_path batches_csv)
timeout 3600 python tools/batch_sweep.py artifacts/batch_fine_r05.json 96,112,128,144,160

# 4. model-zoo step times at 100-step windows (fixes the biased YOLO/flash rows)
timeout 3600 python tools/bench_models.py

# 5. ablations regen (flash ratio at long windows)
timeout 3600 python tools/bench_ablate.py

# 6. GAN hardware evidence + sample grids
timeout 2400 python -m deep_vision_tpu.tools.convergence_run --model dcgan \
  --render-dir examples/output --out artifacts/dcgan_convergence.json
timeout 2400 python -m deep_vision_tpu.tools.convergence_run --model cyclegan \
  --render-dir examples/output --out artifacts/cyclegan_convergence.json

# 7. fattened holdouts (n_val 256 + support counts)
timeout 3600 python -m deep_vision_tpu.tools.convergence_run --model yolov3 \
  --holdout --render-dir examples/output
timeout 3600 python -m deep_vision_tpu.tools.convergence_run --model hourglass \
  --holdout --render-dir examples/output

echo "R05 CHIP QUEUE DONE"
