"""obs/trace.py + obs/health.py: span tracer, NaN-guard policies,
divergence detector, hang watchdog, and the check_journal validator."""
import json
import os
import threading
import time

import numpy as np
import pytest

from deep_vision_tpu.obs import (
    HealthMonitor,
    Registry,
    RunJournal,
    Tracer,
    TrainingHealthError,
    read_journal,
    set_tracer,
    span,
    traced,
)


# -- tracer ------------------------------------------------------------------

def test_tracer_writes_valid_chrome_trace(tmp_path):
    path = str(tmp_path / "t.trace.json")
    tracer = Tracer(path, run_id="run-42")
    with tracer.span("outer", step=1):
        with tracer.span("inner"):
            pass
    tracer.close()
    doc = json.load(open(path))  # valid JSON or this raises
    events = doc["traceEvents"]
    by_name = {e["name"]: e for e in events if e["ph"] == "X"}
    assert set(by_name) == {"outer", "inner"}
    assert doc["metadata"]["run_id"] == "run-42"
    # nesting: inner lies within outer on the same thread
    o, i = by_name["outer"], by_name["inner"]
    assert o["tid"] == i["tid"]
    assert o["ts"] <= i["ts"]
    assert i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 1  # 1us rounding slack
    assert o["args"]["step"] == 1


def test_tracer_file_is_valid_json_mid_run(tmp_path):
    # the crashed-run contract: every flush leaves complete, parseable JSON
    path = str(tmp_path / "mid.trace.json")
    tracer = Tracer(path, flush_every=1)
    with tracer.span("a"):
        pass
    doc = json.load(open(path))
    assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) == 1
    tracer.close()


def test_module_level_span_noop_without_tracer(tmp_path):
    set_tracer(None)
    with span("nothing", x=1) as sp:
        sp.set(y=2)  # must not raise on the null span
    path = str(tmp_path / "m.trace.json")
    tracer = Tracer(path)
    set_tracer(tracer)
    try:
        with span("active", x=1):
            pass

        @traced("decorated", kind="test")
        def f(a):
            return a + 1

        assert f(1) == 2
    finally:
        set_tracer(None)
        tracer.close()
    names = {e["name"] for e in json.load(open(path))["traceEvents"]
             if e["ph"] == "X"}
    assert names == {"active", "decorated"}


def test_tracer_ring_buffer_caps_and_reports_drops(tmp_path):
    path = str(tmp_path / "ring.trace.json")
    tracer = Tracer(path, flush_every=10_000, max_events=1000)
    for i in range(2500):
        with tracer.span("s", i=i):
            pass
    tracer.close()
    doc = json.load(open(path))
    assert len(doc["traceEvents"]) <= 1001  # cap (+1 thread_name meta)
    assert doc["metadata"]["dropped_events"] > 0
    # the survivors are the most RECENT window (post-mortem wants the end)
    last = [e["args"]["i"] for e in doc["traceEvents"]
            if e["ph"] == "X"][-1]
    assert last == 2499


def test_tracer_thread_safety_and_thread_names(tmp_path):
    path = str(tmp_path / "threads.trace.json")
    tracer = Tracer(path, flush_every=10_000)

    def worker():
        for _ in range(50):
            with tracer.span("w"):
                pass

    threads = [threading.Thread(target=worker, name=f"worker-{i}")
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tracer.close()
    doc = json.load(open(path))
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(spans) == 200
    meta_names = {e["args"]["name"] for e in doc["traceEvents"]
                  if e["ph"] == "M"}
    assert {f"worker-{i}" for i in range(4)} <= meta_names


# -- health monitor: NaN guard ------------------------------------------------

def _nan_journal(tmp_path, name):
    return RunJournal(str(tmp_path / f"{name}.jsonl"))


def test_health_warn_policy_continues(tmp_path):
    j = _nan_journal(tmp_path, "warn")
    reg = Registry()
    h = HealthMonitor(policy="warn", journal=j, registry=reg)
    assert h.check_step(1, loss=1.0, grad_norm=2.0) == "ok"
    assert h.check_step(2, loss=float("nan"), grad_norm=1.0) == "warn"
    assert h.check_step(3, loss=1.0, grad_norm=float("inf")) == "warn"
    j.close()
    health = [e for e in read_journal(j.path) if e["event"] == "health"]
    assert [e["kind"] for e in health] == ["non_finite", "non_finite"]
    assert health[0]["fields"] == ["loss"]
    assert health[1]["fields"] == ["grad_norm"]
    assert reg.counter("health_nonfinite_steps_total").value == 2
    assert reg.counter("health_skipped_steps_total").value == 0


def test_health_abort_policy_raises_after_journal(tmp_path):
    j = _nan_journal(tmp_path, "abort")
    h = HealthMonitor(policy="abort", journal=j, registry=Registry())
    with pytest.raises(TrainingHealthError):
        h.check_step(7, loss=float("nan"))
    j._atexit()  # the dying process stamps the crash marker
    kinds = [e["event"] for e in read_journal(j.path)]
    # the typed health event precedes the crash marker: the post-mortem
    # reads health(non_finite) -> crash
    assert kinds.index("health") < kinds.index("crash")


def test_health_divergence_zscore(tmp_path):
    j = _nan_journal(tmp_path, "div")
    reg = Registry()
    h = HealthMonitor(policy="warn", journal=j, registry=reg,
                      window=30, min_history=10, z_threshold=4.0, patience=3)
    rng = np.random.RandomState(0)
    for i in range(20):
        assert h.check_step(i, loss=1.0 + 0.01 * rng.randn()) == "ok"
    for i in range(20, 23):
        assert h.check_step(i, loss=100.0) == "spike"
    j.close()
    kinds = [e["kind"] for e in read_journal(j.path) if e["event"] == "health"]
    assert kinds == ["loss_spike", "loss_spike", "divergence"]
    assert reg.counter("health_loss_spikes_total").value == 3


def test_health_divergence_aborts_under_abort_policy(tmp_path):
    h = HealthMonitor(policy="abort", registry=Registry(),
                      window=30, min_history=5, z_threshold=4.0, patience=2)
    for i in range(10):
        h.check_step(i, loss=1.0 + 0.01 * i)
    h.check_step(10, loss=50.0)
    with pytest.raises(TrainingHealthError, match="divergence"):
        h.check_step(11, loss=60.0)


def test_health_check_summary(tmp_path):
    j = _nan_journal(tmp_path, "summary")
    h = HealthMonitor(policy="warn", journal=j, registry=Registry())
    h.check_summary(0, {"g_loss": 1.0, "d_loss": 2.0})  # fine
    h.check_summary(1, {"g_loss": float("nan"), "d_loss": 2.0})
    with pytest.raises(TrainingHealthError):
        HealthMonitor(policy="abort", journal=j, registry=Registry()) \
            .check_summary(2, {"loss": float("inf")})
    j.close()
    health = [e for e in read_journal(j.path) if e["event"] == "health"]
    assert [e.get("epoch") for e in health] == [1, 2]
    assert health[0]["fields"] == ["g_loss"]


# -- health monitor: watchdog -------------------------------------------------

def test_watchdog_fires_on_stall_and_dumps_stacks(tmp_path):
    j = _nan_journal(tmp_path, "hang")
    reg = Registry()
    h = HealthMonitor(policy="warn", journal=j, registry=reg,
                      watchdog_timeout=0.2)
    h.start_watchdog()
    try:
        h.beat()
        time.sleep(0.6)  # stall: no beats
    finally:
        h.stop()
    j.close()
    health = [e for e in read_journal(j.path) if e["event"] == "health"]
    kinds = [e["kind"] for e in health]
    assert kinds[0] == "watchdog_started"
    assert "hang" in kinds
    hang = health[kinds.index("hang")]
    assert hang["stalled_s"] >= 0.2
    # the dump names this (stalled) test thread and carries real frames
    assert any("MainThread" in k for k in hang["stacks"])
    frames = "\n".join(sum(hang["stacks"].values(), []))
    assert "test_watchdog_fires_on_stall" in frames
    assert reg.counter("health_watchdog_fires_total").value >= 1
    # one stall = one dump (the latch), re-armed only by a beat
    assert kinds.count("hang") == 1


def test_watchdog_rearms_after_beat(tmp_path):
    j = _nan_journal(tmp_path, "rearm")
    h = HealthMonitor(policy="warn", journal=j, registry=Registry(),
                      watchdog_timeout=0.15)
    h.start_watchdog()
    try:
        time.sleep(0.4)   # first stall
        h.beat()          # progress resumes
        time.sleep(0.4)   # second stall
    finally:
        h.stop()
    j.close()
    kinds = [e["kind"] for e in read_journal(j.path) if e["event"] == "health"]
    assert kinds.count("hang") == 2


def test_watchdog_quiet_with_heartbeats(tmp_path):
    j = _nan_journal(tmp_path, "quiet")
    h = HealthMonitor(policy="warn", journal=j, registry=Registry(),
                      watchdog_timeout=0.3)
    h.start_watchdog()
    try:
        for _ in range(6):
            time.sleep(0.05)
            h.beat()
    finally:
        h.stop()
    j.close()
    kinds = [e["kind"] for e in read_journal(j.path) if e["event"] == "health"]
    assert "hang" not in kinds


# -- trainer integration ------------------------------------------------------

def _tiny_trainer(mesh8, **kw):
    import jax.numpy as jnp

    from deep_vision_tpu.losses import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train import Trainer, build_optimizer

    return Trainer(
        get_model("lenet5", num_classes=4),
        build_optimizer("adam", 1e-3),
        classification_loss_fn,
        jnp.ones((2, 32, 32, 1)),
        mesh=mesh8,
        **kw,
    )


def _batches_with_nan(n_clean=3, bs=8, nan_at=1):
    rng = np.random.RandomState(0)
    out = [
        {"image": rng.rand(bs, 32, 32, 1).astype(np.float32),
         "label": rng.randint(0, 4, (bs,)).astype(np.int32)}
        for _ in range(n_clean)
    ]
    out.insert(nan_at, {
        "image": np.full((bs, 32, 32, 1), np.nan, np.float32),
        "label": np.zeros((bs,), np.int32),
    })
    return out


def test_trainer_nan_warn_policy_run_completes(tmp_path, mesh8):
    path = str(tmp_path / "warn.jsonl")
    j = RunJournal(path)
    h = HealthMonitor(policy="warn", journal=j, registry=Registry())
    t = _tiny_trainer(mesh8, journal=j, registry=h.registry, health=h)
    t.fit(lambda: _batches_with_nan(), epochs=1, handle_preemption=False)
    t.close()
    j.close()
    events = read_journal(path)
    assert events[-1]["event"] == "exit"  # warn continues to a clean exit
    kinds = [e["kind"] for e in events if e["event"] == "health"]
    assert "non_finite" in kinds


def test_trainer_nan_skip_step_policy(tmp_path, mesh8):
    path = str(tmp_path / "skip.jsonl")
    j = RunJournal(path)
    reg = Registry()
    h = HealthMonitor(policy="skip_step", journal=j, registry=reg)
    t = _tiny_trainer(mesh8, journal=j, registry=reg, health=h)
    t.fit(lambda: _batches_with_nan(), epochs=1, handle_preemption=False)
    import jax

    # the poisoned update was discarded: weights stayed finite throughout
    leaves = jax.tree_util.tree_leaves(t.state.params)
    assert all(bool(np.all(np.isfinite(np.asarray(x)))) for x in leaves)
    # and the step counter advanced only for the 3 applied updates
    assert int(t.state.step) == 3
    t.close()
    j.close()
    assert reg.counter("health_skipped_steps_total").value == 1
    summary = [e for e in read_journal(path) if e["event"] == "epoch"][0]
    # the skipped step's garbage loss stayed out of the epoch mean
    assert np.isfinite(summary["summary"]["loss"])


def test_watchdog_only_health_keeps_divergence_fatal(tmp_path, mesh8):
    # --watchdog-timeout alone defaults the NaN policy to warn, but that
    # implicit default must NOT relax the pre-existing fatal
    # non-finite-epoch-mean check (the user never chose a policy)
    j = RunJournal(str(tmp_path / "wd.jsonl"))
    h = HealthMonitor(policy="warn", journal=j, registry=Registry(),
                      watchdog_timeout=60, policy_explicit=False)
    t = _tiny_trainer(mesh8, journal=j, registry=h.registry, health=h)
    with pytest.raises(FloatingPointError):
        t.fit(lambda: _batches_with_nan(), epochs=2, handle_preemption=False)
    t.close()
    j.close()


def test_trainer_nan_abort_policy(tmp_path, mesh8):
    path = str(tmp_path / "abort.jsonl")
    j = RunJournal(path)
    h = HealthMonitor(policy="abort", journal=j, registry=Registry())
    t = _tiny_trainer(mesh8, journal=j, registry=h.registry, health=h)
    with pytest.raises(TrainingHealthError):
        t.fit(lambda: _batches_with_nan(), epochs=1, handle_preemption=False)
    t.close()
    j._atexit()
    kinds = [e["event"] for e in read_journal(path)]
    assert kinds.index("health") < kinds.index("crash")


def test_trainer_trace_has_nested_step_eval_spans(tmp_path, mesh8):
    path = str(tmp_path / "run.trace.json")
    tracer = Tracer(path)
    set_tracer(tracer)
    try:
        t = _tiny_trainer(mesh8)
        data = _batches_with_nan(n_clean=2, nan_at=2)[:2]  # clean only
        t.fit(lambda: data, lambda: data, epochs=1, handle_preemption=False)
        t.close()
    finally:
        set_tracer(None)
        tracer.close()
    doc = json.load(open(path))
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in spans}
    assert {"train/epoch", "train/step", "eval"} <= names
    # step spans nest inside their epoch span
    epoch = next(e for e in spans if e["name"] == "train/epoch")
    steps = [e for e in spans if e["name"] == "train/step"]
    assert len(steps) == 2
    for s in steps:
        assert epoch["ts"] <= s["ts"]
        assert s["ts"] + s["dur"] <= epoch["ts"] + epoch["dur"] + 1
        assert "step" in s["args"]
    # validator agrees the artifact is well-formed
    from tools.check_journal import check_trace

    assert check_trace(path) == []


def test_dataloader_emits_fetch_and_batch_spans(tmp_path):
    from deep_vision_tpu.data.pipeline import DataLoader

    path = str(tmp_path / "dl.trace.json")
    tracer = Tracer(path)
    set_tracer(tracer)
    try:
        ds = [{"x": np.ones((2,), np.float32)} for _ in range(8)]
        dl = DataLoader(ds, batch_size=4, num_workers=1, prefetch=2,
                        name="trace-test")
        assert sum(1 for _ in dl) == 2
    finally:
        set_tracer(None)
        tracer.close()
    spans = [e for e in json.load(open(path))["traceEvents"]
             if e["ph"] == "X"]
    names = [e["name"] for e in spans]
    # one fetch per BATCH: the end-of-epoch sentinel get is producer-drain
    # wait, not fetch time, and must not appear in the totals
    assert names.count("data/fetch") == 2
    assert names.count("data/augment_batch") == 2
    fetch = next(e for e in spans if e["name"] == "data/fetch")
    assert fetch["args"]["loader"] == "trace-test"


# -- check_journal validator --------------------------------------------------

def test_check_journal_accepts_real_journal(tmp_path):
    from tools.check_journal import check_journal

    path = str(tmp_path / "good.jsonl")
    with RunJournal(path, kind="train") as j:
        j.manifest(config={"name": "lenet5"})
        j.step(1, step_time_ms=1.0)
        j.write("checkpoint", step=1, epoch=0, saved=True)
        j.write("health", kind="non_finite", step=2, fields=["loss"])
    assert check_journal(path, require_exit=True) == []


def test_check_journal_rejects_bad_events(tmp_path):
    from tools.check_journal import check_journal

    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"event": "step", "ts": 1.0, "run_id": "r"}) + "\n")
        f.write(json.dumps({"event": "wat", "ts": 1.0, "run_id": "r"}) + "\n")
        f.write(json.dumps({"event": "exit", "ts": 1.0}) + "\n")
    # unknown event types are tolerated by default (forward compatibility:
    # an old checker must accept a newer producer's journals)...
    assert not any("unknown event type" in e for e in check_journal(path))
    # ...and violations under --strict
    errs = check_journal(path, strict=True)
    assert any("step event missing field 'step'" in e for e in errs)
    assert any("unknown event type 'wat'" in e for e in errs)
    assert any("missing envelope field 'run_id'" in e for e in errs)
    # crash terminal fails --require-exit
    path2 = str(tmp_path / "crashed.jsonl")
    j = RunJournal(path2)
    j.step(1, step_time_ms=1.0)
    j._atexit()
    assert check_journal(path2) == []
    assert any("crash marker" in e
               for e in check_journal(path2, require_exit=True))


def test_check_journal_cli_exit_codes(tmp_path, capsys):
    """0 = valid, 2 = invalid file, 64 = usage error — so make targets and
    wrappers can tell a bad journal from a bad invocation."""
    from tools.check_journal import EXIT_INVALID, EXIT_USAGE, main

    good = str(tmp_path / "good.jsonl")
    with RunJournal(good, kind="train") as j:
        j.step(1, step_time_ms=1.0)
    assert main([good]) == 0

    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as f:
        f.write("{not json}\n" + json.dumps(
            {"event": "exit", "ts": 1.0, "run_id": "r", "status": "ok"}) + "\n")
    assert main([bad]) == EXIT_INVALID

    with pytest.raises(SystemExit) as exc:
        main([])  # journals are required
    assert exc.value.code == EXIT_USAGE
    capsys.readouterr()


def test_check_journal_cli_strict_flag(tmp_path, capsys):
    from tools.check_journal import EXIT_INVALID, main

    path = str(tmp_path / "forward.jsonl")
    rows = [
        {"event": "from_the_future", "ts": 1.0, "run_id": "r"},
        {"event": "exit", "ts": 2.0, "run_id": "r", "status": "ok"},
    ]
    with open(path, "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in rows)
    assert main([path]) == 0  # forward-compatible by default
    assert main([path, "--strict"]) == EXIT_INVALID
    # strict also demands the exit marker
    noexit = str(tmp_path / "alive.jsonl")
    with open(noexit, "w") as f:
        f.write(json.dumps({"event": "step", "ts": 1.0, "run_id": "r",
                            "step": 1}) + "\n")
    assert main([noexit]) == 0
    assert main([noexit, "--strict"]) == EXIT_INVALID
    capsys.readouterr()


def test_check_trace_rejects_malformed(tmp_path):
    from tools.check_journal import check_trace

    bad = tmp_path / "bad.trace.json"
    bad.write_text("{not json")
    assert any("not valid JSON" in e for e in check_trace(str(bad)))
    empty = tmp_path / "empty.trace.json"
    empty.write_text(json.dumps({"traceEvents": []}))
    assert any("no complete" in e for e in check_trace(str(empty)))
    missing = tmp_path / "missing.trace.json"
    missing.write_text(json.dumps(
        {"traceEvents": [{"name": "x", "ph": "X", "ts": 1.0}]}))
    assert any("missing 'dur'" in e for e in check_trace(str(missing)))


def test_obs_report_renders_health_and_trace(tmp_path, capsys):
    from tools.obs_report import main as report_main

    jpath = str(tmp_path / "r.jsonl")
    with RunJournal(jpath, kind="train") as j:
        j.manifest(config={"name": "lenet5", "task": "classification"})
        j.step(1, step_time_ms=10.0)
        j.write("health", kind="non_finite", step=2, fields=["loss"],
                action="warn", policy="warn")
        j.write("health", kind="hang", stalled_s=12.0, timeout_s=10.0,
                stacks={"MainThread (1)": ["frame"]})
    tpath = str(tmp_path / "r.trace.json")
    tracer = Tracer(tpath)
    with tracer.span("train/step", step=1):
        pass
    tracer.close()
    assert report_main([jpath, "--trace", tpath]) == 0
    out = capsys.readouterr().out
    assert "non_finitex1" in out and "hangx1" in out
    assert "1 thread stacks dumped" in out
    assert "span time summary" in out and "train/step" in out
