"""YOLO anchor assignment: padded boxes -> per-scale grid targets, vectorized.

Replaces the TensorArray + tensor_scatter_nd_update autograph loops at
YOLO/tensorflow/preprocess.py:137-269 (`preprocess_label_for_one_scale`,
`find_best_anchor`) with a single masked scatter: every (padded, fixed-count)
ground-truth box computes its best anchor by IoU against the 9 anchor shapes
(:226-269), then scatters (xywh, obj, one-hot class) into the (g, g, A, 5+C)
grid of the scale owning that anchor. Static shapes throughout — the TPU-native
form of 'ragged boxes' (SURVEY.md §7 hard parts).
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

# COCO anchors normalized by 416 (yolov3.py header); rows: (w, h)
YOLO_ANCHORS = np.array(
    [(10, 13), (16, 30), (33, 23), (30, 61), (62, 45), (59, 119),
     (116, 90), (156, 198), (373, 326)],
    np.float32,
) / 416.0
# scale 0 = stride 32 (large objects) gets anchors 6,7,8, etc.
YOLO_ANCHOR_MASKS = np.array([[6, 7, 8], [3, 4, 5], [0, 1, 2]])


def _anchor_iou(wh, anchors):
    """IoU of box shapes (N,2) vs anchors (A,2), both centered at origin."""
    inter = jnp.minimum(wh[:, None, 0], anchors[None, :, 0]) * jnp.minimum(
        wh[:, None, 1], anchors[None, :, 1]
    )
    area_box = wh[:, 0] * wh[:, 1]
    area_anchor = anchors[:, 0] * anchors[:, 1]
    return inter / jnp.maximum(area_box[:, None] + area_anchor[None] - inter, 1e-9)


def assign_anchors_to_grid(
    boxes_xywh,
    classes,
    grid_sizes: Sequence[int],
    anchors=YOLO_ANCHORS,
    anchor_masks=YOLO_ANCHOR_MASKS,
    num_classes: int = 80,
):
    """Build per-scale YOLO training targets from padded GT boxes.

    boxes_xywh: (N, 4) normalized; padded rows have w == h == 0.
    classes: (N,) int ids.
    Returns a list over scales of (g, g, A, 5 + num_classes) targets with
    layout [x, y, w, h, obj, onehot...] matching preprocess.py:137-224.
    Use `jax.vmap` for a batch dimension.
    """
    anchors = jnp.asarray(anchors)
    anchor_masks = jnp.asarray(anchor_masks)
    n = boxes_xywh.shape[0]
    valid = (boxes_xywh[..., 2] > 0) & (boxes_xywh[..., 3] > 0)

    iou = _anchor_iou(boxes_xywh[:, 2:4], anchors)  # (N, 9)
    best_anchor = jnp.argmax(iou, axis=-1)  # (N,)

    onehot = jax.nn.one_hot(classes, num_classes, dtype=boxes_xywh.dtype)
    targets = []
    for s, g in enumerate(grid_sizes):
        mask = anchor_masks[s]  # (A,) anchor ids owned by this scale
        # which slot (if any) within this scale each box lands in
        slot = jnp.argmax(best_anchor[:, None] == mask[None, :], axis=-1)
        owned = jnp.any(best_anchor[:, None] == mask[None, :], axis=-1) & valid

        cell = jnp.floor(boxes_xywh[:, :2] * g).astype(jnp.int32)
        cell = jnp.clip(cell, 0, g - 1)
        rows = jnp.where(owned, cell[:, 1], g)  # g = out-of-range drop row
        cols = jnp.where(owned, cell[:, 0], g)

        value = jnp.concatenate(
            [boxes_xywh, jnp.ones((n, 1), boxes_xywh.dtype), onehot], axis=-1
        )
        grid = jnp.zeros((g + 1, g + 1, mask.shape[0], 5 + num_classes),
                         boxes_xywh.dtype)
        grid = grid.at[rows, cols, slot].set(value)
        targets.append(grid[:g, :g])
    return targets
