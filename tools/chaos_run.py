"""Chaos smoke: a tiny CPU train run under injected faults, then validate.

    PYTHONPATH=. python tools/chaos_run.py [--workdir artifacts/chaos_smoke]

The CI teeth behind the resilience/ contracts (`make chaos-smoke`), the
way obs-smoke is the teeth behind the obs/ schemas. Three phased runs of
a record-backed LeNet-scale train (tiny synthetic shards written on the
fly), each a real `train_cli.main()` subprocess:

  1. bad-data     `data.read:io_error@0.02` with a bad-record budget:
                  the run must COMPLETE, every skipped record must land
                  in the dead-letter JSONL with file+offset, the skip
                  count must sit within budget, and the journal must
                  pass `check_journal --strict` (typed `fault` +
                  `data_skip` events included).
  2. torn-save    `ckpt.sidecar:corrupt@2;ckpt.sidecar:crash_after_write@3`:
                  epoch 2's sidecar is bit-flipped after checksumming
                  (storage rot) and epoch 3's save is SIGKILLed inside
                  the torn-write window. The run must die by SIGKILL —
                  that is the injected preemption — and the flight
                  recorder must leave an atomic, crc-valid postmortem
                  bundle written in the instants before the kill.
  3. resume       same checkpoint dir, no faults: `resume()` must
                  QUARANTINE the corrupt/incomplete steps (typed
                  `ckpt_quarantine` events), fall back to the newest
                  valid one, and train to completion.

Then the observability contracts on top (obs/flight.py, obs/autoprof.py,
obs/merge.py):

  4. autoprof     an induced step-time regression must yield exactly one
                  `profile_capture` capture per episode (a REAL
                  jax.profiler window on CPU), with triggers inside the
                  cooldown journaled as skipped_cooldown and triggers
                  past the budget as skipped_budget.
  5. obs_merge    a simulated 2-process run (two per-host journals, one
                  host slow on three steps) must merge into one timeline
                  whose `straggler` events finger the slow host, passing
                  `check_journal --strict` and `obs_report --merged`.
  6. locksmith    the runtime lock-order sanitizer (obs/locksmith.py) is
                  armed in every child (DVT_LOCKSMITH=1) and around the
                  in-process phases — all of which must journal ZERO
                  `lock_order_violation` events — and a forced A->B/B->A
                  inversion must be detected, journaled with both
                  acquisition stacks, and pass `--strict`.
  7. shrink-mesh  the elastic loop end-to-end: a child training on a
                  FORCED 4-device CPU mesh is SIGTERMed under live
                  training — it must write an atomic preempt checkpoint,
                  journal a typed `preempt_checkpoint` event, and exit
                  with the scheduler's requeue code (EX_TEMPFAIL 75,
                  obs.flight.REQUEUE_EXIT_CODE); a second child then
                  resumes from that checkpoint under a 2-device mesh
                  (cross-mesh sidecar sharding metadata), with the step
                  counter CONTINUING from the preempt step — losses
                  resume, they do not restart — and both journals
                  passing `check_journal --strict`.
  8. data-resume the data plane's determinism contract
                  (tools/data_smoke.py phase_resume_determinism, shared
                  with `make data-smoke`): a record-backed train is
                  SIGKILLed mid-epoch by an injected data.read crash,
                  resumes from the crc32c sidecar's DataLoaderState, and
                  the post-resume batch sequence must be byte-identical
                  (content hashes) to an uninterrupted run's from the
                  same offset, with a strict-valid typed `data_resume`
                  event — PR 10's exact-step resume extended to the
                  input stream.

Plus overhead probes: with no spec installed an injection point is one
module-global load + None check, flight recording (one tap call per
journal event) must stay under 2% of the measured phase-1 step time,
and a disabled locksmith lock cycle pays the same None-check budget.

Exit status 0 = every phase held; 1 = a contract is broken.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time
from typing import List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from tools.smoke_util import read_jsonl  # noqa: E402

CONFIG = "lenet5_chaos"
SCHEMA = "chaos_mnist"
EPOCHS = 3
TRAIN_RECORDS_PER_SHARD = 96
TRAIN_SHARDS = 2
VAL_RECORDS = 48
# one module-global load + None check; 2us would already be absurd
MAX_DISABLED_FIRE_NS = 2000.0


def register_chaos_config() -> None:
    """Register the records-backed tiny config + raw-image schema the
    chaos children train with (kept out of the production registry: only
    chaos_run processes ever see it)."""
    import numpy as np

    from deep_vision_tpu.configs import ExperimentConfig, register_config
    from deep_vision_tpu.data import datasets

    def chaos_mnist_schema(feats):
        img = np.frombuffer(feats["image/raw"][0], np.uint8).reshape(28, 28, 1)
        return {"image": img, "label": np.int32(feats["image/class/label"][0])}

    datasets.SCHEMAS.setdefault(SCHEMA, chaos_mnist_schema)
    if CONFIG not in __import__(
            "deep_vision_tpu.configs", fromlist=["CONFIG_REGISTRY"]
    ).CONFIG_REGISTRY:
        register_config(ExperimentConfig(
            name=CONFIG, task="classification", model="lenet5",
            input_shape=(32, 32, 1), num_classes=10, batch_size=16,
            epochs=EPOCHS,
            optimizer={"name": "adam", "learning_rate": 1e-3},
            dataset={"kind": "records", "schema": SCHEMA},
        ))


def child_main(argv: List[str]) -> int:
    """`chaos_run.py --child <train args...>`: a normal train_cli run with
    the chaos config registered first."""
    register_chaos_config()
    from deep_vision_tpu.train_cli import main

    return main(argv)


# -- parent-side helpers ------------------------------------------------------

def write_shards(data_dir: str) -> None:
    import numpy as np

    from deep_vision_tpu.data.example_codec import encode_example
    from deep_vision_tpu.data.records import write_records

    os.makedirs(data_dir, exist_ok=True)
    rng = np.random.RandomState(0)

    def example(label: int) -> bytes:
        img = rng.randint(0, 256, size=(28, 28, 1), dtype=np.uint8)
        return encode_example({
            "image/raw": [img.tobytes()],
            "image/class/label": [label],
        })

    for s in range(TRAIN_SHARDS):
        write_records(
            os.path.join(data_dir, f"train-{s:05d}"),
            [example(i % 10) for i in range(TRAIN_RECORDS_PER_SHARD)],
        )
    write_records(
        os.path.join(data_dir, "val-00000"),
        [example(i % 10) for i in range(VAL_RECORDS)],
    )


def _child_env(extra_env: Optional[dict] = None) -> dict:
    # every child trains with the runtime lock sanitizer armed
    # (train_cli.arm_from_env): an inversion between the journal, flight,
    # health-watchdog, or data-budget locks journals a typed
    # lock_order_violation event the parent then asserts absent
    env = dict(os.environ, PYTHONPATH=ROOT, JAX_PLATFORMS="cpu",
               DVT_LOCKSMITH="1")
    # a parent-installed spec must never leak into a child that did not
    # ask for one (phase 3 resumes WITHOUT faults)
    env.pop("DVT_FAULT_SPEC", None)
    env.pop("DVT_FAULT_SEED", None)
    if extra_env:
        env.update(extra_env)  # phase 7 REPLACES XLA_FLAGS to force a
                               # specific virtual device count per child
    return env


def run_child(train_args: List[str], log_path: str,
              timeout: float = 600.0,
              extra_env: Optional[dict] = None) -> int:
    with open(log_path, "w") as log:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"]
            + train_args,
            cwd=ROOT, env=_child_env(extra_env), stdout=log,
            stderr=subprocess.STDOUT, timeout=timeout,
        )
    return proc.returncode


def start_child(train_args: List[str], log_path: str,
                extra_env: Optional[dict] = None):
    """Popen form for phases that signal the child mid-run (phase 7 sends
    SIGTERM under live training); returns (proc, log_file)."""
    log = open(log_path, "w")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child"] + train_args,
        cwd=ROOT, env=_child_env(extra_env), stdout=log,
        stderr=subprocess.STDOUT,
    )
    return proc, log


def check_journal_strict(path: str) -> bool:
    rc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "check_journal.py"),
         path, "--strict"],
        cwd=ROOT, env=dict(os.environ, PYTHONPATH=ROOT),
    ).returncode
    return rc == 0


class Failures:
    def __init__(self):
        self.errors: List[str] = []

    def check(self, ok: bool, what: str) -> bool:
        print(("  ok  " if ok else "  FAIL") + f"  {what}")
        if not ok:
            self.errors.append(what)
        return ok


def probe_disabled_overhead() -> float:
    """ns per faults.fire() call with no spec installed."""
    from deep_vision_tpu.resilience import faults

    assert faults.installed() is None
    n = 200_000
    fire = faults.fire
    t0 = time.perf_counter()
    for _ in range(n):
        fire("data.read")
    return (time.perf_counter() - t0) / n * 1e9


MAX_FLIGHT_OVERHEAD_FRAC = 0.02  # flight tap budget: 2% of step time


def _phase1_mean_step_ms(work: str) -> float:
    """Mean step_time_ms from the phase-1 journal — the denominator the
    flight-overhead budget is measured against."""
    steps = [e for e in read_jsonl(os.path.join(work,
                                                "journal_bad_data.jsonl"))
             if e.get("event") == "step" and "step_time_ms" in e]
    if not steps:
        return 1.0  # degenerate floor: the probe then demands < 20us
    return sum(float(e["step_time_ms"]) for e in steps) / len(steps)


def probe_flight_overhead(work: str) -> "tuple[float, float]":
    """(ms per observe() tap call with a recorder attached, ns per
    flight.note() with NO recorder installed — the disabled path)."""
    from deep_vision_tpu.obs import flight as flight_mod
    from deep_vision_tpu.obs.flight import FlightRecorder

    fr = FlightRecorder(os.path.join(work, "flight_probe"))
    row = {"event": "step", "ts": 0.0, "run_id": "probe", "step": 1,
           "step_time_ms": 10.0, "data_wait_ms": 1.0}
    n = 100_000
    observe = fr.observe
    t0 = time.perf_counter()
    for _ in range(n):
        observe(row)
    tap_ms = (time.perf_counter() - t0) / n * 1e3
    fr.close()

    assert flight_mod.get_flight() is None
    note = flight_mod.note
    t0 = time.perf_counter()
    for _ in range(n):
        note("probe")
    idle_ns = (time.perf_counter() - t0) / n * 1e9
    return tap_ms, idle_ns


def probe_autoprof(work: str, f: "Failures") -> None:
    """Drive a real AutoProfiler (REAL jax.profiler captures on CPU)
    through a synthetic step-time series with three induced regressions:
    capture, skipped_cooldown, capture, skipped_budget — then validate
    the journaled decisions and the trace artifacts."""
    from deep_vision_tpu.obs import AutoProfiler, RunJournal
    from deep_vision_tpu.obs.registry import Registry

    j_path = os.path.join(work, "journal_autoprof.jsonl")
    prof_dir = os.path.join(work, "autoprof")
    journal = RunJournal(j_path)
    journal.manifest()
    ap = AutoProfiler(prof_dir, journal=journal, registry=Registry(),
                      auto=True, window_steps=3, cooldown_steps=40,
                      max_captures=2, z_threshold=4.0, min_history=10)
    step = 0

    def feed(ms: float) -> None:
        nonlocal step
        step += 1
        ap.on_step_start(step)
        ap.observe_step(step, {"step_time_ms": ms,
                               "data_wait_ms": ms * 0.05})

    for i in range(20):
        feed(10.0 + 0.1 * (i % 5))   # steady baseline
    feed(400.0)                       # regression 1 -> arms a capture
    for _ in range(5):
        feed(10.0)                    # capture window runs + closes
    feed(400.0)                       # regression 2: inside cooldown
    for _ in range(60):
        feed(10.0)                    # cooldown expires
    feed(400.0)                       # regression 3 -> second capture
    for _ in range(60):
        feed(10.0)
    feed(400.0)                       # regression 4: budget spent
    for _ in range(3):
        feed(10.0)
    ap.close()
    journal.close()

    caps = [e for e in read_jsonl(j_path)
            if e.get("event") == "profile_capture"]
    by_outcome: dict = {}
    for e in caps:
        by_outcome.setdefault(e["outcome"], []).append(e)
    captured = by_outcome.get("captured", [])
    f.check(len(captured) == 2,
            f"exactly one capture per regression episode "
            f"({len(captured)} captured, budget 2)")
    f.check(len(by_outcome.get("skipped_cooldown", [])) == 1,
            "in-cooldown regression journaled skipped_cooldown, "
            "not a second capture")
    f.check(len(by_outcome.get("skipped_budget", [])) == 1,
            "post-budget regression journaled skipped_budget")
    f.check(all(e.get("reason") == "step_time_z" for e in caps),
            "every capture decision names the step_time_z trigger")
    # ordering: capture 1 closed BEFORE the cooldown skip, which precedes
    # capture 2's start — one capture in flight at a time, ever
    order = [e["outcome"] for e in caps]
    f.check(order == ["started", "captured", "skipped_cooldown",
                      "started", "captured", "skipped_budget"],
            f"capture lifecycle ordered correctly ({order})")
    trace_files = []
    for root, _dirs, files in os.walk(prof_dir):
        trace_files += files
    f.check(len(trace_files) >= 1,
            f"jax.profiler wrote real trace artifacts "
            f"({len(trace_files)} files)")
    f.check(check_journal_strict(j_path),
            "check_journal --strict accepts profile_capture events")


def probe_locksmith(work: str, f: "Failures") -> None:
    """The runtime half of the concurrency contracts (obs/locksmith.py):
    a forced A->B / B->A inversion must be detected and journaled as a
    typed `lock_order_violation` (passing --strict), and the DISABLED
    wrapper must cost one module-global None check on top of the raw
    primitive — the same budget as faults.fire and flight.note."""
    import threading

    from deep_vision_tpu.obs import RunJournal, locksmith
    from deep_vision_tpu.obs.registry import Registry

    j_path = os.path.join(work, "journal_locksmith.jsonl")
    journal = RunJournal(j_path)
    journal.manifest()
    san = locksmith.arm(journal=journal, registry=Registry())
    a = locksmith.lock("probe.A")
    b = locksmith.lock("probe.B")
    done = threading.Event()

    def inverted():
        # the second thread takes the locks in the OPPOSITE order —
        # sequenced after the first path fully released, so the probe
        # demonstrates detection without gambling on a real deadlock
        with b:
            with a:
                done.set()

    with a:
        with b:
            pass
    t = threading.Thread(target=inverted, name="locksmith-probe")
    t.start()
    t.join(timeout=10)
    f.check(done.is_set(), "forced-inversion probe thread completed")
    v = san.violations()
    f.check(len(v) == 1 and {v[0]["lock_a"], v[0]["lock_b"]}
            == {"probe.A", "probe.B"},
            f"runtime sanitizer detected the forced A->B/B->A inversion "
            f"({len(v)} violation(s))")
    rep = san.report()
    f.check(rep["locks"].get("probe.A", {}).get("acquisitions", 0) >= 2,
            "per-lock acquisition stats recorded")
    locksmith.disarm()
    journal.close()
    ev = read_jsonl(j_path)
    viol = [e for e in ev if e.get("event") == "lock_order_violation"]
    f.check(len(viol) == 1 and viol[0].get("stack")
            and viol[0].get("prior_stack"),
            "violation journaled with both acquisition stacks")
    f.check(check_journal_strict(j_path),
            "check_journal --strict accepts lock_order_violation events")

    # disabled-mode overhead: one global load + None check per op
    lk = locksmith.lock("probe.idle")
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        with lk:
            pass
    ns = (time.perf_counter() - t0) / n * 1e9
    f.check(ns < MAX_DISABLED_FIRE_NS,
            f"disabled locksmith wrapper costs {ns:.0f}ns/cycle "
            f"(< {MAX_DISABLED_FIRE_NS:.0f}ns)")


def probe_obs_merge(work: str, f: "Failures") -> None:
    """Synthesize a 2-host run (host 1 straggling on three steps), merge
    via the tools/obs_merge.py CLI, and validate the straggler events,
    schema, and --merged rendering."""
    base = os.path.join(work, "journal_2host.jsonl")
    t0 = time.time()
    slow_steps = {10, 11, 12}
    for host in (0, 1):
        rows = [{"event": "run_manifest", "ts": t0, "kind": "train",
                 "argv": ["chaos"], "run_id": f"chaos-2host-h{host}",
                 "process_index": host, "process_count": 2}]
        for s in range(1, 31):
            ms = 300.0 if (host == 1 and s in slow_steps) else 50.0
            rows.append({"event": "step", "ts": t0 + s * 0.05,
                         "run_id": f"chaos-2host-h{host}", "step": s,
                         "step_time_ms": ms, "data_wait_ms": 2.0,
                         "dispatch_ms": 5.0})
        rows.append({"event": "exit", "ts": t0 + 2.0, "status": "clean_exit",
                     "run_id": f"chaos-2host-h{host}"})
        with open(f"{base}.p{host}", "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")

    merged = base + ".merged"
    rc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "obs_merge.py"),
         "--auto", base, "-o", merged],
        cwd=ROOT, env=dict(os.environ, PYTHONPATH=ROOT),
    ).returncode
    f.check(rc == 0, f"obs_merge CLI merged the per-host journals (rc={rc})")
    events = read_jsonl(merged)
    stragglers = [e for e in events if e.get("event") == "straggler"]
    f.check(len(stragglers) == len(slow_steps),
            f"straggler detected on each induced slow step "
            f"({len(stragglers)}/{len(slow_steps)})")
    f.check(all(e.get("host") == 1 for e in stragglers),
            "stragglers finger the slow host (1)")
    f.check({e.get("step") for e in stragglers} == slow_steps,
            "straggler steps match the induced ones")
    f.check(all(abs(e.get("gap_ms", 0) - 125.0) < 1.0 for e in stragglers),
            "max-median gap computed correctly (300 - median(175) = 125)")
    f.check(check_journal_strict(merged),
            "check_journal --strict accepts the merged timeline")
    rc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "obs_report.py"),
         "--merged", merged],
        cwd=ROOT, env=dict(os.environ, PYTHONPATH=ROOT),
        stdout=subprocess.PIPE,
    ).returncode
    f.check(rc == 0, f"obs_report --merged renders the timeline (rc={rc})")


def phase7_shrink_mesh(work: str, data_dir: str, f: "Failures") -> None:
    """The elastic loop, end to end on CPU: train on a forced 4-device
    mesh, SIGTERM it under live training, then resume the run under 2
    devices from the preempt checkpoint — the 'fleet shrank while you
    were requeued' scenario. The first child must exit with the requeue
    code after an atomic checkpoint + typed `preempt_checkpoint` event;
    the second must restore that exact step (cross-mesh restore via the
    sidecar sharding metadata) and CONTINUE counting from it."""
    from deep_vision_tpu.obs.flight import REQUEUE_EXIT_CODE

    ckpt = os.path.join(work, "ckpt_shrink")
    j_a = os.path.join(work, "journal_shrink_preempt.jsonl")
    j_b = os.path.join(work, "journal_shrink_resume.jsonl")

    proc, log = start_child(
        ["-m", CONFIG, "--data-dir", data_dir, "--epochs", "6",
         "--ckpt-dir", ckpt, "--journal", j_a],
        os.path.join(work, "phase7a.log"),
        extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=4"},
    )
    # SIGTERM only once training is demonstrably live (>= 3 step events
    # in the journal): preempting during compile would prove less
    try:
        deadline = time.time() + 420
        n_steps = 0
        while time.time() < deadline and proc.poll() is None:
            n_steps = sum(1 for e in read_jsonl(j_a)
                          if e.get("event") == "step")
            if n_steps >= 3:
                break
            time.sleep(0.5)
        f.check(proc.poll() is None and n_steps >= 3,
                f"reached live training on the 4-device mesh before "
                f"SIGTERM ({n_steps} steps)")
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=300)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=60)
        log.close()
    f.check(rc == REQUEUE_EXIT_CODE,
            f"preempted run exits with the requeue code "
            f"({rc} == EX_TEMPFAIL {REQUEUE_EXIT_CODE})")
    ev_a = read_jsonl(j_a)
    mesh_a = [e for e in ev_a
              if e.get("event") == "note" and e.get("mesh_shape")]
    f.check(bool(mesh_a) and mesh_a[0]["mesh_shape"].get("data") == 4,
            "first run trained on the forced 4-device mesh")
    pc = [e for e in ev_a if e.get("event") == "preempt_checkpoint"]
    f.check(len(pc) == 1 and pc[0].get("saved") is True,
            f"SIGTERM escalated to an atomic preempt checkpoint "
            f"(journaled preempt_checkpoint, saved={pc and pc[0].get('saved')})")
    f.check(check_journal_strict(j_a),
            "check_journal --strict accepts the preempted journal")
    if not pc or not pc[0].get("saved"):
        return  # nothing to resume from; the failures above tell the story
    saved_step = int(pc[0]["step"])

    rc = run_child(
        ["-m", CONFIG, "--data-dir", data_dir, "--epochs", "6",
         "--ckpt-dir", ckpt, "-c", ckpt, "--journal", j_b],
        os.path.join(work, "phase7b.log"),
        extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
    )
    f.check(rc == 0, f"resumed run completed on the 2-device mesh (rc={rc})")
    ev_b = read_jsonl(j_b)
    mesh_b = [e for e in ev_b
              if e.get("event") == "note" and e.get("mesh_shape")]
    f.check(bool(mesh_b) and mesh_b[0]["mesh_shape"].get("data") == 2,
            "resume ran on the SHRUNK 2-device mesh")
    resumed = [e for e in ev_b
               if e.get("event") == "note" and e.get("note") == "resumed"]
    f.check(bool(resumed) and resumed[0].get("step") == saved_step,
            f"cross-mesh restore landed on the preempt step "
            f"({resumed and resumed[0].get('step')} == {saved_step})")
    resharded = [e for e in ev_b if e.get("event") == "note"
                 and e.get("note") == "ckpt_resharded"]
    f.check(bool(resharded)
            and resharded[0].get("saved_mesh", {}).get("data") == 4
            and resharded[0].get("mesh", {}).get("data") == 2,
            "restore journaled the 4 -> 2 device re-placement")
    steps_b = sorted(e.get("step") for e in ev_b
                     if e.get("event") == "step")
    f.check(bool(steps_b) and steps_b[0] == saved_step + 1,
            f"losses CONTINUE from the checkpoint (first resumed step "
            f"{steps_b[:1]} == {saved_step + 1}), not restart at 1")
    f.check(check_journal_strict(j_b),
            "check_journal --strict accepts the resumed journal")


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--child":
        return child_main(argv[1:])

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default="artifacts/chaos_smoke")
    args = p.parse_args(argv)

    work = os.path.abspath(args.workdir)
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work)
    data_dir = os.path.join(work, "data")
    write_shards(data_dir)
    f = Failures()

    # -- phase 1: bad data under budget ---------------------------------
    print("phase 1: data.read:io_error@0.02 under a bad-record budget")
    ckpt1 = os.path.join(work, "ckpt_bad_data")
    j1 = os.path.join(work, "journal_bad_data.jsonl")
    dead = os.path.join(work, "dead_letter.jsonl")
    rc = run_child(
        ["-m", CONFIG, "--data-dir", data_dir, "--epochs", str(EPOCHS),
         "--ckpt-dir", ckpt1, "--journal", j1,
         "--fault-spec", "data.read:io_error@0.02", "--fault-seed", "7",
         "--bad-record-budget", "50", "--dead-letter", dead],
        os.path.join(work, "phase1.log"),
    )
    f.check(rc == 0, f"bad-data run completed (rc={rc})")
    skips = read_jsonl(dead)
    f.check(len(skips) >= 1, f"dead-letter has skipped records ({len(skips)})")
    f.check(len(skips) <= 50, f"skips within budget ({len(skips)} <= 50)")
    f.check(all("path" in s and "offset" in s and "reason" in s
                for s in skips), "dead-letter rows carry path+offset+reason")
    ev1 = {e.get("event") for e in read_jsonl(j1)}
    f.check("fault" in ev1 and "data_skip" in ev1,
            f"journal carries typed fault + data_skip events ({sorted(ev1)})")
    f.check(check_journal_strict(j1), "check_journal --strict accepts journal")

    # -- phase 2: rot one sidecar, SIGKILL inside the next torn window --
    print("phase 2: sidecar rot + SIGKILL mid-checkpoint-save "
          "(flight recorder armed)")
    ckpt2 = os.path.join(work, "ckpt_crash")
    j2 = os.path.join(work, "journal_crash.jsonl")
    flight2 = os.path.join(work, "flight_crash")
    rc = run_child(
        ["-m", CONFIG, "--data-dir", data_dir, "--epochs", str(EPOCHS),
         "--ckpt-dir", ckpt2, "--journal", j2, "--flight-dir", flight2,
         "--fault-spec",
         "ckpt.sidecar:corrupt@2;ckpt.sidecar:crash_after_write@3"],
        os.path.join(work, "phase2.log"),
    )
    f.check(rc == -signal.SIGKILL,
            f"run died by injected SIGKILL mid-save (rc={rc})")
    f.check(any(e.get("event") == "fault" and e.get("kind") == "corrupt"
                for e in read_jsonl(j2)),
            "journal recorded the injected sidecar corruption")
    # the black box: the injected kill must leave an atomic, crc-valid
    # postmortem bundle (obs/flight.py), journaled as a flight_dump event
    from deep_vision_tpu.obs.flight import find_bundles, validate_bundle

    bundles = find_bundles(flight2)
    f.check(len(bundles) == 1,
            f"SIGKILL left exactly one flight bundle ({len(bundles)})")
    if bundles:
        errs = validate_bundle(bundles[0])
        f.check(not errs, "flight bundle structure + crc valid"
                + ("" if not errs else f" ({errs[0]})"))
        f.check("injected_crash_after_write" in os.path.basename(bundles[0]),
                "bundle names the injected-kill reason")
        steps_dumped = read_jsonl(os.path.join(bundles[0], "steps.jsonl"))
        f.check(len(steps_dumped) >= 1,
                f"bundle carries recent step records ({len(steps_dumped)})")
    f.check(any(e.get("event") == "flight_dump"
                and e.get("outcome") == "written"
                for e in read_jsonl(j2)),
            "journal carries the typed flight_dump event")
    leftovers = ([d for d in os.listdir(flight2) if ".tmp-" in d]
                 if os.path.isdir(flight2) else ["flight dir missing"])
    f.check(not leftovers,
            "no torn .tmp- bundle dirs left behind (atomic rename)")

    # -- phase 3: resume must quarantine and fall back ------------------
    print("phase 3: resume quarantines the torn steps and recovers")
    j3 = os.path.join(work, "journal_resume.jsonl")
    rc = run_child(
        ["-m", CONFIG, "--data-dir", data_dir, "--epochs", str(EPOCHS),
         "--ckpt-dir", ckpt2, "-c", ckpt2, "--journal", j3],
        os.path.join(work, "phase3.log"),
    )
    f.check(rc == 0, f"resume run completed (rc={rc})")
    ev3 = read_jsonl(j3)
    quarantined = [e for e in ev3 if e.get("event") == "ckpt_quarantine"]
    f.check(len(quarantined) >= 1,
            f"resume quarantined the corrupt step(s) ({len(quarantined)})")
    f.check(os.path.isdir(os.path.join(ckpt2, "quarantine")),
            "quarantined artifacts preserved under ckpt/quarantine/")
    f.check(any(e.get("event") == "note" and e.get("note") == "resumed"
                and e.get("step", 0) > 0 for e in ev3),
            "resume restored a non-zero fallback step")
    f.check(check_journal_strict(j3), "check_journal --strict accepts journal")

    # -- phase 4: induced regression -> exactly one capture per episode -
    print("phase 4: step-time regression triggers one profile_capture "
          "(cooldown + budget enforced)")
    # phases 4-5 run in-process: arm the lock sanitizer around them so
    # the journal/flight/registry lock traffic they generate runs
    # order-checked, then assert it stayed clean
    from deep_vision_tpu.obs import locksmith

    parent_san = locksmith.arm()
    probe_autoprof(work, f)

    # -- phase 5: simulated 2-process run merges with a straggler -------
    print("phase 5: 2-host journal merge detects the straggler")
    probe_obs_merge(work, f)
    f.check(not parent_san.violations(),
            "locksmith: zero lock-order violations across the in-process "
            "obs probes")
    locksmith.disarm()

    # -- phase 6: runtime lock sanitizer contracts ----------------------
    print("phase 6: locksmith detects a forced inversion; disabled "
          "wrapper stays at None-check cost")
    probe_locksmith(work, f)
    f.check("lock_order_violation" not in ev1
            and not any(e.get("event") == "lock_order_violation"
                        for e in ev3),
            "armed children journaled zero lock_order_violation events")

    # -- phase 7: shrink the mesh mid-run -------------------------------
    print("phase 7: SIGTERM under live 4-device training -> preempt "
          "checkpoint -> resume on a 2-device mesh")
    phase7_shrink_mesh(work, data_dir, f)

    # -- phase 8: deterministic data resume -----------------------------
    print("phase 8: SIGKILL mid-epoch -> sidecar resume -> byte-identical "
          "batch stream (data/snapshot.py)")
    import importlib

    data_smoke = importlib.import_module("tools.data_smoke")
    ds_work = os.path.join(work, "data_resume")
    os.makedirs(ds_work, exist_ok=True)
    ds_f = data_smoke.Failures()
    data_smoke.phase_resume_determinism(ds_work, ds_f)
    for err in ds_f.errors:
        f.errors.append(f"data-resume: {err}")
    f.check(not ds_f.errors,
            f"deterministic-resume phase held "
            f"({len(ds_f.errors)} broken contract(s))")

    # -- disabled-injection overhead ------------------------------------
    ns = probe_disabled_overhead()
    f.check(ns < MAX_DISABLED_FIRE_NS,
            f"disabled injection point costs {ns:.0f}ns/call "
            f"(< {MAX_DISABLED_FIRE_NS:.0f}ns)")

    # -- flight-recording overhead against the measured step time ------
    step_ms = _phase1_mean_step_ms(work)
    tap_ms, idle_ns = probe_flight_overhead(work)
    f.check(tap_ms < MAX_FLIGHT_OVERHEAD_FRAC * step_ms,
            f"flight tap costs {tap_ms * 1e3:.1f}us/step vs step time "
            f"{step_ms:.1f}ms (< {MAX_FLIGHT_OVERHEAD_FRAC:.0%})")
    f.check(idle_ns < MAX_DISABLED_FIRE_NS,
            f"flight.note with no recorder costs {idle_ns:.0f}ns/call "
            f"(< {MAX_DISABLED_FIRE_NS:.0f}ns)")

    if f.errors:
        print(f"\nchaos-smoke: {len(f.errors)} contract(s) BROKEN "
              f"(artifacts in {work})")
        return 1
    print(f"\nchaos-smoke: all resilience contracts held (artifacts in {work})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
