"""Ring attention: sequence/context parallelism over the mesh's data axis.

The reference is a CNN zoo with no attention or sequence dimension anywhere
(SURVEY.md §5 'long-context: N/A'), but this framework treats long-context as
first-class so attention workloads scale past one chip's HBM. Design follows
the blockwise-parallel / ring-attention recipe (Liu et al. 2023): shard the
sequence across devices, keep Q resident, rotate K/V blocks around the ring
with `ppermute` (one ICI hop per step, compute overlapping communication),
and merge per-block attention with a numerically-stable online softmax — the
same log-sum-exp accumulation flash attention uses, so the result is exact,
not approximate.

Layout contract: (batch, seq, heads, head_dim) with seq sharded over
`axis_name`. Collectives ride ICI inside a slice, DCN across hosts — no
NCCL/MPI analog needed (cf. SURVEY.md §2.5 comm-backend row).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deep_vision_tpu.parallel.mesh import DATA_AXIS


def _block_attend(q, k, v, scale, mask):
    """Scores + masked stable-softmax pieces for one (q_blk, kv_blk) pair.

    Returns (numerator (B,T,H,D), TRUE row max (B,H,T) — -inf for rows with
    no visible keys in this block — and row sumexp (B,H,T)). Carrying the
    true max (not a 0-clamped one) keeps the online-softmax merge exact even
    when every real score is far below zero.
    """
    # upcast K/V here (not in the ring carry: ppermute should move the
    # narrow input dtype, half the ICI bytes per hop for bf16)
    k = k.astype(q.dtype)
    v = v.astype(q.dtype)
    s = jnp.einsum("bthd,bshd->bhts", q, k) * scale  # (B,H,Tq,Ts)
    s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # (B,H,Tq); -inf when fully masked
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])  # fully-masked rows: exp(-inf) = 0
    l = jnp.sum(p, axis=-1)  # (B,H,Tq)
    o = jnp.einsum("bhts,bshd->bthd", p, v)
    return o, m, l


NEG = -1e30  # "no visible keys" marker: finite, so exp/logaddexp never NaN


def _flash_block(q, k_blk, v_blk, scale, causal: bool):
    """One ring step through the fused Pallas kernel.

    Returns (normalized out (B,T,H,D) f32, lse (B,H,T) f32). Normalized-form
    merging (out, lse) is algebraically identical to the (numerator, m, l)
    online softmax: lse' = logaddexp(lse_a, lse_b), out' = sum of outs
    reweighted by exp(lse - lse').
    """
    from deep_vision_tpu.ops.pallas.flash_attention import (
        flash_attention_with_lse,
    )

    b, t, h, d = q.shape
    out, lse = flash_attention_with_lse(
        q, k_blk.astype(q.dtype), v_blk.astype(q.dtype),
        causal=causal, scale=scale,
        block_q=min(512, t), block_k=min(1024, k_blk.shape[1]),
    )
    lse = lse[:, :, 0].reshape(b, h, t)
    return out.astype(jnp.float32), lse


def _ring_attention_local_flash(q, k, v, *, axis_name: str, causal: bool,
                                scale: Optional[float]):
    """Flash-kernel per-shard body: O(T_loc) memory per ring step.

    The dense body materializes a (T_loc, T_loc) score block per step; with
    long local shards that is exactly the quadratic buffer ring attention
    exists to avoid. Here each step runs the fused flash kernel
    (ops/pallas/flash_attention.py) and merges normalized (out, lse) pairs.
    """
    out_dtype = q.dtype
    q = q.astype(jnp.float32)
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    t_loc = q.shape[1]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    perm = [(j, (j + 1) % n) for j in range(n)]
    b, _, h, d = q.shape

    def attend(src, k_blk, v_blk):
        if not causal:
            return _flash_block(q, k_blk, v_blk, scale, causal=False)
        zeros = (
            jnp.zeros((b, t_loc, h, d), jnp.float32),
            jnp.full((b, h, t_loc), NEG, jnp.float32),
        )
        # src == my: the aligned diagonal block (causal within);
        # src < my: entirely in the past (full); src > my: invisible
        return jax.lax.cond(
            src == my,
            lambda: _flash_block(q, k_blk, v_blk, scale, causal=True),
            lambda: jax.lax.cond(
                src < my,
                lambda: _flash_block(q, k_blk, v_blk, scale, causal=False),
                lambda: zeros,
            ),
        )

    def step(i, carry):
        out, lse, k_blk, v_blk = carry
        src = (my - i) % n
        out_i, lse_i = attend(src, k_blk, v_blk)
        lse_new = jnp.logaddexp(lse, lse_i)
        a = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
        b_w = jnp.exp(lse_i - lse_new).transpose(0, 2, 1)[..., None]
        out = out * a + out_i * b_w
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return out, lse_new, k_blk, v_blk

    out0 = jnp.zeros((b, t_loc, h, d), jnp.float32)
    lse0 = jnp.full((b, h, t_loc), NEG, jnp.float32)
    out0 = jax.lax.pvary(out0, (axis_name,))
    lse0 = jax.lax.pvary(lse0, (axis_name,))
    out, _, _, _ = jax.lax.fori_loop(0, n, step, (out0, lse0, k, v))
    return out.astype(out_dtype)


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool,
                          scale: Optional[float]):
    """Per-shard body (runs under shard_map). q/k/v: (B, T_loc, H, D)."""
    # accumulate in f32: the online-softmax state (m, l, o) sums exp() terms
    # over the whole ring, and bf16 accumulation loses real precision there
    # (the flash kernel upcasts to f32 VMEM scratch for the same reason).
    # K/V stay in the input dtype — they ride the ring and _block_attend
    # upcasts per block, so ppermute moves the narrow dtype.
    out_dtype = q.dtype
    q = q.astype(jnp.float32)
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    t_loc = q.shape[1]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    q_pos = my * t_loc + jnp.arange(t_loc)  # global positions of local queries

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        o, m, l, k_blk, v_blk = carry
        src = (my - i) % n  # which shard this K/V block came from
        k_pos = src * t_loc + jnp.arange(t_loc)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]  # (Tq, Ts)
        else:
            mask = jnp.ones((t_loc, t_loc), bool)
        o_i, m_i, l_i = _block_attend(q, k_blk, v_blk, scale,
                                      mask[None, None, :, :])
        # online-softmax merge of (o, m, l) with the new block; maxes are the
        # TRUE row maxes (possibly -inf), so guard the -inf - -inf case
        m_new = jnp.maximum(m, m_i)
        m_new_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        a = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new_safe), 0.0)
        b = jnp.where(jnp.isfinite(m_i), jnp.exp(m_i - m_new_safe), 0.0)
        o = o * a.transpose(0, 2, 1)[..., None] + o_i * b.transpose(0, 2, 1)[..., None]
        l = l * a + l_i * b
        # rotate K/V one hop around the ring (overlaps with next block's FLOPs)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return o, m_new, l, k_blk, v_blk

    o0 = jnp.zeros_like(q)
    m0 = jnp.full((q.shape[0], q.shape[2], t_loc), -jnp.inf, q.dtype)
    l0 = jnp.zeros((q.shape[0], q.shape[2], t_loc), q.dtype)
    # constants start axis-unvarying under shard_map; mark them varying so the
    # loop carry type is stable across iterations
    m0 = jax.lax.pvary(m0, (axis_name,))
    l0 = jax.lax.pvary(l0, (axis_name,))
    o, m, l, _, _ = jax.lax.fori_loop(0, n, step, (o0, m0, l0, k, v))
    denom = jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(out_dtype)


def _default_use_flash(t_loc: int) -> bool:
    """Flash-kernel routing for a ring shard of `t_loc` local tokens:
    TPU only, at or above the shared `flash_min_tokens()` floor
    (ops/pallas/flash_attention.py; DVT_FLASH_MIN_TOKENS overrides it
    per platform — the ring path must honor the same knob as ViT, not
    a hard-coded 1024), AND block-divisible: `_flash_block` runs the
    kernel at block_q=512 / block_k=1024, whose grid asserts
    `t % block == 0` — a lowered floor must route a 768-token shard to
    the dense body, not into the kernel's shape assert (the same
    `t % 1024 == 0` guard models/vit.py keeps)."""
    from deep_vision_tpu.core.backend import get_backend
    from deep_vision_tpu.ops.pallas.flash_attention import flash_min_tokens

    return (get_backend().pallas_compiled
            and t_loc >= flash_min_tokens()
            and t_loc % 1024 == 0)


def ring_attention(
    q, k, v, mesh: Mesh, *, causal: bool = False,
    axis_name: str = DATA_AXIS, scale: Optional[float] = None,
    use_flash: Optional[bool] = None,
):
    """Exact attention over a sequence sharded across `axis_name`.

    q, k, v: (B, T, H, D) global shapes, T divisible by the axis size.
    Returns (B, T, H, D) with the same sharding.

    `use_flash` routes each ring step through the fused Pallas kernel
    (O(T_loc) memory instead of a dense (T_loc, T_loc) score block); default
    None auto-enables it on TPU for long local shards (the
    `flash_min_tokens()` floor, DVT_FLASH_MIN_TOKENS-overridable — the
    same knob that governs the ViT backbone's routing).
    """
    if use_flash is None:
        use_flash = _default_use_flash(q.shape[1] // mesh.shape[axis_name])
    spec = P(None, axis_name, None, None)
    body = _ring_attention_local_flash if use_flash else _ring_attention_local
    fn = functools.partial(
        body, axis_name=axis_name, causal=causal, scale=scale
    )
    mapped = jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        # pallas_call outputs carry no varying-mesh-axes annotation, so the
        # flash body opts out of the vma check (the dense body keeps it)
        check_vma=not use_flash,
    )
    return mapped(q, k, v)


def dense_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None):
    """Single-device reference implementation (golden for tests)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    if causal:
        t, s_ = s.shape[-2], s.shape[-1]
        mask = jnp.arange(t)[:, None] >= jnp.arange(s_)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v)
